"""`orion-tpu db migrate-ids`: rewrite an experiment's trial ids to a new
identity scheme, crash-resumably and byte-verified.

The cube_hash identity (``orion_tpu.core.trial.compute_cube_ids``) is ~an
order of magnitude cheaper per point than the historical repr+md5, but an
existing experiment's documents carry md5 ``_id``s — and every consumer
(reservation CAS, duplicate-point unique index, parents lineage) keys on
them.  :class:`IdMigrator` closes the gap with the PR 13 rebalancer's
state-machine shape, recorded in a per-experiment override doc any crashed
run resumes from:

======================  ======================================================
migration doc state     meaning
======================  ======================================================
(absent)                experiment ids match its ``id_scheme`` — nothing to do
``pinned``              migration claimed; new-id twins are being copied in
``copied``              copy complete and byte-verified; the flip is next
``flipped``             ``id_scheme`` flipped on the experiment doc; old-id
                        originals await deletion
(absent again)          migration complete
======================  ======================================================

Phase order per experiment: pin → copy each trial/lying-trial doc under its
new id (parents lineage remapped old→new in the same pass) → byte-verify
every non-id field against the original (canonical JSON, the same oracle
the rebalancer uses) + clean experiment audit → flip ``id_scheme`` on the
experiment doc → delete the old-id originals → drop the override.  Every
phase is diff-driven off the *recomputable* expected ids (the scheme hash
is a pure function of the params), so re-running any phase is a no-op —
which is the whole crash-resume story: no copied-id manifest to lose.

One code path covers all four backends AND the sharded router: every op
carries the ``experiment`` key (or the experiment's own ``_id``), which is
exactly what :class:`~orion_tpu.storage.shard.ShardedNetworkDB` routes by
— the migration doc, the new-id twins and the deletes all land on the
experiment's home shard without the migrator knowing the topology.

Run it with no active producers on the experiment: a producer that loaded
the pre-flip config would keep registering old-scheme ids after the flip.
"""

import logging
import time

from orion_tpu.core.trial import ID_SCHEMES, compute_scheme_ids
from orion_tpu.space.dsl import build_space
from orion_tpu.storage.audit import audit_experiment
from orion_tpu.storage.documents import dumps_canonical
from orion_tpu.storage.retry import MODE_ALWAYS, create_retry_policy
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import DatabaseError, DuplicateKeyError

log = logging.getLogger(__name__)

#: Collections holding docs keyed by a trial id.  ``lying_trials`` ids are
#: hashed with the lie marker, mirroring ``Trial.id``.
ID_COLLECTIONS = (("trials", False), ("lying_trials", True))

#: Per-experiment migration override docs.  NOT ``_placement``: routers
#: interpret that collection's states (pin/fence routing) and the
#: rebalancer's planner sweeps it — id migration is shard-local and must
#: never read as a half-finished move.
MIGRATION_COLLECTION = "_id_migrations"

#: Batched-write chunk for the copy path (one lock hold / wire request per
#: chunk on capable backends).
COPY_BATCH = 256

MIGRATE_RETRY = {
    "max_attempts": 5,
    "base_delay": 0.05,
    "max_delay": 1.0,
    "deadline": 30.0,
}


def migration_doc_id(experiment_id):
    return f"idmig:{experiment_id}"


class IdMigration:
    """One experiment's row in the migration plan."""

    def __init__(self, exp_id, name, version, from_scheme, to_scheme, state):
        self.exp_id = exp_id
        self.name = name
        self.version = version
        self.from_scheme = from_scheme
        self.to_scheme = to_scheme
        self.state = state  # None (fresh) | pinned | copied | flipped
        self.rewritten = 0

    def describe(self):
        return (
            f"{self.name} v{self.version} ({self.exp_id}) "
            f"{self.from_scheme} -> {self.to_scheme}"
            + (f" [{self.state}]" if self.state else "")
        )


class IdMigrator:
    """Crash-resumable trial-id rewriter over any document storage.

    ``crash_at`` is a test hook called with a stage label per experiment
    (``"after_copy"``, ``"after_verify"``, ``"after_flip"``); raising from
    it simulates a migrator crash at that exact point — the crash-resume
    suite drives it."""

    def __init__(self, storage, to_scheme="cube_hash", retry=None,
                 copy_batch=COPY_BATCH, crash_at=None):
        if to_scheme not in ID_SCHEMES:
            raise DatabaseError(
                f"unknown id scheme {to_scheme!r}; one of {ID_SCHEMES}"
            )
        self.storage = storage
        self.db = storage.db
        self.to_scheme = to_scheme
        self.policy = create_retry_policy(
            dict(MIGRATE_RETRY) if retry is None else retry
        )
        self.copy_batch = int(copy_batch)
        self.crash_at = crash_at

    # --- plan ----------------------------------------------------------------
    def plan(self, experiment=None):
        """Experiments whose ids need rewriting: scheme differs from the
        target, or a standing migration doc records an unfinished run.
        Recomputed from storage every time — which is what makes a crashed
        run resumable with no local state."""
        overrides = {
            str(doc.get("experiment")): doc
            for doc in self._read(MIGRATION_COLLECTION, {})
        }
        rows = []
        for doc in self._read("experiments", {}):
            name = doc.get("name")
            if experiment is not None and name != experiment:
                continue
            exp_id = str(doc["_id"])
            scheme = doc.get("id_scheme") or "md5"
            override = overrides.get(exp_id)
            if scheme == self.to_scheme and override is None:
                continue
            rows.append(
                IdMigration(
                    exp_id,
                    name,
                    doc.get("version", 1),
                    scheme,
                    self.to_scheme,
                    override.get("state") if override else None,
                )
            )
        return rows

    # --- run -----------------------------------------------------------------
    def run(self, rows=None, experiment=None):
        """Carry every planned migration to completion; safe to re-run
        after any crash (each phase is diff-driven and convergent)."""
        rows = self.plan(experiment=experiment) if rows is None else rows
        for row in rows:
            self._migrate(row)
        return rows

    def _migrate(self, row):
        space = self._space_for(row.exp_id)
        if row.state is None:
            self._set_state(row, "pinned")
        if row.state == "pinned":
            row.rewritten = self._copy(row, space)
            self._hook("after_copy", row)
            self._verify(row, space)
            self._hook("after_verify", row)
            self._set_state(row, "copied")
        if row.state == "copied":
            self._flip(row)
            self._set_state(row, "flipped")
            self._hook("after_flip", row)
        if row.state == "flipped":
            self._delete_old(row, space)
            self._drop_state(row)
            row.state = None
            TELEMETRY.count("storage.migrated_id_experiments")
            log.info("migrated ids for %s", row.describe())

    def _hook(self, stage, row):
        if self.crash_at is not None:
            self.crash_at(stage, row.exp_id)

    # --- helpers -------------------------------------------------------------
    def _read(self, collection, query):
        return self.policy.run(
            lambda: self.db.read(collection, query),
            op=f"migrate_ids.read.{collection}", mode=MODE_ALWAYS,
        )

    def _space_for(self, exp_id):
        docs = self._read("experiments", {"_id": exp_id})
        if not docs:
            raise DatabaseError(f"experiment {exp_id!r} vanished mid-migration")
        doc = docs[0]
        priors = doc.get("priors") or (doc.get("metadata") or {}).get(
            "priors", {}
        )
        return build_space(priors) if priors else None

    def _id_map(self, row, space):
        """``{collection: [(doc, expected_id), ...]}`` plus the global
        old→new id mapping.  Expected ids are recomputed from the params —
        a pure function, so every phase (and every re-run) agrees on them.
        Docs the target scheme cannot encode keep their ids (the scheme
        helper's deterministic md5 fallback) and drop out of every diff."""
        per_collection = {}
        mapping = {}
        for collection, lie in ID_COLLECTIONS:
            docs = self._read(collection, {"experiment": row.exp_id})
            if not docs:
                per_collection[collection] = []
                continue
            expected = compute_scheme_ids(
                row.exp_id,
                [doc.get("params") or {} for doc in docs],
                lie=lie,
                id_scheme=self.to_scheme,
                space=space,
            )
            pairs = list(zip(docs, expected))
            per_collection[collection] = pairs
            for doc, new_id in pairs:
                mapping[doc.get("_id")] = new_id
        return per_collection, mapping

    def _twin(self, doc, new_id, mapping):
        """The doc's new-id twin: ``_id`` rewritten, parents lineage
        remapped through the same migration; every other field is carried
        byte-for-byte (the verify phase holds us to that)."""
        twin = dict(doc)
        twin["_id"] = new_id
        parents = twin.get("parents")
        if parents:
            twin["parents"] = [mapping.get(p, p) for p in parents]
        return twin

    def _copy(self, row, space):
        """Diff-driven copy-under-new-ids: insert the twins the store
        lacks, overwrite ones that differ.  Convergent under crash/re-run
        — inserts dedup on ``_id``, updates are absolute by-id writes."""
        per_collection, mapping = self._id_map(row, space)
        copied = 0
        for collection, pairs in per_collection.items():
            moving = [(d, n) for d, n in pairs if d.get("_id") != n]
            if not moving:
                continue
            # `pairs` holds EVERY doc in the collection (a crashed run's
            # already-inserted twins included), so it doubles as the
            # presence map — no second read.
            have = {d.get("_id"): _canonical(d) for d, _ in pairs}
            ops = []
            for doc, new_id in moving:
                twin = self._twin(doc, new_id, mapping)
                found = have.get(new_id)
                if found is None:
                    ops.append((twin, None))
                elif found != _canonical(twin):
                    ops.append((twin, new_id))
            for start in range(0, len(ops), self.copy_batch):
                chunk = ops[start:start + self.copy_batch]
                inserts = [t for t, q in chunk if q is None]
                if inserts:
                    self.policy.run(
                        lambda docs=inserts: self._insert(collection, docs),
                        op=f"migrate_ids.copy.{collection}", mode=MODE_ALWAYS,
                    )
                for twin, new_id in chunk:
                    if new_id is None:
                        continue
                    self.policy.run(
                        lambda t=twin, n=new_id: self.db.write(
                            collection,
                            {k: v for k, v in t.items() if k != "_id"},
                            query={"_id": n, "experiment": row.exp_id},
                        ),
                        op=f"migrate_ids.fix.{collection}", mode=MODE_ALWAYS,
                    )
                copied += len(chunk)
        return copied

    def _insert(self, collection, docs):
        try:
            self.db.write(collection, list(docs))
        except DuplicateKeyError:
            # A resend raced its own earlier apply: converge per-doc.
            for doc in docs:
                try:
                    self.db.write(collection, dict(doc))
                except DuplicateKeyError:
                    pass

    def _verify(self, row, space):
        """Every rewritten document must exist under its new id with every
        non-id field BYTE-IDENTICAL to the original (canonical JSON — the
        rebalancer's oracle), parents lineage remapped; and the experiment
        must pass the invariant audit."""
        per_collection, mapping = self._id_map(row, space)
        for collection, pairs in per_collection.items():
            have = {d.get("_id"): _canonical(d) for d, _ in pairs}
            for doc, new_id in pairs:
                if doc.get("_id") == new_id:
                    continue
                twin = self._twin(doc, new_id, mapping)
                found = have.get(new_id)
                if found is None or found != _canonical(twin):
                    raise DatabaseError(
                        f"migrate-ids verify failed for {row.exp_id}: "
                        f"{collection} doc {doc.get('_id')!r} "
                        + ("missing" if found is None else "differs")
                        + f" under new id {new_id!r}"
                    )
        exp_docs = self._read("experiments", {"_id": row.exp_id})
        report = audit_experiment(
            self.storage, exp_docs[0], lost_timeout=3600.0
        )
        # The old-id originals are still present beside their twins here,
        # so the duplicate-point check necessarily sees doubles; every
        # OTHER invariant must hold.  (The post-delete `audit --all` the
        # acceptance gate runs sees a fully clean experiment.)
        real = [
            v for v in report.violations
            if v.get("check") != "duplicate-point"
        ]
        if real:
            raise DatabaseError(
                f"migrate-ids verify failed for {row.exp_id}: audit dirty: "
                f"{real}"
            )

    def _flip(self, row):
        self.policy.run(
            lambda: self.db.write(
                "experiments",
                {"id_scheme": self.to_scheme},
                query={"_id": row.exp_id},
            ),
            op="migrate_ids.flip", mode=MODE_ALWAYS,
        )

    def _delete_old(self, row, space):
        """Remove the old-id originals (only reached after the flip): any
        doc whose id differs from its expected id while the expected id
        exists is a pre-migration original."""
        per_collection, _mapping = self._id_map(row, space)
        for collection, pairs in per_collection.items():
            present = {doc.get("_id") for doc, _ in pairs}
            for doc, new_id in pairs:
                old_id = doc.get("_id")
                if old_id == new_id or new_id not in present:
                    continue
                self.policy.run(
                    lambda o=old_id: self.db.remove(
                        collection, {"_id": o, "experiment": row.exp_id}
                    ),
                    op=f"migrate_ids.delete.{collection}", mode=MODE_ALWAYS,
                )

    # --- migration-state doc -------------------------------------------------
    def _set_state(self, row, state):
        """Upsert the override doc — same write-with-query / insert /
        re-update race handling as the rebalancer's placement CAS."""
        doc_id = migration_doc_id(row.exp_id)
        # Queries carry the experiment key so the sharded router routes
        # them straight to the experiment's home shard (no fan-out).
        query = {"_id": doc_id, "experiment": row.exp_id}
        fields = {
            "experiment": row.exp_id,
            "state": state,
            "to": self.to_scheme,
            "ts": time.time(),
        }

        def upsert():
            if self.db.write(MIGRATION_COLLECTION, dict(fields), query=dict(query)):
                return
            try:
                self.db.write(MIGRATION_COLLECTION, dict(fields, _id=doc_id))
            except DuplicateKeyError:
                self.db.write(
                    MIGRATION_COLLECTION, dict(fields), query=dict(query)
                )

        self.policy.run(
            upsert, op=f"migrate_ids.state.{state}", mode=MODE_ALWAYS
        )
        row.state = state

    def _drop_state(self, row):
        doc_id = migration_doc_id(row.exp_id)
        self.policy.run(
            lambda: self.db.remove(
                MIGRATION_COLLECTION,
                {"_id": doc_id, "experiment": row.exp_id},
            ),
            op="migrate_ids.state.drop", mode=MODE_ALWAYS,
        )


def _canonical(doc):
    try:
        return dumps_canonical(doc)
    except TypeError:  # pragma: no cover - non-JSON legacy value
        return repr(sorted(doc.items(), key=lambda kv: kv[0]))
