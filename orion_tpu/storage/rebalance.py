"""Live ring rebalancing: move experiments to their ring homes without
stopping the hunt.

Adding (or removing) a shard changes the consistent-hash ring: ~1/N of
the experiments now hash to a different shard, but their documents still
live where the OLD ring put them.  :class:`Rebalancer` closes that gap —
``orion-tpu db rebalance`` drives it — by migrating each displaced
experiment through a crash-resumable state machine recorded in a
per-experiment *placement override* doc that every router consults
before the ring (``storage/shard.py``):

======================  ======================================================
placement doc state     meaning
======================  ======================================================
(absent)                the experiment lives at its ring home — ring routes
``pinned``  @ source    override routes to the source; the migrator is
                        copying collections to the destination
``fenced``  @ source    flip window: routers hold experiment ops with a
                        transient error (the op-level retry re-routes after
                        the flip); never cached, so the window stays short
``moved``   @ dest      flip done: routers route to the destination; the
                        source copy and the override itself await deletion
(absent again)          move complete — the ring IS the placement again
======================  ======================================================

The override doc lives on the experiment's (new-)ring shard — the one
place any router can find without knowing the answer.  Phase order per
run: pin every mover, copy (batched, per-slot convergent), fence every
mover, wait ONE placement-TTL grace so every router cache expires and
observes the fence, then per mover delta-copy + verify **byte-identical**
documents + clean destination audit, flip, delete the source copy, drop
the override.  A crash anywhere resumes idempotently: the next run
recomputes the plan from the standing placement docs and actual document
locations and continues from the recorded state — copy and delete are
diff-driven (re-running them is a no-op), the flip is a single-doc
upsert.

Writes during migration: the pin keeps every router writing to the
SOURCE while copies run (the delta pass after the fence picks those up);
the fence holds writes entirely across verify+flip.  A router that
cached the pin just before the fence re-reads within one TTL — which is
exactly why the fence grace must cover ``placement_ttl``.
"""

import logging
import time
from collections import Counter

from orion_tpu.health import FLIGHT
from orion_tpu.storage.audit import audit_experiment
from orion_tpu.storage.base import DocumentStorage
from orion_tpu.storage.documents import dumps_canonical
from orion_tpu.storage.retry import MODE_ALWAYS, create_retry_policy
from orion_tpu.storage.shard import PLACEMENT_COLLECTION, placement_doc_id
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import DatabaseError, DuplicateKeyError

log = logging.getLogger(__name__)

#: Per-experiment collections keyed by the ``experiment`` field; the
#: experiments doc itself moves by ``_id``.  Everything a shard holds for
#: one experiment is one of these (INDEX_SPECS + the telemetry channel).
EXPERIMENT_COLLECTIONS = (
    "trials",
    "lying_trials",
    "telemetry",
    "metrics",
    "spans",
    "health",
)

#: Diagnostics channels whose ``_id`` is the backend's per-shard
#: auto-increment counter: the same integer id names DIFFERENT documents
#: on different shards, so two experiments migrating onto one shard
#: collide on ``_id`` even though their documents are unrelated.  These
#: move by experiment-scoped CONTENT (id stripped, destination assigns a
#: fresh id); everything else keeps its id byte-identical.
AUTO_ID_COLLECTIONS = frozenset(("telemetry", "metrics", "spans", "health"))

#: Batched-write chunk for the copy path: one ``apply_batch`` wire request
#: per chunk (one lock hold / transaction server-side).
COPY_BATCH = 256

#: Retry knobs for migration ops (tighter deadline than the op-level
#: default: the migrator is a foreground CLI command).
REBALANCE_RETRY = {
    "max_attempts": 5,
    "base_delay": 0.05,
    "max_delay": 1.0,
    "deadline": 30.0,
}


class Move:
    """One experiment's migration row in the plan."""

    def __init__(self, exp_id, name, version, src_index, dst_index, state):
        self.exp_id = exp_id
        self.name = name
        self.version = version
        self.src_index = src_index
        self.dst_index = dst_index
        self.state = state  # None (fresh) | pinned | fenced | moved

    def describe(self):
        return (
            f"{self.name} v{self.version} ({self.exp_id}) "
            f"shard {self.src_index} -> {self.dst_index}"
            + (f" [{self.state}]" if self.state else "")
        )


class RebalancePlan:
    """Ring diff: which experiments move, which stay."""

    def __init__(self, moves, stays, strays):
        self.moves = moves
        self.stays = stays
        self.strays = strays  # [(exp_id, [indices])] — need operator eyes

    @property
    def total(self):
        return len(self.moves) + self.stays

    @property
    def move_fraction(self):
        return len(self.moves) / self.total if self.total else 0.0

    def summary(self):
        return {
            "experiments": self.total,
            "moves": len(self.moves),
            "stays": self.stays,
            "move_fraction": round(self.move_fraction, 4),
            "strays": len(self.strays),
        }


class Rebalancer:
    """Crash-resumable experiment migrator over a
    :class:`~orion_tpu.storage.shard.ShardedNetworkDB` router.

    ``crash_at`` is a test hook called with a stage label per experiment
    (``"after_pin"``, ``"after_copy"``, ``"after_fence"``,
    ``"after_verify"``, ``"after_flip"``); raising from it simulates a
    migrator crash at that exact point — the crash-resume suite drives
    it.  ``fence_grace`` defaults to the router's placement TTL: the flip
    is only safe once every router's cached pre-fence placement has
    expired."""

    def __init__(self, router, retry=None, fence_grace=None, copy_batch=COPY_BATCH,
                 crash_at=None, sleep=time.sleep):
        self.router = router
        self.policy = create_retry_policy(
            dict(REBALANCE_RETRY) if retry is None else retry
        )
        self.fence_grace = (
            router.placement_ttl if fence_grace is None else float(fence_grace)
        )
        self.copy_batch = int(copy_batch)
        self.crash_at = crash_at
        self._sleep = sleep
        self._conns = dict(router.shard_connections())

    # --- plan ----------------------------------------------------------------
    def plan(self):
        """Compute the ring diff from ACTUAL document locations: for every
        experiment, where its documents live (standing placement docs
        first — they encode a mid-flight migration — then the shard its
        doc is found on) versus where the CURRENT ring says it belongs.
        Needs no record of the old topology, which is also exactly what
        makes a crashed run resumable."""
        placements = {}
        for index, conn in self._conns.items():
            docs = self.policy.run(
                lambda conn=conn: conn.read(PLACEMENT_COLLECTION, {}),
                op="rebalance.plan.placements", mode=MODE_ALWAYS,
            )
            for doc in docs:
                placements[str(doc.get("experiment"))] = doc
        located = {}
        meta = {}
        for index, conn in self._conns.items():
            docs = self.policy.run(
                lambda conn=conn: conn.read("experiments", {}),
                op="rebalance.plan.experiments", mode=MODE_ALWAYS,
            )
            for doc in docs:
                exp_id = str(doc["_id"])
                located.setdefault(exp_id, []).append(index)
                meta.setdefault(
                    exp_id, (doc.get("name"), doc.get("version", 1))
                )
        moves, stays, strays = [], 0, []
        for exp_id in sorted(set(located) | set(placements)):
            name, version = meta.get(exp_id, ("?", "?"))
            dst_index = self.router.shard_for(exp_id)
            placement = placements.get(exp_id)
            if placement is not None:
                state = placement.get("state")
                identity = placement.get("shard")
                src_index = self._index_of(identity)
                if src_index is None:
                    strays.append((exp_id, [identity]))
                    continue
                if state == "moved":
                    # Flip done; src is wherever stale copies remain.
                    stale = [i for i in located.get(exp_id, ()) if i != dst_index]
                    src_index = stale[0] if stale else src_index
                    moves.append(
                        Move(exp_id, name, version, src_index, dst_index, state)
                    )
                    continue
                if src_index == dst_index and state in (None, "pinned"):
                    # An override pointing at the ring home: leftover from
                    # an aborted plan — just drop it.
                    moves.append(
                        Move(exp_id, name, version, src_index, dst_index, "moved")
                    )
                    continue
                moves.append(
                    Move(exp_id, name, version, src_index, dst_index, state)
                )
                continue
            homes = located.get(exp_id, [])
            if dst_index in homes and len(homes) == 1:
                stays += 1
                continue
            if len(homes) > 1:
                # No override yet the experiment exists on several shards:
                # not a state this machine produces — operator eyes needed.
                strays.append((exp_id, homes))
                continue
            if not homes:
                continue  # placement-only ghost handled above
            moves.append(Move(exp_id, name, version, homes[0], dst_index, None))
        return RebalancePlan(moves, stays, strays)

    def _index_of(self, identity):
        for index, conn in self._conns.items():
            if f"{conn.host}:{conn.port}" == identity:
                return index
        # The identity may be a shard's RING identity while the connection
        # points at a promoted replica — resolve through the router.
        return self.router._identity_index.get(identity)

    # --- run -----------------------------------------------------------------
    def run(self, plan=None):
        """Execute ``plan`` (or a fresh one) to completion; returns the
        plan with every move carried out.  Safe to re-run after any crash."""
        plan = self.plan() if plan is None else plan
        if plan.strays:
            raise DatabaseError(
                f"rebalance refuses to run with {len(plan.strays)} stray "
                f"experiment(s) living on multiple shards without a "
                f"placement record: {plan.strays[:3]} — resolve manually "
                "(db copy + remove) first"
            )
        movers = [m for m in plan.moves if m.state != "moved"]
        finishers = [m for m in plan.moves if m.state == "moved"]
        # Phase 1+2: pin + copy (routers keep writing to the source).
        self._note_phase("pin_copy")
        for move in movers:
            if move.state is None:
                self._set_placement(move, "pinned", self._identity(move.src_index))
                move.state = "pinned"
                self._hook("after_pin", move)
            self._copy(move)
            self._hook("after_copy", move)
            self._note_progress()
        # Phase 3: fence every mover, then ONE grace wait covering the
        # placement TTL — after it, every router observes the fence.
        self._note_phase("fence")
        for move in movers:
            if move.state == "pinned":
                self._set_placement(move, "fenced", self._identity(move.src_index))
                move.state = "fenced"
                self._hook("after_fence", move)
                self._note_progress()
        if movers and self.fence_grace > 0:
            self._sleep(self.fence_grace)
        # Phase 4: delta-copy + verify + flip, one mover at a time.
        self._note_phase("verify_flip")
        for move in movers:
            self._copy(move)  # the delta written since the first pass
            self._verify(move)
            self._hook("after_verify", move)
            self._set_placement(move, "moved", self._identity(move.dst_index))
            move.state = "moved"
            if FLIGHT.enabled:
                FLIGHT.record(
                    "rebalance.flip",
                    args={"experiment": move.exp_id, "dst": move.dst_index},
                )
            self._hook("after_flip", move)
            self._note_progress()
        # Phase 5+6: delete the source copy, then drop the override — the
        # ring IS the placement again.
        self._note_phase("cleanup")
        for move in movers + finishers:
            self._delete_source(move)
            self._drop_placement(move)
            TELEMETRY.count("storage.shard.rebalanced_experiments")
            log.info("rebalanced %s", move.describe())
            self._note_progress()
        self._note_phase(None)
        return plan

    def _hook(self, stage, move):
        if self.crash_at is not None:
            self.crash_at(stage, move.exp_id)

    def _note_phase(self, name):
        """Phase-boundary hook (``None`` = run complete).  The base
        migrator publishes nothing; the drain specialization books the
        ``storage.drain.phase_age_s`` gauge the DX060 doctor rule watches."""

    def _note_progress(self):
        """Per-move progress hook inside a phase (see :meth:`_note_phase`)."""

    def _identity(self, index):
        conn = self._conns[index]
        for shard in self.router._shards:
            if shard.index == index:
                return shard.identity
        return f"{conn.host}:{conn.port}"  # pragma: no cover - defensive

    # --- placement ops (STO005: batched + explicit retry mode) ---------------
    def _placement_conn(self, move):
        """The shard holding ``move``'s override doc: the experiment's
        CURRENT-ring home — the destination for a rebalance (the ring
        already points there), the SOURCE for a drain (the drained shard
        is still on the routers' ring until ``set_topology`` drops it)."""
        return self._conns[move.dst_index]

    def _set_placement(self, move, state, identity):
        """Upsert the override doc on the experiment's ring shard
        (:meth:`_placement_conn`) — the single-doc CAS every router's
        routing consults.  Converges under re-application: an absolute
        by-id upsert."""
        dst = self._placement_conn(move)
        doc_id = placement_doc_id(move.exp_id)
        fields = {
            "experiment": move.exp_id,
            "state": state,
            "shard": identity,
            "ts": time.time(),
        }

        def upsert():
            if dst.write(PLACEMENT_COLLECTION, dict(fields), query={"_id": doc_id}):
                return
            try:
                dst.write(PLACEMENT_COLLECTION, dict(fields, _id=doc_id))
            except DuplicateKeyError:
                # Raced our own resend: the doc exists now — update wins.
                dst.write(PLACEMENT_COLLECTION, dict(fields), query={"_id": doc_id})

        self.policy.run(
            upsert, op=f"rebalance.placement.{state}", mode=MODE_ALWAYS
        )

    def _drop_placement(self, move):
        dst = self._placement_conn(move)
        doc_id = placement_doc_id(move.exp_id)
        self.policy.run(
            lambda: dst.remove(PLACEMENT_COLLECTION, {"_id": doc_id}),
            op="rebalance.placement.drop", mode=MODE_ALWAYS,
        )

    # --- copy / verify / delete ----------------------------------------------
    def _exp_docs(self, conn, collection, exp_id):
        if collection == "experiments":
            query = {"_id": exp_id}
        else:
            query = {"experiment": exp_id}
        return self.policy.run(
            lambda: conn.read(collection, query),
            op=f"rebalance.read.{collection}", mode=MODE_ALWAYS,
        )

    def _copy(self, move):
        """Diff-driven batched copy source -> destination: insert what the
        destination lacks, overwrite what differs (byte-identical target).
        Convergent under crash/re-run — inserts dedup on ``_id``, updates
        are absolute by-id writes."""
        src = self._conns[move.src_index]
        dst = self._conns[move.dst_index]
        copied = 0
        for collection in ("experiments",) + EXPERIMENT_COLLECTIONS:
            src_docs = self._exp_docs(src, collection, move.exp_id)
            if not src_docs:
                continue
            dst_docs = self._exp_docs(dst, collection, move.exp_id)
            ops = []
            if collection in AUTO_ID_COLLECTIONS:
                # Content-keyed diff: insert only the multiset difference,
                # id stripped so the destination assigns from ITS counter
                # (a copied id could collide with a co-resident
                # experiment's rows).  Convergent under crash/re-run —
                # already-copied rows count toward the destination
                # multiset regardless of the id they landed under.
                have = Counter(_canonical(_strip_id(d)) for d in dst_docs)
                for doc in src_docs:
                    key = _canonical(_strip_id(doc))
                    if have[key] > 0:
                        have[key] -= 1
                        continue
                    ops.append(("write", [collection, _strip_id(doc)], {}))
            else:
                dst_by_id = {d.get("_id"): _canonical(d) for d in dst_docs}
                for doc in src_docs:
                    _id = doc.get("_id")
                    have = dst_by_id.get(_id)
                    if have is None:
                        ops.append(("write", [collection, doc], {}))
                    elif have != _canonical(doc):
                        ops.append(
                            (
                                "write",
                                [collection, _strip_id(doc)],
                                {"query": {"_id": _id}},
                            )
                        )
            for start in range(0, len(ops), self.copy_batch):
                chunk = ops[start:start + self.copy_batch]
                outcomes = self.policy.run(
                    lambda chunk=chunk: dst.apply_batch(chunk),
                    op=f"rebalance.copy.{collection}", mode=MODE_ALWAYS,
                )
                for outcome in outcomes:
                    if isinstance(outcome, DuplicateKeyError):
                        continue  # a resend raced its own earlier apply
                    if isinstance(outcome, Exception):
                        raise outcome
                copied += len(chunk)
        if copied and FLIGHT.enabled:
            FLIGHT.record(
                "rebalance.copy",
                args={"experiment": move.exp_id, "docs": copied},
            )
        return copied

    def _verify(self, move):
        """Every source document must exist BYTE-IDENTICAL on the
        destination (canonical JSON — the same oracle ``db copy`` uses),
        and the destination must pass the invariant audit for this
        experiment.  Runs inside the fence, so the comparison is stable."""
        src = self._conns[move.src_index]
        dst = self._conns[move.dst_index]
        for collection in ("experiments",) + EXPERIMENT_COLLECTIONS:
            src_docs = self._exp_docs(src, collection, move.exp_id)
            if not src_docs:
                continue
            dst_docs = self._exp_docs(dst, collection, move.exp_id)
            if collection in AUTO_ID_COLLECTIONS:
                # Auto-increment channels moved by content: every source
                # row must exist on the destination with identical bytes
                # OUTSIDE the id (the destination assigned its own).
                have = Counter(_canonical(_strip_id(d)) for d in dst_docs)
                for doc in src_docs:
                    key = _canonical(_strip_id(doc))
                    if have[key] <= 0:
                        raise DatabaseError(
                            f"rebalance verify failed for {move.exp_id}: "
                            f"{collection} doc {doc.get('_id')!r} missing "
                            "on the destination shard"
                        )
                    have[key] -= 1
                continue
            dst_by_id = {d.get("_id"): _canonical(d) for d in dst_docs}
            for doc in src_docs:
                have = dst_by_id.get(doc.get("_id"))
                if have is None or have != _canonical(doc):
                    raise DatabaseError(
                        f"rebalance verify failed for {move.exp_id}: "
                        f"{collection} doc {doc.get('_id')!r} "
                        + ("missing" if have is None else "differs")
                        + " on the destination shard"
                    )
        # Audit exactly THIS experiment on the destination (the movers are
        # fenced for the whole verify loop — auditing every co-resident
        # experiment per move would grow the write-unavailability window
        # with the shard's population, not with the work being verified).
        exp_docs = self._exp_docs(dst, "experiments", move.exp_id)
        if exp_docs:
            report = audit_experiment(
                DocumentStorage(dst), exp_docs[0], lost_timeout=3600.0
            )
            if not report.ok:
                raise DatabaseError(
                    f"rebalance verify failed for {move.exp_id}: destination "
                    f"audit dirty: {report.violations}"
                )

    def _delete_source(self, move):
        """Remove the experiment's documents from the source shard (only
        reached after the flip — routers no longer route there)."""
        if move.src_index == move.dst_index:
            return
        src = self._conns[move.src_index]
        removed = 0
        for collection in EXPERIMENT_COLLECTIONS:
            removed += self.policy.run(
                lambda collection=collection: src.remove(
                    collection, {"experiment": move.exp_id}
                ),
                op=f"rebalance.delete.{collection}", mode=MODE_ALWAYS,
            ) or 0
        removed += self.policy.run(
            lambda: src.remove("experiments", {"_id": move.exp_id}),
            op="rebalance.delete.experiments", mode=MODE_ALWAYS,
        ) or 0
        if removed and FLIGHT.enabled:
            FLIGHT.record(
                "rebalance.delete",
                args={"experiment": move.exp_id, "docs": removed},
            )


def _canonical(doc):
    try:
        return dumps_canonical(doc)
    except TypeError:  # pragma: no cover - non-JSON legacy value
        return repr(sorted(doc.items(), key=lambda kv: kv[0]))


def _strip_id(doc):
    return {k: v for k, v in doc.items() if k != "_id"}
