"""Sharded, replicated network storage: the scale-out control plane.

One :class:`~orion_tpu.storage.netdb.NetworkDB` talks to one server — the
last single point of failure in the stack.  :class:`ShardedNetworkDB` is
an AbstractDB-contract router over N independent netdb shards, slotting
UNDER :class:`~orion_tpu.storage.base.DocumentStorage` exactly where a
single client would sit (Oríon's design keeps all coordination behind the
storage protocol, so nothing above this layer changes):

- **Consistent-hash routing on experiment id** (Dynamo-style ring with
  virtual nodes for balance): every document and query that names an
  experiment — trial registration, reservation CAS, status polls, the
  telemetry/health channels — routes to exactly one shard, so the hot
  paths cost what they cost today regardless of shard count.  Experiment
  ids are deterministic hashes of the experiment's unique identity
  (``core.experiment.experiment_id``; the router mints the same way for
  raw inserts that arrive without one), so two racing creators of the
  same experiment land on the SAME shard and collide on its unique
  index, exactly as they would on one server.
- **Cross-experiment fan-out**: ops that span experiments
  (``fetch_experiments``, fleet audits, id-only lookups that miss the
  owner cache) run on every shard CONCURRENTLY and merge.  A fan-out leg
  rides its shard's own :class:`~orion_tpu.storage.retry.RetryPolicy`
  (reads only — mutations keep the op-level policy's applied-or-not
  discipline), so one slow or dead shard never serializes the rest.
- **Read-replica fan-out with staleness failover**: when a shard declares
  replicas, reads (``read``/``count`` and all-read batches — the
  ``fetch_trials``/status-poll/``fetch_health`` hot path) go to a replica
  round-robin.  Replication is asynchronous, so every replica reply
  carries the replica's applied sequence (``netdb.py``); the router
  compares it against the highest sequence ITS writes ever got from that
  shard's primary and fails the read over to the primary when the replica
  is behind — monotonic read-your-writes per router, counted as
  ``storage.shard.replica_stale_reads``.  Transport errors fail over too
  (``storage.shard.failovers``) and bench the replica briefly.
- **Degraded mode**: shards are independent connections with independent
  retry state, so ops routed to healthy shards proceed while ops on a
  dead shard ride the ordinary retry/deadline policy — no global stall.
  Aggregated fan-out failures propagate the STRICTEST ``maybe_applied``
  of their parts (:func:`merge_maybe_applied`; lint rule STO004 pins the
  discipline).
- **Provable pass-through**: a single-shard, no-replica config delegates
  every op verbatim to the one underlying ``NetworkDB`` — no minting, no
  fan-out machinery, byte-identical wire traffic (differential-pinned in
  tests/unit/test_shard.py).

The soak harness (``orion_tpu/storage/soak.py``, ``bench.py --soak``)
drives 1000+ simulated workers against a 3-shard x 2-replica topology of
real servers under fault-proxy partitions and shard restarts; the pass
bar is a clean ``orion-tpu audit --all`` on every shard and zero lost
observations.
"""

import functools
import hashlib
import logging
import threading
import weakref
from bisect import bisect_right
from collections import OrderedDict

from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.health import FLIGHT
from orion_tpu.storage.netdb import NetworkDB
from orion_tpu.storage.retry import MODE_ALWAYS, create_retry_policy, is_transient
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import DatabaseError

log = logging.getLogger(__name__)

#: Virtual nodes per shard on the hash ring.  Enough that removing/adding
#: one shard moves ~1/N of the keyspace with low variance; small enough
#: that ring construction stays trivial.
DEFAULT_VNODES = 64

#: Bounded (collection, _id) -> shard map harvested from routed results, so
#: id-only queries (``set_trial_status``'s CAS, ``get_trial``) route
#: directly instead of fanning out.  A miss is never wrong — it just costs
#: a fan-out that re-populates the entry.
OWNER_CACHE_CAP = 65536

#: Per-shard policy for fan-out READ legs: tighter than the op-level
#: policy (which still wraps the whole op above this layer) — its job is
#: riding out a blip on ONE shard without re-running the healthy ones.
DEFAULT_SHARD_RETRY = {
    "max_attempts": 3,
    "base_delay": 0.02,
    "max_delay": 0.25,
    "deadline": 5.0,
}

#: Seconds a replica sits out after a transport failure before reads try
#: it again (connection state is per shard, per replica).
REPLICA_RETRY_S = 1.0

#: Timeout for election/health ``seq`` probes (dedicated short-lived
#: connections): a hung node must cost a probe this much, never the data
#: path's full client timeout.
PROBE_TIMEOUT_S = 2.0

#: Confirmation window before automatic replica promotion: a shard's
#: primary must fail CONTINUOUSLY for this long before a router runs an
#: election.  Long enough to ride out a same-port restart (the soak's
#: restart_primary takes well under a second); short enough that a dead
#: box heals inside one op-level retry deadline.
DEFAULT_PROMOTE_AFTER_S = 1.5

#: Confirmation window before automatic replica REPROVISIONING: a shard's
#: replica must fail probes continuously for this long before the router
#: provisions and adopts a replacement.  Longer than the promotion window
#: on purpose — a replica rebooting in place is cheaper than a fresh
#: snapshot resync, so reprovisioning waits out ordinary restarts.
DEFAULT_REPROVISION_AFTER_S = 5.0

#: Collection holding per-experiment placement override docs (live ring
#: rebalancing, storage/rebalance.py).  Routers consult it BEFORE the
#: ring; the docs live on the experiment's RING shard so any router can
#: find them without knowing the answer.
PLACEMENT_COLLECTION = "_placement"

#: Seconds a placement lookup (override or its absence) stays cached per
#: router.  Also the floor a migrator must hold an experiment FENCED
#: before flipping it: once every router's cache entry has expired, every
#: router re-reads the override and observes the fence.
PLACEMENT_TTL_S = 5.0

#: Bounded placement cache (same rationale as the owner cache).
PLACEMENT_CACHE_CAP = 65536


def placement_doc_id(experiment_id):
    """``_placement`` doc id for one experiment's override."""
    return f"placement:{experiment_id}"


@functools.lru_cache(maxsize=512)
def _lag_gauge_name(index):
    """Per-shard gauge names, interned once per index (TEL001: no
    per-iteration key building on the probe loop)."""
    return f"netdb.replication.lag.s{index}"


@functools.lru_cache(maxsize=512)
def _epoch_gauge_name(index):
    return f"netdb.replication.epoch.s{index}"


#: Routers registered for replication-lag sampling (the /metrics plane
#: scrape hook calls :func:`sample_replication_lag`).
_ROUTER_REGISTRY = weakref.WeakSet()
_SAMPLE_GATE_LOCK = threading.Lock()
_last_lag_sample = 0.0

#: Seconds between /metrics-driven replication probes (each probe is one
#: tiny ``seq`` request per node — cheap, but a hot scrape loop must not
#: turn it into load).
LAG_SAMPLE_INTERVAL_S = 5.0


def sample_replication_lag(force=False):
    """Publish ``netdb.replication.lag.s{i}`` / ``.epoch.s{i}`` gauges for
    every live router (rate-limited).  Called from the /metrics scrape
    path; never raises — metrics must not break serving."""
    global _last_lag_sample
    import time as _time

    now = _time.monotonic()
    with _SAMPLE_GATE_LOCK:
        if not force and now - _last_lag_sample < LAG_SAMPLE_INTERVAL_S:
            return
        _last_lag_sample = now
    for router in list(_ROUTER_REGISTRY):
        try:
            router.replication_health()
        except Exception:  # pragma: no cover - observability never raises
            log.debug("replication lag sample failed", exc_info=True)


def merge_maybe_applied(errors):
    """The STRICTEST applied-or-not verdict of a fan-out's parts: if ANY
    leg may have applied, the aggregate may have applied — anything weaker
    would let the retry policy blind-resend a non-converging mutation one
    shard already executed."""
    return any(getattr(error, "maybe_applied", False) for error in errors)


def shard_fanout_error(message, errors):
    """The one blessed way to aggregate per-shard ``DatabaseError``s
    (STO004): build the summary error and stamp the merged verdict."""
    parts = "; ".join(f"{type(e).__name__}: {e}" for e in errors) or "no detail"
    error = DatabaseError(f"{message}: {parts}")
    error.maybe_applied = merge_maybe_applied(errors)
    return error


def mint_experiment_id(doc):
    """Deterministic experiment id from the unique identity the
    experiments collection enforces — ``(name, version, metadata.user)``
    — computed by THE framework formula (``core.experiment
    .experiment_id``), not a lookalike: an experiment created through the
    builder (which pre-sets ``_id`` with that formula) and a raw
    ``create_experiment`` for the same identity must mint the SAME id,
    land on the SAME shard, and collide on its unique index exactly as on
    one server.  A divergent formula would silently split one experiment
    across two shards."""
    from orion_tpu.core.experiment import experiment_id

    return experiment_id(
        doc.get("name"),
        doc.get("version", 1),
        (doc.get("metadata") or {}).get("user"),
    )


class HashRing:
    """Consistent-hash ring with virtual nodes.

    Each shard contributes ``vnodes`` md5 points keyed by its stable
    identity (the primary's ``host:port``); a key hashes once and lands on
    the first point clockwise.  Every router instance built from the same
    shard list computes identical placement — there is no coordination
    channel, the ring IS the agreement.
    """

    def __init__(self, identities, vnodes=DEFAULT_VNODES):
        if not identities:
            raise DatabaseError("a hash ring needs at least one shard")
        self.vnodes = int(vnodes)
        points = []
        for index, identity in enumerate(identities):
            for v in range(self.vnodes):
                points.append((self._hash(f"{identity}#{v}"), index))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._indices = [i for _, i in points]

    @staticmethod
    def _hash(key):
        return int.from_bytes(
            hashlib.md5(str(key).encode("utf-8")).digest()[:8], "big"
        )

    def lookup(self, key):
        """Shard index owning ``key``."""
        position = bisect_right(self._hashes, self._hash(key))
        if position == len(self._hashes):
            position = 0
        return self._indices[position]


def parse_shard_specs(shards, default_secret=None):
    """Normalize a ``storage.shards`` config list into
    ``[{"host", "port", "replicas": [(host, port), ...]}, ...]``.  Entries
    may be ``"host:port"`` strings or dicts with ``host``/``port`` or
    ``address`` plus an optional ``replicas`` list of the same shapes."""

    def addr_of(entry):
        if isinstance(entry, str):
            host, _, port = entry.rpartition(":")
            if not host or not port:
                raise DatabaseError(
                    f"bad shard address {entry!r}; expected host:port"
                )
            return host, int(port)
        if isinstance(entry, (tuple, list)):
            host, port = entry
            return host, int(port)
        host = entry.get("host", "127.0.0.1")
        port = entry.get("port")
        address = entry.get("address")
        if address:
            return addr_of(str(address))
        if port is None:
            raise DatabaseError(f"shard entry {entry!r} needs a port or address")
        return host, int(port)

    specs = []
    for entry in shards or ():
        host, port = addr_of(entry)
        replicas = []
        if isinstance(entry, dict):
            replicas = [addr_of(r) for r in entry.get("replicas") or ()]
        specs.append(
            {
                "host": host,
                "port": port,
                "replicas": replicas,
                "secret": (
                    entry.get("secret", default_secret)
                    if isinstance(entry, dict)
                    else default_secret
                ),
            }
        )
    if not specs:
        raise DatabaseError("storage.shards is empty")
    return specs


class _Shard:
    """One shard's connections + read-path state: the primary client, its
    replica clients, the write-sequence floor replica reads are checked
    against, and the per-shard fan-out retry policy."""

    def __init__(self, index, spec, client_kwargs, policy):
        self.index = index
        self.host = spec["host"]
        self.port = int(spec["port"])
        self.primary = NetworkDB(host=self.host, port=self.port, **client_kwargs)
        self.replicas = [
            NetworkDB(host=h, port=p, **client_kwargs)
            for h, p in spec.get("replicas") or ()
        ]
        #: The replica addresses this shard was CONFIGURED with (identity
        #: comparison for live topology swaps — the live ``replicas`` list
        #: reorders on promotion).
        self.replica_addrs = frozenset(
            f"{h}:{int(p)}" for h, p in spec.get("replicas") or ()
        )
        self.policy = policy
        self._lock = threading.Lock()
        self._write_floor = 0
        self._rr = 0
        self._down_until = [0.0] * len(self.replicas)
        #: Read-path health counters, exported per shard as
        #: ``storage.shard.s{i}.failovers`` / ``.replica_stale_reads``.
        self.failovers = 0
        self.replica_stale_reads = 0
        #: Promotion state: the highest replication epoch this router ever
        #: saw from this shard (the fencing floor), the monotonic start of
        #: the current consecutive primary-failure streak, and a guard so
        #: one thread per router runs an election at a time.
        self._epoch = 0
        self._fail_since = None
        self._promote_guard = threading.Lock()
        self.promotions = 0

    @property
    def identity(self):
        """The shard's STABLE ring identity: the address of its original
        primary.  Never changes on promotion — the ring (and therefore
        experiment placement) must not move because a replica took over."""
        return f"{self.host}:{self.port}"

    @property
    def reconnects(self):
        return self.primary.reconnects + sum(r.reconnects for r in self.replicas)

    def note_write(self, client=None):
        """Raise the staleness floor to the primary's latest stamped seq
        (replicating primaries stamp mutating replies; plain ones never do,
        and the floor stays 0 = every replica read is acceptable), and the
        epoch floor to its stamped epoch.  Returns True when the reply came
        from a LOWER epoch than this router has already seen on the shard —
        a stale primary the caller must fence (the write landed on a
        condemned fork that the promoted timeline will erase).
        ``client`` pins the stamp to the connection the mutation actually
        rode: a concurrent promotion may swap ``self.primary`` between the
        call and this check, and the swapped-in client's stamp would miss
        exactly the stale-epoch reply the fence exists to catch."""
        seq, epoch = (client or self.primary).stamp_snapshot()
        stale = False
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            if epoch is not None:
                if epoch < self._epoch:
                    stale = True
                elif epoch > self._epoch:
                    self._epoch = epoch
            if not stale and seq is not None and seq > self._write_floor:
                self._write_floor = seq
        return stale

    def note_epoch(self, epoch):
        """Lift the epoch floor (a not-primary refusal or probe reported a
        newer epoch than any stamped reply so far)."""
        if not epoch:
            return
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            if epoch > self._epoch:
                self._epoch = epoch

    def epoch_floor(self):
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            return self._epoch

    # --- primary failure detection / promotion ------------------------------
    def note_primary_failure(self, now):
        """Mark one failed primary op; the streak starts at the FIRST
        consecutive failure and clears on any success."""
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            if self._fail_since is None:
                self._fail_since = now

    def clear_primary_failure(self):
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            self._fail_since = None

    def failing_for(self, now):
        """Seconds the primary has been failing continuously (0 if healthy)."""
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            return 0.0 if self._fail_since is None else now - self._fail_since

    def promote_swap(self, replica_index, epoch, now):
        """Swap the shard's primary client for the promoted replica's; the
        old primary client takes the replica's slot (briefly benched — when
        the dead box is reborn it comes back demoted, a legitimate read
        replica).  The shard's ring identity does NOT change."""
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            winner = self.replicas[replica_index]
            self.replicas[replica_index] = self.primary
            self.primary = winner
            self._down_until[replica_index] = now + REPLICA_RETRY_S
            if epoch > self._epoch:
                self._epoch = epoch
            self._fail_since = None
            self.promotions += 1
        return winner

    def promote_in_place(self, epoch):
        """The primary-slot client itself won the election (a promoted
        node that restarted back into its configured replica role): no
        swap, just the epoch/streak/counter bookkeeping."""
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            if epoch > self._epoch:
                self._epoch = epoch
            self._fail_since = None
            self.promotions += 1

    def write_floor(self):
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            return self._write_floor

    def pick_replica(self, now):
        """Round-robin replica index skipping benched ones, or None."""
        if not self.replicas:
            return None
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            n = len(self.replicas)
            for offset in range(n):
                candidate = (self._rr + offset) % n
                if self._down_until[candidate] <= now:
                    self._rr = (candidate + 1) % n
                    return candidate
        return None

    def bench_replica(self, index, now):
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            self._down_until[index] = now + REPLICA_RETRY_S
            self.failovers += 1

    def note_stale(self):
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            self.replica_stale_reads += 1

    def adopt_replacement(self, replica_index, client, dead_addr, now):
        """Swap a dead replica's client for a freshly provisioned one
        (auto-reprovisioning).  The replacement is benched briefly — it
        starts empty and must snapshot-resync before serving reads — and
        the declared ``replica_addrs`` identity follows the swap, so a
        later ``set_topology`` matching on it compares against the set
        this shard ACTUALLY runs.  Returns the replaced client (closed by
        the caller, outside this lock)."""
        addr = f"{client.host}:{client.port}"
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            old = self.replicas[replica_index]
            self.replicas[replica_index] = client
            self._down_until[replica_index] = now + REPLICA_RETRY_S
            self.replica_addrs = frozenset(
                (self.replica_addrs - {dead_addr}) | {addr}
            )
        return old

    def close(self):
        self.primary.close()
        for replica in self.replicas:
            replica.close()


#: Query/doc values that can route: concrete scalars, never operator dicts.
def _concrete(value):
    if value is None or isinstance(value, (dict, list, tuple)):
        return None
    return value


class ShardedNetworkDB:
    """AbstractDB-contract consistent-hash router over N netdb shards.

    See the module docstring for the full contract.  Constructed by
    ``create_storage`` from a ``storage.shards`` config stanza; sits under
    ``DocumentStorage`` exactly like a single ``NetworkDB``.
    """

    #: Counts and targeted reads are one small request on one shard.
    cheap_counts = True

    def __init__(
        self,
        shards,
        vnodes=DEFAULT_VNODES,
        timeout=60.0,
        idle_probe=1.0,
        secret=None,
        reconnect_jitter=0.1,
        shard_retry=None,
        replica_reads=True,
        auto_promote=True,
        promote_after=DEFAULT_PROMOTE_AFTER_S,
        placement_ttl=PLACEMENT_TTL_S,
        replica_provisioner=None,
        reprovision_after=DEFAULT_REPROVISION_AFTER_S,
    ):
        specs = parse_shard_specs(shards, default_secret=secret)
        self._client_base = {
            "timeout": timeout,
            "idle_probe": idle_probe,
            "reconnect_jitter": reconnect_jitter,
        }
        self._default_secret = secret
        self._retry_config = (
            dict(DEFAULT_SHARD_RETRY) if shard_retry is None else shard_retry
        )
        #: Automatic replica promotion: after ``promote_after`` seconds of
        #: continuous primary failure, elect the most-caught-up replica
        #: (deterministic: highest seq, address tie-break — concurrent
        #: routers converge on the SAME winner).
        self.auto_promote = bool(auto_promote)
        self.promote_after = float(promote_after)
        #: Placement-override lookup cache TTL (0 disables overrides —
        #: single-topology deployments that never rebalance).
        self.placement_ttl = float(placement_ttl)
        self._topology_lock = threading.Lock()
        self._shards = [
            # Each shard gets its OWN policy instance: independent jitter
            # streams and deadlines, so one shard's outage never consumes
            # another's retry budget.
            _Shard(
                index,
                spec,
                dict(self._client_base, secret=spec.get("secret")),
                create_retry_policy(self._retry_config),
            )
            for index, spec in enumerate(specs)
        ]
        self._ring = HashRing([s.identity for s in self._shards], vnodes=vnodes)
        self._identity_index = {s.identity: s.index for s in self._shards}
        self.replica_reads = bool(replica_reads)
        #: Pure pass-through mode: one shard, no replicas — every op
        #: delegates verbatim to the single NetworkDB (bit-identical wire
        #: traffic; differential-pinned).
        self._passthrough = (
            len(self._shards) == 1 and not self._shards[0].replicas
        )
        self._owner_lock = threading.Lock()
        self._owners = OrderedDict()  # (collection, _id) -> shard index
        self._placement_lock = threading.Lock()
        #: experiment key -> (shard identity or None, state, expires_at).
        self._placements = OrderedDict()
        self._stats_lock = threading.Lock()
        self.fan_outs = 0
        self._monotonic = None  # injectable clock for tests
        #: Replica auto-reprovisioning (day-2 operations): with a
        #: ``replica_provisioner`` callable — ``provisioner(shard_index) ->
        #: "host:port"`` of a freshly started empty server — a background
        #: sweep detects a replica that has failed probes continuously for
        #: ``reprovision_after`` seconds on a PROMOTED shard (the
        #: one-replica-short-forever state a permanent primary loss leaves
        #: behind), provisions a replacement, has the current primary adopt
        #: it over the ``adopt_replica`` wire op (bounded snapshot resync),
        #: and swaps the dead client out of the shard's replica set.
        self.replica_provisioner = replica_provisioner
        self.reprovision_after = float(reprovision_after)
        self.reprovisions = 0
        self._reprovision_lock = threading.Lock()
        #: (shard identity, replica address) -> monotonic first-failure
        #: time; shared between the sweep thread and close() — every
        #: access under _reprovision_lock, TSAN-annotated.
        self._replica_down_since = {}
        self._reprovision_stop = threading.Event()
        self._reprovision_thread = None
        self._register_shard_counters()
        _ROUTER_REGISTRY.add(self)
        if replica_provisioner is not None:
            self._reprovision_thread = threading.Thread(
                target=self._reprovision_loop,
                name="shard-reprovision",
                daemon=True,
            )
            self._reprovision_thread.start()

    _SHARD_COUNTER_ATTRS = (
        "reconnects", "failovers", "replica_stale_reads", "promotions",
    )

    def _register_shard_counters(self):
        for shard in self._shards:
            prefix = f"storage.shard.s{shard.index}"
            for attr in self._SHARD_COUNTER_ATTRS:
                TELEMETRY.register_external_counter(
                    f"{prefix}.{attr}", shard, attr
                )

    def _unregister_shard_counters(self, shards):
        """Drop ``shards``' registrations at their CURRENT indices — run
        before a topology change reindexes/removes them, or a surviving
        shard would keep exporting under its old ``s{i}`` name too."""
        for shard in shards:
            prefix = f"storage.shard.s{shard.index}"
            for attr in self._SHARD_COUNTER_ATTRS:
                TELEMETRY.unregister_external_counter(
                    f"{prefix}.{attr}", shard
                )

    # --- aggregate counters (DocumentStorage re-exports these) ---------------
    @property
    def reconnects(self):
        return sum(s.reconnects for s in self._shards)

    @property
    def round_trips(self):
        return sum(
            s.primary.round_trips + sum(r.round_trips for r in s.replicas)
            for s in self._shards
        )

    @property
    def wire_requests(self):
        return sum(
            s.primary.wire_requests + sum(r.wire_requests for r in s.replicas)
            for s in self._shards
        )

    @property
    def failovers(self):
        return sum(s.failovers for s in self._shards)

    @property
    def replica_stale_reads(self):
        return sum(s.replica_stale_reads for s in self._shards)

    @property
    def promotions(self):
        return sum(s.promotions for s in self._shards)

    # --- topology surface (CLI: db ring, audit, info) ------------------------
    @property
    def n_shards(self):
        return len(self._shards)

    def shard_for(self, experiment_id):
        """Ring placement of an experiment id (audit/CLI surface)."""
        return self._ring.lookup(str(experiment_id))

    def describe_topology(self):
        return {
            "shards": [
                {
                    "index": s.index,
                    "address": s.identity,
                    "replicas": [f"{r.host}:{r.port}" for r in s.replicas],
                    "primary": f"{s.primary.host}:{s.primary.port}",
                    "epoch": s.epoch_floor(),
                    "promotions": s.promotions,
                }
                for s in self._shards
            ],
            "vnodes": self._ring.vnodes,
            "replica_reads": self.replica_reads,
        }

    def replication_health(self):
        """Probe every shard node's ``seq`` op: per-shard epoch, primary
        position, per-replica applied position and lag (primary − replica).
        Publishes the ``netdb.replication.lag.s{i}`` / ``.epoch.s{i}``
        gauges the /metrics plane exports; ``orion-tpu top --all`` and
        ``info --all`` render the same structure in their topology
        headers.  Probes are tiny one-line requests, run CONCURRENTLY per
        shard (a dark, partitioned shard costs the whole view one
        PROBE_TIMEOUT_S, never a stall per node); a dead node reports an
        ``error`` instead of failing the whole view."""
        shards = list(self._shards)
        health = [None] * len(shards)

        def probe_shard(slot, shard):
            entry = {
                "index": shard.index,
                "address": shard.identity,
                "primary": f"{shard.primary.host}:{shard.primary.port}",
                "replicas": [],
            }
            primary_seq = None
            try:
                info = self._probe_seq(shard.primary)
            except Exception as exc:
                entry["error"] = f"{type(exc).__name__}: {exc}"
            else:
                primary_seq = int(info.get("seq", 0))
                entry["seq"] = primary_seq
                entry["epoch"] = int(info.get("epoch", 0) or 0)
                entry["role"] = "replica" if info.get("replica") else "primary"
                entry["quorum"] = int(info.get("quorum", 0) or 0)
                shard.note_epoch(entry["epoch"])
            lags = []
            for replica in shard.replicas:
                row = {"address": f"{replica.host}:{replica.port}"}
                try:
                    info = self._probe_seq(replica)
                except Exception as exc:
                    row["error"] = f"{type(exc).__name__}: {exc}"
                else:
                    row["seq"] = int(info.get("seq", 0))
                    row["epoch"] = int(info.get("epoch", 0) or 0)
                    if info.get("resyncing"):
                        row["resyncing"] = True
                    if primary_seq is not None:
                        row["lag"] = max(0, primary_seq - row["seq"])
                        lags.append(row["lag"])
                entry["replicas"].append(row)
            entry["max_lag"] = max(lags) if lags else None
            health[slot] = entry

        if len(shards) == 1:
            probe_shard(0, shards[0])
        else:
            threads = [
                threading.Thread(
                    target=probe_shard, args=(slot, shard), daemon=True
                )
                for slot, shard in enumerate(shards)
            ]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join()
        if TELEMETRY.enabled:
            for entry, shard in zip(health, shards):
                # Interned per-index names (lru_cache): no per-iteration
                # key building, same discipline as devmem's bucket gauges.
                lag_name = _lag_gauge_name(shard.index)
                epoch_name = _epoch_gauge_name(shard.index)
                if entry["max_lag"] is not None:
                    TELEMETRY.set_gauge(lag_name, entry["max_lag"])
                if entry.get("epoch") is not None:
                    TELEMETRY.set_gauge(epoch_name, entry.get("epoch", 0))
        return health

    def shard_connections(self):
        """``[(index, primary NetworkDB), ...]`` — the per-shard direct
        surface the soak/audit tooling uses to verify every shard alone."""
        return [(s.index, s.primary) for s in self._shards]

    def set_topology(self, shards, vnodes=None):
        """Rebuild the ring and shard set IN PLACE for a new topology —
        the router half of live rebalancing (``orion-tpu db rebalance``).

        Shards whose identity (original primary ``host:port``) AND replica
        set survive keep their connections, counters, epoch floors and
        failure state; new shards connect fresh; removed (or reshaped)
        shards close.  The owner and placement caches reset — both may
        point across the topology change.  Ops in flight on a removed
        shard fail transiently and re-route through the new ring on their
        op-level retry."""
        specs = parse_shard_specs(shards, default_secret=self._default_secret)
        with self._topology_lock:
            # Old-name registrations go first: a surviving shard may land
            # on a NEW index and must not keep exporting under the old one.
            self._unregister_shard_counters(self._shards)
            old = {s.identity: s for s in self._shards}
            rebuilt = []
            for index, spec in enumerate(specs):
                identity = f"{spec['host']}:{int(spec['port'])}"
                survivor = old.get(identity)
                replica_addrs = frozenset(
                    f"{h}:{int(p)}" for h, p in spec.get("replicas") or ()
                )
                if survivor is not None and survivor.replica_addrs == replica_addrs:
                    del old[identity]
                    survivor.index = index
                    rebuilt.append(survivor)
                else:
                    rebuilt.append(
                        _Shard(
                            index,
                            spec,
                            dict(self._client_base, secret=spec.get("secret")),
                            create_retry_policy(self._retry_config),
                        )
                    )
            self._shards = rebuilt
            self._ring = HashRing(
                [s.identity for s in rebuilt],
                vnodes=self._ring.vnodes if vnodes is None else vnodes,
            )
            self._identity_index = {s.identity: s.index for s in rebuilt}
            self._passthrough = len(rebuilt) == 1 and not rebuilt[0].replicas
            with self._owner_lock:
                TSAN.write("ShardedNetworkDB._owners", self)
                self._owners.clear()
            with self._placement_lock:
                TSAN.write("ShardedNetworkDB._placements", self)
                self._placements.clear()
            self._register_shard_counters()
        for shard in old.values():
            shard.close()

    def close(self):
        _ROUTER_REGISTRY.discard(self)
        self._reprovision_stop.set()
        if self._reprovision_thread is not None:
            self._reprovision_thread.join(timeout=2.0)
        for shard in self._shards:
            shard.close()

    # --- routing core --------------------------------------------------------
    def _now(self):
        if self._monotonic is not None:
            return self._monotonic()
        import time

        return time.monotonic()

    def _route(self, collection, doc=None, query=None):
        """Shard index for a doc/query, or None (fan out).  Experiments
        route by their own ``_id``; everything else routes by the
        ``experiment`` field, falling back to the owner cache for id-only
        queries and to the id's own ring point for id-carrying docs.
        Experiment-keyed routes consult the per-experiment placement
        override (live rebalancing) before the ring."""
        if collection == "experiments":
            key = None
            if query is not None:
                key = _concrete(query.get("_id"))
            if key is None and doc is not None:
                key = _concrete(doc.get("_id"))
            return None if key is None else self._placed_index(str(key))
        exp = None
        if query is not None:
            exp = _concrete(query.get("experiment"))
        if exp is None and doc is not None:
            exp = _concrete(doc.get("experiment"))
        if exp is not None:
            return self._placed_index(str(exp))
        if doc is not None:
            _id = _concrete(doc.get("_id"))
            if _id is not None:
                return self._ring.lookup(str(_id))
        if query is not None:
            _id = _concrete(query.get("_id"))
            if _id is not None:
                return self._owner_of(collection, _id)
        return None

    def _shard_at(self, index):
        """Indexed shard access that tolerates a concurrent
        :meth:`set_topology`: the ring and the shard list are swapped in
        two assignments, so an op that routed against the OLD ring may
        briefly hold an index past the NEW list.  Surface it as the
        transient it is — the op-level retry re-routes through the new
        ring — instead of an IndexError no retry policy classifies."""
        shards = self._shards
        if index >= len(shards):
            error = DatabaseError(
                f"shard index {index} routed against a topology of "
                f"{len(shards)} shard(s) — the ring changed mid-route; "
                "retrying re-routes"
            )
            error.maybe_applied = merge_maybe_applied(())
            raise error
        return shards[index]

    # --- placement overrides (live rebalancing) ------------------------------
    def _placed_index(self, key):
        """Ring placement with the per-experiment override consulted first.

        The override doc lives on the experiment's RING shard (the one
        place any router can find without knowing the answer); a cached
        lookup costs a dict read, a miss costs one tiny primary read that
        is then cached for :attr:`placement_ttl` seconds.  A FENCED
        experiment (mid-flip migration window) raises a transient error —
        the op-level retry re-routes after the flip."""
        ring_index = self._ring.lookup(key)
        if self.placement_ttl <= 0 or self._passthrough:
            return ring_index
        entry = self._placement_cached(key)
        if entry is None:
            entry = self._placement_read(key, ring_index)
        identity, state = entry
        if state == "fenced":
            error = DatabaseError(
                f"experiment {key} is fenced mid-migration "
                "(placement flip in progress); the op will re-route on retry"
            )
            # Pre-flight refusal: the op never ran anywhere.
            error.maybe_applied = merge_maybe_applied(())
            raise error
        if identity is None:
            return ring_index
        index = self._identity_index.get(identity)
        if index is None:
            # The override names a shard this topology doesn't carry —
            # a half-rolled-out topology change.  The ring is the best
            # remaining answer; say so once per TTL (the cache holds it).
            log.warning(
                "placement override for %s names unknown shard %s; "
                "falling back to the ring", key, identity,
            )
            return ring_index
        return index

    def _placement_cached(self, key):
        now = self._now()
        with self._placement_lock:
            TSAN.write("ShardedNetworkDB._placements", self)
            entry = self._placements.get(key)
            if entry is None or entry[2] <= now:
                return None
            return entry[0], entry[1]

    def _placement_read(self, key, ring_index):
        try:
            docs = self._shard_at(ring_index).primary.read(
                PLACEMENT_COLLECTION, {"_id": placement_doc_id(key)}
            )
        except Exception:
            # The ring shard is unreachable: route by the ring — the op
            # itself will surface (and retry) the outage through its own
            # path; a placement probe must not add a second failure mode.
            return None, None
        doc = docs[0] if docs else None
        identity = doc.get("shard") if doc else None
        state = doc.get("state") if doc else None
        if state == "fenced":
            # Never cached: re-read until the migrator flips it.
            return identity, state
        with self._placement_lock:
            TSAN.write("ShardedNetworkDB._placements", self)
            placements = self._placements
            placements[key] = (identity, state, self._now() + self.placement_ttl)
            placements.move_to_end(key)
            while len(placements) > PLACEMENT_CACHE_CAP:
                placements.popitem(last=False)
        return identity, state

    def _invalidate_placement(self, collection, query):
        """Drop the placement cache entry behind an empty ROUTED answer —
        but only when an override (not the ring) routed it: a router whose
        cache still points at a migrated-away source would otherwise keep
        reading deleted ground until the TTL expired.  Ring-routed empties
        (a fresh experiment with no trials yet) invalidate nothing, so the
        hot status-poll path never pays an extra probe."""
        key = None
        if query is not None:
            if collection == "experiments":
                key = _concrete(query.get("_id"))
            else:
                key = _concrete(query.get("experiment"))
        if key is None:
            return
        with self._placement_lock:
            TSAN.write("ShardedNetworkDB._placements", self)
            entry = self._placements.get(str(key))
            if entry is not None and entry[0] is not None:
                del self._placements[str(key)]

    def _owner_of(self, collection, _id):
        with self._owner_lock:
            TSAN.write("ShardedNetworkDB._owners", self)
            return self._owners.get((collection, _id))

    def _remember_owner(self, collection, _id, index):
        if _id is None:
            return
        with self._owner_lock:
            TSAN.write("ShardedNetworkDB._owners", self)
            owners = self._owners
            owners[(collection, _id)] = index
            owners.move_to_end((collection, _id))
            while len(owners) > OWNER_CACHE_CAP:
                owners.popitem(last=False)

    def _harvest_owners(self, collection, docs, index):
        """Remember the shard of every id-bearing doc a routed/fanned read
        returned, so later id-only CAS ops route directly."""
        for doc in docs or ():
            if isinstance(doc, dict):
                self._remember_owner(collection, doc.get("_id"), index)

    # --- fan-out machinery ---------------------------------------------------
    def _collect_shards(self, fn, read_only=False, op="fan_out"):
        """Run ``fn(shard)`` on every shard CONCURRENTLY; returns
        ``(results, errors)`` as per-shard lists (exactly one of the pair
        is non-None per slot).  Read legs ride the shard's own policy so a
        blip on one shard heals locally; mutation legs run bare — the
        op-level policy above owns their applied-or-not discipline."""
        shards = self._shards
        with self._stats_lock:
            TSAN.write("ShardedNetworkDB._stats", self)
            self.fan_outs += 1
        TELEMETRY.count("storage.shard.fan_outs")
        results = [None] * len(shards)
        errors = [None] * len(shards)

        def leg(i, shard):
            try:
                if read_only and shard.policy is not None:
                    results[i] = shard.policy.run(
                        lambda: fn(shard), op=f"shard.s{i}.{op}", mode=MODE_ALWAYS
                    )
                else:
                    results[i] = fn(shard)
            except Exception as exc:
                errors[i] = exc

        if len(shards) == 1:
            leg(0, shards[0])
        else:
            threads = [
                threading.Thread(target=leg, args=(i, shard), daemon=True)
                for i, shard in enumerate(shards)
            ]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join()
        return results, errors

    def _each_shard(self, fn, read_only=False, op="fan_out"):
        """Fan out and REQUIRE every shard: aggregated failures raise with
        the strictest ``maybe_applied`` of the parts."""
        results, errors = self._collect_shards(fn, read_only=read_only, op=op)
        failed = [e for e in errors if e is not None]
        if failed:
            raise shard_fanout_error(
                f"{op} failed on {len(failed)}/{len(self._shards)} shard(s)",
                failed,
            )
        return results

    # --- replica read path ---------------------------------------------------
    def _shard_read(self, shard, op, *args, **kwargs):
        """One read on one shard: replica round-robin with staleness check,
        failover to the primary on transport error or lag."""
        if self.replica_reads and shard.replicas:
            now = self._now()
            index = shard.pick_replica(now)
            if index is not None:
                replica = shard.replicas[index]
                try:
                    result = getattr(replica, op)(*args, **kwargs)
                except Exception as exc:
                    if not is_transient(exc):
                        raise
                    # Dead/partitioned replica: bench it briefly and take
                    # the primary — a failover, the first-class signal a
                    # flapping replica tier emits.
                    shard.bench_replica(index, now)
                    TELEMETRY.count("storage.shard.failovers")
                else:
                    stamp = replica.seq_snapshot()
                    floor = shard.write_floor()
                    if not floor or (stamp is not None and stamp >= floor):
                        return result
                    # The replica answered from BEFORE this router's last
                    # acknowledged write on the shard (the stamp check is
                    # per connection, so a concurrent reader can only make
                    # it stricter-to-pass, never falsely fresh for the
                    # floor it read).  Re-read from the primary.
                    shard.note_stale()
                    TELEMETRY.count("storage.shard.replica_stale_reads")
        try:
            # Reads never CLEAR the failure streak: a demoted node serves
            # reads happily while refusing every mutation — read successes
            # resetting the streak would starve the re-election the
            # refusals are feeding.
            return getattr(shard.primary, op)(*args, **kwargs)
        except Exception as exc:
            self._note_primary_error(shard, exc)
            raise

    def _shard_mutate(self, shard, op, *args, **kwargs):
        """One mutation on one shard's PRIMARY; lifts the staleness floor
        from the stamped reply.  Failures feed the promotion detector;
        a reply stamped with a LOWER epoch than this router has seen is
        FENCED — refused after the fact, because it landed on a stale
        primary whose fork the promoted timeline will erase."""
        # Capture the client once: a concurrent promotion may swap
        # shard.primary mid-call, and the fence below must stamp-check the
        # connection this op actually rode.
        primary = shard.primary
        try:
            result = getattr(primary, op)(*args, **kwargs)
        except Exception as exc:
            self._note_primary_error(shard, exc)
            raise
        # Fence BEFORE clearing the failure streak: a wire-successful write
        # answered from a stale epoch is a FAILURE of the shard (it landed
        # on a condemned fork), and repeated fenced writes must accumulate
        # the same streak dead sockets do — that streak is the only road
        # back to an election when a stale claimant is the one answering.
        self._fence_stale_write(shard, primary, op)
        shard.clear_primary_failure()
        return result

    def _fence_stale_write(self, shard, primary, op):
        """Raise when the mutating reply on ``primary`` carried a LOWER
        epoch than this router has seen on the shard."""
        if not shard.note_write(primary):
            return
        shard.note_primary_failure(self._now())
        TELEMETRY.count("storage.shard.fenced_writes")
        error = DatabaseError(
            f"shard {shard.index} answered {op!r} from a stale epoch "
            f"(below {shard.epoch_floor()}): the write landed on a "
            "demoting primary and will not survive its resync — "
            "retrying against the promoted primary"
        )
        # The stale primary DID apply it, but that application is a
        # condemned fork the next resync erases: in the surviving
        # timeline nothing was applied, so the op-level retry must
        # re-run it (maybe_applied=True would make non-converging ops
        # give up and lose the write for real).
        error.maybe_applied = merge_maybe_applied(())
        self._refresh_shard_primary(shard)
        raise error

    def _note_primary_error(self, shard, exc):
        """Feed one failed primary op into the promotion detector."""
        now = self._now()
        if getattr(exc, "not_primary", False):
            # The node we call primary answered as a REPLICA.  Usually a
            # concurrent router promoted — adopt its winner.  But when
            # NOBODY claims primary (the promoted node itself restarted
            # back into its configured replica role), adoption finds
            # nothing — so the refusals feed the same confirmation window
            # and a real election re-promotes the caught-up node IN PLACE.
            shard.note_epoch(getattr(exc, "epoch", 0))
            shard.note_primary_failure(now)
            if (
                self.auto_promote
                and shard.failing_for(now) >= self.promote_after
            ):
                self._run_election(shard)
            else:
                self._refresh_shard_primary(shard)
            return
        if not is_transient(exc):
            return
        shard.note_primary_failure(now)
        if (
            self.auto_promote
            and shard.replicas
            and shard.failing_for(now) >= self.promote_after
        ):
            self._run_election(shard)

    def _refresh_shard_primary(self, shard):
        """Re-discover which node serves the shard (post-promotion or
        post-fence): probe all nodes, adopt whichever claims primary at
        the highest epoch.  Never elects — that needs the confirmation
        window; this only catches up with an election someone else ran."""
        if not shard.replicas:
            return
        if not shard._promote_guard.acquire(blocking=False):
            return  # someone on this router is already sorting it out
        try:
            self._elect(shard, adopt_only=True)
        finally:
            shard._promote_guard.release()

    def _run_election(self, shard):
        """Confirmation window expired: elect and promote (one thread per
        router at a time; concurrent routers converge via the
        deterministic winner + the idempotent promote op)."""
        if not shard._promote_guard.acquire(blocking=False):
            return
        try:
            # Re-check under the guard: a concurrent thread may have just
            # promoted and cleared the streak.
            if shard.failing_for(self._now()) < self.promote_after:
                return
            self._elect(shard, adopt_only=False)
        finally:
            shard._promote_guard.release()

    def _probe_seq(self, client):
        """One ``seq`` probe over a short-lived, SHORT-timeout connection:
        elections and health views must not borrow the data path's (long)
        timeout or contend its connection lock — a hung node costs at
        most PROBE_TIMEOUT_S here, not a data-timeout-long stall."""
        probe = NetworkDB(
            host=client.host, port=client.port, timeout=PROBE_TIMEOUT_S,
            secret=client.secret, reconnect_jitter=0,
        )
        try:
            return probe._call("seq") or {}
        finally:
            probe.close()

    def _elect(self, shard, adopt_only):
        max_epoch = shard.epoch_floor()
        candidates = []
        promoted_elsewhere = None
        # The primary slot gets one last probe: a box that answers AS A
        # PRIMARY mid-window was merely slow/restarting — no election.
        # One that answers as a REPLICA is itself the leading candidate:
        # a previously promoted node that restarted back into its
        # configured replica role sits in this slot precisely because an
        # earlier election chose it (its seq decides like any other's).
        floor = shard.epoch_floor()
        try:
            info = self._probe_seq(shard.primary)
        except Exception:
            pass
        else:
            epoch = int(info.get("epoch", 0) or 0)
            max_epoch = max(max_epoch, epoch)
            if not info.get("replica"):
                if epoch >= floor:
                    shard.note_epoch(epoch)
                    shard.clear_primary_failure()
                    return
                # A primary claimant BELOW this router's epoch floor is a
                # stale fork that never heard of its own demotion (its
                # newer-epoch peer may be dead too).  Never honored, never
                # electable: blessing the fork would silently discard the
                # newer timeline — that trade-off belongs to an operator.
            elif not info.get("resyncing") and epoch >= floor:
                # Electable only at/above the floor: a replica still on an
                # OLDER epoch missed writes a newer primary acknowledged.
                candidates.append(
                    (int(info.get("seq", 0)), shard.primary, None)
                )
        for index, replica in enumerate(shard.replicas):
            try:
                info = self._probe_seq(replica)
            except Exception:
                continue  # unreachable: not electable from here
            epoch = int(info.get("epoch", 0) or 0)
            max_epoch = max(max_epoch, epoch)
            if not info.get("replica"):
                # Already a primary (a concurrent router won the race):
                # adopt the highest-epoch claimant — but never one BELOW
                # the epoch floor (a stale fork, see above).
                if epoch >= floor and (
                    promoted_elsewhere is None or epoch > promoted_elsewhere[1]
                ):
                    promoted_elsewhere = (index, epoch)
                continue
            if info.get("resyncing") or epoch < floor:
                # A fork mid-repair, or a replica still on an older epoch
                # (it missed writes a newer primary acknowledged): not
                # electable.
                continue
            candidates.append((int(info.get("seq", 0)), replica, index))
        if promoted_elsewhere is not None:
            index, epoch = promoted_elsewhere
            self._adopt_primary(shard, index, epoch, elected=False)
            return
        if adopt_only or not candidates:
            return
        # Deterministic winner: most caught-up wins; ties break on address
        # so every router probing the same fleet elects the SAME replica.
        candidates.sort(key=lambda c: (-c[0], f"{c[1].host}:{c[1].port}"))
        seq, winner, index = candidates[0]
        winner_addr = f"{winner.host}:{winner.port}"
        peers = [
            addr
            for addr in [shard.identity]
            + [f"{r.host}:{r.port}" for r in shard.replicas]
            if addr != winner_addr
        ]
        new_epoch = max_epoch + 1
        try:
            # Rides the shard policy with an explicit mode (STO005): the
            # promote op is idempotent by construction — a resend at the
            # same epoch reports the standing state, never re-flips.
            result = shard.policy.run(
                lambda: winner._call(
                    "promote", {"epoch": new_epoch, "replicate_to": peers}
                ),
                op=f"shard.s{shard.index}.promote",
                mode=MODE_ALWAYS,
            ) or {}
        except Exception as exc:
            log.warning(
                "promotion of %s:%s (shard %d) failed: %s",
                winner.host, winner.port, shard.index, exc,
            )
            return
        if not result.get("primary"):
            # Lost a cross-router race (the winner already heard a higher
            # epoch as a replica) — the next failure cycle adopts.
            shard.note_epoch(int(result.get("epoch", 0) or 0))
            return
        self._adopt_primary(
            shard, index, int(result.get("epoch", new_epoch)), elected=True
        )

    def _adopt_primary(self, shard, replica_index, epoch, elected):
        if replica_index is None:
            winner = shard.primary
            shard.promote_in_place(epoch)
        else:
            winner = shard.promote_swap(replica_index, epoch, self._now())
        TELEMETRY.count("storage.shard.promotions")
        if FLIGHT.enabled:
            FLIGHT.record(
                "promote",
                args={
                    "shard": shard.index,
                    "winner": f"{winner.host}:{winner.port}",
                    "epoch": epoch,
                    "elected": elected,
                },
            )
        log.warning(
            "shard %d: %s %s:%s as primary at epoch %d (was %s)",
            shard.index,
            "promoted" if elected else "adopted",
            winner.host, winner.port, epoch, shard.identity,
        )

    # --- replica auto-reprovisioning (day-2 operations) ----------------------
    def _reprovision_loop(self):
        """Background sweep: probe replica health and replace the dead.
        Runs only when a ``replica_provisioner`` is configured; never
        raises — replica repair must not take the router down with it."""
        interval = max(0.25, min(1.0, self.reprovision_after / 4.0))
        while not self._reprovision_stop.wait(interval):
            try:
                self._reprovision_sweep()
            except Exception:  # pragma: no cover - defensive
                log.debug("reprovision sweep failed", exc_info=True)

    def _reprovision_sweep(self):
        shards = list(self._shards)
        now = self._now()
        live = {s.identity for s in shards}
        with self._reprovision_lock:
            TSAN.write("ShardedNetworkDB._replica_down", self)
            # Entries for shards a topology change removed never fire.
            for key in [
                k for k in self._replica_down_since if k[0] not in live
            ]:
                del self._replica_down_since[key]
        for shard in shards:
            if self._reprovision_stop.is_set():
                return
            if shard.epoch_floor() == 0:
                # Never promoted: the configured replica set is authoritative
                # and a down replica is expected to come back AS ITSELF (a
                # reboot) — reprovisioning belongs to the post-promotion
                # one-short-forever state.
                continue
            if shard.failing_for(now) > 0:
                # The PRIMARY is failing: adoption has nobody to talk to,
                # and the election machinery owns this phase.
                continue
            for replica_index, replica in enumerate(list(shard.replicas)):
                addr = f"{replica.host}:{replica.port}"
                key = (shard.identity, addr)
                try:
                    self._probe_seq(replica)
                except Exception:
                    with self._reprovision_lock:
                        TSAN.write("ShardedNetworkDB._replica_down", self)
                        since = self._replica_down_since.setdefault(key, now)
                    if now - since >= self.reprovision_after:
                        self._reprovision(shard, replica_index, addr)
                else:
                    with self._reprovision_lock:
                        TSAN.write("ShardedNetworkDB._replica_down", self)
                        self._replica_down_since.pop(key, None)

    def _reprovision(self, shard, replica_index, dead_addr):
        """Provision and adopt a replacement for one dead replica: ask the
        provisioner for a fresh empty server, tell the shard's CURRENT
        primary to adopt it (``adopt_replica`` — the pusher's ordinary gap
        logic snapshot-resyncs it, bounded by the server's resync gate),
        then swap the dead client out of the router's replica set."""
        TELEMETRY.set_gauge("storage.reprovision.in_progress", 1)
        if FLIGHT.enabled:
            FLIGHT.record(
                "reprovision.start",
                args={"shard": shard.index, "dead": dead_addr},
            )
        try:
            address = self.replica_provisioner(shard.index)
            host, _, port = str(address).rpartition(":")
            if not host or not port:
                raise DatabaseError(  # lint: disable=STO004 -- caught by this method's own except; retried next sweep, never a client reply
                    f"provisioner returned {address!r}; expected host:port"
                )
            result = shard.primary._call(
                "adopt_replica", {"address": f"{host}:{int(port)}"}
            ) or {}
            if not result.get("adopted"):
                raise DatabaseError(  # lint: disable=STO004 -- caught by this method's own except; retried next sweep, never a client reply
                    f"shard {shard.index} primary refused to adopt "
                    f"{address!r}: {result}"
                )
            client = NetworkDB(
                host=host, port=int(port),
                **dict(self._client_base, secret=shard.primary.secret),
            )
            old = shard.adopt_replacement(
                replica_index, client, dead_addr, self._now()
            )
            old.close()
            with self._reprovision_lock:
                TSAN.write("ShardedNetworkDB._replica_down", self)
                self._replica_down_since.pop((shard.identity, dead_addr), None)
                self.reprovisions += 1
            TELEMETRY.count("storage.shard.reprovisions")
            if FLIGHT.enabled:
                FLIGHT.record(
                    "reprovision.done",
                    args={
                        "shard": shard.index,
                        "dead": dead_addr,
                        "replica": f"{host}:{port}",
                    },
                )
            log.warning(
                "shard %d: reprovisioned dead replica %s -> %s",
                shard.index, dead_addr, f"{host}:{port}",
            )
        except Exception as exc:
            # The window keeps running: the NEXT sweep past the threshold
            # retries (a provisioner outage must not wedge repair forever).
            log.warning(
                "shard %d: reprovisioning replica %s failed: %s",
                shard.index, dead_addr, exc,
            )
        finally:
            TELEMETRY.set_gauge("storage.reprovision.in_progress", 0)

    # --- AbstractDB contract -------------------------------------------------
    def ping(self):
        if self._passthrough:
            return self._shards[0].primary.ping()
        results = self._each_shard(
            lambda shard: shard.primary.ping(), read_only=True, op="ping"
        )
        return all(results)

    def ensure_index(self, collection, keys, unique=False):
        if self._passthrough:
            return self._shards[0].primary.ensure_index(
                collection, keys, unique=unique
            )
        self._ensure_through_promotion(
            lambda shard: self._shard_mutate(
                shard, "ensure_index", collection, keys, unique=unique
            ),
            op="ensure_index",
        )

    def ensure_indexes(self, specs):
        if self._passthrough:
            return self._shards[0].primary.ensure_indexes(specs)
        specs = [list(s) for s in specs]
        self._ensure_through_promotion(
            lambda shard: self._shard_mutate(shard, "ensure_indexes", specs),
            op="ensure_indexes",
        )

    def _ensure_through_promotion(self, leg, op):
        """Index setup runs at CONSTRUCTION time — before any op has fed
        the failure detector — so a dead primary would otherwise crash
        every fresh process (CLI command, new worker) even though a
        caught-up replica is one election away.  Re-run the fan-out
        (idempotent) long enough for the per-leg failures to accumulate a
        promotion streak and for the election to heal the shard; a shard
        that stays dead past the window raises exactly as before."""
        import time

        deadline = (
            self._now() + self.promote_after * 2 + 2.0
            if self.auto_promote
            else None
        )
        while True:
            try:
                return self._each_shard(leg, op=op)
            except DatabaseError:
                if deadline is None or self._now() >= deadline:
                    raise
                time.sleep(0.2)

    def index_information(self, collection):
        if self._passthrough:
            return self._shards[0].primary.index_information(collection)
        merged = {}
        for info in self._each_shard(
            lambda shard: shard.primary.index_information(collection),
            read_only=True,
            op="index_information",
        ):
            merged.update(info or {})
        return merged

    def drop_index(self, collection, name):
        if self._passthrough:
            return self._shards[0].primary.drop_index(collection, name)
        results, errors = self._collect_shards(
            lambda shard: shard.primary.drop_index(collection, name),
            op="drop_index",
        )
        key_errors = [e for e in errors if isinstance(e, KeyError)]
        hard = [e for e in errors if e is not None and not isinstance(e, KeyError)]
        if hard:
            raise shard_fanout_error(
                f"drop_index({collection!r}, {name!r}) failed", hard
            )
        if key_errors and len(key_errors) == len(self._shards):
            # Missing EVERYWHERE is the single-server "index not found";
            # missing somewhere is a partially-applied earlier drop that
            # this call just finished converging.
            raise key_errors[0]

    def write(self, collection, data, query=None):
        if self._passthrough:
            return self._shards[0].primary.write(collection, data, query=query)
        if query is not None:
            index = self._route(collection, query=query)
            if index is not None:
                return self._shard_mutate(
                    self._shard_at(index), "write", collection, data, query=query
                )
            results = self._each_shard(
                lambda shard: self._shard_mutate(
                    shard, "write", collection, data, query=query
                ),
                op="write",
            )
            return sum(r or 0 for r in results)
        return self._insert(collection, data)

    def _insert(self, collection, data):
        single = isinstance(data, dict)
        docs = [data] if single else list(data)
        if collection == "experiments":
            docs = [self._with_minted_id(doc) for doc in docs]
        groups = OrderedDict()  # shard index -> [(position, doc)]
        for position, doc in enumerate(docs):
            index = self._route(collection, doc=doc)
            if index is None:
                # No experiment, no id: an auto-id document with no routable
                # identity (third-party collections).  Ring-place by the
                # collection name so placement stays deterministic.
                index = self._ring.lookup(collection)
            groups.setdefault(index, []).append((position, doc))
        if single:
            # One document, one shard: preserve the single-insert return
            # shape (the inserted id, minted or server-assigned).
            (index, members), = groups.items()
            doc = members[0][1]
            result = self._shard_mutate(self._shard_at(index), "write", collection, doc)
            self._remember_owner(collection, doc.get("_id"), index)
            return result
        out = [None] * len(docs)
        for index, members in groups.items():
            payload = [doc for _, doc in members]
            ids = self._shard_mutate(
                self._shard_at(index), "write", collection, payload
            )
            for (position, doc), _id in zip(members, ids):
                out[position] = _id
                self._remember_owner(collection, doc.get("_id"), index)
        return out

    def _with_minted_id(self, doc):
        if "_id" in doc:
            return doc
        doc = dict(doc)
        doc["_id"] = mint_experiment_id(doc)
        return doc

    def update_many(self, collection, pairs):
        if self._passthrough:
            return self._shards[0].primary.update_many(collection, pairs)
        routed = OrderedDict()
        broadcast = []
        for query, update in pairs:
            index = self._route(collection, query=query)
            if index is None:
                broadcast.append((query, update))
            else:
                routed.setdefault(index, []).append((query, update))
        total = 0
        for index, shard_pairs in routed.items():
            total += self._shard_mutate(
                self._shard_at(index), "update_many", collection, shard_pairs
            )
        if broadcast:
            # Un-keyed updates apply to matching docs WHEREVER they live —
            # the correct cross-shard semantics of a query-driven update.
            results = self._each_shard(
                lambda shard: self._shard_mutate(
                    shard, "update_many", collection, broadcast
                ),
                op="update_many",
            )
            total += sum(r or 0 for r in results)
        return total

    def read(self, collection, query=None, projection=None):
        if self._passthrough:
            return self._shards[0].primary.read(
                collection, query=query, projection=projection
            )
        index = self._route(collection, query=query)
        if index is not None:
            docs = self._shard_read(
                self._shard_at(index), "read", collection, query=query,
                projection=projection,
            )
            self._harvest_owners(collection, docs, index)
            if not docs:
                # Invalidated-on-miss: an override-routed empty answer may
                # mean the experiment moved on (post-delete stale cache).
                self._invalidate_placement(collection, query)
            return docs
        merged = []
        results = self._each_shard(
            lambda shard: self._shard_read(
                shard, "read", collection, query=query, projection=projection
            ),
            read_only=True,
            op="read",
        )
        for shard, docs in zip(self._shards, results):
            self._harvest_owners(collection, docs, shard.index)
            merged.extend(docs or [])
        return merged

    def count(self, collection, query=None):
        if self._passthrough:
            return self._shards[0].primary.count(collection, query=query)
        index = self._route(collection, query=query)
        if index is not None:
            result = self._shard_read(
                self._shard_at(index), "count", collection, query=query
            )
            if not result:
                self._invalidate_placement(collection, query)
            return result
        results = self._each_shard(
            lambda shard: self._shard_read(shard, "count", collection, query=query),
            read_only=True,
            op="count",
        )
        return sum(r or 0 for r in results)

    def read_and_write(self, collection, query, data):
        if self._passthrough:
            return self._shards[0].primary.read_and_write(collection, query, data)
        index = self._route(collection, query=query)
        if index is not None:
            doc = self._shard_mutate(
                self._shard_at(index), "read_and_write", collection, query, data
            )
            if isinstance(doc, dict):
                self._remember_owner(collection, doc.get("_id"), index)
            else:
                self._invalidate_placement(collection, query)
            return doc
        if _concrete((query or {}).get("_id")) is None:
            # A find-ONE-and-update keyed by neither _id nor experiment has
            # no correct cross-shard spelling: running it on every shard
            # would CAS up to N documents where one server swaps exactly
            # one.  Refuse loudly (pre-flight: nothing ran anywhere).
            error = DatabaseError(
                f"read_and_write({collection!r}) query {query!r} carries "
                "neither an _id nor an experiment key — a single-document "
                "CAS cannot be routed (and must not run on every shard)"
            )
            error.maybe_applied = merge_maybe_applied(())
            raise error
        # Id-only owner-cache miss: ids are globally unique, so at most
        # ONE shard matches; the others no-op to None.  Each leg rides
        # _shard_mutate so failures feed the promotion detector and the
        # fence stamps the connection the CAS actually rode.
        results, errors = self._collect_shards(
            lambda shard: self._shard_mutate(
                shard, "read_and_write", collection, query, data
            ),
            op="read_and_write",
        )
        winner = None
        for shard, doc in zip(self._shards, results):
            if isinstance(doc, dict):
                winner = doc
                self._remember_owner(collection, doc.get("_id"), shard.index)
        failed = [e for e in errors if e is not None]
        if winner is not None:
            # The unique-id invariant (the query carries a concrete _id,
            # enforced above) means the matching shard answered; an error
            # on a NON-matching shard cannot have applied this CAS (its
            # query matched nothing there).
            return winner
        if failed:
            raise shard_fanout_error(
                f"read_and_write({collection!r}) failed on "
                f"{len(failed)}/{len(self._shards)} shard(s)",
                failed,
            )
        return None

    def remove(self, collection, query=None):
        if self._passthrough:
            return self._shards[0].primary.remove(collection, query=query)
        index = self._route(collection, query=query)
        if index is not None:
            return self._shard_mutate(
                self._shard_at(index), "remove", collection, query=query
            )
        results = self._each_shard(
            lambda shard: self._shard_mutate(shard, "remove", collection, query=query),
            op="remove",
        )
        return sum(r or 0 for r in results)

    # --- batch primitives ----------------------------------------------------
    def apply_batch(self, ops):
        if self._passthrough:
            return self._shards[0].primary.apply_batch(ops)
        return self._batch(ops, "apply_batch")

    def pipeline(self, ops):
        if self._passthrough:
            return self._shards[0].primary.pipeline(ops)
        return self._batch(ops, "pipeline")

    def _route_sub_op(self, op, args, kwargs):
        collection = args[0] if args else None
        if op == "write":
            data = args[1] if len(args) > 1 else None
            query = (kwargs or {}).get("query")
            if query is None and len(args) > 2:
                query = args[2]
            if query is not None:
                return self._route(collection, query=query)
            doc = None
            if isinstance(data, dict):
                doc = data
            elif isinstance(data, (list, tuple)) and data:
                doc = data[0] if isinstance(data[0], dict) else None
            return self._route(collection, doc=doc)
        query = args[1] if len(args) > 1 else (kwargs or {}).get("query")
        if not isinstance(query, dict):
            query = None
        return self._route(collection, query=query)

    def _batch(self, ops, primitive):
        """Split a batch by target shard, dispatch the per-shard
        sub-batches CONCURRENTLY through the shard's own batch primitive,
        and reassemble per-slot outcomes in the original order.
        Unroutable slots execute through the op-level router methods
        (which fan out) and land their outcome — or their exception — in
        place.  A shard whose whole sub-batch died raises the aggregated
        error with the strictest ``maybe_applied``: healthy shards' slots
        applied durably, and the op-level retry's re-run converges through
        the same dedup contracts a single server's retry does."""
        ops = list(ops)
        if not ops:
            return []
        groups = OrderedDict()  # shard index -> [(position, sub_op)]
        loose = []  # [(position, sub_op)] — unroutable
        for position, (op, args, kwargs) in enumerate(ops):
            index = self._route_sub_op(op, list(args), kwargs)
            if index is None:
                loose.append((position, (op, args, kwargs)))
            else:
                groups.setdefault(index, []).append((position, (op, args, kwargs)))
        out = [None] * len(ops)
        errors = []

        def run_group(index, members):
            shard = self._shard_at(index)
            sub_ops = [sub for _, sub in members]
            mutating = any(
                op not in ("read", "count") for op, _, _ in sub_ops
            )
            try:
                if mutating:
                    # Same discipline as _shard_mutate: the client is
                    # captured once (fence-stamps the connection the batch
                    # rode), failures feed the promotion detector — the
                    # producer's q-round rides THIS path, so a dead
                    # primary must trip the election from here too.
                    primary = shard.primary
                    try:
                        outcomes = getattr(primary, primitive)(sub_ops)
                    except Exception as exc:
                        self._note_primary_error(shard, exc)
                        raise
                    self._fence_stale_write(shard, primary, primitive)
                    shard.clear_primary_failure()
                else:
                    outcomes = self._shard_read(shard, primitive, sub_ops)
            except Exception as exc:
                errors.append(exc)
                return
            for (position, sub), outcome in zip(members, outcomes):
                out[position] = outcome
                if sub[0] in ("read", "read_and_write"):
                    docs = outcome if isinstance(outcome, list) else [outcome]
                    self._harvest_owners(sub[1][0] if sub[1] else None, [
                        d for d in docs if isinstance(d, dict)
                    ], index)

        if len(groups) <= 1:
            for index, members in groups.items():
                run_group(index, members)
        else:
            threads = [
                threading.Thread(
                    target=run_group, args=(index, members), daemon=True
                )
                for index, members in groups.items()
            ]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join()
        for position, (op, args, kwargs) in loose:
            try:
                out[position] = getattr(self, op)(*args, **kwargs)
            except Exception as exc:
                # Slot containment, same contract as a server-side refused
                # slot: the exception IS the outcome.
                out[position] = exc
        if errors:
            raise shard_fanout_error(
                f"{primitive} failed on {len(errors)} shard(s)", errors
            )
        return out
