"""Sharded, replicated network storage: the scale-out control plane.

One :class:`~orion_tpu.storage.netdb.NetworkDB` talks to one server — the
last single point of failure in the stack.  :class:`ShardedNetworkDB` is
an AbstractDB-contract router over N independent netdb shards, slotting
UNDER :class:`~orion_tpu.storage.base.DocumentStorage` exactly where a
single client would sit (Oríon's design keeps all coordination behind the
storage protocol, so nothing above this layer changes):

- **Consistent-hash routing on experiment id** (Dynamo-style ring with
  virtual nodes for balance): every document and query that names an
  experiment — trial registration, reservation CAS, status polls, the
  telemetry/health channels — routes to exactly one shard, so the hot
  paths cost what they cost today regardless of shard count.  Experiment
  ids are deterministic hashes of the experiment's unique identity
  (``core.experiment.experiment_id``; the router mints the same way for
  raw inserts that arrive without one), so two racing creators of the
  same experiment land on the SAME shard and collide on its unique
  index, exactly as they would on one server.
- **Cross-experiment fan-out**: ops that span experiments
  (``fetch_experiments``, fleet audits, id-only lookups that miss the
  owner cache) run on every shard CONCURRENTLY and merge.  A fan-out leg
  rides its shard's own :class:`~orion_tpu.storage.retry.RetryPolicy`
  (reads only — mutations keep the op-level policy's applied-or-not
  discipline), so one slow or dead shard never serializes the rest.
- **Read-replica fan-out with staleness failover**: when a shard declares
  replicas, reads (``read``/``count`` and all-read batches — the
  ``fetch_trials``/status-poll/``fetch_health`` hot path) go to a replica
  round-robin.  Replication is asynchronous, so every replica reply
  carries the replica's applied sequence (``netdb.py``); the router
  compares it against the highest sequence ITS writes ever got from that
  shard's primary and fails the read over to the primary when the replica
  is behind — monotonic read-your-writes per router, counted as
  ``storage.shard.replica_stale_reads``.  Transport errors fail over too
  (``storage.shard.failovers``) and bench the replica briefly.
- **Degraded mode**: shards are independent connections with independent
  retry state, so ops routed to healthy shards proceed while ops on a
  dead shard ride the ordinary retry/deadline policy — no global stall.
  Aggregated fan-out failures propagate the STRICTEST ``maybe_applied``
  of their parts (:func:`merge_maybe_applied`; lint rule STO004 pins the
  discipline).
- **Provable pass-through**: a single-shard, no-replica config delegates
  every op verbatim to the one underlying ``NetworkDB`` — no minting, no
  fan-out machinery, byte-identical wire traffic (differential-pinned in
  tests/unit/test_shard.py).

The soak harness (``orion_tpu/storage/soak.py``, ``bench.py --soak``)
drives 1000+ simulated workers against a 3-shard x 2-replica topology of
real servers under fault-proxy partitions and shard restarts; the pass
bar is a clean ``orion-tpu audit --all`` on every shard and zero lost
observations.
"""

import hashlib
import threading
from bisect import bisect_right
from collections import OrderedDict

from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.storage.netdb import NetworkDB
from orion_tpu.storage.retry import MODE_ALWAYS, create_retry_policy, is_transient
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import DatabaseError

#: Virtual nodes per shard on the hash ring.  Enough that removing/adding
#: one shard moves ~1/N of the keyspace with low variance; small enough
#: that ring construction stays trivial.
DEFAULT_VNODES = 64

#: Bounded (collection, _id) -> shard map harvested from routed results, so
#: id-only queries (``set_trial_status``'s CAS, ``get_trial``) route
#: directly instead of fanning out.  A miss is never wrong — it just costs
#: a fan-out that re-populates the entry.
OWNER_CACHE_CAP = 65536

#: Per-shard policy for fan-out READ legs: tighter than the op-level
#: policy (which still wraps the whole op above this layer) — its job is
#: riding out a blip on ONE shard without re-running the healthy ones.
DEFAULT_SHARD_RETRY = {
    "max_attempts": 3,
    "base_delay": 0.02,
    "max_delay": 0.25,
    "deadline": 5.0,
}

#: Seconds a replica sits out after a transport failure before reads try
#: it again (connection state is per shard, per replica).
REPLICA_RETRY_S = 1.0


def merge_maybe_applied(errors):
    """The STRICTEST applied-or-not verdict of a fan-out's parts: if ANY
    leg may have applied, the aggregate may have applied — anything weaker
    would let the retry policy blind-resend a non-converging mutation one
    shard already executed."""
    return any(getattr(error, "maybe_applied", False) for error in errors)


def shard_fanout_error(message, errors):
    """The one blessed way to aggregate per-shard ``DatabaseError``s
    (STO004): build the summary error and stamp the merged verdict."""
    parts = "; ".join(f"{type(e).__name__}: {e}" for e in errors) or "no detail"
    error = DatabaseError(f"{message}: {parts}")
    error.maybe_applied = merge_maybe_applied(errors)
    return error


def mint_experiment_id(doc):
    """Deterministic experiment id from the unique identity the
    experiments collection enforces — ``(name, version, metadata.user)``
    — computed by THE framework formula (``core.experiment
    .experiment_id``), not a lookalike: an experiment created through the
    builder (which pre-sets ``_id`` with that formula) and a raw
    ``create_experiment`` for the same identity must mint the SAME id,
    land on the SAME shard, and collide on its unique index exactly as on
    one server.  A divergent formula would silently split one experiment
    across two shards."""
    from orion_tpu.core.experiment import experiment_id

    return experiment_id(
        doc.get("name"),
        doc.get("version", 1),
        (doc.get("metadata") or {}).get("user"),
    )


class HashRing:
    """Consistent-hash ring with virtual nodes.

    Each shard contributes ``vnodes`` md5 points keyed by its stable
    identity (the primary's ``host:port``); a key hashes once and lands on
    the first point clockwise.  Every router instance built from the same
    shard list computes identical placement — there is no coordination
    channel, the ring IS the agreement.
    """

    def __init__(self, identities, vnodes=DEFAULT_VNODES):
        if not identities:
            raise DatabaseError("a hash ring needs at least one shard")
        self.vnodes = int(vnodes)
        points = []
        for index, identity in enumerate(identities):
            for v in range(self.vnodes):
                points.append((self._hash(f"{identity}#{v}"), index))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._indices = [i for _, i in points]

    @staticmethod
    def _hash(key):
        return int.from_bytes(
            hashlib.md5(str(key).encode("utf-8")).digest()[:8], "big"
        )

    def lookup(self, key):
        """Shard index owning ``key``."""
        position = bisect_right(self._hashes, self._hash(key))
        if position == len(self._hashes):
            position = 0
        return self._indices[position]


def parse_shard_specs(shards, default_secret=None):
    """Normalize a ``storage.shards`` config list into
    ``[{"host", "port", "replicas": [(host, port), ...]}, ...]``.  Entries
    may be ``"host:port"`` strings or dicts with ``host``/``port`` or
    ``address`` plus an optional ``replicas`` list of the same shapes."""

    def addr_of(entry):
        if isinstance(entry, str):
            host, _, port = entry.rpartition(":")
            if not host or not port:
                raise DatabaseError(
                    f"bad shard address {entry!r}; expected host:port"
                )
            return host, int(port)
        if isinstance(entry, (tuple, list)):
            host, port = entry
            return host, int(port)
        host = entry.get("host", "127.0.0.1")
        port = entry.get("port")
        address = entry.get("address")
        if address:
            return addr_of(str(address))
        if port is None:
            raise DatabaseError(f"shard entry {entry!r} needs a port or address")
        return host, int(port)

    specs = []
    for entry in shards or ():
        host, port = addr_of(entry)
        replicas = []
        if isinstance(entry, dict):
            replicas = [addr_of(r) for r in entry.get("replicas") or ()]
        specs.append(
            {
                "host": host,
                "port": port,
                "replicas": replicas,
                "secret": (
                    entry.get("secret", default_secret)
                    if isinstance(entry, dict)
                    else default_secret
                ),
            }
        )
    if not specs:
        raise DatabaseError("storage.shards is empty")
    return specs


class _Shard:
    """One shard's connections + read-path state: the primary client, its
    replica clients, the write-sequence floor replica reads are checked
    against, and the per-shard fan-out retry policy."""

    def __init__(self, index, spec, client_kwargs, policy):
        self.index = index
        self.host = spec["host"]
        self.port = int(spec["port"])
        self.primary = NetworkDB(host=self.host, port=self.port, **client_kwargs)
        self.replicas = [
            NetworkDB(host=h, port=p, **client_kwargs)
            for h, p in spec.get("replicas") or ()
        ]
        self.policy = policy
        self._lock = threading.Lock()
        self._write_floor = 0
        self._rr = 0
        self._down_until = [0.0] * len(self.replicas)
        #: Read-path health counters, exported per shard as
        #: ``storage.shard.s{i}.failovers`` / ``.replica_stale_reads``.
        self.failovers = 0
        self.replica_stale_reads = 0

    @property
    def identity(self):
        return f"{self.host}:{self.port}"

    @property
    def reconnects(self):
        return self.primary.reconnects + sum(r.reconnects for r in self.replicas)

    def note_write(self):
        """Raise the staleness floor to the primary's latest stamped seq
        (replicating primaries stamp mutating replies; plain ones never do,
        and the floor stays 0 = every replica read is acceptable)."""
        seq = self.primary.seq_snapshot()
        if seq is None:
            return
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            if seq > self._write_floor:
                self._write_floor = seq

    def write_floor(self):
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            return self._write_floor

    def pick_replica(self, now):
        """Round-robin replica index skipping benched ones, or None."""
        if not self.replicas:
            return None
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            n = len(self.replicas)
            for offset in range(n):
                candidate = (self._rr + offset) % n
                if self._down_until[candidate] <= now:
                    self._rr = (candidate + 1) % n
                    return candidate
        return None

    def bench_replica(self, index, now):
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            self._down_until[index] = now + REPLICA_RETRY_S
            self.failovers += 1

    def note_stale(self):
        with self._lock:
            TSAN.write("ShardedNetworkDB._shard_state", self)
            self.replica_stale_reads += 1

    def close(self):
        self.primary.close()
        for replica in self.replicas:
            replica.close()


#: Query/doc values that can route: concrete scalars, never operator dicts.
def _concrete(value):
    if value is None or isinstance(value, (dict, list, tuple)):
        return None
    return value


class ShardedNetworkDB:
    """AbstractDB-contract consistent-hash router over N netdb shards.

    See the module docstring for the full contract.  Constructed by
    ``create_storage`` from a ``storage.shards`` config stanza; sits under
    ``DocumentStorage`` exactly like a single ``NetworkDB``.
    """

    #: Counts and targeted reads are one small request on one shard.
    cheap_counts = True

    def __init__(
        self,
        shards,
        vnodes=DEFAULT_VNODES,
        timeout=60.0,
        idle_probe=1.0,
        secret=None,
        reconnect_jitter=0.1,
        shard_retry=None,
        replica_reads=True,
    ):
        specs = parse_shard_specs(shards, default_secret=secret)
        client_base = {
            "timeout": timeout,
            "idle_probe": idle_probe,
            "reconnect_jitter": reconnect_jitter,
        }
        retry_config = (
            dict(DEFAULT_SHARD_RETRY) if shard_retry is None else shard_retry
        )
        self._shards = []
        for index, spec in enumerate(specs):
            # Each shard gets its OWN policy instance: independent jitter
            # streams and deadlines, so one shard's outage never consumes
            # another's retry budget.
            policy = create_retry_policy(retry_config)
            kwargs = dict(client_base, secret=spec.get("secret"))
            self._shards.append(_Shard(index, spec, kwargs, policy))
        self._ring = HashRing([s.identity for s in self._shards], vnodes=vnodes)
        self.replica_reads = bool(replica_reads)
        #: Pure pass-through mode: one shard, no replicas — every op
        #: delegates verbatim to the single NetworkDB (bit-identical wire
        #: traffic; differential-pinned).
        self._passthrough = (
            len(self._shards) == 1 and not self._shards[0].replicas
        )
        self._owner_lock = threading.Lock()
        self._owners = OrderedDict()  # (collection, _id) -> shard index
        self._stats_lock = threading.Lock()
        self.fan_outs = 0
        self._monotonic = None  # injectable clock for tests
        for shard in self._shards:
            prefix = f"storage.shard.s{shard.index}"
            TELEMETRY.register_external_counter(
                f"{prefix}.reconnects", shard, "reconnects"
            )
            TELEMETRY.register_external_counter(
                f"{prefix}.failovers", shard, "failovers"
            )
            TELEMETRY.register_external_counter(
                f"{prefix}.replica_stale_reads", shard, "replica_stale_reads"
            )

    # --- aggregate counters (DocumentStorage re-exports these) ---------------
    @property
    def reconnects(self):
        return sum(s.reconnects for s in self._shards)

    @property
    def round_trips(self):
        return sum(
            s.primary.round_trips + sum(r.round_trips for r in s.replicas)
            for s in self._shards
        )

    @property
    def wire_requests(self):
        return sum(
            s.primary.wire_requests + sum(r.wire_requests for r in s.replicas)
            for s in self._shards
        )

    @property
    def failovers(self):
        return sum(s.failovers for s in self._shards)

    @property
    def replica_stale_reads(self):
        return sum(s.replica_stale_reads for s in self._shards)

    # --- topology surface (CLI: db ring, audit, info) ------------------------
    @property
    def n_shards(self):
        return len(self._shards)

    def shard_for(self, experiment_id):
        """Ring placement of an experiment id (audit/CLI surface)."""
        return self._ring.lookup(str(experiment_id))

    def describe_topology(self):
        return {
            "shards": [
                {
                    "index": s.index,
                    "address": s.identity,
                    "replicas": [f"{r.host}:{r.port}" for r in s.replicas],
                }
                for s in self._shards
            ],
            "vnodes": self._ring.vnodes,
            "replica_reads": self.replica_reads,
        }

    def shard_connections(self):
        """``[(index, primary NetworkDB), ...]`` — the per-shard direct
        surface the soak/audit tooling uses to verify every shard alone."""
        return [(s.index, s.primary) for s in self._shards]

    def close(self):
        for shard in self._shards:
            shard.close()

    # --- routing core --------------------------------------------------------
    def _now(self):
        if self._monotonic is not None:
            return self._monotonic()
        import time

        return time.monotonic()

    def _route(self, collection, doc=None, query=None):
        """Shard index for a doc/query, or None (fan out).  Experiments
        route by their own ``_id``; everything else routes by the
        ``experiment`` field, falling back to the owner cache for id-only
        queries and to the id's own ring point for id-carrying docs."""
        if collection == "experiments":
            key = None
            if query is not None:
                key = _concrete(query.get("_id"))
            if key is None and doc is not None:
                key = _concrete(doc.get("_id"))
            return None if key is None else self._ring.lookup(str(key))
        exp = None
        if query is not None:
            exp = _concrete(query.get("experiment"))
        if exp is None and doc is not None:
            exp = _concrete(doc.get("experiment"))
        if exp is not None:
            return self._ring.lookup(str(exp))
        if doc is not None:
            _id = _concrete(doc.get("_id"))
            if _id is not None:
                return self._ring.lookup(str(_id))
        if query is not None:
            _id = _concrete(query.get("_id"))
            if _id is not None:
                return self._owner_of(collection, _id)
        return None

    def _owner_of(self, collection, _id):
        with self._owner_lock:
            TSAN.write("ShardedNetworkDB._owners", self)
            return self._owners.get((collection, _id))

    def _remember_owner(self, collection, _id, index):
        if _id is None:
            return
        with self._owner_lock:
            TSAN.write("ShardedNetworkDB._owners", self)
            owners = self._owners
            owners[(collection, _id)] = index
            owners.move_to_end((collection, _id))
            while len(owners) > OWNER_CACHE_CAP:
                owners.popitem(last=False)

    def _harvest_owners(self, collection, docs, index):
        """Remember the shard of every id-bearing doc a routed/fanned read
        returned, so later id-only CAS ops route directly."""
        for doc in docs or ():
            if isinstance(doc, dict):
                self._remember_owner(collection, doc.get("_id"), index)

    # --- fan-out machinery ---------------------------------------------------
    def _collect_shards(self, fn, read_only=False, op="fan_out"):
        """Run ``fn(shard)`` on every shard CONCURRENTLY; returns
        ``(results, errors)`` as per-shard lists (exactly one of the pair
        is non-None per slot).  Read legs ride the shard's own policy so a
        blip on one shard heals locally; mutation legs run bare — the
        op-level policy above owns their applied-or-not discipline."""
        shards = self._shards
        with self._stats_lock:
            TSAN.write("ShardedNetworkDB._stats", self)
            self.fan_outs += 1
        TELEMETRY.count("storage.shard.fan_outs")
        results = [None] * len(shards)
        errors = [None] * len(shards)

        def leg(i, shard):
            try:
                if read_only and shard.policy is not None:
                    results[i] = shard.policy.run(
                        lambda: fn(shard), op=f"shard.s{i}.{op}", mode=MODE_ALWAYS
                    )
                else:
                    results[i] = fn(shard)
            except Exception as exc:
                errors[i] = exc

        if len(shards) == 1:
            leg(0, shards[0])
        else:
            threads = [
                threading.Thread(target=leg, args=(i, shard), daemon=True)
                for i, shard in enumerate(shards)
            ]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join()
        return results, errors

    def _each_shard(self, fn, read_only=False, op="fan_out"):
        """Fan out and REQUIRE every shard: aggregated failures raise with
        the strictest ``maybe_applied`` of the parts."""
        results, errors = self._collect_shards(fn, read_only=read_only, op=op)
        failed = [e for e in errors if e is not None]
        if failed:
            raise shard_fanout_error(
                f"{op} failed on {len(failed)}/{len(self._shards)} shard(s)",
                failed,
            )
        return results

    # --- replica read path ---------------------------------------------------
    def _shard_read(self, shard, op, *args, **kwargs):
        """One read on one shard: replica round-robin with staleness check,
        failover to the primary on transport error or lag."""
        if self.replica_reads and shard.replicas:
            now = self._now()
            index = shard.pick_replica(now)
            if index is not None:
                replica = shard.replicas[index]
                try:
                    result = getattr(replica, op)(*args, **kwargs)
                except Exception as exc:
                    if not is_transient(exc):
                        raise
                    # Dead/partitioned replica: bench it briefly and take
                    # the primary — a failover, the first-class signal a
                    # flapping replica tier emits.
                    shard.bench_replica(index, now)
                    TELEMETRY.count("storage.shard.failovers")
                else:
                    stamp = replica.seq_snapshot()
                    floor = shard.write_floor()
                    if not floor or (stamp is not None and stamp >= floor):
                        return result
                    # The replica answered from BEFORE this router's last
                    # acknowledged write on the shard (the stamp check is
                    # per connection, so a concurrent reader can only make
                    # it stricter-to-pass, never falsely fresh for the
                    # floor it read).  Re-read from the primary.
                    shard.note_stale()
                    TELEMETRY.count("storage.shard.replica_stale_reads")
        return getattr(shard.primary, op)(*args, **kwargs)

    def _shard_mutate(self, shard, op, *args, **kwargs):
        """One mutation on one shard's PRIMARY; lifts the staleness floor
        from the stamped reply."""
        result = getattr(shard.primary, op)(*args, **kwargs)
        shard.note_write()
        return result

    # --- AbstractDB contract -------------------------------------------------
    def ping(self):
        if self._passthrough:
            return self._shards[0].primary.ping()
        results = self._each_shard(
            lambda shard: shard.primary.ping(), read_only=True, op="ping"
        )
        return all(results)

    def ensure_index(self, collection, keys, unique=False):
        if self._passthrough:
            return self._shards[0].primary.ensure_index(
                collection, keys, unique=unique
            )
        self._each_shard(
            lambda shard: shard.primary.ensure_index(collection, keys, unique=unique),
            op="ensure_index",
        )

    def ensure_indexes(self, specs):
        if self._passthrough:
            return self._shards[0].primary.ensure_indexes(specs)
        specs = [list(s) for s in specs]
        self._each_shard(
            lambda shard: shard.primary.ensure_indexes(specs), op="ensure_indexes"
        )

    def index_information(self, collection):
        if self._passthrough:
            return self._shards[0].primary.index_information(collection)
        merged = {}
        for info in self._each_shard(
            lambda shard: shard.primary.index_information(collection),
            read_only=True,
            op="index_information",
        ):
            merged.update(info or {})
        return merged

    def drop_index(self, collection, name):
        if self._passthrough:
            return self._shards[0].primary.drop_index(collection, name)
        results, errors = self._collect_shards(
            lambda shard: shard.primary.drop_index(collection, name),
            op="drop_index",
        )
        key_errors = [e for e in errors if isinstance(e, KeyError)]
        hard = [e for e in errors if e is not None and not isinstance(e, KeyError)]
        if hard:
            raise shard_fanout_error(
                f"drop_index({collection!r}, {name!r}) failed", hard
            )
        if key_errors and len(key_errors) == len(self._shards):
            # Missing EVERYWHERE is the single-server "index not found";
            # missing somewhere is a partially-applied earlier drop that
            # this call just finished converging.
            raise key_errors[0]

    def write(self, collection, data, query=None):
        if self._passthrough:
            return self._shards[0].primary.write(collection, data, query=query)
        if query is not None:
            index = self._route(collection, query=query)
            if index is not None:
                return self._shard_mutate(
                    self._shards[index], "write", collection, data, query=query
                )
            results = self._each_shard(
                lambda shard: self._shard_mutate(
                    shard, "write", collection, data, query=query
                ),
                op="write",
            )
            return sum(r or 0 for r in results)
        return self._insert(collection, data)

    def _insert(self, collection, data):
        single = isinstance(data, dict)
        docs = [data] if single else list(data)
        if collection == "experiments":
            docs = [self._with_minted_id(doc) for doc in docs]
        groups = OrderedDict()  # shard index -> [(position, doc)]
        for position, doc in enumerate(docs):
            index = self._route(collection, doc=doc)
            if index is None:
                # No experiment, no id: an auto-id document with no routable
                # identity (third-party collections).  Ring-place by the
                # collection name so placement stays deterministic.
                index = self._ring.lookup(collection)
            groups.setdefault(index, []).append((position, doc))
        if single:
            # One document, one shard: preserve the single-insert return
            # shape (the inserted id, minted or server-assigned).
            (index, members), = groups.items()
            doc = members[0][1]
            result = self._shard_mutate(self._shards[index], "write", collection, doc)
            self._remember_owner(collection, doc.get("_id"), index)
            return result
        out = [None] * len(docs)
        for index, members in groups.items():
            payload = [doc for _, doc in members]
            ids = self._shard_mutate(
                self._shards[index], "write", collection, payload
            )
            for (position, doc), _id in zip(members, ids):
                out[position] = _id
                self._remember_owner(collection, doc.get("_id"), index)
        return out

    def _with_minted_id(self, doc):
        if "_id" in doc:
            return doc
        doc = dict(doc)
        doc["_id"] = mint_experiment_id(doc)
        return doc

    def update_many(self, collection, pairs):
        if self._passthrough:
            return self._shards[0].primary.update_many(collection, pairs)
        routed = OrderedDict()
        broadcast = []
        for query, update in pairs:
            index = self._route(collection, query=query)
            if index is None:
                broadcast.append((query, update))
            else:
                routed.setdefault(index, []).append((query, update))
        total = 0
        for index, shard_pairs in routed.items():
            total += self._shard_mutate(
                self._shards[index], "update_many", collection, shard_pairs
            )
        if broadcast:
            # Un-keyed updates apply to matching docs WHEREVER they live —
            # the correct cross-shard semantics of a query-driven update.
            results = self._each_shard(
                lambda shard: self._shard_mutate(
                    shard, "update_many", collection, broadcast
                ),
                op="update_many",
            )
            total += sum(r or 0 for r in results)
        return total

    def read(self, collection, query=None, projection=None):
        if self._passthrough:
            return self._shards[0].primary.read(
                collection, query=query, projection=projection
            )
        index = self._route(collection, query=query)
        if index is not None:
            docs = self._shard_read(
                self._shards[index], "read", collection, query=query,
                projection=projection,
            )
            self._harvest_owners(collection, docs, index)
            return docs
        merged = []
        results = self._each_shard(
            lambda shard: self._shard_read(
                shard, "read", collection, query=query, projection=projection
            ),
            read_only=True,
            op="read",
        )
        for shard, docs in zip(self._shards, results):
            self._harvest_owners(collection, docs, shard.index)
            merged.extend(docs or [])
        return merged

    def count(self, collection, query=None):
        if self._passthrough:
            return self._shards[0].primary.count(collection, query=query)
        index = self._route(collection, query=query)
        if index is not None:
            return self._shard_read(
                self._shards[index], "count", collection, query=query
            )
        results = self._each_shard(
            lambda shard: self._shard_read(shard, "count", collection, query=query),
            read_only=True,
            op="count",
        )
        return sum(r or 0 for r in results)

    def read_and_write(self, collection, query, data):
        if self._passthrough:
            return self._shards[0].primary.read_and_write(collection, query, data)
        index = self._route(collection, query=query)
        if index is not None:
            doc = self._shard_mutate(
                self._shards[index], "read_and_write", collection, query, data
            )
            if isinstance(doc, dict):
                self._remember_owner(collection, doc.get("_id"), index)
            return doc
        if _concrete((query or {}).get("_id")) is None:
            # A find-ONE-and-update keyed by neither _id nor experiment has
            # no correct cross-shard spelling: running it on every shard
            # would CAS up to N documents where one server swaps exactly
            # one.  Refuse loudly (pre-flight: nothing ran anywhere).
            error = DatabaseError(
                f"read_and_write({collection!r}) query {query!r} carries "
                "neither an _id nor an experiment key — a single-document "
                "CAS cannot be routed (and must not run on every shard)"
            )
            error.maybe_applied = merge_maybe_applied(())
            raise error
        # Id-only owner-cache miss: ids are globally unique, so at most
        # ONE shard matches; the others no-op to None.
        results, errors = self._collect_shards(
            lambda shard: shard.primary.read_and_write(collection, query, data),
            op="read_and_write",
        )
        winner = None
        for shard, doc in zip(self._shards, results):
            if isinstance(doc, dict):
                winner = doc
                self._remember_owner(collection, doc.get("_id"), shard.index)
                shard.note_write()
        failed = [e for e in errors if e is not None]
        if winner is not None:
            # The unique-id invariant (the query carries a concrete _id,
            # enforced above) means the matching shard answered; an error
            # on a NON-matching shard cannot have applied this CAS (its
            # query matched nothing there).
            return winner
        if failed:
            raise shard_fanout_error(
                f"read_and_write({collection!r}) failed on "
                f"{len(failed)}/{len(self._shards)} shard(s)",
                failed,
            )
        return None

    def remove(self, collection, query=None):
        if self._passthrough:
            return self._shards[0].primary.remove(collection, query=query)
        index = self._route(collection, query=query)
        if index is not None:
            return self._shard_mutate(
                self._shards[index], "remove", collection, query=query
            )
        results = self._each_shard(
            lambda shard: self._shard_mutate(shard, "remove", collection, query=query),
            op="remove",
        )
        return sum(r or 0 for r in results)

    # --- batch primitives ----------------------------------------------------
    def apply_batch(self, ops):
        if self._passthrough:
            return self._shards[0].primary.apply_batch(ops)
        return self._batch(ops, "apply_batch")

    def pipeline(self, ops):
        if self._passthrough:
            return self._shards[0].primary.pipeline(ops)
        return self._batch(ops, "pipeline")

    def _route_sub_op(self, op, args, kwargs):
        collection = args[0] if args else None
        if op == "write":
            data = args[1] if len(args) > 1 else None
            query = (kwargs or {}).get("query")
            if query is None and len(args) > 2:
                query = args[2]
            if query is not None:
                return self._route(collection, query=query)
            doc = None
            if isinstance(data, dict):
                doc = data
            elif isinstance(data, (list, tuple)) and data:
                doc = data[0] if isinstance(data[0], dict) else None
            return self._route(collection, doc=doc)
        query = args[1] if len(args) > 1 else (kwargs or {}).get("query")
        if not isinstance(query, dict):
            query = None
        return self._route(collection, query=query)

    def _batch(self, ops, primitive):
        """Split a batch by target shard, dispatch the per-shard
        sub-batches CONCURRENTLY through the shard's own batch primitive,
        and reassemble per-slot outcomes in the original order.
        Unroutable slots execute through the op-level router methods
        (which fan out) and land their outcome — or their exception — in
        place.  A shard whose whole sub-batch died raises the aggregated
        error with the strictest ``maybe_applied``: healthy shards' slots
        applied durably, and the op-level retry's re-run converges through
        the same dedup contracts a single server's retry does."""
        ops = list(ops)
        if not ops:
            return []
        groups = OrderedDict()  # shard index -> [(position, sub_op)]
        loose = []  # [(position, sub_op)] — unroutable
        for position, (op, args, kwargs) in enumerate(ops):
            index = self._route_sub_op(op, list(args), kwargs)
            if index is None:
                loose.append((position, (op, args, kwargs)))
            else:
                groups.setdefault(index, []).append((position, (op, args, kwargs)))
        out = [None] * len(ops)
        errors = []

        def run_group(index, members):
            shard = self._shards[index]
            sub_ops = [sub for _, sub in members]
            mutating = any(
                op not in ("read", "count") for op, _, _ in sub_ops
            )
            try:
                if mutating:
                    outcomes = getattr(shard.primary, primitive)(sub_ops)
                    shard.note_write()
                else:
                    outcomes = self._shard_read(shard, primitive, sub_ops)
            except Exception as exc:
                errors.append(exc)
                return
            for (position, sub), outcome in zip(members, outcomes):
                out[position] = outcome
                if sub[0] in ("read", "read_and_write"):
                    docs = outcome if isinstance(outcome, list) else [outcome]
                    self._harvest_owners(sub[1][0] if sub[1] else None, [
                        d for d in docs if isinstance(d, dict)
                    ], index)

        if len(groups) <= 1:
            for index, members in groups.items():
                run_group(index, members)
        else:
            threads = [
                threading.Thread(
                    target=run_group, args=(index, members), daemon=True
                )
                for index, members in groups.items()
            ]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join()
        for position, (op, args, kwargs) in loose:
            try:
                out[position] = getattr(self, op)(*args, **kwargs)
            except Exception as exc:
                # Slot containment, same contract as a server-side refused
                # slot: the exception IS the outcome.
                out[position] = exc
        if errors:
            raise shard_fanout_error(
                f"{primitive} failed on {len(errors)} shard(s)", errors
            )
        return out
