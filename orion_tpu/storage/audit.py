"""Storage invariant auditor: the cross-trial consistency oracle.

The coordination protocol rests on a handful of invariants no single
operation checks end to end — each op is individually atomic, but a
crashed worker, a mid-batch fault, or a buggy migration can still leave
the *collection* in a state the optimizer silently mis-learns from.
This module walks an experiment's raw trial documents and reports every
violation of:

- **unique identity**: no duplicate ``_id``s, and no two distinct live
  trials sitting on the same parameter point (the deterministic
  md5-of-params identity + unique index are supposed to make that
  impossible; an auditor that trusts the mechanism it audits is
  useless after a ``db copy`` or a hand-edit);
- **status machine sanity**: every status is a known one, ``reserved``
  trials carry the ``heartbeat``/``start_time`` the pacemaker and
  lost-trial sweep key on;
- **completed ⇒ results**: a ``completed`` trial has a results list with
  an objective entry — a completed trial without one is a LOST
  observation (the algorithm can never learn from it);
- **no orphaned reservations**: no trial has sat ``reserved`` with a
  heartbeat older than the sweep threshold — the state a dead worker
  leaves behind when the recovery sweep is not running.

Surfaced as ``orion-tpu audit`` (cli/audit.py), as
``Experiment.audit()``, and as the final assertion of the chaos suite
(tests/functional/test_chaos.py): an experiment driven to completion
under a seeded fault schedule must audit clean — zero duplicated trials,
zero lost observations.
"""

import time

from orion_tpu.core.trial import ALL_STATUSES, Trial

#: Default orphaned-reservation threshold when the caller has no
#: experiment-level heartbeat to hand (matches DEFAULT_HEARTBEAT).
DEFAULT_LOST_TIMEOUT = 120.0


class AuditReport:
    """Violations + collection stats for one audited experiment."""

    def __init__(self, experiment_id, n_trials, status_counts, violations):
        self.experiment_id = experiment_id
        self.n_trials = n_trials
        self.status_counts = dict(status_counts)
        self.violations = list(violations)

    @property
    def ok(self):
        return not self.violations

    def summary(self):
        lines = [
            f"experiment {self.experiment_id}: {self.n_trials} trials "
            + ", ".join(
                f"{n} {status}"
                # str() key: a malformed doc's status may be None or any
                # type — that is a finding to print, not a sort crash.
                for status, n in sorted(
                    self.status_counts.items(), key=lambda kv: str(kv[0])
                )
            )
        ]
        if self.ok:
            lines.append("audit: OK (no invariant violations)")
        else:
            lines.append(f"audit: {len(self.violations)} violation(s)")
            for v in self.violations:
                lines.append(f"  [{v['check']}] trial {v['trial']}: {v['message']}")
        return "\n".join(lines)


def _violation(check, trial_id, message):
    return {"check": check, "trial": trial_id, "message": message}


def _trial_docs(storage, exp_id):
    """Raw trial documents — raw, not Trial objects, so a malformed doc is
    a *finding*, never a crash that hides the rest of the audit."""
    read_docs = getattr(storage, "read_trial_docs", None)
    if read_docs is not None:
        return read_docs(exp_id)
    return [t.to_dict() for t in storage.fetch_trials(uid=exp_id)]


def audit_experiment(storage, experiment, lost_timeout=None, now=None):
    """Audit one experiment's trials; returns an :class:`AuditReport`.

    ``experiment`` may be an Experiment (its ``heartbeat`` supplies the
    orphaned-reservation threshold), a config dict, or a bare id.
    ``lost_timeout`` overrides the threshold; ``now`` pins the clock for
    deterministic tests.
    """
    exp_id = getattr(experiment, "id", None)
    if exp_id is None:
        exp_id = experiment["_id"] if isinstance(experiment, dict) else experiment
    if lost_timeout is None:
        if isinstance(experiment, dict):
            lost_timeout = experiment.get("heartbeat") or DEFAULT_LOST_TIMEOUT
        else:
            lost_timeout = getattr(experiment, "heartbeat", DEFAULT_LOST_TIMEOUT)
    now = time.time() if now is None else now

    docs = _trial_docs(storage, exp_id)
    violations = []
    status_counts = {}
    seen_ids = set()
    point_owner = {}  # hash_params -> first trial id on that point

    for doc in docs:
        tid = doc.get("_id")
        status = doc.get("status")
        status_counts[status] = status_counts.get(status, 0) + 1

        if tid in seen_ids:
            violations.append(
                _violation("unique-id", tid, "duplicate trial id in storage")
            )
        seen_ids.add(tid)

        if status not in ALL_STATUSES:
            violations.append(
                _violation("status", tid, f"unknown status {status!r}")
            )

        point = Trial.compute_id(doc.get("experiment"), doc.get("params") or {})
        other = point_owner.setdefault(point, tid)
        if other != tid:
            violations.append(
                _violation(
                    "duplicate-point",
                    tid,
                    f"same parameter point as trial {other} — duplicated trial",
                )
            )

        if status == "reserved":
            heartbeat = doc.get("heartbeat")
            if heartbeat is None:
                violations.append(
                    _violation(
                        "heartbeat", tid, "reserved trial without a heartbeat"
                    )
                )
            elif now - heartbeat > lost_timeout:
                violations.append(
                    _violation(
                        "orphaned-reservation",
                        tid,
                        f"heartbeat is {now - heartbeat:.1f}s stale "
                        f"(sweep threshold {lost_timeout:.1f}s) — the "
                        "lost-trial sweep is not recovering it",
                    )
                )
            if doc.get("start_time") is None:
                violations.append(
                    _violation(
                        "heartbeat", tid, "reserved trial without a start_time"
                    )
                )

        if status == "completed":
            results = doc.get("results") or []
            has_objective = any(
                isinstance(r, dict) and r.get("type") == "objective"
                for r in results
            )
            if not has_objective:
                violations.append(
                    _violation(
                        "lost-observation",
                        tid,
                        "completed trial has no objective result — the "
                        "observation is lost to the algorithm",
                    )
                )
            if doc.get("end_time") is None:
                violations.append(
                    _violation("lost-observation", tid, "completed trial has no end_time")
                )

    return AuditReport(exp_id, len(docs), status_counts, violations)


def audit_storage(storage, lost_timeout=None, now=None):
    """Audit every experiment in the storage; returns a list of reports."""
    return [
        audit_experiment(
            storage, doc, lost_timeout=lost_timeout, now=now
        )
        for doc in storage.fetch_experiments({})
    ]
