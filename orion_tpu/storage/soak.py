"""Thousand-worker soak harness for the sharded control plane.

THE shared load driver behind ``bench.py --soak`` and
``tests/functional/test_soak.py`` (the two cannot drift apart), shipped in
the package so operators can soak their own topology the same way.  Two
pieces:

- :class:`SoakTopology` — an in-process N-shard x R-replica deployment of
  REAL :class:`~orion_tpu.storage.netdb.DBServer`\\ s: every primary
  replicates to its replicas and sits behind a PR-5
  :class:`~orion_tpu.storage.faults.FaultProxy`, so partitions
  (blackhole windows), reconnect storms (``drop_all``) and shard
  kill/restart (persisted primary restarted on the same port) exercise
  the REAL wire paths — client reconnects, replication resync, replica
  failover — not mocks.

- :func:`drive_soak` — N simulated workers (threads sharing a pool of
  routers, the way real worker processes share nothing) each register,
  reserve and complete trials through the full ``DocumentStorage``
  protocol while a seeded chaos controller runs storms/partitions/
  restarts on a fixed cycle.  The pass bar, asserted by the callers:

  * the run completes inside its deadline,
  * ZERO lost observations — every registered trial ends completed with
    an objective, counted through the router AND as the sum of direct
    per-shard reads (the two views must agree),
  * ``orion-tpu audit --all`` comes back clean through the router and on
    every shard individually,
  * replica failover and degraded-mode shard loss actually happened
    (``storage.shard.failovers`` / reconnects moved).
"""

import logging
import os
import threading
import time

from orion_tpu.core.trial import Result, Trial
from orion_tpu.storage.audit import audit_storage
from orion_tpu.storage.base import DocumentStorage
from orion_tpu.storage.faults import FaultProxy
from orion_tpu.storage.netdb import DBServer
from orion_tpu.storage.retry import is_transient
from orion_tpu.storage.shard import ShardedNetworkDB
from orion_tpu.utils.exceptions import DuplicateKeyError

log = logging.getLogger(__name__)

#: DocumentStorage retry knobs for soak runs: enough deadline to ride out
#: a blackhole window plus a shard restart, tight backoff so the run
#: stays fast.
SOAK_RETRY = {
    "max_attempts": 10,
    "base_delay": 0.01,
    "max_delay": 0.5,
    "deadline": 60.0,
}


class _ShardDeployment:
    """One shard's processes: R replicas, a replicating primary (persisted,
    so a restart is lossless), and the fault proxy clients dial through."""

    def __init__(self, index, replicas, persist_dir, secret=None,
                 client_timeout=5.0, quorum=0):
        self.index = index
        self.secret = secret
        self.client_timeout = client_timeout
        self.quorum = quorum
        self.persist = (
            os.path.join(persist_dir, f"shard{index}.pkl") if persist_dir else None
        )
        self.replica_servers = []
        for _ in range(replicas):
            # Replicas carry the quorum floor too: the one a promotion
            # elects becomes a primary and must keep enforcing it.
            server = DBServer(port=0, secret=secret, replica=True, quorum=quorum)
            server.serve_background()
            self.replica_servers.append(server)
        self.primary_host = "127.0.0.1"
        self.primary_port = 0
        self.primary = self._start_primary(port=0)
        self.primary_host, self.primary_port = self.primary.address
        self.primary.serve_background()
        self.proxy = FaultProxy(self.primary_host, self.primary_port)
        self.proxy.serve_background()
        self.restarts = 0
        self.killed = False
        self._make_db = None

    def _start_primary(self, port):
        return DBServer(
            host="127.0.0.1",
            port=port,
            persist=self.persist,
            persist_interval=0.05,
            secret=self.secret,
            replicate_to=[s.address for s in self.replica_servers if s is not None],
            quorum=self.quorum,
        )

    def serve_spec(self):
        """The router-facing spec: the primary THROUGH its proxy, replicas
        direct (partitions target the write path; replica loss is its own
        chaos action)."""
        return {
            "host": self.proxy.address[0],
            "port": self.proxy.address[1],
            "replicas": [s.address for s in self.replica_servers if s is not None],
            "secret": self.secret,
        }

    def restart_primary(self):
        """Shard kill/restart: the primary shuts down (final durable
        snapshot), every live connection drops, and a fresh server comes
        back on the SAME port from the persisted state — its pushers
        re-probe the replicas and resume (or snapshot-resync) the
        stream."""
        port = self.primary_port
        self.primary.shutdown()
        self.primary.server_close()
        deadline = time.monotonic() + 10.0
        while True:
            try:
                self.primary = self._start_primary(port=port)
                break
            except OSError:  # port not yet released
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)
        if self._make_db is not None:
            # Faults survive the restart: the schedule keeps counting ops
            # on the reborn primary (a restart silently un-wrapping the
            # store made short runs' "every fault class fired" assertions
            # hash-placement-flaky — a lightly loaded shard could restart
            # before its first plan index).
            self.primary.db = self._make_db(self.primary.db)
        self.primary.serve_background()
        self.restarts += 1

    def kill_replica(self, replica_index=0):
        """Replica loss: reads that picked it fail over to the primary."""
        server = self.replica_servers[replica_index]
        if server is None:
            return
        server.shutdown()
        server.server_close()
        self.replica_servers[replica_index] = None

    def wait_replicated(self, timeout=10.0):
        """Block until at least one live replica has acknowledged the
        primary's full position.  Replication is ASYNCHRONOUS — a primary
        killed with an unreplicated tail loses that tail by design; the
        zero-lost promotion scenario is 'the most-caught-up replica holds
        everything', which this wait establishes deterministically."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.primary.replication_status()
            want = status["seq"]
            acked = [
                link["acked_seq"]
                for link in status["links"]
                if link["acked_seq"] is not None
            ]
            if not want or (acked and max(acked) >= want):
                return True
            time.sleep(0.02)
        return False

    def kill_primary(self, wait_catchup=True):
        """PERMANENT primary loss — no restart, no graceful flush: the
        automatic-promotion scenario.  Routers that keep writing must
        elect the most-caught-up replica themselves (no human in the
        loop)."""
        import socketserver as _socketserver

        if wait_catchup:
            self.wait_replicated()
        primary = self.primary
        primary._stop_flusher.set()
        for link in primary._repl_links:
            link.stop(flush=False)
        if getattr(primary, "_serving", False):
            _socketserver.ThreadingTCPServer.shutdown(primary)
        primary.close_connections()
        primary.server_close()
        self.killed = True

    def install_faults(self, make_db):
        """Wrap the primary's store (e.g. in a seeded
        :class:`~orion_tpu.storage.faults.FaultyDB`) — BEFORE any client
        connects, so every handler sees the wrapped store.  The wrapper is
        re-applied across :meth:`restart_primary` (a fresh wrapper around
        the reborn store, driven by the SAME schedule — the op count and
        fault budget carry across the restart)."""
        self._make_db = make_db
        self.primary.db = make_db(self.primary.db)

    def stop(self):
        self.proxy.stop()
        for server in ([] if self.killed else [self.primary]) + self.replica_servers:
            if server is None:
                continue
            server.shutdown()
            server.server_close()


class SoakTopology:
    """An in-process sharded, replicated deployment under fault control."""

    def __init__(self, n_shards=3, replicas=2, persist_dir=None, secret=None,
                 quorum=0):
        self.replicas = replicas
        self.persist_dir = persist_dir
        self.secret = secret
        self.quorum = quorum
        self.shards = [
            _ShardDeployment(i, replicas, persist_dir, secret=secret,
                             quorum=quorum)
            for i in range(n_shards)
        ]

    def specs(self):
        return [shard.serve_spec() for shard in self.shards]

    def add_shard(self, replicas=None):
        """Grow the topology by one shard (the rebalance-mid-soak leg):
        the new deployment starts empty; `db rebalance` moves ~1/N of the
        experiments onto it once routers adopt the new spec list."""
        shard = _ShardDeployment(
            len(self.shards),
            self.replicas if replicas is None else replicas,
            self.persist_dir,
            secret=self.secret,
            quorum=self.quorum,
        )
        self.shards.append(shard)
        return shard

    def make_router(self, **kwargs):
        kwargs.setdefault("timeout", 5.0)
        kwargs.setdefault("reconnect_jitter", 0.05)
        # Soak runs compress time: a dead primary should promote within a
        # couple of op retries, not the production-grade 1.5s window.
        kwargs.setdefault("promote_after", 0.4)
        return ShardedNetworkDB(self.specs(), **kwargs)

    def drop_all(self):
        """Reconnect storm: every proxied primary connection dies at once."""
        for shard in self.shards:
            shard.proxy.drop_all()

    def partition(self, shard_index, seconds):
        """Blackhole one shard's primary for a window (ops on it stall and
        ride the retry/deadline policy; other shards proceed — the
        degraded-mode contract)."""
        proxy = self.shards[shard_index].proxy
        proxy.set_blackhole(True)
        try:
            time.sleep(seconds)
        finally:
            proxy.set_blackhole(False)
            proxy.drop_all()  # blackholed sockets are dead weight; drop them

    def stop(self):
        for shard in self.shards:
            shard.stop()


def _spread_names(router, n_experiments, n_shards):
    """Deterministic soak experiment names, greedily SPREAD across the
    ring: shard identities carry per-run ephemeral ports, so the fixed
    ``soak-{e}`` names can (rarely) all hash onto one shard — and an
    all-on-the-victim draw starves the chaos legs' signals (no traffic
    ever touches a killed replica, so the replica-failover gate can
    never fire).  Per slot, the candidate whose ring home currently
    holds the fewest experiments wins; pure function of the ring, so
    every caller agrees on placement.  Returns ``(names, loads)`` —
    the per-shard counts are the placement truth, computed ONCE."""
    from orion_tpu.core.experiment import experiment_id

    names = []
    loads = {index: 0 for index in range(n_shards)}
    for e in range(n_experiments):
        candidates = [f"soak-{e}"] + [
            f"soak-{e}-{suffix}" for suffix in "abcdefghijk"
        ]
        best_name, best_home = None, None
        for name in candidates:
            home = router.shard_for(experiment_id(name, 1, "soak"))
            if best_home is None or loads[home] < loads[best_home]:
                best_name, best_home = name, home
        names.append(best_name)
        loads[best_home] += 1
    return names, loads


def soak_experiment_names(router, n_experiments, n_shards):
    """The spread names alone — what ``drive_soak`` creates."""
    names, _loads = _spread_names(router, n_experiments, n_shards)
    return names


def busiest_shard(topology, router, n_experiments):
    """Shard index the ring gave the most soak experiments — the
    kill-primary chaos legs target it, so promotion must heal a shard
    under live write load, never an idle corner.  Reads the load map the
    name spreading already computed (one placement truth, not two)."""
    _names, loads = _spread_names(router, n_experiments, len(topology.shards))
    return max(loads, key=lambda index: loads[index])


def grow_and_rebalance(topology, storages, fence_grace=0.3,
                       placement_ttl=0.2, max_grows=5):
    """The rebalance-mid-soak hook body, shared by ``bench.py --soak`` and
    the tier-1 pin (the gate and the pin must exercise ONE scenario):
    grow the topology until the ring diff actually moves something —
    shard identities carry randomly assigned ports, so a tiny experiment
    set can (rarely) hash entirely onto the survivors and each extra
    shard re-rolls the draw — retarget every live router in place, then
    run the migrator to completion.  Returns
    ``{"planned": <plan summary>, "n_shards": N, "executed": True}``."""
    from orion_tpu.storage.rebalance import Rebalancer

    outcome = {}
    admin = None
    plan = None
    try:
        for _ in range(max_grows):
            topology.add_shard()
            specs = topology.specs()
            for storage in storages:
                storage.db.set_topology(specs)
            if admin is not None:
                admin.close()
            admin = topology.make_router(
                replica_reads=False, placement_ttl=placement_ttl
            )
            plan = Rebalancer(admin, fence_grace=fence_grace).plan()
            if plan.moves:
                break
        outcome["planned"] = plan.summary()
        outcome["n_shards"] = len(topology.shards)
        Rebalancer(admin, fence_grace=fence_grace).run(plan)
        outcome["executed"] = True
    finally:
        if admin is not None:
            admin.close()
    return outcome


def drain_and_remove(topology, storages, fence_grace=0.3,
                     placement_ttl=0.2, drain_index=None):
    """The drain-mid-soak hook body, shared by ``bench.py --soak`` and the
    tier-1 pin (the gate and the pin must exercise ONE scenario): drain
    one shard — the one holding the most experiments unless
    ``drain_index`` says otherwise, so removal always runs under live
    data — through the crash-resumable migrator (storage/drain.py),
    verify zero experiments remain on it, retarget every live router to
    the surviving topology, then stop the drained deployment.  Returns
    ``{"planned": <plan summary>, "ring_share": f, "residual": 0,
    "drained_index": i, "n_shards": N, "executed": True}``."""
    from orion_tpu.storage.drain import Drainer

    outcome = {}
    admin = topology.make_router(
        replica_reads=False, placement_ttl=placement_ttl
    )
    try:
        if drain_index is None:
            loads = {
                index: len(conn.read("experiments", {}))
                for index, conn in admin.shard_connections()
            }
            drain_index = max(loads, key=lambda index: loads[index])
        drainer = Drainer(admin, drain_index, fence_grace=fence_grace)
        plan = drainer.plan()
        outcome["planned"] = plan.summary()
        outcome["ring_share"] = drainer.ring_share()
        outcome["drained_index"] = drain_index
        drainer.run(plan)
        outcome["residual"] = len(drainer.residual_experiments())
        # Only now does the shard leave the topology: survivors' ring ==
        # the drainer's destination ring (same identities, same vnodes),
        # so placement doesn't shift again.
        drained = topology.shards.pop(drain_index)
        specs = topology.specs()
        for storage in storages:
            storage.db.set_topology(specs)
        drained.stop()
        outcome["n_shards"] = len(topology.shards)
        outcome["executed"] = True
    finally:
        admin.close()
    return outcome


class ReplicaProvisioner:
    """A fresh empty replica server per request — the soak/test stand-in
    for a real fleet's machine allocator, handed to the router as its
    ``replica_provisioner``.  Tracks what it started so the caller can
    stop them."""

    def __init__(self, secret=None, quorum=0):
        self.secret = secret
        self.quorum = quorum
        self.servers = []
        self._lock = threading.Lock()

    def __call__(self, shard_index):
        server = DBServer(
            port=0, secret=self.secret, replica=True, quorum=self.quorum
        )
        server.serve_background()
        with self._lock:
            self.servers.append(server)
        return "%s:%s" % server.address

    def stop(self):
        with self._lock:
            servers, self.servers = list(self.servers), []
        for server in servers:
            server.shutdown()
            server.server_close()


class SoakResult:
    """Outcome of one :func:`drive_soak` run."""

    def __init__(self):
        self.registered = 0
        self.completed = 0
        self.completed_per_shard = {}
        self.router_reports = []
        self.shard_reports = {}
        self.worker_errors = 0
        self.duration_s = 0.0
        self.failovers = 0
        self.replica_stale_reads = 0
        self.reconnects = 0
        self.restarts = 0
        self.promotions = 0
        self.primary_kills = 0

    @property
    def audits_clean(self):
        reports = list(self.router_reports)
        for shard_reports in self.shard_reports.values():
            reports.extend(shard_reports)
        return bool(reports) and all(r.ok for r in reports)

    @property
    def lost_observations(self):
        return self.registered - self.completed

    def summary(self):
        return {
            "registered": self.registered,
            "completed": self.completed,
            "lost_observations": self.lost_observations,
            "completed_per_shard": dict(self.completed_per_shard),
            "audits_clean": self.audits_clean,
            "worker_errors": self.worker_errors,
            "failovers": self.failovers,
            "replica_stale_reads": self.replica_stale_reads,
            "reconnects": self.reconnects,
            "shard_restarts": self.restarts,
            "promotions": self.promotions,
            "primary_kills": self.primary_kills,
            "duration_s": round(self.duration_s, 3),
        }


def _chaos_loop(topology, stop, period=1.0, partition_s=0.4, kill_replica=True):
    """The seeded chaos cycle: storm -> partition a shard -> restart a
    shard -> (once) kill a replica, round-robin over shards until the
    workers finish.  Deterministic ORDER; wall-clock timing is whatever
    the run's load makes it."""
    cycle = 0
    killed = False
    while not stop.wait(period):
        action = cycle % 3
        shard_index = cycle % len(topology.shards)
        try:
            if action == 0:
                topology.drop_all()
            elif action == 1:
                topology.partition(shard_index, partition_s)
            else:
                topology.shards[shard_index].restart_primary()
                if kill_replica and not killed and topology.shards and (
                    topology.shards[0].replica_servers
                ):
                    # Once per run: lose a replica outright, so the read
                    # path's failover-to-primary leg provably fires.
                    topology.shards[0].kill_replica(0)
                    killed = True
        except Exception:  # pragma: no cover - chaos must not kill the run
            log.exception("chaos action %d failed", action)
        cycle += 1


def drive_soak(
    topology,
    n_workers=1000,
    n_experiments=24,
    trials_per_worker=3,
    n_routers=32,
    retry=None,
    chaos=True,
    chaos_period=1.0,
    deadline=600.0,
    mid_hook=None,
):
    """Drive ``n_workers`` simulated workers against ``topology``.

    Workers are threads sharing ``n_routers`` router-backed storages (real
    worker fleets share nothing; a router per thread would need
    ``n_workers x n_shards`` sockets, so groups of workers share one the
    way threads inside one worker process share its storage).  Each worker
    registers its own UNIQUE trials on its assigned experiment, reserves
    whatever is pending, and completes what it reserved, riding the
    unified retry policy through whatever the chaos controller is doing.
    A convergence sweep then completes any trial a mid-chaos worker
    abandoned, and the invariant audit runs through the router AND on
    every shard directly.

    ``chaos=True`` runs the periodic controller (storms, partitions,
    restarts on a cycle — the long-soak shape); ``mid_hook`` instead (or
    additionally) runs ONE scripted chaos action at a deterministic
    point: every worker rendezvouses at its halfway trial and exactly one
    thread executes the hook (e.g. a shard restart) while the rest hold —
    in-flight state guaranteed, no timing luck.  Short tier-1 runs use
    ``mid_hook``; wall-clock soaks use the periodic controller.
    """
    from orion_tpu.core.experiment import experiment_id

    stop_at = time.monotonic() + deadline
    t0 = time.monotonic()
    result = SoakResult()
    retry = dict(SOAK_RETRY) if retry is None else retry
    storages = [
        DocumentStorage(topology.make_router(), retry=retry)
        for _ in range(min(n_routers, n_workers))
    ]

    # --- experiments ---------------------------------------------------------
    exp_ids = []
    names = soak_experiment_names(
        storages[0].db, n_experiments, len(topology.shards)
    )
    for e, name in enumerate(names):
        config = {
            "_id": experiment_id(name, 1, "soak"),
            "name": name,
            "version": 1,
            "metadata": {"user": "soak"},
            "max_trials": float("inf"),
        }
        try:
            storages[e % len(storages)].create_experiment(config)
        except DuplicateKeyError:
            pass  # re-run against a persisted topology
        exp_ids.append(config["_id"])

    def check_deadline():
        if time.monotonic() >= stop_at:
            raise TimeoutError(f"soak failed to converge within {deadline}s")

    # --- workers -------------------------------------------------------------
    errors_lock = threading.Lock()
    barrier = None
    if mid_hook is not None:
        # A hook declaring a parameter receives the live router-backed
        # storages — the rebalance-mid-soak leg retargets their topology
        # in place while every worker holds at the barrier.
        import inspect

        try:
            hook_params = list(inspect.signature(mid_hook).parameters)
        except (TypeError, ValueError):  # pragma: no cover - builtins
            hook_params = []

        def hook_action():
            try:
                if hook_params:
                    mid_hook(storages)
                else:
                    mid_hook()
            except Exception:  # pragma: no cover - chaos must not kill the run
                log.exception("mid-run chaos hook failed")

        barrier = threading.Barrier(n_workers, action=hook_action)

    def worker(w):
        storage = storages[w % len(storages)]
        exp_id = exp_ids[w % len(exp_ids)]
        half = max(1, trials_per_worker // 2)
        for i in range(trials_per_worker):
            if barrier is not None and i == half:
                try:
                    barrier.wait(timeout=max(1.0, deadline / 2))
                except threading.BrokenBarrierError:
                    pass  # a worker died/timed out; the rest proceed
            # Unique parameter point per (worker, slot): trial ids are
            # md5(experiment, params), so registration is convergent under
            # resends and the zero-lost-observations count is exact.
            value = (w * trials_per_worker + i + 1) / (
                n_workers * trials_per_worker + 2
            )
            trial = Trial(experiment=exp_id, params={"/x": value})
            while True:
                if time.monotonic() >= stop_at:
                    return
                try:
                    try:
                        storage.register_trial(trial)
                    except DuplicateKeyError:
                        pass  # an earlier (reply-lost) attempt applied
                    claimed = storage.reserve_trials(exp_id, 1)
                    for got in claimed:
                        storage.update_completed_trial(
                            got,
                            [Result("obj", "objective", float(got.params["/x"]))],
                        )
                    # The status poll every real worker loop runs (the
                    # is_done check) — THE hot read the replica tier
                    # exists to serve, and what exercises staleness
                    # failover under chaos.
                    storage.count_completed_trials(exp_id)
                    break
                except Exception as exc:
                    if not is_transient(exc):
                        raise
                    with errors_lock:
                        result.worker_errors += 1
                    time.sleep(0.02)

    chaos_stop = threading.Event()
    chaos_thread = None
    if chaos:
        chaos_thread = threading.Thread(
            target=_chaos_loop,
            args=(topology, chaos_stop),
            kwargs={"period": chaos_period},
            daemon=True,
        )
        chaos_thread.start()
    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(n_workers)
    ]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(max(0.0, stop_at - time.monotonic()) + 5.0)
    chaos_stop.set()
    if chaos_thread is not None:
        chaos_thread.join(timeout=10.0)
    check_deadline()

    # --- convergence sweep ---------------------------------------------------
    # Complete anything a mid-chaos worker abandoned (reserved when its
    # thread hit the deadline, or registered but never claimed).  This is
    # the production lost-trial story: reservations are recoverable state,
    # never lost data.  The sweep AND the verification below read with
    # replica_reads OFF: replicas promise per-router read-your-writes, not
    # fleet-wide freshness — a replica caught up to THIS router's writes
    # can still trail another router's, and verification wants the
    # authoritative answer, not an eventually-consistent one.
    sweep_router = topology.make_router(replica_reads=False)
    # A permanently killed primary is likely already healed by the worker
    # routers' elections, but THIS fresh router still dials the dead
    # address: poke each killed shard until its failure detector adopts
    # the promoted replica — BEFORE DocumentStorage's index setup fans
    # out to every shard.
    for position, deployment in enumerate(topology.shards):
        if not deployment.killed:
            continue
        poke_deadline = time.monotonic() + 15.0
        while time.monotonic() < poke_deadline:
            check_deadline()
            try:
                sweep_router._shard_read(
                    sweep_router._shards[position], "count", "experiments"
                )
                break
            except Exception:
                time.sleep(0.1)
    sweep_storage = DocumentStorage(sweep_router, retry=retry)
    storages.append(sweep_storage)
    for exp_id in exp_ids:
        while True:
            check_deadline()
            try:
                pending = sweep_storage.fetch_noncompleted_trials(exp_id)
                if not pending:
                    break
                for trial in pending:
                    try:
                        sweep_storage.update_completed_trial(
                            trial,
                            [Result("obj", "objective", float(trial.params["/x"]))],
                        )
                    except Exception as exc:
                        if not is_transient(exc):
                            raise
            except Exception as exc:
                if not is_transient(exc):
                    raise
                time.sleep(0.05)

    # --- settle + verify -----------------------------------------------------
    router = sweep_storage.db
    expected = n_workers * trials_per_worker
    result.registered = expected
    # Through the router (replica reads allowed; staleness failover keeps
    # the answer fresh).
    result.completed = sum(
        sweep_storage.count_completed_trials(exp_id) for exp_id in exp_ids
    )
    result.router_reports = audit_storage(sweep_storage, lost_timeout=3600.0)
    # Directly on every shard: the router view must be the sum of its
    # parts, and every shard must audit clean ON ITS OWN.
    for index, conn in router.shard_connections():
        direct = DocumentStorage(conn, retry=retry)
        result.shard_reports[index] = audit_storage(direct, lost_timeout=3600.0)
        result.completed_per_shard[index] = sum(
            direct.count_completed_trials(r.experiment_id)
            for r in result.shard_reports[index]
        )
    # Health counters summed over EVERY router the workers used (each
    # tracks its own shards' connections).
    result.failovers = sum(s.db.failovers for s in storages)
    result.replica_stale_reads = sum(s.db.replica_stale_reads for s in storages)
    result.reconnects = sum(s.db.reconnects for s in storages)
    result.restarts = sum(s.restarts for s in topology.shards)
    result.promotions = sum(s.db.promotions for s in storages)
    result.primary_kills = sum(1 for s in topology.shards if s.killed)
    result.duration_s = time.monotonic() - t0
    for storage in storages:
        storage.db.close()
    return result
