"""Deterministic fault injection for the storage layer.

Two instruments, both driven by a seeded :class:`FaultSchedule` so a chaos
run is REPLAYABLE (same seed + same op order = same faults):

- :class:`FaultyDB` wraps any document backend (memory/pickled/sqlite/
  network client, or a third-party AbstractDB) and executes the schedule
  at the op boundary — raise-before-apply, apply-then-drop-reply (the
  applied-and-reply-lost ambiguity the retry policy must converge
  through), latency spikes, and mid-batch kills on
  ``apply_batch``/``pipeline`` (a prefix applies, then the "server dies").
  Interception is capability-preserving: ``FaultyDB`` only exposes the
  batch primitives its inner backend has, so ``DocumentStorage``'s
  capability probes see the wrapped backend exactly as they would the
  real one.

- :class:`FaultProxy` is a byte-level TCP proxy for the network backend:
  it sits between :class:`~orion_tpu.storage.netdb.NetworkDB` and a real
  :class:`~orion_tpu.storage.netdb.DBServer` and drops, stalls,
  black-holes, or mid-line-cuts connections — so chaos tests exercise the
  driver's REAL reconnect/resend/idle-probe paths against a live server,
  not mocks.  One-shot ``fail_next`` modes make server-restart-mid-batch
  scenarios deterministic (never-applied vs. applied-and-reply-lost,
  pinned in tests/unit/test_crash_consistency.py).

The chaos suite (tests/functional/test_chaos.py) composes both with the
invariant auditor (``storage/audit.py``): an experiment must run to
completion under a seeded schedule on every backend with zero duplicated
trials and zero lost observations.
"""

import logging
import random
import socket
import threading
import time

from orion_tpu.utils.exceptions import DatabaseError

log = logging.getLogger(__name__)

#: The round classes a schedule can inject, in the storage layer's terms.
FAULT_KINDS = ("error", "reply_lost", "latency", "kill")

#: Ops FaultyDB intercepts — the write/read cycle of the AbstractDB
#: contract.  Index management and snapshots stay clean: they are
#: startup-time work, and faulting them would test construction, not the
#: coordination protocol.
FAULTABLE_OPS = frozenset(
    {"write", "read", "read_and_write", "count", "remove", "update_many"}
)
#: Batch primitives: the only ops a ``kill`` (mid-batch death) can hit.
BATCH_OPS = frozenset({"apply_batch", "pipeline"})


class InjectedFault(DatabaseError):
    """A fault the schedule injected (never a real backend failure).

    Transient by classification (a DatabaseError that is not one of the
    semantic subtypes), so the retry policy treats it exactly like the
    outage it simulates."""


class FaultSchedule:
    """Seeded, deterministic plan of which intercepted op faults and how.

    ``plan`` pins faults to exact op indices (``{op_index: kind}``) — the
    chaos tests use this to guarantee every round class fires at least
    once on a short run.  ``rates`` adds seeded random faults on top
    (``{kind: probability}``), drawn ONCE per intercepted op in call
    order, so the whole schedule is a pure function of (seed, op order).
    ``max_faults`` bounds the total so a run always converges.

    A ``kill`` drawn while a non-batch op is executing is DEFERRED to the
    next batch op (a mid-batch death needs a batch to die in the middle
    of) — deferral keeps the plan meaningful without making it
    op-shape-aware.
    """

    def __init__(self, seed=0, plan=None, rates=None, latency=0.01, max_faults=None):
        self._rng = random.Random(seed)
        self.plan = dict(plan or {})
        self.rates = dict(rates or {})
        for kind in list(self.plan.values()) + list(self.rates):
            if kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {kind!r}; one of {FAULT_KINDS}")
        self.latency = float(latency)
        self.max_faults = max_faults
        self.op_count = 0
        self.injected = {kind: 0 for kind in FAULT_KINDS}
        self._pending_kill = False
        self._lock = threading.Lock()

    @property
    def total_injected(self):
        return sum(self.injected.values())

    def _budget_left(self):
        return self.max_faults is None or self.total_injected < self.max_faults

    def draw(self, op, batchable):
        """The fault (or None) for the next intercepted op.  Called once
        per op in execution order; thread-safe so a multi-worker chaos run
        stays well-defined (though only single-writer runs are strictly
        replayable)."""
        with self._lock:
            index = self.op_count
            self.op_count += 1
            kind = self.plan.get(index)
            if kind is None and self.rates:
                # One draw per rate entry, in fixed key order, EVERY op —
                # the stream position depends only on op index, never on
                # which faults happened to fire.
                for rate_kind in FAULT_KINDS:
                    rate = self.rates.get(rate_kind)
                    if rate is None:
                        continue
                    hit = self._rng.random() < rate
                    if hit and kind is None:
                        kind = rate_kind
            if kind == "kill" and not batchable:
                self._pending_kill = True
                kind = None
            elif kind is None and self._pending_kill and batchable:
                kind = "kill"
            if kind is None or not self._budget_left():
                return None
            if kind == "kill":
                self._pending_kill = False
            self.injected[kind] += 1
            return kind


def _raise_injected(op, kind, maybe_applied=False):
    exc = InjectedFault(f"injected fault ({kind}) during {op!r}")
    exc.maybe_applied = maybe_applied
    raise exc


class FaultyDB:
    """Schedule-executing wrapper around a document backend.

    Delegates everything (attributes, counters, ``cheap_counts``, index
    management) to the inner backend; the FAULTABLE_OPS and whichever
    BATCH_OPS the inner backend actually has are intercepted through
    ``__getattr__``-built wrappers, so capability probes
    (``getattr(db, "apply_batch", None)``) see exactly the inner
    backend's surface.
    """

    def __init__(self, inner, schedule=None):
        self._inner = inner
        self.schedule = schedule or FaultSchedule()

    @property
    def inner(self):
        return self._inner

    @property
    def faults_injected(self):
        return dict(self.schedule.injected)

    def __getattr__(self, name):
        inner = self.__dict__.get("_inner")
        if inner is None:
            # Mid-unpickle (or a half-built instance) has no __dict__ yet;
            # recursing through self._inner here is a stack overflow.
            raise AttributeError(name)
        target = getattr(inner, name)  # AttributeError propagates
        if name in FAULTABLE_OPS:
            return self._wrap_op(name, target)
        if name in BATCH_OPS:
            return self._wrap_batch(name, target)
        return target

    def _wrap_op(self, op, target):
        schedule = self.schedule

        def faulted(*args, **kwargs):
            kind = schedule.draw(op, batchable=False)
            if kind == "error":
                _raise_injected(op, kind)
            if kind == "latency":
                time.sleep(schedule.latency)
            result = target(*args, **kwargs)
            if kind == "reply_lost":
                _raise_injected(op, kind, maybe_applied=True)
            return result

        return faulted

    def _wrap_batch(self, op, target):
        schedule = self.schedule

        def faulted(ops):
            kind = schedule.draw(op, batchable=True)
            if kind == "error":
                _raise_injected(op, kind)
            if kind == "latency":
                time.sleep(schedule.latency)
            if kind == "kill":
                # The server died mid-batch: a prefix applied durably, the
                # rest never arrived, and the caller cannot know the split.
                applied = len(ops) // 2
                if applied:
                    target(list(ops)[:applied])
                _raise_injected(op, kind, maybe_applied=True)
            result = target(ops)
            if kind == "reply_lost":
                _raise_injected(op, kind, maybe_applied=True)
            return result

        return faulted


class _ProxyConnection:
    """One client<->upstream pair with its two pump threads."""

    def __init__(self, proxy, client):
        self.proxy = proxy
        self.client = client
        self.upstream = socket.create_connection(
            (proxy.upstream_host, proxy.upstream_port), timeout=proxy.timeout
        )
        self.drop_reply_armed = False
        self._closed = threading.Event()

    def start(self):
        for fn in (self._pump_up, self._pump_down):
            threading.Thread(target=fn, daemon=True).start()

    def close(self):
        if self._closed.is_set():
            return
        self._closed.set()
        for sock in (self.client, self.upstream):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self.proxy._forget(self)

    def _pump_up(self):
        """client -> upstream, where the one-shot fault modes fire."""
        proxy = self.proxy
        try:
            while not self._closed.is_set():
                data = self.client.recv(65536)
                if not data:
                    break
                if proxy.capture:
                    with proxy._lock:
                        proxy.captured_up.extend(data)
                mode = proxy._take_mode()
                if mode == "drop_request":
                    # Nothing reaches the server: the never-applied case.
                    proxy._fired(mode)
                    break
                if mode == "cut_first_line":
                    # Exactly the first request line survives the "crash":
                    # deterministic mid-batch partial delivery (the
                    # server's readline guard drops the torn remainder).
                    newline = data.find(b"\n")
                    if newline >= 0:
                        self.upstream.sendall(data[: newline + 1])
                    proxy._fired(mode)
                    break
                if mode == "drop_reply":
                    # Forward the request fully; the down pump will eat
                    # the server's reply: applied-and-reply-lost.
                    self.drop_reply_armed = True
                    proxy._fired(mode)
                if proxy.blackhole:
                    continue  # swallow bytes; the client times out
                if proxy.stall_s:
                    time.sleep(proxy.stall_s)
                self.upstream.sendall(data)
        except OSError:
            pass
        finally:
            self.close()

    def _pump_down(self):
        """upstream -> client."""
        proxy = self.proxy
        try:
            while not self._closed.is_set():
                data = self.upstream.recv(65536)
                if not data:
                    break
                if self.drop_reply_armed:
                    break  # reply eaten; connection dies with it
                if proxy.blackhole:
                    continue
                if proxy.stall_s:
                    time.sleep(proxy.stall_s)
                self.client.sendall(data)
        except OSError:
            pass
        finally:
            self.close()


class FaultProxy:
    """TCP fault proxy between a NetworkDB client and a real DBServer.

    Point the client at ``serve_background()``'s address; bytes flow
    through unmodified until a fault is requested:

    - ``fail_next(mode)`` arms a ONE-SHOT fault against the next client
      transmission: ``"drop_request"`` (connection dies before anything
      reaches the server — never applied), ``"drop_reply"`` (request
      forwarded whole, reply eaten — applied but unknowable),
      ``"cut_first_line"`` (only the first request line of a batch/
      pipeline survives — deterministic partial application);
    - ``set_stall(seconds)`` / ``set_blackhole(on)`` shape every
      connection until cleared (latency spikes / a black-holed link);
    - ``drop_all()`` kills every live connection now (a server restart's
      client-side signature).

    ``faults_fired`` counts by mode; ``connections_accepted`` and
    ``connections_dropped`` track churn — the chaos suite correlates
    these with the driver's ``reconnects`` counter.
    """

    def __init__(self, upstream_host, upstream_port, listen_host="127.0.0.1",
                 timeout=60.0):
        self.upstream_host = upstream_host
        self.upstream_port = int(upstream_port)
        self.listen_host = listen_host
        self.timeout = timeout
        self.stall_s = 0.0
        self.blackhole = False
        self.connections_accepted = 0
        self.connections_dropped = 0
        #: Wall-clock of every accepted connection (monotonic): the
        #: reconnect-herd tests assert the SPREAD of these after a
        #: drop_all() — lockstep re-handshakes all land within one jitter
        #: window, spread ones don't.
        self.accept_times = []
        #: When True, every client->upstream byte is appended to
        #: ``captured_up`` (across connections, in order): the router
        #: pass-through differential compares these byte streams.
        self.capture = False
        self.captured_up = bytearray()
        self.faults_fired = {}
        self._mode = None
        self._lock = threading.Lock()
        self._conns = set()
        self._listener = None
        self._stopped = threading.Event()
        self.address = None

    # --- lifecycle ----------------------------------------------------------
    def serve_background(self):
        """Bind + accept on a daemon thread; returns (host, port)."""
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self.listen_host, 0))
        listener.listen()
        self._listener = listener
        self.address = listener.getsockname()[:2]
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self.address

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                client, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            try:
                conn = _ProxyConnection(self, client)
            except OSError:
                # Upstream down: refuse by closing, the client sees a
                # connection error exactly as with a dead server.
                try:
                    client.close()
                except OSError:
                    pass
                continue
            with self._lock:
                self._conns.add(conn)
                self.connections_accepted += 1
                self.accept_times.append(time.monotonic())
            conn.start()

    def stop(self):
        self._stopped.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self.drop_all()

    # --- fault controls -----------------------------------------------------
    def fail_next(self, mode):
        if mode not in ("drop_request", "drop_reply", "cut_first_line"):
            raise ValueError(f"unknown proxy fault mode {mode!r}")
        with self._lock:
            self._mode = mode

    def set_stall(self, seconds):
        self.stall_s = float(seconds)

    def set_blackhole(self, on=True):
        self.blackhole = bool(on)

    def drop_all(self):
        with self._lock:
            doomed = list(self._conns)
        for conn in doomed:
            conn.close()

    # --- internals ----------------------------------------------------------
    def _take_mode(self):
        with self._lock:
            mode, self._mode = self._mode, None
            return mode

    def _fired(self, mode):
        with self._lock:
            self.faults_fired[mode] = self.faults_fired.get(mode, 0) + 1

    def _forget(self, conn):
        with self._lock:
            if conn in self._conns:
                self._conns.discard(conn)
                self.connections_dropped += 1
