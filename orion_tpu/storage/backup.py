"""Cross-shard snapshot backup / restore (`orion-tpu db backup` / `db restore`).

``backup_topology`` streams one CONSISTENT snapshot per shard — the
``snapshot`` wire op returns the same full-state dump replica resyncs
ship, taken under the server's replication lock so no mutation
interleaves, stamped with the shard's applied ``seq`` and ``epoch`` —
into ``DIR/shard<i>.json`` files plus a ``manifest.json`` recording the
topology and per-shard positions.  The manifest is written LAST
(atomically): a crashed backup leaves no manifest and a restore refuses
to touch it.

``restore_topology`` rebuilds a FRESH topology from a backup directory:
every document is routed through the destination router's OWN ring, so
the restore target may have a different shard count than the source —
the documents land wherever the new ring says they belong.  Placement
override docs (``_placement``) are deliberately dropped: they encode the
OLD topology's mid-migration state, and on the new ring the documents
are placed directly at their homes.  Restores are convergent: re-running
a crashed restore dedups on document ids.
"""

import json
import logging
import os
import tempfile
import time

from orion_tpu.storage.documents import json_default
from orion_tpu.storage.retry import MODE_ALWAYS, create_retry_policy
from orion_tpu.storage.shard import PLACEMENT_COLLECTION
from orion_tpu.utils.exceptions import DatabaseError, DuplicateKeyError

log = logging.getLogger(__name__)

MANIFEST = "manifest.json"

#: Server-internal collections never restored: replication bookkeeping is
#: per-server state and placement overrides encode the OLD topology.
_SKIP_RESTORE = frozenset({"_replmeta", PLACEMENT_COLLECTION})

#: Batched restore chunk (one apply_batch request per chunk per shard).
RESTORE_BATCH = 256

RESTORE_RETRY = {
    "max_attempts": 5,
    "base_delay": 0.05,
    "max_delay": 1.0,
    "deadline": 30.0,
}


def _shard_surfaces(db):
    """``[(index, NetworkDB), ...]`` for a router or a single client."""
    connections = getattr(db, "shard_connections", None)
    if connections is not None:
        return connections()
    return [(0, db)]


def backup_topology(db, out_dir):
    """Snapshot every shard of ``db`` (router or single NetworkDB) into
    ``out_dir``; returns the manifest dict."""
    os.makedirs(out_dir, exist_ok=True)
    describe = getattr(db, "describe_topology", None)
    manifest = {
        "version": 1,
        "created_at": time.time(),
        "topology": describe() if describe is not None else {"shards": 1},
        "shards": [],
    }
    for index, conn in _shard_surfaces(db):
        payload = conn._call("snapshot")
        if not isinstance(payload, dict):
            raise DatabaseError(
                f"shard {index} ({conn.host}:{conn.port}) returned no "
                "snapshot — is the server older than the backup protocol?"
            )
        collections = payload.get("collections") or {}
        entry = {
            "index": index,
            "address": f"{conn.host}:{conn.port}",
            "seq": int(payload.get("seq", 0)),
            "epoch": int(payload.get("epoch", 0) or 0),
            "file": f"shard{index}.json",
            "docs": sum(len(v) for v in collections.values()),
            "collections": {k: len(v) for k, v in collections.items()},
        }
        _atomic_json(os.path.join(out_dir, entry["file"]), payload)
        manifest["shards"].append(entry)
        log.info(
            "backed up shard %d (%s): %d docs at seq %d epoch %d",
            index, entry["address"], entry["docs"], entry["seq"], entry["epoch"],
        )
    _atomic_json(os.path.join(out_dir, MANIFEST), manifest)
    return manifest


def load_manifest(src_dir):
    path = os.path.join(src_dir, MANIFEST)
    if not os.path.exists(path):
        raise DatabaseError(
            f"{src_dir!r} holds no {MANIFEST} — not a completed "
            "`orion-tpu db backup` directory"
        )
    with open(path) as handle:
        return json.load(handle)


def restore_topology(db, src_dir, require_empty=True, retry=None):
    """Restore a backup directory into ``db`` (router or single client).

    The destination must be EMPTY (no experiments) unless
    ``require_empty=False`` — a restore is a disaster-recovery rebuild,
    not a merge (``db load`` merges).  Returns a summary dict with
    per-collection document counts; raises when the restored counts do
    not match the manifest."""
    manifest = load_manifest(src_dir)
    policy = create_retry_policy(dict(RESTORE_RETRY) if retry is None else retry)
    if require_empty:
        existing = policy.run(
            lambda: db.count("experiments", {}),
            op="restore.precheck", mode=MODE_ALWAYS,
        )
        if existing:
            raise DatabaseError(
                f"restore target already holds {existing} experiment(s); "
                "restore rebuilds a FRESH topology — point it at empty "
                "shards (or pass --force to merge at your own risk)"
            )
    expected = {}
    restored = {}
    for entry in manifest["shards"]:
        path = os.path.join(src_dir, entry["file"])
        with open(path) as handle:
            payload = json.load(handle)
        for collection, docs in (payload.get("collections") or {}).items():
            if collection in _SKIP_RESTORE:
                continue
            expected[collection] = expected.get(collection, 0) + len(docs)
            if not docs:
                continue
            for start in range(0, len(docs), RESTORE_BATCH):
                chunk = docs[start:start + RESTORE_BATCH]
                ops = [("write", [collection, doc], {}) for doc in chunk]
                outcomes = policy.run(
                    lambda ops=ops: db.apply_batch(ops),
                    op=f"restore.write.{collection}", mode=MODE_ALWAYS,
                )
                landed = 0
                for outcome in outcomes:
                    if isinstance(outcome, DuplicateKeyError):
                        landed += 1  # a crashed earlier restore got here
                        continue
                    if isinstance(outcome, Exception):
                        raise outcome
                    landed += 1
                restored[collection] = restored.get(collection, 0) + landed
    # Verify: the destination (through the new ring) must hold exactly the
    # backed-up document counts.
    mismatches = []
    for collection, count in sorted(expected.items()):
        have = policy.run(
            lambda collection=collection: db.count(collection, {}),
            op=f"restore.verify.{collection}", mode=MODE_ALWAYS,
        )
        if have < count:
            mismatches.append((collection, count, have))
    if mismatches:
        raise DatabaseError(
            "restore incomplete: "
            + "; ".join(
                f"{c}: expected {want}, destination holds {have}"
                for c, want, have in mismatches
            )
        )
    return {
        "manifest": manifest,
        "collections": expected,
        "documents": sum(expected.values()),
    }


def _atomic_json(path, payload):
    out_dir = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp_path = tempfile.mkstemp(dir=out_dir, suffix=".backup-partial")
    try:
        with os.fdopen(fd, "w") as handle:
            json.dump(payload, handle, default=json_default)
        os.replace(tmp_path, path)
    except BaseException:
        try:
            os.unlink(tmp_path)
        except OSError:  # pragma: no cover - best-effort cleanup
            pass
        raise
