"""Storage: the distributed coordination backend.

What NCCL is to a trainer, atomic ``read_and_write`` on the trials collection
is to this framework (see SURVEY.md §2.3/§5): all inter-worker communication
— trial queue, reservation locking, heartbeats, experiment configs, EVC links
— flows through a shared document store.  Backends:

- ``memory`` — in-process, for tests/--debug (reference EphemeralDB).
- ``pickled`` — single file + advisory file lock, multi-process safe on one
  node (reference PickledDB); the default.
- ``network`` — TCP client to an `orion-tpu db serve` server, multi-NODE
  safe over DCN (reference MongoDB driver; see ``orion_tpu.storage.netdb``).
- ``network`` with a ``shards:`` stanza — the consistent-hash router over
  N netdb shards with read replicas (``orion_tpu.storage.shard``; the
  scale-out control plane, docs/multi_node.md).

Intra-suggest parallelism (on-device vmap/shard_map over a TPU mesh) is a
*different* layer — see ``orion_tpu.parallel``.
"""

from orion_tpu.storage.audit import AuditReport, audit_experiment, audit_storage
from orion_tpu.storage.base import (
    BaseStorage,
    DocumentStorage,
    ReadOnlyStorage,
    create_storage,
    get_storage,
    setup_storage,
)
from orion_tpu.storage.documents import MemoryDB
from orion_tpu.storage.backends import PickledDB
from orion_tpu.storage.faults import FaultProxy, FaultSchedule, FaultyDB
from orion_tpu.storage.netdb import DBServer, NetworkDB
from orion_tpu.storage.retry import RetryPolicy, is_transient
from orion_tpu.storage.shard import HashRing, ShardedNetworkDB

__all__ = [
    "AuditReport",
    "BaseStorage",
    "DBServer",
    "DocumentStorage",
    "FaultProxy",
    "FaultSchedule",
    "FaultyDB",
    "HashRing",
    "MemoryDB",
    "NetworkDB",
    "PickledDB",
    "ReadOnlyStorage",
    "RetryPolicy",
    "ShardedNetworkDB",
    "audit_experiment",
    "audit_storage",
    "create_storage",
    "get_storage",
    "is_transient",
    "setup_storage",
]
