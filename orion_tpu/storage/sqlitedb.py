"""SQLite document database — durable multi-process storage without a server.

Fills the slot the reference covers with PickledDB (whole-file flock +
unpickle per op, `src/orion/core/io/database/pickleddb.py:162-207`) but with
row-granular writes and real cross-process atomicity: WAL mode lets readers
proceed under a writer, `BEGIN IMMEDIATE` serializes compare-and-swap
reservations, and uniqueness is enforced by an actual UNIQUE constraint (a
durable mirror of the in-memory backend's hash indexes), so concurrent
workers get `DuplicateKeyError` from the database itself rather than from an
advisory lock.

Document semantics (dotted-path queries/updates, `$in`/`$gte`/... operators,
projections) are shared with the in-memory backend — same helpers, same
behavior, one contract test suite over both.
"""

import functools
import json
import sqlite3
import threading
import time

from orion_tpu.telemetry import TELEMETRY
from orion_tpu.storage.documents import (
    MemoryDB,
    apply_update,
    dumps_canonical as _dumps,
    index_key as _index_key,
    _matches,
    _project,
)
from orion_tpu.utils.exceptions import DatabaseError, DuplicateKeyError


def _translate_errors(method):
    """Raw sqlite3 errors -> the unified DatabaseError family, so callers
    handling lock contention / corrupt files behave the same across
    backends (exceptions.py unifies storage errors by design)."""

    @functools.wraps(method)
    def wrapper(*args, **kwargs):
        try:
            return method(*args, **kwargs)
        except sqlite3.Error as exc:
            raise DatabaseError(f"sqlite: {exc}") from exc

    return wrapper

_SCHEMA = """
CREATE TABLE IF NOT EXISTS docs (
    collection TEXT NOT NULL,
    id TEXT NOT NULL,
    doc TEXT NOT NULL,
    PRIMARY KEY (collection, id)
);
CREATE TABLE IF NOT EXISTS idx_meta (
    collection TEXT NOT NULL,
    name TEXT NOT NULL,
    fields TEXT NOT NULL,
    is_unique INTEGER NOT NULL,
    PRIMARY KEY (collection, name)
);
CREATE TABLE IF NOT EXISTS unique_keys (
    collection TEXT NOT NULL,
    fields TEXT NOT NULL,
    key TEXT NOT NULL,
    id TEXT NOT NULL,
    PRIMARY KEY (collection, fields, key)
);
CREATE TABLE IF NOT EXISTS counters (
    collection TEXT PRIMARY KEY,
    next_id INTEGER NOT NULL
);
"""


def _id_key(_id):
    """Canonical string form of a document id (ids are ints or strings)."""
    return _dumps(_id)


def sqlite_path_selected(path):
    """Should ``path`` use the SQLite backend?  An EXISTING file is
    identified by its 16-byte header (a pickle snapshot named results.db
    must keep loading as pickled — extension sniffing alone would hand
    pickle bytes to sqlite3); only new files go by extension.  Shared by
    the CLI --storage-path routing and the network server's --persist."""
    import os

    if os.path.exists(path) and os.path.getsize(path) > 0:
        with open(path, "rb") as f:
            return f.read(16).startswith(b"SQLite format 3\x00")
    # Nonexistent OR empty: sqlite3.connect creates the file zero-byte before
    # the first schema commit writes the header, so a crash in that window
    # must not silently flip a *.sqlite path to the pickle format.
    return path.endswith((".sqlite", ".sqlite3", ".db"))


class SQLiteDB:
    """AbstractDB-contract database over a single SQLite file."""

    #: Counts/targeted queries are SQL-side — no full-DB reload per op
    #: (the producer's count-gated sync keys on this).
    cheap_counts = True

    def __init__(self, path, timeout=60.0):
        self._path = str(path)
        self._timeout = float(timeout)
        self._local = threading.local()
        #: Transactions opened since construction (each one COMMIT, i.e. one
        #: WAL sync cycle) — the instrument bench.py's storage breakdown
        #: reads to prove a q-batch registration costs O(1) transactions.
        #: Lock-guarded: connections are per-thread by design, so the
        #: counter must not lose increments across threads.
        self.txn_count = 0
        self._txn_count_lock = threading.Lock()
        with self._conn():  # create schema eagerly so first reads see tables
            pass

    # --- connection management --------------------------------------------
    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(
                self._path,
                timeout=self._timeout,
                isolation_level=None,  # explicit transaction control
            )
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            conn.executescript(_SCHEMA)
            self._local.conn = conn
        return conn

    class _Txn:
        """IMMEDIATE transaction: the cross-process synchronization point.

        Wall time from BEGIN to COMMIT/ROLLBACK (lock wait + statements +
        WAL sync) feeds the ``storage.sqlite.txn`` telemetry histogram —
        the commit-latency signal next to the ``txn_count`` counter."""

        def __init__(self, conn):
            self.conn = conn
            self._t0 = None

        def __enter__(self):
            self._t0 = time.perf_counter() if TELEMETRY.enabled else None
            self.conn.execute("BEGIN IMMEDIATE")
            return self.conn

        def __exit__(self, exc_type, exc, tb):
            if exc_type is None:
                self.conn.execute("COMMIT")
            else:
                self.conn.execute("ROLLBACK")
            if self._t0 is not None:
                TELEMETRY.observe(
                    "storage.sqlite.txn", time.perf_counter() - self._t0
                )

    def _txn(self):
        with self._txn_count_lock:
            self.txn_count += 1
        return self._Txn(self._conn())

    # --- indexes -----------------------------------------------------------
    @_translate_errors
    def ensure_index(self, collection, keys, unique=False):
        fields = [k[0] if isinstance(k, (tuple, list)) else k for k in keys]
        name = "_".join(fields) + "_1"
        with self._txn() as conn:
            conn.execute(
                "INSERT OR REPLACE INTO idx_meta VALUES (?, ?, ?, ?)",
                (collection, name, _dumps(fields), int(unique)),
            )
            fields_key = _dumps(fields)
            if unique:
                # Backfill the durable unique map for existing documents.
                # Pre-existing duplicates are tolerated last-wins — the
                # memory/pickled backends do the same (_build_unique_map),
                # and storage construction must never make legacy data
                # unreadable; NEW duplicates are rejected from here on.
                for doc in self._scan(conn, collection):
                    conn.execute(
                        "INSERT OR REPLACE INTO unique_keys VALUES (?, ?, ?, ?)",
                        (
                            collection,
                            fields_key,
                            _index_key(doc, fields),
                            _id_key(doc["_id"]),
                        ),
                    )
            else:
                conn.execute(
                    "DELETE FROM unique_keys WHERE collection = ? AND fields = ?",
                    (collection, fields_key),
                )

    def ensure_indexes(self, specs):
        for collection, keys, unique in specs:
            self.ensure_index(collection, keys, unique=unique)

    @_translate_errors
    def index_information(self, collection):
        rows = self._conn().execute(
            "SELECT name, is_unique FROM idx_meta WHERE collection = ?",
            (collection,),
        )
        return {name: bool(u) for name, u in rows}

    @_translate_errors
    def drop_index(self, collection, name):
        with self._txn() as conn:
            row = conn.execute(
                "SELECT fields FROM idx_meta WHERE collection = ? AND name = ?",
                (collection, name),
            ).fetchone()
            if row is None:
                raise KeyError(f"index not found: {name}")
            conn.execute(
                "DELETE FROM idx_meta WHERE collection = ? AND name = ?",
                (collection, name),
            )
            conn.execute(
                "DELETE FROM unique_keys WHERE collection = ? AND fields = ?",
                (collection, row[0]),
            )

    @_translate_errors
    def collection_names(self):
        """Every collection present in documents OR index metadata — the
        enumeration surface the netdb replication snapshot and `db dump`
        walk (an indexed-but-empty collection must survive a resync)."""
        rows = self._conn().execute(
            "SELECT DISTINCT collection FROM docs "
            "UNION SELECT DISTINCT collection FROM idx_meta"
        )
        return sorted(name for (name,) in rows)

    @_translate_errors
    def index_specs(self):
        """``[(collection, [field, ...], unique), ...]`` in the shape
        ``ensure_index`` accepts (snapshot-resync rebuild surface)."""
        rows = self._conn().execute(
            "SELECT collection, fields, is_unique FROM idx_meta "
            "ORDER BY collection, name"
        )
        return [(col, json.loads(fields), bool(u)) for col, fields, u in rows]

    def _unique_specs(self, conn, collection):
        rows = conn.execute(
            "SELECT fields FROM idx_meta WHERE collection = ? AND is_unique = 1",
            (collection,),
        ).fetchall()
        return [json.loads(f) for (f,) in rows]

    # --- document plumbing -------------------------------------------------
    @staticmethod
    def _sql_prefilter(query):
        """SQL WHERE fragments for the simple top-level conditions of a
        query (equality / $in on scalar values) via json_extract, so hot
        scans — reservation filters on status — skip Python-parsing rows
        that cannot match.  Python `_matches` still runs afterwards; this
        only narrows, never decides."""
        def pushable(v):
            if isinstance(v, bool):
                return False  # json_extract yields 0/1, Python has True/False
            if isinstance(v, int):
                return -(2**63) <= v < 2**63  # sqlite INTEGER range
            return isinstance(v, (str, float))

        clauses, params = [], []
        for key, qv in (query or {}).items():
            if not key.isidentifier():  # dotted/odd keys: leave to _matches
                continue
            path = f"$.{key}"
            if pushable(qv):
                clauses.append("json_extract(doc, ?) = ?")
                params.extend([path, qv])
            elif (
                isinstance(qv, dict)
                and set(qv) == {"$in"}
                and all(pushable(v) for v in qv["$in"])
            ):
                marks = ",".join("?" * len(qv["$in"]))
                clauses.append(f"json_extract(doc, ?) IN ({marks})")
                params.extend([path, *qv["$in"]])
        return clauses, params

    def _scan_iter(self, conn, collection, query=None):
        """Lazily yield parsed documents matching the query's SQL-pushable
        prefix (first-match paths stop early — read_and_write holds the
        exclusive write lock while scanning, so parsing the whole
        collection there would serialize every worker behind O(n) JSON
        work per reservation)."""
        _id = (query or {}).get("_id")
        if _id is not None and not isinstance(_id, dict):
            rows = conn.execute(
                "SELECT doc FROM docs WHERE collection = ? AND id = ?",
                (collection, _id_key(_id)),
            )
            for (d,) in rows:
                yield json.loads(d)
            return
        clauses, params = self._sql_prefilter(query)
        sql = "SELECT doc FROM docs WHERE collection = ?"
        if clauses:
            sql += " AND " + " AND ".join(clauses)
        yielded = set()
        try:
            for (d,) in conn.execute(sql, (collection, *params)):
                doc = json.loads(d)
                yielded.add(_id_key(doc.get("_id")))
                yield doc
        except sqlite3.OperationalError:
            # A doc carrying a NaN/Infinity token (json.dumps emits them for
            # non-finite objectives) breaks SQLite's json_extract mid-scan;
            # Python json.loads accepts them, so finish with the unfiltered
            # scan + _matches, skipping rows already yielded.
            for (d,) in conn.execute(
                "SELECT doc FROM docs WHERE collection = ?", (collection,)
            ).fetchall():
                doc = json.loads(d)
                if _id_key(doc.get("_id")) not in yielded:
                    yield doc

    def _scan(self, conn, collection, query=None):
        """Materialized scan — required where the loop body mutates the
        table it is scanning (write/remove)."""
        return list(self._scan_iter(conn, collection, query))

    def _next_id(self, conn, collection):
        conn.execute(
            "INSERT INTO counters VALUES (?, 1) "
            "ON CONFLICT(collection) DO UPDATE SET next_id = next_id + 1",
            (collection,),
        )
        (value,) = conn.execute(
            "SELECT next_id FROM counters WHERE collection = ?", (collection,)
        ).fetchone()
        return value

    def _insert(self, conn, collection, doc):
        doc = json.loads(_dumps(doc))  # canonical JSON round-trip
        if "_id" not in doc:
            doc["_id"] = self._next_id(conn, collection)
        idk = _id_key(doc["_id"])
        for fields in self._unique_specs(conn, collection):
            try:
                conn.execute(
                    "INSERT INTO unique_keys VALUES (?, ?, ?, ?)",
                    (collection, _dumps(fields), _index_key(doc, fields), idk),
                )
            except sqlite3.IntegrityError:
                raise DuplicateKeyError(f"duplicate key on index {fields}")
        try:
            conn.execute(
                "INSERT INTO docs VALUES (?, ?, ?)", (collection, idk, _dumps(doc))
            )
        except sqlite3.IntegrityError:
            raise DuplicateKeyError(f"duplicate _id {doc['_id']!r}")
        return doc["_id"]

    def _replace(self, conn, collection, old_doc, new_doc):
        idk = _id_key(old_doc["_id"])
        for fields in self._unique_specs(conn, collection):
            fields_key = _dumps(fields)
            old_key = _index_key(old_doc, fields)
            new_key = _index_key(new_doc, fields)
            if old_key == new_key:
                continue
            conn.execute(
                "DELETE FROM unique_keys "
                "WHERE collection = ? AND fields = ? AND key = ? AND id = ?",
                (collection, fields_key, old_key, idk),
            )
            try:
                conn.execute(
                    "INSERT INTO unique_keys VALUES (?, ?, ?, ?)",
                    (collection, fields_key, new_key, idk),
                )
            except sqlite3.IntegrityError:
                raise DuplicateKeyError(f"duplicate key on index {fields}")
        conn.execute(
            "UPDATE docs SET doc = ? WHERE collection = ? AND id = ?",
            (_dumps(new_doc), collection, idk),
        )

    def _insert_many(self, conn, collection, docs):
        """Bulk insert inside the caller's transaction: per-doc outcomes
        (the new ``_id``, or the DuplicateKeyError that doc raised).

        The happy path is one ``executemany`` per statement — the q-batch
        registration shape the batched write path commits — under a single
        SAVEPOINT.  Any integrity conflict rolls that back (auto-id
        counter bumps included) and re-runs per-doc under individual
        SAVEPOINTs, so only the conflicting docs fail AND auto-assigned
        ids come out exactly as q sequential inserts would hand them out
        (a failed slot's counter bump rolls back with its savepoint on
        both paths).  A doc that cannot canonicalize to JSON fails its own
        slot with the TypeError the sequential write would raise — never
        the whole batch."""
        outcomes = [None] * len(docs)
        prepared = []  # (slot index, canonical doc)
        for i, doc in enumerate(docs):
            try:
                prepared.append((i, json.loads(_dumps(doc))))
            except Exception as exc:
                outcomes[i] = exc
        auto_id_docs = [doc for _, doc in prepared if "_id" not in doc]
        specs = self._unique_specs(conn, collection)
        conn.execute("SAVEPOINT batch_insert")
        try:
            for doc in auto_id_docs:
                doc["_id"] = self._next_id(conn, collection)
            for fields in specs:
                fields_key = _dumps(fields)
                conn.executemany(
                    "INSERT INTO unique_keys VALUES (?, ?, ?, ?)",
                    [
                        (collection, fields_key, _index_key(doc, fields),
                         _id_key(doc["_id"]))
                        for _, doc in prepared
                    ],
                )
            conn.executemany(
                "INSERT INTO docs VALUES (?, ?, ?)",
                [
                    (collection, _id_key(doc["_id"]), _dumps(doc))
                    for _, doc in prepared
                ],
            )
        except sqlite3.IntegrityError:
            conn.execute("ROLLBACK TO batch_insert")
            conn.execute("RELEASE batch_insert")
            # The rollback undid the happy path's id assignments; strip
            # them so each slot's _insert re-draws its own (and a failed
            # slot's draw rolls back with its savepoint — sequential
            # semantics).
            for doc in auto_id_docs:
                doc.pop("_id", None)
            for i, doc in prepared:
                conn.execute("SAVEPOINT one_insert")
                try:
                    outcomes[i] = self._insert(conn, collection, doc)
                    conn.execute("RELEASE one_insert")
                except DuplicateKeyError as exc:
                    conn.execute("ROLLBACK TO one_insert")
                    conn.execute("RELEASE one_insert")
                    outcomes[i] = exc
            return outcomes
        conn.execute("RELEASE batch_insert")
        for i, doc in prepared:
            outcomes[i] = doc["_id"]
        return outcomes

    def _write_in(self, conn, collection, data, query=None):
        if query is None:
            if isinstance(data, (list, tuple)):
                return [self._insert(conn, collection, doc) for doc in data]
            return self._insert(conn, collection, data)
        data = json.loads(_dumps(data))
        count = 0
        for doc in self._scan(conn, collection, query):
            if not _matches(doc, query):
                continue
            new_doc = apply_update(doc, data)
            new_doc["_id"] = doc["_id"]
            self._replace(conn, collection, doc, new_doc)
            count += 1
        return count

    def _read_in(self, conn, collection, query=None, projection=None):
        return [
            _project(doc, projection)
            for doc in self._scan_iter(conn, collection, query)
            if _matches(doc, query)
        ]

    def _read_and_write_in(self, conn, collection, query, data):
        data = json.loads(_dumps(data))
        for doc in self._scan_iter(conn, collection, query):
            if _matches(doc, query):
                new_doc = apply_update(doc, data)
                new_doc["_id"] = doc["_id"]
                self._replace(conn, collection, doc, new_doc)
                return new_doc
        return None

    def _remove_in(self, conn, collection, query=None):
        doomed = [
            doc
            for doc in self._scan(conn, collection, query)
            if _matches(doc, query)
        ]
        for doc in doomed:
            idk = _id_key(doc["_id"])
            conn.execute(
                "DELETE FROM docs WHERE collection = ? AND id = ?",
                (collection, idk),
            )
            conn.execute(
                "DELETE FROM unique_keys WHERE collection = ? AND id = ?",
                (collection, idk),
            )
        return len(doomed)

    @staticmethod
    def _is_plain_insert(op, args, kwargs):
        """A ``write`` carrying one document and no query — the slot shape
        apply_batch coalesces into :meth:`_insert_many` runs.  The query
        check must be ``is None``: an EMPTY query dict means update-all,
        not insert (write()'s own routing)."""
        return (
            op == "write"
            and len(args) == 2
            and not isinstance(args[1], (list, tuple))
            and (kwargs or {}).get("query") is None
        )

    @_translate_errors
    def apply_batch(self, ops):
        """Apply ``[(op, args, kwargs), ...]`` in ONE transaction: one
        COMMIT (and one WAL sync) per q-batch instead of q.  Outcome
        contract matches MemoryDB.apply_batch — per-slot results or
        exception instances, each failing op rolled back to its own
        SAVEPOINT so the rest of the batch commits.  Consecutive plain
        inserts into one collection ride :meth:`_insert_many`'s
        ``executemany`` fast path (the register_trials shape).  An op name
        outside BATCH_OPS rejects the whole batch upfront (nothing
        applied), same as every other backend."""
        if not ops:
            return []
        for op, _args, _kwargs in ops:
            if op not in MemoryDB.BATCH_OPS:
                raise DatabaseError(f"bad batch op {op!r}")
        if all(op in ("read", "count") for op, _, _ in ops):
            # Pure reads never need the IMMEDIATE write lock — taking it
            # would serialize every worker's per-round sync poll
            # (fetch_update_view) behind real commits.  WAL autocommit
            # reads see a consistent snapshot per statement, exactly what
            # the previous direct-call path gave.
            conn = self._conn()
            out = []
            for op, args, kwargs in ops:
                try:
                    out.append(getattr(self, f"_{op}_in")(conn, *args, **kwargs))
                except sqlite3.Error as exc:
                    out.append(DatabaseError(f"sqlite: {exc}"))
                except Exception as exc:
                    out.append(exc)
            return out
        out = []
        with self._txn() as conn:
            i = 0
            while i < len(ops):
                op, args, kwargs = ops[i]
                if self._is_plain_insert(op, args, kwargs):
                    j = i + 1
                    while j < len(ops) and self._is_plain_insert(
                        *ops[j]
                    ) and ops[j][1][0] == args[0]:
                        j += 1
                    out.extend(
                        self._insert_many(
                            conn, args[0], [o[1][1] for o in ops[i:j]]
                        )
                    )
                    i = j
                    continue
                conn.execute("SAVEPOINT batch_op")
                try:
                    result = getattr(self, f"_{op}_in")(conn, *args, **kwargs)
                    conn.execute("RELEASE batch_op")
                    out.append(result)
                except Exception as exc:
                    conn.execute("ROLLBACK TO batch_op")
                    conn.execute("RELEASE batch_op")
                    if isinstance(exc, sqlite3.Error):
                        exc = DatabaseError(f"sqlite: {exc}")
                    out.append(exc)
                i += 1
        return out

    # --- AbstractDB contract ----------------------------------------------
    @_translate_errors
    def write(self, collection, data, query=None):
        with self._txn() as conn:
            return self._write_in(conn, collection, data, query)

    @_translate_errors
    def update_many(self, collection, pairs):
        """All updates in ONE transaction (see MemoryDB.update_many)."""
        total = 0
        with self._txn() as conn:
            for query, data in pairs:
                data = json.loads(_dumps(data))
                for doc in self._scan(conn, collection, query):
                    if not _matches(doc, query):
                        continue
                    new_doc = apply_update(doc, data)
                    new_doc["_id"] = doc["_id"]
                    self._replace(conn, collection, doc, new_doc)
                    total += 1
        return total

    @_translate_errors
    def read(self, collection, query=None, projection=None):
        return self._read_in(self._conn(), collection, query, projection)

    @_translate_errors
    def read_and_write(self, collection, query, data):
        with self._txn() as conn:
            return self._read_and_write_in(conn, collection, query, data)

    @_translate_errors
    def count(self, collection, query=None):
        return self._count_in(self._conn(), collection, query)

    def _count_in(self, conn, collection, query=None):
        if not query:
            (n,) = conn.execute(
                "SELECT COUNT(*) FROM docs WHERE collection = ?", (collection,)
            ).fetchone()
            return n
        clauses, params = self._sql_prefilter(query)
        if len(clauses) == len(query):
            # Every condition was pushed to SQL, so COUNT(*) decides exactly
            # — no JSON parse per row.  The producer's count-gated sync
            # calls this every round with {experiment, status}, both
            # pushable.
            sql = (
                "SELECT COUNT(*) FROM docs WHERE collection = ? AND "
                + " AND ".join(clauses)
            )
            try:
                (n,) = conn.execute(sql, (collection, *params)).fetchone()
                return n
            except sqlite3.OperationalError:
                pass  # non-finite JSON token mid-scan: fall through
        return sum(
            1
            for doc in self._scan_iter(conn, collection, query)
            if _matches(doc, query)
        )

    @_translate_errors
    def remove(self, collection, query=None):
        with self._txn() as conn:
            return self._remove_in(conn, collection, query)

    def close(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None
