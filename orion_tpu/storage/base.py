"""Storage protocol: every coordination primitive workers rely on.

Capability parity: reference `src/orion/storage/base.py` (BaseStorageProtocol,
singleton access) + `src/orion/storage/legacy.py` (protocol mapped onto a
document DB: unique (name, version) experiment index, atomic trial
reservation via find-one-and-update, CAS status updates raising FailedUpdate,
stale-heartbeat lost-trial queries, lies in a separate collection).

Timestamps are ``time.time()`` floats everywhere (device-friendly and
pickle-stable), not datetimes.
"""

import functools
import time

from orion_tpu.core.trial import RESERVABLE_STATUSES, Trial
from orion_tpu.health import FLIGHT
from orion_tpu.storage.backends import PickledDB
from orion_tpu.storage.documents import MemoryDB
from orion_tpu.storage.retry import MODE_ALWAYS, MODE_UNAPPLIED, create_retry_policy
from orion_tpu.telemetry import (
    TELEMETRY,
    current_trace_context,
    set_trace_context,
)
from orion_tpu.utils.exceptions import DatabaseError, FailedUpdate


class BaseStorage:
    """Abstract protocol; see :class:`DocumentStorage` for the semantics.

    The batch operations (``register_trials`` / ``reserve_trials`` /
    ``update_completed_trials``) ship DEFAULT loop implementations over
    their singular siblings, so a third-party storage protocol that only
    defines the per-trial ops automatically satisfies the batch API the
    producer and client commit through.  Backends that can amortize
    (:class:`DocumentStorage` over a transactional or networked store)
    override them with single-transaction / single-round-trip versions —
    semantics are identical either way: one outcome per slot, a failing
    slot never blocking the rest."""

    def create_experiment(self, config):
        raise NotImplementedError

    def update_experiment(self, experiment=None, uid=None, where=None, **kwargs):
        raise NotImplementedError

    def fetch_experiments(self, query, projection=None):
        raise NotImplementedError

    def register_trial(self, trial):
        raise NotImplementedError

    def register_trials(self, trials):
        """Batch-register: one outcome per trial — the trial itself, or the
        exception (DuplicateKeyError for an already-taken point) that slot
        raised.  Default loop fallback; see the class docstring."""
        out = []
        for trial in trials:
            try:
                out.append(self.register_trial(trial))
            except Exception as exc:
                out.append(exc)
        return out

    def reserve_trials(self, experiment, num):
        """Claim up to ``num`` pending trials.  Default loop fallback."""
        out = []
        for _ in range(max(0, num)):
            trial = self.reserve_trial(experiment)
            if trial is None:
                break
            out.append(trial)
        return out

    def update_completed_trials(self, pairs):
        """Batch-complete ``[(trial, results), ...]``: one outcome per pair
        — the completed trial, or the exception that slot raised (a
        failing slot never aborts the rest; same containment the batched
        backends give).  Default loop fallback."""
        out = []
        for trial, results in pairs:
            try:
                out.append(self.update_completed_trial(trial, results))
            except Exception as exc:
                out.append(exc)
        return out

    def register_lie(self, trial):
        raise NotImplementedError

    # --- framework telemetry channel (optional capability) ------------------
    # Default no-ops so third-party storage protocols that predate the
    # telemetry subsystem keep satisfying the worker flush path (which is
    # fire-and-forget anyway: the producer wraps it in try/except).
    def record_metrics(self, experiment, snapshot, worker=None):
        """Upsert one worker's telemetry metrics snapshot."""

    def fetch_metrics(self, experiment):
        """All workers' metric snapshot docs for ``experiment``."""
        return []

    def record_spans(self, experiment, spans):
        """Append drained span records for ``experiment``."""

    def fetch_spans(self, experiment):
        """Every stored span record for ``experiment``, time-ordered."""
        return []

    def record_health(self, experiment, record, worker=None):
        """Append one per-round optimization-health record (orion_tpu.health)."""

    def fetch_health(self, experiment):
        """Every stored health record for ``experiment``, time-ordered."""
        return []

    def fetch_lies(self, experiment):
        raise NotImplementedError

    def reserve_trial(self, experiment):
        raise NotImplementedError

    def fetch_trials(self, experiment=None, uid=None):
        raise NotImplementedError

    def fetch_trials_by_status(self, experiment, status):
        raise NotImplementedError

    def get_trial(self, trial=None, uid=None):
        raise NotImplementedError

    def set_trial_status(self, trial, status, was=None):
        raise NotImplementedError

    def update_heartbeat(self, trial):
        raise NotImplementedError

    def fetch_lost_trials(self, experiment, timeout):
        raise NotImplementedError

    def push_trial_results(self, trial):
        raise NotImplementedError

    def update_completed_trial(self, trial, results):
        raise NotImplementedError

    def count_completed_trials(self, experiment):
        raise NotImplementedError

    def count_broken_trials(self, experiment):
        raise NotImplementedError

    def fetch_noncompleted_trials(self, experiment):
        raise NotImplementedError


# Canonical index layout; the unique specs double as the conflict oracle for
# `orion-tpu db copy` pre-flight planning (cli/db.py).
INDEX_SPECS = [
    # The user is part of experiment identity (per-user namespacing):
    # two users may own same-named experiments.
    ("experiments", ["name", "version", "metadata.user"], True),
    ("trials", ["experiment"], False),
    ("trials", ["status"], False),
    ("trials", ["experiment", "status"], False),
    ("lying_trials", ["experiment"], False),
    # Unified-telemetry channel: spans are counted/pruned and metrics
    # upserted by (experiment, worker) on every worker flush round.
    ("metrics", ["experiment"], False),
    ("spans", ["experiment"], False),
    # Optimization-health channel: one record per producer round, appended
    # and pruned by (experiment, time) like the spans above.
    ("health", ["experiment"], False),
]


#: Telemetry label per backend class; unknown (third-party) backends fall
#: back to their lowercased class name.
_BACKEND_LABELS = {
    "MemoryDB": "memory",
    "PickledDB": "pickled",
    "SQLiteDB": "sqlite",
    "NetworkDB": "network",
    "ShardedNetworkDB": "shard",
}

#: Backend-maintained monotonic counters re-exported through the telemetry
#: registry (sampled at snapshot time — zero hot-path cost).  The sharded
#: router adds its read-path health counters (failovers to a primary,
#: stale replica reads, cross-shard fan-outs); other backends simply lack
#: the attributes and skip them.
_BACKEND_COUNTER_ATTRS = (
    "txn_count",
    "wire_requests",
    "round_trips",
    "reconnects",
    "failovers",
    "replica_stale_reads",
    "fan_outs",
)


def _traced(op, span_name=None, retry=MODE_ALWAYS):
    """Time a DocumentStorage protocol op into the telemetry registry: a
    ``storage.{op}`` span (overridable — ``register_trials`` reports as
    ``storage.commit``, the produce round's write) plus a per-backend
    per-op latency histogram ``storage.{backend}.{op}``.  Disabled
    telemetry costs one attribute check.

    ``retry`` applies the storage instance's unified
    :class:`~orion_tpu.storage.retry.RetryPolicy` around the op (the mode
    says whether the op converges under re-application; None opts out).
    Retries happen INSIDE the span/histogram window, so the recorded op
    latency is what the caller actually waited — the separate
    ``storage.retries`` counter says how much of it was retry."""

    def decorate(fn):
        name = span_name or f"storage.{op}"

        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            policy = self._retry
            if policy is not None and retry is not None:
                def run():
                    return policy.run(
                        lambda: fn(self, *args, **kwargs), op=op, mode=retry
                    )
            else:
                def run():
                    return fn(self, *args, **kwargs)
            if not TELEMETRY.enabled:
                return run()
            t0 = time.perf_counter()
            # Run the op AS a child trace context: wire drivers underneath
            # (NetworkDB) inject the ambient context into their request
            # envelopes, so the server's apply span parents at THIS op span
            # (storage.commit -> netdb.apply in the distributed merge).
            parent = current_trace_context()
            ctx = parent.child() if parent is not None and parent.sampled else None
            if ctx is not None:
                set_trace_context(ctx)
            try:
                return run()
            finally:
                if ctx is not None:
                    set_trace_context(parent)
                duration = time.perf_counter() - t0
                backend = self._backend_label
                # histogram=False: the sample's ONE histogram home is the
                # per-backend key below — same-name span histograms would
                # double every snapshot's payload and duplicate info rows.
                TELEMETRY.record_span(
                    name,
                    start=t0,
                    args={"backend": backend},
                    histogram=False,
                    span_ctx=ctx,
                    parent_ctx=parent if ctx is not None else None,
                )
                TELEMETRY.observe(f"storage.{backend}.{op}", duration)

        return wrapper

    return decorate


def _retrying(op, mode=MODE_ALWAYS):
    """Retry-only wrapper (no span) for the protocol ops outside the traced
    set — reads and auxiliary writes share the same policy and transient
    classification as the hot-path ops, they just don't each earn a
    telemetry stream."""

    def decorate(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            policy = self._retry
            if policy is None:
                return fn(self, *args, **kwargs)
            return policy.run(lambda: fn(self, *args, **kwargs), op=op, mode=mode)

        return wrapper

    return decorate


class DocumentStorage(BaseStorage):
    """Protocol over any AbstractDB-style document backend."""

    def __init__(self, db, retry=None):
        self._db = db
        # Unified retry policy (storage/retry.py): default ON with modest
        # settings — every protocol op below shares one backoff/deadline/
        # classification contract across all four backends.  ``retry``
        # accepts a RetryPolicy, a ``storage.retry`` config dict, or
        # False to disable (raw pre-policy behavior).
        self._retry = create_retry_policy(retry)
        self._backend_label = _BACKEND_LABELS.get(
            type(db).__name__, type(db).__name__.lower()
        )
        for attr in _BACKEND_COUNTER_ATTRS:
            if isinstance(getattr(db, attr, None), int):
                TELEMETRY.register_external_counter(
                    f"storage.{self._backend_label}.{attr}", db, attr
                )
        self._setup_indexes()

    @property
    def db(self):
        return self._db

    def _setup_indexes(self):
        # Reference `legacy.py:70-88`; batched into one backend write cycle.
        try:
            # Schema migration: the pre-user index would keep enforcing
            # name+version uniqueness across users on older databases.
            self._db.drop_index("experiments", "name_version_1")
        except (KeyError, DatabaseError):
            pass
        self._db.ensure_indexes(INDEX_SPECS)

    # --- experiments --------------------------------------------------------
    @_retrying("create_experiment", mode=MODE_ALWAYS)
    def create_experiment(self, config):
        """Insert a new experiment config; DuplicateKeyError if (name, version)
        already exists — callers translate that into a RaceCondition retry.
        Retry-converging: a re-send of an applied-but-unacknowledged create
        surfaces as that same DuplicateKeyError, which the builder already
        treats as a lost creation race and resolves by reloading."""
        config = dict(config)
        config.setdefault("version", 1)
        _id = self._db.write("experiments", config)
        config["_id"] = _id
        return config

    @_retrying("update_experiment", mode=MODE_ALWAYS)
    def update_experiment(self, experiment=None, uid=None, where=None, **kwargs):
        query = dict(where or {})
        if uid is not None:
            query["_id"] = uid
        elif experiment is not None:
            query["_id"] = experiment["_id"]
        if not query:
            # Reference raises MissingArguments here (`legacy.py:94-109`);
            # never allow an accidental collection-wide update.
            raise DatabaseError(
                "update_experiment requires an experiment, uid, or where query"
            )
        return self._db.write("experiments", kwargs, query=query)

    @_retrying("fetch_experiments", mode=MODE_ALWAYS)
    def fetch_experiments(self, query, projection=None):
        return self._db.read("experiments", query, projection)

    # --- trials -------------------------------------------------------------
    @_traced("register_trial", retry=MODE_ALWAYS)
    def register_trial(self, trial):
        """Insert a new trial; DuplicateKeyError on a duplicate point id."""
        trial.submit_time = trial.submit_time or time.time()
        self._db.write("trials", trial.to_dict())
        return trial

    @_retrying("register_lie", mode=MODE_ALWAYS)
    def register_lie(self, trial):
        trial.submit_time = trial.submit_time or time.time()
        self._db.write("lying_trials", trial.to_dict())
        return trial

    @_retrying("fetch_lies", mode=MODE_ALWAYS)
    def fetch_lies(self, experiment):
        docs = self._db.read("lying_trials", {"experiment": _exp_id(experiment)})
        return [Trial.from_dict(d) for d in docs]

    def _reservation_ops(self, experiment):
        """The one reservation query/update pair — single-claim and batch
        paths MUST write identical documents, so both build from here.

        The claim stamps ``worker`` (host:pid) — the reference declares the
        field on Trial (`trial.py:45-46`) but never fills it; stamping at
        the reservation CAS makes `status --all`/post-mortems attribute
        every trial to the process that ran it."""
        now = time.time()
        query = {
            "experiment": _exp_id(experiment),
            "status": {"$in": list(RESERVABLE_STATUSES)},
        }
        update = {
            "status": "reserved",
            "start_time": now,
            "heartbeat": now,
            "worker": _worker_id(),
        }
        return query, update

    @_traced("reserve_trial", retry=MODE_ALWAYS)
    def reserve_trial(self, experiment):
        """Atomically claim one pending trial (the cross-worker sync point;
        reference `legacy.py:253-273`)."""
        query, update = self._reservation_ops(experiment)
        doc = self._db.read_and_write("trials", query, update)
        return Trial.from_dict(doc) if doc else None

    def _db_batch_capable(self):
        """True when the backend offers a batching primitive — THE
        capability predicate every batch op keys on (so a third primitive
        added to :meth:`_db_batch` is recognized everywhere at once)."""
        return (
            getattr(self._db, "apply_batch", None) is not None
            or getattr(self._db, "pipeline", None) is not None
        )

    def _db_batch(self, ops):
        """One backend round for ``[(op, args, kwargs), ...]`` through the
        cheapest primitive the backend offers: ``apply_batch`` (one
        transaction / one wire request), else ``pipeline`` (N request
        lines in ~1 RTT, network driver).  Callers check
        :meth:`_db_batch_capable` first and loop per-op otherwise.  Either
        primitive returns one outcome per op, exception instances
        included."""
        apply_batch = getattr(self._db, "apply_batch", None)
        if apply_batch is not None:
            return apply_batch(ops)
        return self._db.pipeline(ops)

    @_traced("reserve_trials", retry=MODE_ALWAYS)
    def reserve_trials(self, experiment, num):
        """Claim up to ``num`` pending trials; each claim is individually
        atomic (repeated find-one-and-updates — every op sees the previous
        op's status flip, even inside one transaction, so the claims are
        distinct).  The batch rides one backend round (one transaction on
        SQL, one wire request on the network driver); q=4096 reservation
        over TCP would otherwise pay 4096 serialized RTTs."""
        if num <= 0:
            return []
        if not self._db_batch_capable():
            return super().reserve_trials(experiment, num)
        query, update = self._reservation_ops(experiment)
        # Probe with ONE claim first: callers reserve-then-produce, so the
        # common steady state is an EMPTY queue — batching num futile
        # find-one-and-updates there would double the server's reservation
        # work every round.  Non-empty pays one extra round trip.
        first = self._db.read_and_write("trials", query, update)
        if first is None:
            return []
        if num == 1:
            return [Trial.from_dict(first)]
        remaining = num - 1
        if getattr(self._db, "cheap_counts", False):
            # Cap the claim batch at what is actually pending: num-1
            # find-one-and-updates against a shallow queue are mostly
            # futile full scans — inside ONE transaction on SQL backends,
            # i.e. O(num x collection) work under the exclusive write
            # lock.  The count is advisory (concurrent producers may add
            # or steal trials before the claims run); correctness still
            # comes from each claim's own CAS.
            remaining = min(remaining, self._db.count("trials", query))
        if remaining <= 0:
            return [Trial.from_dict(first)]
        docs = [first] + self._db_batch(
            [("read_and_write", ["trials", query, update], {})] * remaining
        )
        out, error = [], None
        for doc in docs:
            if isinstance(doc, Exception):
                error = error or doc
            elif doc is not None:
                out.append(Trial.from_dict(doc))
        if error is not None and not out:
            # Nothing claimed + server-side failure: surface it exactly as
            # the per-op path would — treating it as "no trials pending"
            # masks the fault and sends the caller off to produce duplicates.
            raise error
        # With claims in hand, RETURN them even if a later slot errored:
        # raising would strand already-reserved trials (no owner, no
        # heartbeat) until the lost-trial sweep.  A persistent fault will
        # surface on the next (empty-handed) round.
        return out

    @_traced("register_trials", span_name="storage.commit", retry=MODE_ALWAYS)
    def register_trials(self, trials):
        """Batch-register; returns one outcome per trial: the trial itself on
        success or the per-trial exception (DuplicateKeyError for an
        already-taken point — slot independence matters: one duplicate must
        not block the rest of a q-batch).  The whole batch is ONE backend
        round: a single ``executemany`` transaction on SQL (one fsync per
        q-batch instead of q), one wire request on the network driver, one
        lock/load/dump cycle on the pickled file."""
        now = time.time()
        # lint: disable=PERF001 -- Trial-object compat path (plugins and
        # direct callers hand real Trials); the producer's columnar round
        # rides register_trial_docs below instead.
        for trial in trials:
            trial.submit_time = trial.submit_time or now
        if not self._db_batch_capable():
            return super().register_trials(trials)
        results = self._db_batch(
            # lint: disable=PERF001 -- per-trial to_dict IS this compat
            # path's contract; the columnar twin builds docs in one pass.
            [("write", ["trials", trial.to_dict()], {}) for trial in trials]
        )
        # lint: disable=PERF001 -- O(1) zip per slot pairing outcomes back
        # to their trials.
        return [
            result if isinstance(result, Exception) else trial
            for trial, result in zip(trials, results)
        ]

    @_traced("register_trials", span_name="storage.commit", retry=MODE_ALWAYS)
    def register_trial_docs(self, docs):
        """Columnar twin of :meth:`register_trials`: RAW trial documents
        (one columnar ``TrialBatch.to_docs`` pass upstream — no ``Trial``
        objects, no per-trial ``to_dict``) committed as ONE backend round.
        One outcome per doc: an exception instance for a failed slot
        (``DuplicateKeyError`` for an already-taken point), any other value
        means the slot registered.  Same wire/transaction shape as
        ``register_trials`` — one ``write`` sub-op per doc through the
        batch primitive — so crash-consistency and convergence contracts
        (docs/robustness.md) are unchanged; shares its telemetry op name
        (``storage.commit`` span) for dashboard continuity."""
        if not self._db_batch_capable():
            out = []
            # lint: disable=PERF001 -- loop fallback for backends without
            # a batch primitive; the hot path is the _db_batch leg below.
            for doc in docs:
                try:
                    out.append(self._db.write("trials", doc))
                except Exception as exc:
                    out.append(exc)
            return out
        # lint: disable=PERF001 -- one wire/transaction sub-op per doc IS
        # the batch primitive's slot shape (per-slot outcomes require it).
        return self._db_batch([("write", ["trials", doc], {}) for doc in docs])

    @_traced("update_completed_trials", retry=MODE_ALWAYS)
    def update_completed_trials(self, pairs):
        """Batch-complete ``[(trial, results), ...]`` — one backend round
        (one transaction on SQL, one wire request on the network driver);
        per-trial FailedUpdate surfaces in the returned outcome list
        instead of aborting the batch."""
        if not self._db_batch_capable():
            return super().update_completed_trials(pairs)
        outcomes = []
        now = time.time()
        ops = []
        for trial, results in pairs:
            trial.results = list(results)
            trial.end_time = now
            ops.append(
                (
                    "read_and_write",
                    [
                        "trials",
                        {"_id": trial.id},
                        {
                            "results": [r.to_dict() for r in trial.results],
                            "end_time": trial.end_time,
                            "status": "completed",
                        },
                    ],
                    {},
                )
            )
        docs = self._db_batch(ops)
        for (trial, _results), doc in zip(pairs, docs):
            if isinstance(doc, Exception):
                outcomes.append(doc)
            elif doc is None:
                outcomes.append(
                    FailedUpdate(f"completed trial {trial.id} vanished from storage")
                )
            else:
                trial.status = "completed"
                outcomes.append(trial)
        return outcomes

    @_traced("fetch_trials", retry=MODE_ALWAYS)
    def fetch_trials(self, experiment=None, uid=None):
        query = {"experiment": uid if uid is not None else _exp_id(experiment)}
        docs = self._db.read("trials", query)
        docs.sort(key=_trial_doc_order)
        return [Trial.from_dict(d) for d in docs]

    @_retrying("read_trial_docs", mode=MODE_ALWAYS)
    def read_trial_docs(self, uid, ids=None, projection=None):
        """Raw trial documents for an experiment, optionally id-filtered and
        projected.  The supported read path for consumers that need
        signature-level reads without Trial construction — the EVC tree
        fetch's incremental cache (`evc/experiment.py`) — and therefore a
        whitelisted READ-ONLY operation; reaching for ``storage.db`` instead
        breaks on `ExperimentView`'s read-only proxy."""
        query = {"experiment": uid}
        if ids is not None:
            query["_id"] = {"$in": list(ids)}
        return self._db.read("trials", query, projection=projection)

    @_traced("fetch_update_view", retry=MODE_ALWAYS)
    def fetch_update_view(self, experiment, known_completed=-1):
        """The producer's per-round sync snapshot: ``(trials, n_completed)``.

        When the backend advertises ``cheap_counts``, the completed history
        is count-gated — re-read only when the completed count moved past
        ``known_completed`` (completed is terminal, so the count can only
        grow); otherwise the round reads just the (small) non-completed
        set.  On a pipeline-capable backend the non-completed read and the
        count share ONE round trip.  Backends without cheap ops (the
        pickled file pays a full lock/unpickle cycle per op) keep the
        single full fetch.

        The two reads are not one atomic snapshot: a trial completing
        between them appears in both (its completed view wins below) or
        flips the count so the gate re-opens — it can never vanish from
        the round.  Trials are returned in the same (submit_time, id)
        order ``fetch_trials`` delivers, which is what keeps replay
        deterministic.
        """
        if not getattr(self._db, "cheap_counts", False):
            trials = self.fetch_trials(experiment)
            return trials, -1
        exp_id = _exp_id(experiment)
        noncompleted_query = {"experiment": exp_id, "status": {"$ne": "completed"}}
        completed_query = {"experiment": exp_id, "status": "completed"}
        if self._db_batch_capable():
            nc_docs, n_completed = self._db_batch(
                [
                    ("read", ["trials", noncompleted_query], {}),
                    ("count", ["trials", completed_query], {}),
                ]
            )
            for result in (nc_docs, n_completed):
                if isinstance(result, Exception):
                    raise result
        else:
            nc_docs = self._db.read("trials", noncompleted_query)
            n_completed = self._db.count("trials", completed_query)
        if n_completed != known_completed:
            done_docs = self._db.read("trials", completed_query)
        else:
            done_docs = []
        by_id = {d["_id"]: d for d in nc_docs}
        by_id.update((d["_id"], d) for d in done_docs)  # completed view wins
        docs = sorted(by_id.values(), key=_trial_doc_order)
        return [Trial.from_dict(d) for d in docs], n_completed

    @_retrying("fetch_trials_by_status", mode=MODE_ALWAYS)
    def fetch_trials_by_status(self, experiment, status):
        statuses = [status] if isinstance(status, str) else list(status)
        docs = self._db.read(
            "trials",
            {"experiment": _exp_id(experiment), "status": {"$in": statuses}},
        )
        return [Trial.from_dict(d) for d in docs]

    @_retrying("get_trial", mode=MODE_ALWAYS)
    def get_trial(self, trial=None, uid=None):
        _id = uid if uid is not None else trial.id
        docs = self._db.read("trials", {"_id": _id})
        return Trial.from_dict(docs[0]) if docs else None

    @_traced("set_trial_status", retry=MODE_UNAPPLIED)
    def set_trial_status(self, trial, status, was=None):
        """Compare-and-swap status update (reference `legacy.py:223-243`).

        Always guarded: the swap only succeeds if the stored status equals
        ``was`` (defaulting to the caller's in-memory view, so a concurrent
        transition by another worker raises FailedUpdate instead of being
        silently overwritten).

        The CAS does NOT converge under blind re-application (a retried
        swap that already applied reports a spurious FailedUpdate), so the
        retry mode is ``unapplied`` and ambiguous losses verify-then-
        converge here: a re-read showing the target status means the lost
        attempt applied (success); one showing the guard status means it
        did not (the ambiguity is cleared and the policy may retry);
        anything else re-raises the ambiguity.
        """
        guard = was if was is not None else trial.status
        query = {"_id": trial.id, "status": guard}
        update = {"status": status}
        if status in ("completed", "interrupted", "broken"):
            update["end_time"] = time.time()
        try:
            doc = self._db.read_and_write("trials", query, update)
        except DatabaseError as exc:
            if not getattr(exc, "maybe_applied", False):
                raise
            try:
                current = self._db.read("trials", {"_id": trial.id})
            except Exception:
                # The verify read failed too, so the ambiguity STANDS —
                # re-raise the original ambiguous error.  Letting the
                # read's own (possibly non-ambiguous) failure propagate
                # would hand the retry policy a transient it happily
                # re-runs, blind-re-executing the non-converging CAS.
                raise exc from None
            stored = current[0].get("status") if current else None
            if stored == status:
                trial.status = status
                return Trial.from_dict(current[0])
            if stored == guard:
                exc.maybe_applied = False  # provably not applied: retriable
            raise
        if doc is None:
            raise FailedUpdate(
                f"trial {trial.id} not updated to {status!r} (was={was!r})"
            )
        trial.status = status
        # Status transitions are flight-recorder events (orion_tpu.health):
        # the crash post-mortem wants the recent lifecycle edges on its
        # timeline.  Guarded — the args dict must not allocate when the
        # recorder is off (this is a per-trial path).
        if FLIGHT.enabled:
            FLIGHT.record(
                "trial.status",
                args={"trial": trial.id, "from": guard, "to": status},
            )
        return Trial.from_dict(doc)

    @_traced("update_heartbeat", retry=MODE_ALWAYS)
    def update_heartbeat(self, trial):
        doc = self._db.read_and_write(
            "trials",
            {"_id": trial.id, "status": "reserved"},
            {"heartbeat": time.time()},
        )
        if doc is None:
            raise FailedUpdate(f"trial {trial.id} is no longer reserved")

    @_retrying("fetch_lost_trials", mode=MODE_ALWAYS)
    def fetch_lost_trials(self, experiment, timeout):
        """Reserved trials whose worker stopped heartbeating (crashed/killed)."""
        threshold = time.time() - timeout
        docs = self._db.read(
            "trials",
            {
                "experiment": _exp_id(experiment),
                "status": "reserved",
                "heartbeat": {"$lt": threshold},
            },
        )
        return [Trial.from_dict(d) for d in docs]

    @_retrying("push_trial_results", mode=MODE_ALWAYS)
    def push_trial_results(self, trial):
        doc = self._db.read_and_write(
            "trials",
            {"_id": trial.id, "status": "reserved"},
            {"results": [r.to_dict() for r in trial.results]},
        )
        if doc is None:
            raise FailedUpdate(f"cannot push results of non-reserved trial {trial.id}")
        return Trial.from_dict(doc)

    @_traced("update_completed_trial", retry=MODE_ALWAYS)
    def update_completed_trial(self, trial, results):
        trial.results = list(results)
        trial.end_time = time.time()
        doc = self._db.read_and_write(
            "trials",
            {"_id": trial.id},
            {
                "results": [r.to_dict() for r in trial.results],
                "end_time": trial.end_time,
                "status": "completed",
            },
        )
        if doc is None:
            raise FailedUpdate(f"completed trial {trial.id} vanished from storage")
        trial.status = "completed"
        return trial

    @_retrying("count_completed_trials", mode=MODE_ALWAYS)
    def count_completed_trials(self, experiment):
        return self._db.count(
            "trials", {"experiment": _exp_id(experiment), "status": "completed"}
        )

    @_retrying("count_broken_trials", mode=MODE_ALWAYS)
    def count_broken_trials(self, experiment):
        return self._db.count(
            "trials", {"experiment": _exp_id(experiment), "status": "broken"}
        )

    # --- telemetry (SURVEY §5: suggest/observe timing, TPU-build addition) ---
    #: Oldest samples are pruned past this per-experiment count so the
    #: telemetry collection cannot grow without bound on long hunts.
    TELEMETRY_CAP = 5000

    def record_timing(self, experiment, op, duration, count=1):
        """One timing sample: op in {'suggest', 'observe'}."""
        self.record_timings(experiment, [(op, duration, count)])

    def record_timings(self, experiment, samples):
        """Batched samples [(op, duration, count), ...] in ONE backend write
        (a write per sample would cost a full lock/rewrite cycle each on the
        file backend — on the producer's hot path)."""
        if not samples:
            return
        self._append_timings(experiment, samples)
        self._prune_timings(experiment)

    # Append leg: a lost-reply re-send would duplicate samples, so the
    # ambiguous case gives up (mode="unapplied") — losing one flush beats
    # double-counting it, and the next round flushes fresh data anyway.
    # The prune leg retries separately so ITS transient failure can never
    # re-run an append that already landed.
    @_retrying("record_timings", mode=MODE_UNAPPLIED)
    def _append_timings(self, experiment, samples):
        now = time.time()
        exp_id = _exp_id(experiment)
        self._db.write(
            "telemetry",
            [
                {
                    "experiment": exp_id,
                    "op": op,
                    "duration": float(duration),
                    "count": int(count),
                    "time": now,
                }
                for op, duration, count in samples
            ],
        )

    # Count/read/remove-below-cutoff all converge under re-application.
    # Raw _db reads, not fetch_timings/fetch_spans: the fetchers carry
    # their own @_retrying, and nesting two policies would compound to
    # max_attempts**2 backend attempts during a sustained outage.
    @_retrying("record_timings.prune", mode=MODE_ALWAYS)
    def _prune_timings(self, experiment):
        exp_id = _exp_id(experiment)
        n = self._db.count("telemetry", {"experiment": exp_id})
        if n > self.TELEMETRY_CAP:
            docs = self._db.read("telemetry", {"experiment": exp_id})
            # Index off the re-read list, not the earlier count: another
            # worker's prune can land between count() and read().
            if len(docs) <= self.TELEMETRY_CAP:
                return
            docs.sort(key=lambda d: d.get("time") or 0.0)
            cutoff = docs[len(docs) - self.TELEMETRY_CAP].get("time") or 0.0
            self._db.remove(
                "telemetry",
                {"experiment": exp_id, "time": {"$lt": cutoff}},
            )

    @_retrying("fetch_timings", mode=MODE_ALWAYS)
    def fetch_timings(self, experiment, op=None):
        query = {"experiment": _exp_id(experiment)}
        if op is not None:
            query["op"] = op
        docs = self._db.read("telemetry", query)
        docs.sort(key=lambda d: d.get("time") or 0.0)
        return docs

    # --- unified telemetry channel (orion_tpu.telemetry snapshots/spans) ----
    #: Span documents are pruned past this per-experiment count (same
    #: unbounded-growth guard as TELEMETRY_CAP for timing samples).
    SPANS_CAP = 20000

    # Upsert keyed by (experiment, worker): re-applying after an ambiguous
    # loss converges on the same latest-snapshot doc, so retry always.
    @_retrying("record_metrics", mode=MODE_ALWAYS)
    def record_metrics(self, experiment, snapshot, worker=None):
        """Upsert one worker's metrics snapshot (``Telemetry.snapshot()``)
        keyed by (experiment, worker) — counters/histograms are per-worker
        monotonic totals, so the latest doc supersedes earlier ones and
        ``fetch_metrics`` + ``telemetry.merge_snapshots`` aggregate across
        the fleet.  ``worker`` defaults to this process's host:pid."""
        exp_id = _exp_id(experiment)
        worker = worker or _worker_id()
        doc = {
            "experiment": exp_id,
            "worker": worker,
            "time": time.time(),
            "counters": dict(snapshot.get("counters") or {}),
            "gauges": dict(snapshot.get("gauges") or {}),
            "histograms": dict(snapshot.get("histograms") or {}),
        }
        updated = self._db.write(
            "metrics", doc, query={"experiment": exp_id, "worker": worker}
        )
        if not updated:
            self._db.write("metrics", doc)

    @_retrying("fetch_metrics", mode=MODE_ALWAYS)
    def fetch_metrics(self, experiment):
        docs = self._db.read("metrics", {"experiment": _exp_id(experiment)})
        docs.sort(key=lambda d: d.get("time") or 0.0)
        return docs

    def record_spans(self, experiment, spans):
        """Append drained span records (``Telemetry.drain_spans()``) in ONE
        backend write; prunes the oldest past :attr:`SPANS_CAP`."""
        if not spans:
            return
        self._append_spans(experiment, spans)
        self._prune_spans(experiment)

    # Append leg, same contract as record_timings: ambiguous losses give up
    # instead of risking duplicated span records, and the prune retries
    # separately so it cannot re-run a landed append.
    @_retrying("record_spans", mode=MODE_UNAPPLIED)
    def _append_spans(self, experiment, spans):
        exp_id = _exp_id(experiment)
        worker = _worker_id()
        self._db.write(
            "spans",
            [{"experiment": exp_id, "worker": worker, **span} for span in spans],
        )

    @_retrying("record_spans.prune", mode=MODE_ALWAYS)
    def _prune_spans(self, experiment):
        exp_id = _exp_id(experiment)
        n = self._db.count("spans", {"experiment": exp_id})
        if n > self.SPANS_CAP:
            # Prune with hysteresis — down to 90% of the cap, not exactly
            # to it: a prune-to-cap would leave the collection full, so
            # EVERY later flush re-pays the full fetch+sort+remove on the
            # producer's hot path; the 10% slack amortizes it to one prune
            # per ~2k spans.
            keep = max(1, int(self.SPANS_CAP * 0.9))
            docs = self._db.read("spans", {"experiment": exp_id})
            # Index off the re-read list, not the earlier count: another
            # worker's prune can land between count() and read().
            if len(docs) <= keep:
                return
            docs.sort(key=lambda d: d.get("ts") or 0.0)
            cutoff = docs[len(docs) - keep].get("ts") or 0.0
            self._db.remove(
                "spans", {"experiment": exp_id, "ts": {"$lt": cutoff}}
            )

    @_retrying("fetch_spans", mode=MODE_ALWAYS)
    def fetch_spans(self, experiment):
        docs = self._db.read("spans", {"experiment": _exp_id(experiment)})
        docs.sort(key=lambda d: d.get("ts") or 0.0)
        return docs

    # --- optimization-health channel (orion_tpu.health records) -------------
    #: Health records are pruned past this per-experiment count — one
    #: record per producer round, so the cap holds the recent few thousand
    #: rounds of every worker (same unbounded-growth guard as SPANS_CAP).
    HEALTH_CAP = 4096

    def record_health(self, experiment, record, worker=None):
        """Append one per-round health record (``BaseAlgorithm
        .health_record()`` merged by the producer) in ONE backend write;
        prunes the oldest past :attr:`HEALTH_CAP`."""
        if not record:
            return
        self._append_health(experiment, record, worker)
        self._prune_health(experiment)

    # Append leg, same contract as record_spans: an ambiguous-loss resend
    # would duplicate the round's record (skewing round-rate and regret
    # curves), so give up on maybe_applied — the next round flushes fresh
    # data anyway.  The prune leg retries separately so its transient
    # failure can never re-run a landed append.
    @_retrying("record_health", mode=MODE_UNAPPLIED)
    def _append_health(self, experiment, record, worker=None):
        doc = dict(record)
        doc["experiment"] = _exp_id(experiment)
        doc["worker"] = worker or _worker_id()
        if doc.get("time") is None:
            doc["time"] = time.time()
        self._db.write("health", doc)

    @_retrying("record_health.prune", mode=MODE_ALWAYS)
    def _prune_health(self, experiment):
        exp_id = _exp_id(experiment)
        n = self._db.count("health", {"experiment": exp_id})
        if n > self.HEALTH_CAP:
            # Hysteresis to 90% of the cap, same rationale as _prune_spans:
            # a prune-to-cap would re-pay the fetch+sort+remove on every
            # later flush of a full collection.
            keep = max(1, int(self.HEALTH_CAP * 0.9))
            docs = self._db.read("health", {"experiment": exp_id})
            # Index off the re-read list, not the earlier count: another
            # worker's prune can land between count() and read().
            if len(docs) <= keep:
                return
            docs.sort(key=lambda d: d.get("time") or 0.0)
            cutoff = docs[len(docs) - keep].get("time") or 0.0
            self._db.remove(
                "health", {"experiment": exp_id, "time": {"$lt": cutoff}}
            )

    @_retrying("fetch_health", mode=MODE_ALWAYS)
    def fetch_health(self, experiment):
        docs = self._db.read("health", {"experiment": _exp_id(experiment)})
        docs.sort(key=lambda d: d.get("time") or 0.0)
        return docs

    @_retrying("fetch_noncompleted_trials", mode=MODE_ALWAYS)
    def fetch_noncompleted_trials(self, experiment):
        docs = self._db.read(
            "trials",
            {"experiment": _exp_id(experiment), "status": {"$ne": "completed"}},
        )
        return [Trial.from_dict(d) for d in docs]


def _trial_doc_order(doc):
    """THE trial ordering: every path that hands trials to an algorithm
    must sort with this one key, or observe order (and with it replay
    determinism) diverges between paths."""
    return (doc.get("submit_time") or 0.0, str(doc.get("_id")))


def _worker_id():
    """host:pid identity of this worker process (computed per call: a
    forked/spawned child must not inherit the parent's pid stamp)."""
    import os
    import socket

    return f"{socket.gethostname()}:{os.getpid()}"


def _exp_id(experiment):
    if isinstance(experiment, dict):
        return experiment["_id"]
    if hasattr(experiment, "id"):
        return experiment.id
    return experiment


_READONLY_METHODS = {
    "fetch_experiments",
    "fetch_trials",
    "fetch_trials_by_status",
    "fetch_lies",
    "fetch_lost_trials",
    "fetch_noncompleted_trials",
    "get_trial",
    "read_trial_docs",
    "count_completed_trials",
    "count_broken_trials",
    "fetch_timings",
    "fetch_metrics",
    "fetch_spans",
    "fetch_health",
}


class ReadOnlyStorage:
    """Whitelist proxy (reference `storage/base.py:251-281`)."""

    def __init__(self, storage):
        self._storage = storage

    def __getattr__(self, name):
        if name not in _READONLY_METHODS:
            raise AttributeError(f"{name!r} is not a read-only storage operation")
        return getattr(self._storage, name)


def _parse_network_address(config):
    """(host, port) from a network-storage config; ``address``/``path`` may
    carry ``host[:port]`` (the ORION_DB_ADDRESS env form)."""
    host = config.get("host", "127.0.0.1")
    port = config.get("port", 8765)
    address = config.get("address")
    if not address and "host" not in config and "port" not in config:
        # `path` doubles as ORION_DB_ADDRESS, but only when host/port are not
        # given: the layered config merge leaks the DEFAULTS pickled path into
        # a network storage section, and it must not hijack the address.
        address = config.get("path")
    if address:
        address = str(address)
        if ":" in address:
            host, _, port = address.rpartition(":")
            if not host or not port:
                raise DatabaseError(
                    f"bad network DB address {address!r}; expected host:port"
                )
        else:
            host = address
    return host, int(port)


def resolve_wire_secret(config, env_prefix="ORION_DB", what="network DB"):
    """Shared secret for an authenticated wire surface: explicit config
    value, a secret file (config or ``{env_prefix}_SECRET_FILE``), or
    ``{env_prefix}_SECRET``.  None = unauthenticated client (open/
    localhost servers).  Shared by the netdb driver (``ORION_DB``) and
    the suggest gateway client (``ORION_SERVE``) so the two wire planes
    resolve credentials identically."""
    import os

    if config.get("secret") is not None:
        return str(config["secret"])
    path = config.get("secret_file") or os.getenv(f"{env_prefix}_SECRET_FILE")
    if path:
        try:
            with open(path) as handle:
                secret = handle.read().strip()
        except OSError as exc:
            raise DatabaseError(
                f"cannot read {what} secret file {path!r}: {exc} "
                "(is the shared mount available on this node?)"
            ) from exc
        if not secret:
            raise DatabaseError(f"{what} secret file {path!r} is empty")
        return secret
    return os.getenv(f"{env_prefix}_SECRET") or None


def _resolve_network_secret(config):
    return resolve_wire_secret(config, env_prefix="ORION_DB", what="network DB")


def create_storage(config=None):
    """Build a storage instance from a config dict.

    ``{"type": "memory"}`` or ``{"type": "pickled", "path": ...}``.
    A ``retry`` sub-dict tunes the unified retry policy knobs
    (``max_attempts``/``base_delay``/``max_delay``/``multiplier``/
    ``jitter``/``deadline`` — docs/robustness.md); ``retry: false``
    disables retries entirely.
    """
    config = dict(config or {})
    retry = config.get("retry")
    db_type = config.get("type", "pickled")
    if db_type in ("memory", "ephemeral", "ephemeraldb"):
        return DocumentStorage(MemoryDB(), retry=retry)
    if db_type in ("pickled", "pickleddb"):
        path = config.get("path", "orion_tpu_db.pkl")
        return DocumentStorage(
            PickledDB(path, lock_timeout=config.get("lock_timeout", 60.0)),
            retry=retry,
        )
    if db_type in ("sqlite", "sqlite3"):
        from orion_tpu.storage.sqlitedb import SQLiteDB

        path = config.get("path", "orion_tpu_db.sqlite")
        return DocumentStorage(
            SQLiteDB(path, timeout=config.get("lock_timeout", 60.0)),
            retry=retry,
        )
    if db_type in ("network", "netdb"):
        from orion_tpu.storage.netdb import NetworkDB

        secret = _resolve_network_secret(config)
        if config.get("shards"):
            # Scale-out control plane: a `shards:` stanza routes this
            # storage through the consistent-hash router (per-shard
            # replicas ride inside each entry; docs/multi_node.md).
            from orion_tpu.storage.shard import ShardedNetworkDB

            from orion_tpu.storage.shard import (
                DEFAULT_PROMOTE_AFTER_S,
                PLACEMENT_TTL_S,
            )

            return DocumentStorage(
                ShardedNetworkDB(
                    config["shards"],
                    vnodes=config.get("vnodes", 64),
                    timeout=config.get("timeout", 60.0),
                    secret=secret,
                    reconnect_jitter=config.get("reconnect_jitter", 0.1),
                    shard_retry=config.get("shard_retry"),
                    replica_reads=config.get("replica_reads", True),
                    # Self-healing knobs (docs/multi_node.md): automatic
                    # replica promotion + its confirmation window, and the
                    # placement-override cache TTL the rebalance fence
                    # grace must cover.
                    auto_promote=config.get("auto_promote", True),
                    promote_after=config.get(
                        "promote_after", DEFAULT_PROMOTE_AFTER_S
                    ),
                    placement_ttl=config.get("placement_ttl", PLACEMENT_TTL_S),
                ),
                retry=retry,
            )
        host, port = _parse_network_address(config)
        return DocumentStorage(
            NetworkDB(
                host=host,
                port=port,
                timeout=config.get("timeout", 60.0),
                secret=secret,
                reconnect_jitter=config.get("reconnect_jitter", 0.1),
            ),
            retry=retry,
        )
    raise DatabaseError(f"Unknown storage type {db_type!r}")


_storage_singleton = None


def setup_storage(config=None, force=False):
    """Initialize the process-wide storage singleton."""
    global _storage_singleton
    if _storage_singleton is None or force:
        _storage_singleton = create_storage(config)
    return _storage_singleton


def get_storage():
    if _storage_singleton is None:
        raise DatabaseError("storage singleton not initialized; call setup_storage()")
    return _storage_singleton
