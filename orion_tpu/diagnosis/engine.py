"""Rule-engine core for ``orion-tpu doctor``.

The stack emits every production signal a hunt can produce — telemetry
counters/gauges/histograms (PR 3), per-round health records and flight
events (PR 7), /metrics gauges and device-memory accounting (PR 10),
replication lag and epochs (PR 13) — but nothing *interprets* them: an
operator must already know that ``jax.retraces`` climbing means a
signature fork, or that a flat EI plus collapsed lengthscales means the
GP died.  This engine turns those signal planes into severity-ranked
findings with runbook links, mirroring the ``analysis/`` lint-rule
architecture: a :class:`DoctorRule` protocol, a registry, and one
``run_rules`` entry point over a joined :class:`~orion_tpu.diagnosis
.snapshot.Snapshot`.

Contracts every rule keeps (lint rule ``TEL006`` machine-checks them):

- ``severity`` is declared explicitly (``info`` | ``warn`` | ``critical``)
  — a finding's severity is the rule's, never computed per call;
- ``runbook`` names an anchor into ``docs/monitoring.md``'s "Diagnosis &
  runbook" section (the registry-completeness test resolves every anchor);
- ``evaluate()`` never builds per-call computed metric keys — the
  per-rule gauge name (``doctor.findings.<ID>``) is minted ONCE at class
  definition, the same discipline TEL001/TEL003 enforce elsewhere.

Rule ids live in the ``DX*`` family: ``DX0xx`` systems (``rules_system``),
``DX02x`` storage/replication (``rules_storage``), ``DX04x`` optimizer
health (``rules_gp``), ``DX05x`` compiler plane (``rules_compiler``).
"""

import json

#: Severity ladder, least to most urgent.  FIXED: the /metrics exposition
#: labels findings with these exact strings.
SEVERITIES = ("info", "warn", "critical")

_SEVERITY_RANK = {name: rank for rank, name in enumerate(SEVERITIES)}

#: Documentation page every runbook anchor resolves into.
RUNBOOK_PAGE = "docs/monitoring.md"


class Finding:
    """One diagnosis: rule identity, severity, human message, runbook
    link, and an optional numeric evidence value (what the rule measured —
    the trend slope, the counter total).

    ``subject`` names WHAT the finding is about when one rule can fire
    for several independent subjects (shard 0 vs shard 2, the queue vs
    the backpressure counter).  The watch-mode alert dedup keys on
    ``(rule_id, subject)`` — never on the message, whose embedded live
    numbers change every pass while the condition persists."""

    __slots__ = (
        "rule_id", "name", "severity", "message", "runbook", "value", "subject"
    )

    def __init__(self, rule, message, value=None, subject=None):
        self.rule_id = rule.id
        self.name = rule.name
        self.severity = rule.severity
        self.runbook = rule.runbook
        self.message = message
        self.value = value
        self.subject = subject

    @property
    def fingerprint(self):
        """The alert-dedup identity of this finding."""
        return (self.rule_id, self.subject)

    def format(self):
        return (
            f"[{self.severity.upper():>8}] {self.rule_id} {self.name}: "
            f"{self.message}  (runbook: {RUNBOOK_PAGE}#{self.runbook})"
        )

    def to_dict(self):
        out = {
            "rule": self.rule_id,
            "name": self.name,
            "severity": self.severity,
            "message": self.message,
            "runbook": f"{RUNBOOK_PAGE}#{self.runbook}",
        }
        if self.value is not None:
            out["value"] = self.value
        return out

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<Finding {self.format()}>"


class DoctorRule:
    """Base class for diagnosis rules.

    Subclasses declare ``id``/``name``/``severity``/``runbook``/
    ``description`` and implement ``evaluate(snapshot)`` yielding
    :class:`Finding`s.  One instance evaluates one snapshot; instances are
    created fresh per :func:`run_rules` call, so rules need no reset
    discipline.  ``gauge_name`` is minted once per class here — evaluate
    bodies must never compute metric keys (TEL006)."""

    id = "DX000"
    name = "abstract"
    severity = "warn"
    runbook = ""
    description = ""
    #: The per-rule findings gauge (``orion_tpu_doctor_findings{rule,
    #: severity}`` on the /metrics plane); set by ``__init_subclass__``.
    gauge_name = "doctor.findings.DX000"

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        cls.gauge_name = "doctor.findings." + cls.id

    def evaluate(self, snapshot):
        """Yield Findings for one snapshot."""
        return ()

    def finding(self, message, value=None, subject=None):
        return Finding(self, message, value=value, subject=subject)


def default_rules():
    """Fresh instances of every registered rule, validated: a rule whose
    severity or runbook anchor is missing would ship findings the report
    cannot rank or the operator cannot act on — refuse at registration,
    exactly where the TEL006 lint rule points."""
    from orion_tpu.diagnosis.rules_compiler import COMPILER_RULES
    from orion_tpu.diagnosis.rules_gp import GP_RULES
    from orion_tpu.diagnosis.rules_storage import STORAGE_RULES
    from orion_tpu.diagnosis.rules_system import SYSTEM_RULES

    rules = []
    for family in (SYSTEM_RULES, STORAGE_RULES, GP_RULES, COMPILER_RULES):
        for cls in family:
            if cls.severity not in SEVERITIES:
                raise ValueError(
                    f"doctor rule {cls.id} declares unknown severity "
                    f"{cls.severity!r} (must be one of {SEVERITIES})"
                )
            if not cls.runbook:
                raise ValueError(
                    f"doctor rule {cls.id} declares no runbook anchor"
                )
            rules.append(cls())
    return rules


def doctor_catalog():
    """(id, name, severity, runbook, description) for every registered
    rule — docs, ``doctor --list-rules``, and the completeness scan."""
    return [
        (r.id, r.name, r.severity, r.runbook, r.description)
        for r in default_rules()
    ]


def rule_severities():
    """id -> severity for every registered rule PLUS the engine's
    broken-rule marker (the /metrics exposition labels the
    ``orion_tpu_doctor_findings`` family with it)."""
    out = {r.id: r.severity for r in default_rules()}
    out[_BROKEN_RULE.id] = _BROKEN_RULE.severity
    return out


class DoctorReport:
    """The outcome of one diagnosis pass: findings (most severe first),
    per-rule counts (zeros included, so publishing clears resolved
    findings), and the status/exit-code contract (``critical`` -> 1)."""

    def __init__(self, findings, rules):
        self.findings = sorted(
            findings,
            key=lambda f: (-_SEVERITY_RANK.get(f.severity, 0), f.rule_id),
        )
        # The engine's broken-rule marker publishes like any rule: a rule
        # crashing in a production watchdog is exactly the condition a
        # scraper must be able to alert on.
        counts = {rule.id: 0 for rule in rules}
        counts.setdefault(_BROKEN_RULE.id, 0)
        for finding in self.findings:
            counts[finding.rule_id] = counts.get(finding.rule_id, 0) + 1
        self.rule_counts = counts
        #: rule id -> its findings gauge name, precomputed by the classes.
        self.gauge_names = {rule.id: rule.gauge_name for rule in rules}
        self.gauge_names.setdefault(_BROKEN_RULE.id, _BROKEN_RULE.gauge_name)

    def count(self, severity):
        return sum(1 for f in self.findings if f.severity == severity)

    @property
    def status(self):
        if self.count("critical"):
            return "critical"
        if self.count("warn"):
            return "warn"
        return "ok"

    @property
    def exit_code(self):
        """The automation contract: 0 healthy (warns included — they are
        advice, not pages), 1 on any critical finding."""
        return 1 if self.count("critical") else 0

    def summary(self):
        """The /healthz doctor block: status + severity counts."""
        return {
            "status": self.status,
            "critical": self.count("critical"),
            "warn": self.count("warn"),
            "info": self.count("info"),
        }

    def to_dict(self):
        return {
            **self.summary(),
            "findings": [f.to_dict() for f in self.findings],
        }

    def format_human(self, label=None):
        head = f"orion-tpu doctor — {label}" if label else "orion-tpu doctor"
        lines = [head]
        if not self.findings:
            lines.append("healthy: no findings")
        for finding in self.findings:
            lines.append(finding.format())
        counts = self.summary()
        lines.append(
            f"status: {counts['status']}  "
            f"(critical: {counts['critical']}, warn: {counts['warn']}, "
            f"info: {counts['info']})"
        )
        return "\n".join(lines)

    def format_json(self):
        return json.dumps(self.to_dict())


def run_rules(snapshot, rules=None):
    """Evaluate every rule over ``snapshot`` and return a
    :class:`DoctorReport`.  A single misbehaving rule must not take down
    the diagnosis pass (the doctor may run inside a worker thread), so
    per-rule exceptions degrade to an engine ``warn`` finding naming the
    rule instead of raising."""
    if rules is None:
        rules = default_rules()
    findings = []
    for rule in rules:
        try:
            findings.extend(rule.evaluate(snapshot))
        except Exception as exc:  # pragma: no cover - defensive
            findings.append(
                Finding(
                    _BROKEN_RULE,
                    f"rule {rule.id} ({rule.name}) crashed during "
                    f"evaluation: {type(exc).__name__}: {exc}",
                )
            )
    return DoctorReport(findings, rules)


class _BrokenRuleMarker(DoctorRule):
    """Identity the engine reports a crashing rule under — itself a warn
    (the diagnosis pass is degraded, not the system)."""

    id = "DX999"
    name = "broken-rule"
    severity = "warn"
    runbook = "dx999-broken-rule"
    description = "a registered doctor rule raised during evaluation"


_BROKEN_RULE = _BrokenRuleMarker()
