"""Dependency-free trend detectors for the doctor's rule catalog.

Trend rules (replication-lag growth, device-memory growth, regret
stagnation) must not fire on one noisy sample, and must not need scipy —
the same discipline as ``benchmarks/regret_gate.py``'s dependency-free
Mann–Whitney.  Two detectors cover every shipped rule:

- :func:`robust_slope` — the Theil–Sen estimator (median of pairwise
  slopes): one outlier sample in a window of ten cannot flip the sign,
  which a least-squares fit (or a naive last-minus-first) can;
- :func:`ewma` — exponentially weighted moving average, for "recent
  level" questions (is EI *still* flat, not was-it-flat-once).

Both accept plain Python floats; records with missing fields are the
caller's job to drop (``Snapshot.series`` already does).
"""


def robust_slope(values):
    """Theil–Sen slope of ``values`` against their indices (units: value
    change per sample).  Returns 0.0 for fewer than 2 points — a window
    too short to claim a trend must read as "no trend", never as noise."""
    points = [float(v) for v in values]
    n = len(points)
    if n < 2:
        return 0.0
    slopes = []
    for i in range(n):
        for j in range(i + 1, n):
            slopes.append((points[j] - points[i]) / float(j - i))
    slopes.sort()
    mid = len(slopes) // 2
    if len(slopes) % 2:
        return slopes[mid]
    return 0.5 * (slopes[mid - 1] + slopes[mid])


def ewma(values, alpha=0.35):
    """Exponentially weighted moving average of ``values`` (newest last).
    Returns None on an empty series — "no data" must stay distinguishable
    from "averages to zero"."""
    result = None
    for value in values:
        value = float(value)
        result = value if result is None else alpha * value + (1 - alpha) * result
    return result


def relative_change(values):
    """``(last - first) / max(|first|, eps)`` over the series — the
    magnitude question a positive slope alone cannot answer (a slope of
    +1 byte/round on a 100 MB buffer is not growth worth a finding)."""
    if len(values) < 2:
        return 0.0
    first, last = float(values[0]), float(values[-1])
    return (last - first) / max(abs(first), 1e-12)
