"""Publishing and watching: findings -> alerts, gauges, /healthz, workon.

Four consumers share one publishing path (:func:`publish_report`):

- the ``orion-tpu doctor --watch`` loop (new findings become
  ``flight.alert`` events written straight into the experiment's spans
  channel, deduplicated so a persistent condition alerts once and
  re-alerts only after it clears);
- the in-process watchdog ``workon`` starts when ``doctor_interval:`` /
  ``ORION_TPU_DOCTOR_INTERVAL`` is set (same dedup, alerts ride the
  process FLIGHT ring and reach storage through the producer's ordinary
  mirror flush);
- the /metrics plane: every registered rule's finding count is published
  as its ``doctor.findings.<ID>`` gauge (zeros included, so a resolved
  finding CLEARS its gauge — exported as the
  ``orion_tpu_doctor_findings{rule,severity}`` family);
- ``/healthz``: the most recent report's summary is held in a process-wide
  slot (:func:`doctor_summary`) so the gateway and worker metrics servers
  answer probes from diagnosis, not bare process liveness.

Cost discipline matches the rest of the observability layer: alert
emission guards its allocating args on ``FLIGHT.enabled`` (TEL004), gauge
names are precomputed per rule class (TEL001/TEL006), and the last-report
slot is one tsan-annotated cell behind its own registered lock.
"""

import logging
import threading
import time

from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.diagnosis.engine import run_rules
from orion_tpu.diagnosis.snapshot import collect_snapshot, local_snapshot
from orion_tpu.health import FLIGHT
from orion_tpu.telemetry import TELEMETRY

log = logging.getLogger(__name__)

#: Most recent published report summary (the /healthz doctor block),
#: stored WITH its publish timestamp: a watchdog whose passes started
#: failing (storage outage) stops publishing, and /healthz must not keep
#: answering the pre-outage verdict forever.
_last_lock = threading.Lock()
_last_summary = None
_last_published = 0.0

#: A published summary older than this is stale: fall back to a fresh
#: local-registry pass (or "unknown") instead of serving a fossil.
SUMMARY_TTL_S = 120.0


class AlertDeduper:
    """Watch-mode alert dedup: a finding alerts when it APPEARS, stays
    silent while it persists, and re-alerts if it clears and comes back.
    Keyed by each finding's ``fingerprint`` — (rule id, subject), NEVER
    the message: messages embed live counter/trend values that change
    every pass while the condition persists, and keying on them would
    re-alert the same retry spike every interval forever."""

    def __init__(self):
        self._active = set()

    def new_findings(self, findings):
        current = {f.fingerprint: f for f in findings}
        fresh = [
            finding
            for key, finding in current.items()
            if key not in self._active
        ]
        self._active = set(current)
        return fresh


def publish_report(report, new_findings=None, storage=None, experiment=None):
    """Publish one diagnosis report: gauges for every rule (zeros clear),
    the /healthz summary slot, and — for ``new_findings`` (the deduper's
    output; None publishes none) — ``flight.alert`` events into the
    process FLIGHT ring and, when ``storage``/``experiment`` are given
    (the CLI watch path, which has no producer to mirror its ring), the
    same events written directly into the spans channel."""
    global _last_summary, _last_published
    if TELEMETRY.enabled:
        for rule_id, count in report.rule_counts.items():
            name = report.gauge_names.get(rule_id)
            if name is not None:
                TELEMETRY.set_gauge(name, count)
    with _last_lock:
        TSAN.write("diagnosis._last_summary")
        _last_summary = report.summary()
        _last_published = time.time()
    events = findings_as_events(new_findings or ())
    if FLIGHT.enabled:
        for event in events:
            FLIGHT.record("alert", args=event["args"])
    if events and storage is not None and experiment is not None:
        from orion_tpu.health import flight_events_as_spans

        try:
            storage.record_spans(experiment, flight_events_as_spans(events))
        except Exception:  # pragma: no cover - alerts must not kill the watch
            log.debug("could not record doctor alerts", exc_info=True)


def findings_as_events(findings):
    """Findings -> flight-recorder event dicts (``kind: alert``) — the
    shape ``flight_events_as_spans`` mirrors into the spans channel as
    ``flight.alert`` records."""
    import os

    now = time.time()
    pid = os.getpid()
    return [
        {
            "kind": "alert",
            "ts": now,
            "pid": pid,
            "args": {
                "rule": finding.rule_id,
                "severity": finding.severity,
                "message": finding.message,
            },
        }
        for finding in findings
    ]


def doctor_summary(evaluate_local=True):
    """The /healthz doctor block: the last published report's summary
    (stamped with its age) while it is FRESH, or — with ``evaluate_local``
    — a fresh pass over this process's own registry (counters/gauges
    rules only; there is no health series locally).  A published summary
    past :data:`SUMMARY_TTL_S` is a fossil — the watchdog that minted it
    stopped publishing (its passes are failing, or it is gone) — so it is
    NOT served as current truth.  Never raises: probes must get an
    answer."""
    now = time.time()
    with _last_lock:
        TSAN.read("diagnosis._last_summary")
        summary = _last_summary
        age = now - _last_published
    if summary is not None and age <= SUMMARY_TTL_S:
        return {**summary, "age_s": round(age, 1)}
    if not evaluate_local:
        if summary is not None:
            # Too old to trust, too informative to hide: degrade the
            # status to unknown but keep the counts + age for the prober.
            return {**summary, "status": "unknown", "age_s": round(age, 1)}
        return {"status": "unknown", "critical": 0, "warn": 0, "info": 0}
    try:
        return run_rules(local_snapshot()).summary()
    except Exception:  # pragma: no cover - a probe must get an answer
        return {"status": "unknown", "critical": 0, "warn": 0, "info": 0}


def _reset_last_summary():
    """Test isolation hook: forget the published slot."""
    global _last_summary, _last_published
    with _last_lock:
        TSAN.write("diagnosis._last_summary")
        _last_summary = None
        _last_published = 0.0


class DoctorWatchdog:
    """The in-process watchdog ``workon`` runs next to the worker loop:
    every ``interval`` seconds, join the experiment's channels into a
    snapshot, evaluate the rule catalog, and publish (gauges + deduped
    ``flight.alert`` events that reach storage through the producer's
    ordinary flight mirror).  Daemon thread; a diagnosis failure is logged
    and the loop continues — observability must never kill a worker."""

    def __init__(self, experiment, interval):
        self.experiment = experiment
        self.interval = max(float(interval), 1.0)
        self._stop = threading.Event()
        self._thread = None
        self._deduper = AlertDeduper()
        #: Accumulated replication probes so the lag-GROWTH trend rule has
        #: a series to work with (bounded window).
        self._replication_series = []

    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name="orion-tpu-doctor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def tick(self):
        """One diagnosis pass (also the unit-test entry point)."""
        snapshot = collect_snapshot(
            self.experiment, replication_series=self._replication_series
        )
        if snapshot.replication:
            self._replication_series.append(snapshot.replication)
            del self._replication_series[:-32]
        report = run_rules(snapshot)
        publish_report(report, new_findings=self._deduper.new_findings(report.findings))
        return report

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:  # pragma: no cover - watchdog never kills workon
                log.debug("doctor watchdog pass failed", exc_info=True)


def maybe_start_watchdog(experiment):
    """Start the workon watchdog when ``ORION_TPU_DOCTOR_INTERVAL`` asks
    for one (the ``doctor_interval:`` config key resolves to the same env
    spelling in cli/base.py, so ``hunt --n-workers`` children inherit it).
    Absent/invalid/non-positive means "not requested" -> None.  Failures
    are logged, never raised."""
    import os

    raw = os.environ.get("ORION_TPU_DOCTOR_INTERVAL", "").strip()
    if not raw:
        return None
    try:
        interval = float(raw)
    except ValueError:
        log.warning("ignoring non-numeric ORION_TPU_DOCTOR_INTERVAL=%r", raw)
        return None
    if interval <= 0:
        return None
    try:
        watchdog = DoctorWatchdog(experiment, interval).start()
    except Exception:  # pragma: no cover - observability never kills workon
        log.warning("could not start doctor watchdog", exc_info=True)
        return None
    log.info("doctor watchdog running every %.1fs", interval)
    return watchdog
