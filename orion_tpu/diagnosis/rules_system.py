"""Systems-plane doctor rules (``DX00x``): device dispatch hygiene,
worker liveness, the wall≈device budget, and serve-plane saturation.

Each rule reads only signals the stack already emits (docs/monitoring.md
names every one); thresholds live as class attributes so the seeded
pathology fixtures in ``tests/unit/test_doctor.py`` can construct
unambiguous extremes and the docs can quote the exact bar.
"""

from orion_tpu.diagnosis.engine import DoctorRule


class RetraceStorm(DoctorRule):
    id = "DX001"
    name = "retrace-storm"
    severity = "critical"
    runbook = "dx001-retrace-storm"
    description = (
        "jax.retraces climbing round over round means a fused-step "
        "signature fork: every produce round pays a synchronous XLA "
        "recompile (tens of seconds on a real TPU) instead of a cache hit."
    )

    #: A healthy hunt pays a handful of compiles (initial signatures +
    #: pow-2 bucket growths); a fork retraces per ROUND.  Both bars must
    #: hold: enough rounds to judge, and retraces keeping pace with them.
    MIN_ROUNDS = 10
    MIN_RETRACES = 10
    RETRACES_PER_ROUND = 0.5

    def evaluate(self, snapshot):
        rounds = snapshot.rounds()
        retraces = snapshot.counter("jax.retraces")
        if rounds >= self.MIN_ROUNDS and retraces >= max(
            self.MIN_RETRACES, self.RETRACES_PER_ROUND * rounds
        ):
            yield self.finding(
                f"{retraces} synchronous retraces over {rounds} rounds "
                "(healthy: a handful total) — a static argument is forking "
                "the fused-step signature every round",
                value=retraces,
            )


class HeartbeatLag(DoctorRule):
    id = "DX002"
    name = "heartbeat-lag"
    severity = "warn"
    runbook = "dx002-heartbeat-lag"
    description = (
        "pacemaker.heartbeat_lag_s approaching the heartbeat threshold: "
        "live reserved trials are about to be swept as lost and re-run."
    )

    #: Fire at half the sweep threshold — early enough to act, late
    #: enough that ordinary scheduling jitter stays quiet.
    LAG_FRACTION = 0.5
    DEFAULT_HEARTBEAT = 120.0

    def evaluate(self, snapshot):
        lag = snapshot.gauge("pacemaker.heartbeat_lag_s")
        if lag is None:
            return
        heartbeat = float(snapshot.heartbeat or self.DEFAULT_HEARTBEAT)
        if lag > self.LAG_FRACTION * heartbeat:
            yield self.finding(
                f"worst heartbeat lag {lag:.1f}s exceeds "
                f"{self.LAG_FRACTION:g}x the {heartbeat:g}s sweep threshold "
                "— reserved trials risk being swept as lost (gauges merge "
                "by MAX, so this is the worst worker's number)",
                value=lag,
            )


class StaleWorker(DoctorRule):
    id = "DX003"
    name = "stale-worker"
    severity = "warn"
    runbook = "dx003-stale-worker"
    description = (
        "a worker stopped flushing metrics/health while the rest of the "
        "fleet is live: crashed, hung, or partitioned — its MAX-merged "
        "gauges are fossils."
    )

    def evaluate(self, snapshot):
        ages = snapshot.worker_ages()
        if len(ages) < 2:
            return
        freshest = min(ages.values())
        # The "fleet is live" gate: when EVERY worker is quiet the hunt
        # ended (or the store is an archive) — that is not a stale-worker
        # pathology, and firing on finished runs would make one-shot
        # diagnosis over old experiments permanently noisy.
        if freshest > snapshot.stale_after:
            return
        stale = sorted(
            worker
            for worker, age in ages.items()
            if age > snapshot.stale_after
        )
        if stale:
            worst = max(ages[worker] for worker in stale)
            yield self.finding(
                f"{len(stale)} worker(s) stopped flushing for > "
                f"{snapshot.stale_after:g}s while the fleet is live: "
                f"{', '.join(stale)}",
                value=worst,
                # Subject = WHICH workers: another worker going quiet is
                # a new alert; the same set aging further is not.
                subject=tuple(stale),
            )


class HostBudgetBreach(DoctorRule):
    id = "DX004"
    name = "host-budget-breach"
    severity = "warn"
    runbook = "dx004-host-budget-breach"
    description = (
        "the mean producer round runs far longer than the mean device "
        "window: host work (codec, storage, Python) dominates the round "
        "again — the wall-=-device contract is regressing."
    )

    #: Mean producer.round vs mean device.dispatch.  The round CONTAINS
    #: the device window, so the bench's host budget of F x device bounds
    #: a healthy round at (1 + F) x device — the threshold is DERIVED from
    #: the same ``orion_tpu.hostbudget`` knob the bench gate and
    #: ``orion-tpu top`` read (ORION_TPU_HOST_BUDGET_FACTOR overrides all
    #: three at once), so the doctor can never drift from the gate.
    MIN_SAMPLES = 4

    @property
    def FACTOR(self):
        from orion_tpu.hostbudget import round_budget_factor

        return round_budget_factor()

    def evaluate(self, snapshot):
        round_mean = snapshot.histogram_mean("producer.round")
        device_mean = snapshot.histogram_mean("device.dispatch")
        if round_mean is None or device_mean is None or device_mean <= 0:
            return
        if (
            int(snapshot.histogram("producer.round").get("count", 0))
            < self.MIN_SAMPLES
        ):
            return
        factor = self.FACTOR
        if round_mean > factor * device_mean:
            yield self.finding(
                f"mean round {round_mean * 1e3:.1f}ms vs mean device window "
                f"{device_mean * 1e3:.1f}ms (> {factor:g}x = 1 + host-budget "
                "factor): the round is host-dominated — see breakdown_ms / "
                "`orion-tpu trace --attribute` for which stage grew",
                value=round_mean / device_mean,
            )


class ServeQueueSaturation(DoctorRule):
    id = "DX005"
    name = "serve-queue-saturation"
    severity = "warn"
    runbook = "dx005-serve-queue-saturation"
    description = (
        "the suggest gateway's admission queue is backing up or tenants "
        "are being told to retry: the device (or the coalescing window) "
        "can no longer keep up with offered load."
    )

    QUEUE_DEPTH = 64
    BACKPRESSURE = 20

    def evaluate(self, snapshot):
        depth = snapshot.gauge("serve.queue_depth", default=0.0)
        latest = snapshot.latest_health() or {}
        depth = max(depth, float(latest.get("serve_queue_depth") or 0.0))
        if depth >= self.QUEUE_DEPTH:
            yield self.finding(
                f"gateway admission queue depth {depth:g} >= "
                f"{self.QUEUE_DEPTH} — suggests are waiting on the "
                "dispatcher; widen max_width, shorten the window, or shard "
                "the gateway",
                value=depth,
                subject="queue",
            )
        backpressure = snapshot.counter("serve.backpressure")
        if backpressure >= self.BACKPRESSURE:
            yield self.finding(
                f"{backpressure} backpressure (RETRY-AFTER) replies — "
                "tenants exceed their inflight quotas or the dispatcher "
                "backlog timer is firing; raise quotas or add capacity",
                value=backpressure,
                subject="backpressure",
            )


class MeshUtilizationSkew(DoctorRule):
    id = "DX006"
    name = "mesh-utilization-skew"
    severity = "warn"
    runbook = "dx006-mesh-utilization-skew"
    description = (
        "a sharded (use_mesh) run whose per-device byte placement is "
        "lopsided: one device holds far more than its even share of the "
        "sharded buffers — candidate sharding has silently regressed "
        "toward single-device execution and the other chips idle."
    )

    #: Worst device's fraction vs the even 1/n share.  Replicated leaves
    #: (GP state) contribute equally everywhere, so a healthy sharded
    #: round sits AT the even share; 2x means at least half the sharded
    #: bytes collapsed onto one device.
    SKEW_FACTOR = 2.0

    def evaluate(self, snapshot):
        latest = snapshot.latest_health() or {}
        # Algo-level fields first (the producer's own fused round), then
        # the gateway's serve_-prefixed twins (coalesced dispatch).
        for prefix in ("", "serve_"):
            devices = latest.get(prefix + "mesh_devices")
            max_frac = latest.get(prefix + "mesh_util_max_frac")
            if not devices or max_frac is None or int(devices) < 2:
                continue
            even = 1.0 / int(devices)
            if float(max_frac) >= self.SKEW_FACTOR * even:
                plane = "gateway" if prefix else "producer"
                yield self.finding(
                    f"{plane} mesh placement skew: worst device holds "
                    f"{float(max_frac):.0%} of sharded bytes vs the even "
                    f"{even:.0%} share over {int(devices)} devices — "
                    "candidate sharding is collapsing onto one chip (check "
                    "pool divisibility and bench --sharded placement)",
                    value=float(max_frac),
                    subject=plane,
                )


class FleetTenantSkew(DoctorRule):
    id = "DX007"
    name = "tenant-skew"
    severity = "warn"
    runbook = "dx007-tenant-skew"
    description = (
        "the gateway fleet's tenant placement is lopsided: one member "
        "hosts far more than its even share of tenants — its device "
        "serializes the coalesced dispatches the other members' idle "
        "devices should be absorbing."
    )

    #: Worst member's tenant count vs the even total/members share.  The
    #: consistent-hash ring balances to within small factors at scale;
    #: sustained 2x means hot experiments hash-collided onto one member
    #: (or the membership list drifted between clients and gateways).
    SKEW_FACTOR = 2.0
    #: Judgement gate: tiny fleets are lumpy by nature (3 tenants over 3
    #: members CAN land 2/1/0 legitimately).
    MIN_TENANTS = 8

    def evaluate(self, snapshot):
        gauges = snapshot.metrics.get("gauges") or {}
        per_member = {
            name: float(value)
            for name, value in gauges.items()
            if name.startswith("serve.fleet.tenants.g")
        }
        if len(per_member) < 2:
            return
        total = sum(per_member.values())
        if total < self.MIN_TENANTS:
            return
        worst_member, worst = max(per_member.items(), key=lambda kv: kv[1])
        even = total / len(per_member)
        if worst >= self.SKEW_FACTOR * even:
            yield self.finding(
                f"fleet member {worst_member.rsplit('.', 1)[-1]} hosts "
                f"{worst:g} of {total:g} tenants vs the even "
                f"{even:g}-per-member share over {len(per_member)} members "
                "— placement is collapsing onto one gateway (check that "
                "every client and member agrees on the fleet list, then "
                "rebalance with fleet_set)",
                value=worst,
                subject=worst_member,
            )


class HandoffStuck(DoctorRule):
    id = "DX008"
    name = "handoff-stuck"
    severity = "critical"
    runbook = "dx008-handoff-stuck"
    description = (
        "a fenced tenant is older than the handoff TTL: a fleet "
        "migration froze mid-flight and the tenant answers RETRY-AFTER "
        "forever — its workers are stalled, not failing over (the state "
        "still lives on the fenced member)."
    )

    #: The gateway's own --handoff-ttl default
    #: (orion_tpu.serve.fleet.HANDOFF_TTL_S).  A handoff is one snapshot
    #: push — milliseconds to seconds; half a minute fenced means the
    #: destination hung or died mid-import.
    TTL_S = 30.0

    def evaluate(self, snapshot):
        age = snapshot.gauge("serve.fleet.fenced_age_s", default=0.0)
        if age > self.TTL_S:
            yield self.finding(
                f"a tenant has been fenced for {age:g}s (> {self.TTL_S:g}s "
                "handoff TTL) — the migration's destination never acked "
                "the import; restart the fenced member (its store/persist "
                "snapshot unfences on boot) or re-run fleet_set",
                value=age,
            )


SYSTEM_RULES = (
    RetraceStorm,
    HeartbeatLag,
    StaleWorker,
    HostBudgetBreach,
    ServeQueueSaturation,
    MeshUtilizationSkew,
    FleetTenantSkew,
    HandoffStuck,
)
