"""Compiler-plane doctor rules (``DX05x``): compile-storm rate, retrace
attribution coverage, prewarm correctness, and HBM headroom.

These read the signals :mod:`orion_tpu.compiler_plane` emits — the
``jax.compiles`` counter, the ``jax.retraces.attributed`` /
``jax.retraces.prewarm_covered`` attribution counters, and the
``compiler.*`` gauges /metrics publishes — so every rule is gated on the
compiler plane actually being active (``jax.compiles > 0`` where it
matters): an old snapshot from a build without the plane must stay quiet,
not fire "unattributed" over counters that never existed.
"""

from orion_tpu.diagnosis.engine import DoctorRule


class CompileStorm(DoctorRule):
    id = "DX050"
    name = "compile-storm"
    severity = "warn"
    runbook = "dx050-compile-storm"
    description = (
        "jax.compiles keeping pace with rounds: the process is paying XLA "
        "compilation continuously (signature churn across families, or a "
        "prewarm loop re-warming the same buckets) instead of a handful of "
        "compiles up front."
    )

    #: A healthy hunt compiles each family a handful of times (initial
    #: signatures + pow-2 bucket growths, prewarms included); a storm
    #: compiles per ROUND.  Both bars must hold, exactly like DX001.
    MIN_ROUNDS = 10
    MIN_COMPILES = 20
    COMPILES_PER_ROUND = 1.0

    def evaluate(self, snapshot):
        rounds = snapshot.rounds()
        compiles = snapshot.counter("jax.compiles")
        if rounds >= self.MIN_ROUNDS and compiles >= max(
            self.MIN_COMPILES, self.COMPILES_PER_ROUND * rounds
        ):
            yield self.finding(
                f"{compiles} XLA compilations over {rounds} rounds (healthy: "
                "a handful total across all jit families) — check `orion-tpu "
                "profile` for which family and which static is churning",
                value=compiles,
            )


class UnattributedRetrace(DoctorRule):
    id = "DX051"
    name = "unattributed-retrace"
    severity = "warn"
    runbook = "dx051-unattributed-retrace"
    description = (
        "jax.retraces counted without a matching compiler-plane "
        "attribution: some jit call site books retraces outside the "
        "CompileRegistry, so `flight.retrace` cannot name the changed "
        "static — the self-diagnosing contract is broken."
    )

    def evaluate(self, snapshot):
        # Gate on the plane being active: a snapshot from a build without
        # the registry has retraces but no compiles counter at all — that
        # is missing instrumentation, not an attribution bug.
        if not snapshot.counter("jax.compiles"):
            return
        retraces = snapshot.counter("jax.retraces")
        attributed = snapshot.counter("jax.retraces.attributed")
        if retraces > attributed:
            yield self.finding(
                f"{retraces - attributed} of {retraces} retraces have no "
                "compiler-plane attribution — a jit call site counts "
                "jax.retraces without CompileRegistry.record_retrace "
                "(the bench smoke gate pins retraces_attributed == retraces)",
                value=retraces - attributed,
            )


class PrewarmCoveredRetrace(DoctorRule):
    id = "DX052"
    name = "prewarm-covered-retrace"
    severity = "critical"
    runbook = "dx052-prewarm-covered-retrace"
    description = (
        "a synchronous retrace landed at a signature a completed prewarm "
        "already recorded: the warm compiled something the real dispatch "
        "then could not reuse — a prewarm bug (statics drift between the "
        "prewarm closure and the dispatch path), paying both the warm AND "
        "the stall."
    )

    def evaluate(self, snapshot):
        covered = snapshot.counter("jax.retraces.prewarm_covered")
        if covered:
            yield self.finding(
                f"{covered} retrace(s) at signatures prewarm had already "
                "warmed — the prewarm compile is not hitting the same jit "
                "cache entry as the dispatch; diff the `flight.retrace` "
                "signature against the prewarm's in `orion-tpu profile`",
                value=covered,
            )


class HbmFootprintNearCapacity(DoctorRule):
    id = "DX053"
    name = "hbm-footprint-near-capacity"
    severity = "warn"
    runbook = "dx053-hbm-footprint-near-capacity"
    description = (
        "the largest compiled plan's HBM footprint (arguments + outputs + "
        "temporaries + generated code) is within the alert fraction of "
        "device capacity: the next q or history-bucket growth may OOM the "
        "device instead of compiling."
    )

    #: Fire when the worst plan pins >= this fraction of device HBM — the
    #: next pow-2 bucket growth roughly doubles the dominant buffers.
    CAPACITY_FRACTION = 0.8

    def evaluate(self, snapshot):
        footprint = snapshot.gauge("compiler.hbm_bytes_max")
        capacity = snapshot.gauge("compiler.hbm_capacity_bytes")
        if not footprint or not capacity:
            return
        ratio = float(footprint) / float(capacity)
        if ratio >= self.CAPACITY_FRACTION:
            yield self.finding(
                f"largest plan HBM footprint {footprint / 1e9:.2f}GB is "
                f"{ratio:.0%} of the {capacity / 1e9:.2f}GB device capacity "
                f"(alert at {self.CAPACITY_FRACTION:.0%}) — the predicted "
                "HBM-bound q is in `orion-tpu profile`; cap q or the fit "
                "bucket before the next growth",
                value=ratio,
            )


COMPILER_RULES = (
    CompileStorm,
    UnattributedRetrace,
    PrewarmCoveredRetrace,
    HbmFootprintNearCapacity,
)
