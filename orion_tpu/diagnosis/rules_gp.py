"""Optimizer-health doctor rules (``DX04x``) over the per-round
health-record series (PR 7, ``orion_tpu.health``).

These are the signals ``orion-tpu top`` renders raw and an experienced
operator reads by eye: a NaN marginal likelihood, lengthscales pinned at
the clip floor, an EI surface gone flat, a q-batch that stopped being
diverse, an incumbent that stopped moving, device memory that only goes
up.  Trend rules use the shared robust-slope detector so one noisy round
cannot fire a finding.
"""

import math

from orion_tpu.diagnosis.engine import DoctorRule
from orion_tpu.diagnosis.trend import relative_change, robust_slope


def _bad(value):
    """NaN/inf guard over a float-ish health field."""
    try:
        value = float(value)
    except (TypeError, ValueError):
        return False
    return math.isnan(value) or math.isinf(value)


class GPDegenerate(DoctorRule):
    id = "DX040"
    name = "gp-degenerate"
    severity = "critical"
    runbook = "dx040-gp-degenerate"
    description = (
        "the GP fit itself died: NaN/inf marginal likelihood or noise, or "
        "every lengthscale collapsed to the clip floor — suggestions are "
        "now draws from a broken model, not a posterior."
    )

    #: All lengthscales below this = the kernel treats EVERY dimension as
    #: pure noise (the per-dim clip floor is 1e-3-scale).
    LS_COLLAPSE = 1e-3

    def evaluate(self, snapshot):
        latest = snapshot.latest_health()
        if not latest:
            return
        for field in ("gp_mll", "gp_noise"):
            if _bad(latest.get(field)):
                yield self.finding(
                    f"latest health record carries a non-finite {field} "
                    f"({latest.get(field)}) — the GP fit has diverged; "
                    "check the objective scale and the copula transform",
                    value=latest.get("round"),
                )
                return
        ls_max = latest.get("gp_ls_max")
        if ls_max is not None and float(ls_max) < self.LS_COLLAPSE:
            yield self.finding(
                f"all fitted lengthscales collapsed below "
                f"{self.LS_COLLAPSE:g} (max {float(ls_max):.2g}) — the "
                "model treats every dimension as noise",
                value=float(ls_max),
            )


class EIFlatline(DoctorRule):
    id = "DX041"
    name = "ei-flatline"
    severity = "warn"
    runbook = "dx041-ei-flatline"
    description = (
        "expected improvement has been ~zero over the whole candidate pool "
        "for several consecutive rounds: either the hunt converged, or the "
        "fit thinks the incumbent is unattainable — both mean new rounds "
        "buy nothing."
    )

    WINDOW = 4
    EI_FLOOR = 1e-8

    def evaluate(self, snapshot):
        ei = snapshot.series("acq_ei_max", last=self.WINDOW)
        if len(ei) < self.WINDOW:
            return
        if all(float(v) < self.EI_FLOOR for v in ei):
            yield self.finding(
                f"acq_ei_max < {self.EI_FLOOR:g} for the last "
                f"{self.WINDOW} rounds — acquisition flattened (converged, "
                "or the GP fit is dead: cross-check DX040/DX043)",
                value=float(ei[-1]),
            )


class QDedupCollapse(DoctorRule):
    id = "DX042"
    name = "q-dedup-collapse"
    severity = "warn"
    runbook = "dx042-q-dedup-collapse"
    description = (
        "the selected q-batch keeps containing mostly duplicate rows: the "
        "candidate generator collapsed onto too few points — most of the "
        "batch's device and evaluation budget is wasted."
    )

    WINDOW = 3
    UNIQUE_FLOOR = 0.5

    def evaluate(self, snapshot):
        fracs = snapshot.series("q_unique_frac", last=self.WINDOW)
        if len(fracs) < self.WINDOW:
            return
        ordered = sorted(float(v) for v in fracs)
        median = ordered[len(ordered) // 2]
        if median < self.UNIQUE_FLOOR:
            yield self.finding(
                f"median q-batch unique fraction {median:.2f} < "
                f"{self.UNIQUE_FLOOR:g} over the last {self.WINDOW} rounds "
                "— the dedup fill is running out of distinct candidates",
                value=median,
            )


class RegretStagnation(DoctorRule):
    id = "DX043"
    name = "regret-stagnation"
    severity = "info"
    runbook = "dx043-regret-stagnation"
    description = (
        "the incumbent has not moved for many rounds: converged, stuck in "
        "a basin, or the optimizer stopped learning — info, because a "
        "finished hunt looks exactly like this on purpose."
    )

    MIN_RECORDS = 10
    #: Relative improvement of best_y across the trailing half-window
    #: below this = stagnant.
    REL_IMPROVEMENT = 1e-4

    def evaluate(self, snapshot):
        best = snapshot.series("best_y")
        if len(best) < self.MIN_RECORDS:
            return
        window = [float(v) for v in best[len(best) // 2:]]
        first, last = window[0], window[-1]
        improvement = (first - last) / max(abs(first), 1e-12)
        if improvement < self.REL_IMPROVEMENT:
            yield self.finding(
                f"incumbent unchanged over the last {len(window)} recorded "
                f"rounds (relative improvement {improvement:.2g}) — "
                "converged or stuck; cross-check DX041 for a flat EI",
                value=improvement,
            )


class MemoryGrowth(DoctorRule):
    id = "DX044"
    name = "memory-growth"
    severity = "warn"
    runbook = "dx044-memory-growth"
    description = (
        "device-resident bytes grow steadily round over round, well past "
        "what history growth explains: leaked buffers (or an unbounded "
        "cache) will eventually OOM the accelerator."
    )

    MIN_RECORDS = 12
    #: Relative growth across the window.  Pow-2 history growth doubles at
    #: most once per window at steady state; 50% SUSTAINED with a positive
    #: robust slope is a leak signature.
    REL_GROWTH = 0.5

    def evaluate(self, snapshot):
        mem = snapshot.series("mem_bytes", last=2 * self.MIN_RECORDS)
        if len(mem) < self.MIN_RECORDS:
            return
        if robust_slope(mem) > 0 and relative_change(mem) >= self.REL_GROWTH:
            yield self.finding(
                f"device-live bytes grew {float(mem[0]) / 1e6:.1f} -> "
                f"{float(mem[-1]) / 1e6:.1f} MB across {len(mem)} rounds "
                "(sustained positive trend) — check memory.* gauges for "
                "which pool is growing",
                value=float(mem[-1]),
            )


GP_RULES = (
    GPDegenerate,
    EIFlatline,
    QDedupCollapse,
    RegretStagnation,
    MemoryGrowth,
)
