"""The joined telemetry view the doctor rules evaluate.

One :class:`Snapshot` merges every signal plane the stack already emits:

- the MERGED cross-worker metrics snapshot (counters/gauges/histograms,
  ``telemetry.merge_snapshots`` semantics) plus the raw per-worker docs
  (their flush timestamps are the staleness signal);
- the per-round health-record time series (``fetch_health`` order) — the
  ONLY stored series, so every trend rule (regret stagnation, memory
  growth, EI flatline) reads it;
- recent flight events (the ``flight.*`` mirror in the spans channel);
- the sharded control plane's replication probe (``replication_health()``)
  and, in watch mode, the accumulated probe SERIES — lag growth needs
  more than one point, and the lag gauges are last-write-wins.

Rules never reach around the snapshot to storage: a snapshot can be built
from storage (:func:`collect_snapshot`), from the in-process registry
alone (:func:`local_snapshot` — the gateway/worker ``/healthz`` path and
the bench gate), or literally in a test fixture — which is what makes
every rule pinnable by a seeded-pathology snapshot.
"""

import time

#: A worker whose last metrics/health flush is older than this is stale:
#: 3x the producer's snapshot-upsert interval (``Producer
#: .METRICS_FLUSH_INTERVAL`` = 2s) — kept as a literal here so building a
#: snapshot never imports the producer (and jax underneath it); the
#: cli/top dashboard derives its marker from the same product.
STALE_AFTER_DEFAULT = 6.0

_EMPTY_HIST = {"buckets": (), "count": 0, "sum": 0.0, "min": 0.0, "max": 0.0}


class Snapshot:
    """One joined, immutable-by-convention view for a diagnosis pass."""

    def __init__(
        self,
        metrics=None,
        per_worker=None,
        health=None,
        flight=None,
        replication=None,
        replication_series=None,
        heartbeat=None,
        stale_after=None,
        now=None,
    ):
        self.metrics = metrics or {"counters": {}, "gauges": {}, "histograms": {}}
        self.per_worker = list(per_worker or ())
        self.health = list(health or ())
        self.flight = list(flight or ())
        self.replication = replication
        # Watch mode appends each frame's probe; a one-shot sees a
        # single-point series (trend rules then stay quiet by design).
        if replication_series is not None:
            self.replication_series = list(replication_series)
        else:
            self.replication_series = [replication] if replication else []
        self.heartbeat = heartbeat
        self.stale_after = (
            float(stale_after) if stale_after is not None else STALE_AFTER_DEFAULT
        )
        self.now = time.time() if now is None else float(now)

    # --- metrics accessors ---------------------------------------------------
    def counter(self, name, default=0):
        return int((self.metrics.get("counters") or {}).get(name, default))

    def counter_sum(self, *needles):
        """Sum every counter whose name contains one of ``needles`` (the
        reconnects counters are per-backend-prefixed, same join the top
        dashboard performs)."""
        total = 0
        for name, value in (self.metrics.get("counters") or {}).items():
            if any(needle in name for needle in needles):
                total += int(value)
        return total

    def gauge(self, name, default=None):
        value = (self.metrics.get("gauges") or {}).get(name)
        return default if value is None else float(value)

    def histogram(self, name):
        return (self.metrics.get("histograms") or {}).get(name) or _EMPTY_HIST

    def histogram_mean(self, name):
        """Mean seconds of one histogram, or None when it has no samples."""
        hist = self.histogram(name)
        count = int(hist.get("count", 0))
        if count <= 0:
            return None
        return float(hist.get("sum", 0.0)) / count

    def rounds(self):
        """Producer rounds covered by this snapshot: the ``producer.round``
        histogram count when the metrics plane saw any, else the length of
        the health series (bench-style snapshots carry records only)."""
        count = int(self.histogram("producer.round").get("count", 0))
        return count if count else len(self.health)

    # --- health-series accessors ---------------------------------------------
    def series(self, field, last=None):
        """The health-record time series of one field, records missing it
        dropped; ``last`` keeps only the trailing window."""
        values = [
            record.get(field)
            for record in self.health
            if record.get(field) is not None
        ]
        if last is not None:
            values = values[-int(last):]
        return values

    def latest_health(self):
        return self.health[-1] if self.health else None

    # --- staleness -----------------------------------------------------------
    def worker_ages(self):
        """worker -> seconds since its freshest metrics/health flush (the
        same min-of-channels age the top dashboard marks STALE)."""
        freshest = {}
        for doc in self.per_worker:
            worker = str(doc.get("worker") or "?")
            stamp = float(doc.get("time") or 0.0)
            freshest[worker] = max(freshest.get(worker, 0.0), stamp)
        for record in self.health:
            worker = str(record.get("worker") or "?")
            stamp = float(record.get("time") or 0.0)
            freshest[worker] = max(freshest.get(worker, 0.0), stamp)
        return {
            worker: max(0.0, self.now - stamp)
            for worker, stamp in freshest.items()
            if stamp > 0.0
        }


def collect_snapshot(experiment, now=None, replication_series=None):
    """Build a :class:`Snapshot` from an experiment's storage channels —
    the ``orion-tpu doctor`` / watchdog path.  ``replication_series`` lets
    watch mode thread its accumulated probe history back in (the fresh
    probe taken here is appended to it)."""
    from orion_tpu.health import spans_as_flight_events

    storage = experiment.storage
    metrics_docs = storage.fetch_metrics(experiment)
    health_docs = storage.fetch_health(experiment)
    try:
        flight = spans_as_flight_events(storage.fetch_spans(experiment))
    except Exception:  # pragma: no cover - channel optional on 3rd-party stores
        flight = []
    replication = probe_replication(storage)
    series = list(replication_series or ())
    if replication:
        series.append(replication)
    stale_after = None
    try:
        from orion_tpu.core.producer import Producer

        stale_after = 3.0 * Producer.METRICS_FLUSH_INTERVAL
    except Exception:  # pragma: no cover - keep the doctor importable alone
        pass
    return Snapshot(
        metrics=_merge(metrics_docs),
        per_worker=metrics_docs,
        health=health_docs,
        flight=flight,
        replication=replication,
        replication_series=series or None,
        heartbeat=getattr(experiment, "heartbeat", None),
        stale_after=stale_after,
        now=now,
    )


def probe_replication(storage):
    """The sharded router's ``replication_health()`` probe, or None when
    the storage is not the consistent-hash control plane (or the probe
    fails — a diagnosis pass must never die on a dark fleet)."""
    db = getattr(storage, "_db", None)
    replication_health = getattr(db, "replication_health", None)
    if replication_health is None:
        return None
    try:
        return replication_health()
    except Exception:  # pragma: no cover - a dead fleet still diagnoses
        return None


def local_snapshot(health=None, now=None):
    """A snapshot of THIS process's registry alone — the gateway/worker
    ``/healthz`` doctor block and the bench gate.  No storage round trips:
    counter/gauge/histogram rules see the live process, series rules see
    whatever ``health`` records the caller hands in (none by default)."""
    from orion_tpu.telemetry import TELEMETRY

    return Snapshot(metrics=TELEMETRY.snapshot(), health=health, now=now)


def _merge(metrics_docs):
    from orion_tpu.telemetry import merge_snapshots

    return merge_snapshots(metrics_docs)
