"""Storage- and replication-plane doctor rules (``DX02x``).

Failure signatures this family covers are exactly the ones the retry
policy (PR 5), the netdb reconnect path, and the sharded/replicated
control plane (PR 11/13) already count: absorbed transient retries,
exhausted policies, reconnect herds, replica lag, epoch-fence refusals,
and dead primaries.  Threshold rules read the merged counters; the lag
GROWTH rule is a trend over the accumulated replication-probe series
(watch mode appends one probe per frame — a single one-shot probe can
only trip the absolute-lag bar, never the growth bar).
"""

from orion_tpu.diagnosis.engine import DoctorRule
from orion_tpu.diagnosis.trend import robust_slope


class StorageRetrySpike(DoctorRule):
    id = "DX020"
    name = "storage-retry-spike"
    severity = "warn"
    runbook = "dx020-storage-retry-spike"
    description = (
        "storage.retries is climbing far faster than rounds complete: the "
        "backoff policy is absorbing a struggling store — latency is being "
        "paid in sleeps, and give-ups are the next stop."
    )

    MIN_RETRIES = 20
    RETRIES_PER_ROUND = 5.0

    def evaluate(self, snapshot):
        retries = snapshot.counter("storage.retries")
        rounds = max(snapshot.rounds(), 1)
        if retries >= self.MIN_RETRIES and retries >= (
            self.RETRIES_PER_ROUND * rounds
        ):
            yield self.finding(
                f"{retries} storage retries over {rounds} rounds "
                f"(> {self.RETRIES_PER_ROUND:g}/round) — the store is "
                "failing transiently at a rate backoff can barely absorb",
                value=retries,
            )


class StorageGaveUp(DoctorRule):
    id = "DX021"
    name = "storage-gave-up"
    severity = "critical"
    runbook = "dx021-storage-gave-up"
    description = (
        "storage.gave_up > 0: a retry policy exhausted its budget and "
        "surfaced the failure — operations actually failed upward, the "
        "line between 'slow' and 'losing work'."
    )

    def evaluate(self, snapshot):
        gave_up = snapshot.counter("storage.gave_up")
        if gave_up > 0:
            yield self.finding(
                f"{gave_up} storage operation(s) exhausted their retry "
                "policy and failed upward — check the store's health and "
                "the audit (`orion-tpu audit`) for lost work",
                value=gave_up,
            )


class ReconnectStorm(DoctorRule):
    id = "DX022"
    name = "reconnect-storm"
    severity = "warn"
    runbook = "dx022-reconnect-storm"
    description = (
        "wire drivers are re-dialing far more often than rounds complete: "
        "a flapping server, a mid-path network fault, or a restart herd."
    )

    MIN_RECONNECTS = 10
    RECONNECTS_PER_ROUND = 1.0

    def evaluate(self, snapshot):
        reconnects = snapshot.counter_sum(".reconnects")
        rounds = max(snapshot.rounds(), 1)
        if reconnects >= self.MIN_RECONNECTS and reconnects >= (
            self.RECONNECTS_PER_ROUND * rounds
        ):
            yield self.finding(
                f"{reconnects} wire reconnects over {rounds} rounds — a "
                "server (or the path to it) is flapping; reconnect jitter "
                "is spreading the herd but the cause needs an operator",
                value=reconnects,
            )


class ReplicationLagGrowth(DoctorRule):
    id = "DX023"
    name = "replication-lag-growth"
    severity = "critical"
    runbook = "dx023-replication-lag-growth"
    description = (
        "a replica's applied position is falling ever further behind its "
        "primary (or is already an epoch behind by a large margin): the "
        "shard's failover capital is draining — a promotion now would "
        "lose the unreplicated tail."
    )

    #: Absolute bar a single probe can trip; growth bar needs a series.
    MAX_LAG = 64
    MIN_PROBES = 3
    MIN_GROWTH = 8

    def evaluate(self, snapshot):
        series = snapshot.replication_series
        if not series:
            return
        # Worst replica lag per probe, per shard.
        per_shard = {}
        for probe in series:
            for entry in probe or ():
                lag = entry.get("max_lag")
                if lag is None:
                    continue
                per_shard.setdefault(entry.get("index"), []).append(int(lag))
        for index, lags in sorted(per_shard.items()):
            latest = lags[-1]
            if latest >= self.MAX_LAG:
                yield self.finding(
                    f"shard {index} replica lag at {latest} entries (>= "
                    f"{self.MAX_LAG}) — replication is stalled or the "
                    "replica is resyncing forever; a promotion now loses "
                    "the tail",
                    value=latest,
                    subject=index,
                )
                continue
            if (
                len(lags) >= self.MIN_PROBES
                and robust_slope(lags) > 0
                and latest - lags[0] >= self.MIN_GROWTH
            ):
                yield self.finding(
                    f"shard {index} replica lag grew {lags[0]} -> {latest} "
                    f"across {len(lags)} probes (robust slope "
                    f"{robust_slope(lags):.2f}/probe) — the replica is "
                    "falling behind a live write load",
                    value=latest,
                    subject=index,
                )


class FencedWriteSpike(DoctorRule):
    id = "DX024"
    name = "fenced-write-spike"
    severity = "warn"
    runbook = "dx024-fenced-write-spike"
    description = (
        "storage.shard.fenced_writes keeps climbing: routers are still "
        "reaching a stale-epoch primary — a promotion is stuck half-done "
        "(the fence is saving correctness, at a retry per write)."
    )

    FENCED = 8

    def evaluate(self, snapshot):
        fenced = snapshot.counter("storage.shard.fenced_writes")
        if fenced >= self.FENCED:
            yield self.finding(
                f"{fenced} epoch-fenced writes — some router (or a reborn "
                "stale primary) is behind the promotion; check `orion-tpu "
                "db ring` for who holds the current epoch",
                value=fenced,
            )


class DegradedShard(DoctorRule):
    id = "DX025"
    name = "degraded-shard"
    severity = "critical"
    runbook = "dx025-degraded-shard"
    description = (
        "a shard's serving primary answers no probe (and no promoted "
        "replica has taken over): every experiment the ring placed there "
        "is down."
    )

    def evaluate(self, snapshot):
        for entry in snapshot.replication or ():
            if entry.get("error"):
                yield self.finding(
                    f"shard {entry.get('index')} primary "
                    f"{entry.get('primary')} is unreachable "
                    f"({entry.get('error')}) — degraded until a replica is "
                    "promoted or the primary returns",
                    value=entry.get("index"),
                    subject=entry.get("index"),
                )


class DrainStuck(DoctorRule):
    id = "DX060"
    name = "drain-stuck"
    severity = "warn"
    runbook = "dx060-drain-stuck"
    description = (
        "a `db drain` phase has made no progress for minutes "
        "(storage.drain.phase_age_s): the migrator is wedged on a dead "
        "destination, an endless retry loop, or a crashed operator "
        "session that left experiments pinned/fenced."
    )

    #: A healthy drain progresses per move in well under this; a fenced
    #: experiment stuck past it is blocking writes.
    MAX_PHASE_AGE_S = 120.0

    def evaluate(self, snapshot):
        age = snapshot.gauge("storage.drain.phase_age_s")
        if age is not None and age >= self.MAX_PHASE_AGE_S:
            yield self.finding(
                f"drain phase stalled for {age:.0f}s (>= "
                f"{self.MAX_PHASE_AGE_S:g}s) — fenced experiments refuse "
                "writes until it finishes; re-run `orion-tpu db drain` "
                "(crash-resumable) or check the destination shard",
                value=age,
            )


class ReplicaShort(DoctorRule):
    id = "DX061"
    name = "replica-short"
    severity = "warn"
    runbook = "dx061-replica-short"
    description = (
        "a promoted primary is running below its declared replica count "
        "with no reprovision in flight: the shard's failover capital is "
        "gone — the next primary loss has no caught-up replica to elect "
        "(and a quorum floor would refuse writes outright)."
    )

    def evaluate(self, snapshot):
        if (snapshot.gauge("storage.reprovision.in_progress", 0.0) or 0.0) > 0:
            return  # repair underway — the gauge drops when it lands
        for entry in snapshot.replication or ():
            if entry.get("error"):
                continue  # a dead PRIMARY is DX025's finding
            if int(entry.get("epoch", 0) or 0) <= 0:
                continue  # never promoted: a down replica reboots as itself
            dead = [
                row.get("address")
                for row in entry.get("replicas", ())
                if row.get("error")
            ]
            if dead:
                yield self.finding(
                    f"shard {entry.get('index')} promoted primary "
                    f"{entry.get('primary')} is short {len(dead)} "
                    f"replica(s) ({', '.join(map(str, dead))}) with no "
                    "reprovision in flight — configure a "
                    "replica_provisioner or start/adopt a replacement "
                    "manually",
                    value=len(dead),
                    subject=entry.get("index"),
                )


STORAGE_RULES = (
    StorageRetrySpike,
    StorageGaveUp,
    ReconnectStorm,
    ReplicationLagGrowth,
    FencedWriteSpike,
    DegradedShard,
    DrainStuck,
    ReplicaShort,
)
