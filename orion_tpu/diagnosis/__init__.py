"""Self-diagnosis for orion-tpu (``orion-tpu doctor``).

A declarative rule engine over every telemetry plane the stack already
emits: merged counters/gauges/histograms, the per-round health-record
series, flight events, replication probes, and worker staleness — joined
into one :class:`~orion_tpu.diagnosis.snapshot.Snapshot` and evaluated by
a catalog of :class:`~orion_tpu.diagnosis.engine.DoctorRule`s, each with
a declared severity and a runbook anchor into ``docs/monitoring.md``.

Surfaces: the ``orion-tpu doctor`` CLI (exit 0 healthy / 1 critical,
``--watch`` with alert dedup), ``flight.alert`` events and the
``orion_tpu_doctor_findings{rule,severity}`` gauge family, the /healthz
doctor block on the gateway and worker metrics servers, an optional
in-process watchdog in ``workon``, and the hard ``bench.py --smoke``
zero-critical gate.

The facade is LAZY (PEP 562), same rationale as ``orion_tpu.analysis``:
``metrics.py`` imports this package on the scrape path only to label the
doctor gauge family — an eager rules import would tax every process
start for a facility most processes never run.
"""

__all__ = [
    "DoctorReport",
    "DoctorRule",
    "Finding",
    "Snapshot",
    "collect_snapshot",
    "default_rules",
    "doctor_catalog",
    "doctor_summary",
    "local_snapshot",
    "publish_report",
    "rule_severities",
    "run_rules",
]

_HOMES = {
    "DoctorReport": "engine",
    "DoctorRule": "engine",
    "Finding": "engine",
    "default_rules": "engine",
    "doctor_catalog": "engine",
    "rule_severities": "engine",
    "run_rules": "engine",
    "Snapshot": "snapshot",
    "collect_snapshot": "snapshot",
    "local_snapshot": "snapshot",
    "doctor_summary": "watch",
    "publish_report": "watch",
}


def __getattr__(name):
    home = _HOMES.get(name)
    if home is not None:
        import importlib

        module = importlib.import_module(f"orion_tpu.diagnosis.{home}")
        return getattr(module, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
