"""orion-tpu: TPU-native asynchronous black-box / hyperparameter optimization.

A ground-up JAX/XLA design with the capability surface of Oríon (reference
mounted at /root/reference): search-space DSL, pluggable algorithms, an
asynchronous producer/consumer worker loop over shared storage with atomic
reservation + heartbeats, parallel "lie" strategies, experiment version
control, and a full CLI — with the optimizer core (sampling, GP posterior,
acquisitions) running as jitted, batched device code.
"""

__version__ = "0.1.0"
