"""orion-tpu: TPU-native asynchronous black-box / hyperparameter optimization.

A ground-up JAX/XLA design with the capability surface of Oríon (reference
mounted at /root/reference): search-space DSL, pluggable algorithms, an
asynchronous producer/consumer worker loop over shared storage with atomic
reservation + heartbeats, parallel "lie" strategies, experiment version
control, and a full CLI — with the optimizer core (sampling, GP posterior,
acquisitions) running as jitted, batched device code.
"""

__version__ = "0.1.0"

# Opt-in runtime concurrency sanitizer (`orion-tpu tsan -- <cmd>` sets the
# env in the child): instrumentation must patch the threading factories
# BEFORE the subsystem modules create their locks, so the hook lives at
# package import.  Without the env var this costs one dict lookup.
import os as _os

if _os.environ.get("ORION_TPU_TSAN", "").strip().lower() in ("1", "on", "true", "yes"):
    from orion_tpu.analysis.sanitizer import TSAN as _TSAN

    _TSAN.enable_from_env()
