"""Consumer: execute one trial of the user's black box across a process
boundary.

Capability parity: reference `src/orion/core/worker/consumer.py` — per-trial
working dir, temp config/results files, concrete cmdline from the parser
template, the ``ORION_*`` environment contract with `orion_tpu.client`,
subprocess launch with SIGTERM forwarding, heartbeat pacemaker during the
run, JSON results parsing on success, `interrupted` on Ctrl-C (re-raised),
`broken` on nonzero exit.
"""

import json
import logging
import os
import signal
import subprocess
import sys
import tempfile

from orion_tpu.core.pacemaker import TrialPacemaker
from orion_tpu.core.trial import Result
from orion_tpu.utils.exceptions import (
    ExecutionError,
    FailedUpdate,
    InvalidResult,
    MissingResultFile,
)
from orion_tpu.utils.working_dir import WorkingDir

log = logging.getLogger(__name__)


class Consumer:
    def __init__(self, experiment, cmdline_parser, heartbeat_interval=60.0,
                 interrupt_signal_code=130):
        self.experiment = experiment
        self.parser = cmdline_parser
        self.heartbeat_interval = heartbeat_interval
        self.interrupt_signal_code = interrupt_signal_code

    def consume(self, trial):
        """Run the user script for one reserved trial; returns True on success."""
        temp_dir = self.experiment.working_dir is None
        prefix = f"{self.experiment.name}-{self.experiment.version}-"
        with WorkingDir(
            self.experiment.working_dir, temp=temp_dir, prefix=prefix, suffix=trial.id
        ) as workdir:
            trial.working_dir = workdir
            try:
                self._consume(trial, workdir)
            except KeyboardInterrupt:
                self._safe_status(trial, "interrupted")
                raise
            except (ExecutionError, MissingResultFile, InvalidResult) as exc:
                log.warning("Trial %s broken: %s", trial.id, exc)
                self._safe_status(trial, "broken")
                return False
        return True

    def _safe_status(self, trial, status):
        try:
            self.experiment.set_trial_status(trial, status, was="reserved")
        except FailedUpdate:  # pragma: no cover - concurrent transition
            pass

    def _consume(self, trial, workdir):
        results_file = tempfile.NamedTemporaryFile(
            mode="w", prefix="results_", suffix=".log", dir=workdir, delete=False
        )
        results_file.close()
        config_path = None
        if self.parser.has_config_file:
            conf = tempfile.NamedTemporaryFile(
                mode="w", prefix="trial_", suffix=".conf", dir=workdir, delete=False
            )
            conf.close()
            config_path = conf.name
            self.parser.generate_config(config_path, trial)

        env = self._execution_environment(trial, results_file.name)
        command = self.parser.format(trial, self.experiment, config_path=config_path)
        self._execute_process(command, env, trial)
        self._retrieve_results(trial, results_file.name)

    def _execution_environment(self, trial, results_path):
        """The env contract user scripts rely on (reference `consumer.py:108-159`)."""
        env = dict(os.environ)
        env["ORION_EXPERIMENT_ID"] = str(self.experiment.id)
        env["ORION_EXPERIMENT_NAME"] = str(self.experiment.name)
        env["ORION_EXPERIMENT_VERSION"] = str(self.experiment.version)
        env["ORION_TRIAL_ID"] = str(trial.id)
        env["ORION_WORKING_DIR"] = str(trial.working_dir)
        env["ORION_RESULTS_PATH"] = str(results_path)
        # Guarantee `orion_tpu.client` is importable in the user script even
        # when the framework runs from a source checkout (not pip-installed)
        # and the trial's working dir is elsewhere.
        # Appended (not prepended) so user PYTHONPATH overrides keep priority.
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        existing = env.get("PYTHONPATH")
        if not existing:
            env["PYTHONPATH"] = pkg_root
        elif pkg_root not in existing.split(os.pathsep):
            env["PYTHONPATH"] = existing + os.pathsep + pkg_root
        return env

    def _execute_process(self, command, env, trial):
        command = list(command)
        if command and command[0].endswith(".py") and not os.access(command[0], os.X_OK):
            command = [sys.executable] + command
        pacemaker = TrialPacemaker(
            self.experiment.storage, trial, wait_time=self.heartbeat_interval
        )
        pacemaker.start()
        try:
            process = subprocess.Popen(command, env=env)
            previous = signal.signal(signal.SIGTERM, _make_sigterm_handler(process))
            try:
                return_code = process.wait()
            finally:
                signal.signal(signal.SIGTERM, previous)
            if return_code != 0:
                raise ExecutionError(
                    f"{' '.join(command)} exited with code {return_code}"
                )
        finally:
            pacemaker.stop()

    def _retrieve_results(self, trial, results_path):
        if not os.path.exists(results_path) or os.path.getsize(results_path) == 0:
            raise MissingResultFile(
                f"script exited 0 but reported no results (did it call "
                f"orion_tpu.client.report_results?)"
            )
        with open(results_path) as handle:
            try:
                raw = json.load(handle)
            except json.JSONDecodeError as exc:
                raise InvalidResult(f"results file is not valid JSON: {exc}") from exc
        results = [Result(r["name"], r["type"], r["value"]) for r in raw]
        if not any(r.type == "objective" for r in results):
            raise InvalidResult("no result of type 'objective' was reported")
        self.experiment.update_completed_trial(trial, results)


def _make_sigterm_handler(process):
    def handler(signum, frame):  # pragma: no cover - signal path
        process.terminate()
        raise KeyboardInterrupt("SIGTERM received; trial interrupted")

    return handler
