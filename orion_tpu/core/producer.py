"""Producer: turn algorithm suggestions into registered trials.

Capability parity: reference `src/orion/core/worker/producer.py` — observe
completed trials in the real algorithm + strategy; build a *naive* copy that
additionally observes fantasized results ("lies") for incomplete trials;
suggest from the naive copy so concurrent suggestion stays diverse; register
trials with lineage parents; gaussian-jitter backoff on duplicate points and
a `max_idle_time` guard against algorithms that stop producing new points.
"""

import copy
import inspect
import logging
import os
import time
from collections import deque

import numpy as np

from orion_tpu.core.trial import RESERVABLE_STATUSES, Result, Trial, TrialBatch
from orion_tpu.devmem import sample_memory
from orion_tpu.health import FLIGHT, flight_events_as_spans
from orion_tpu.storage.retry import RetryPolicy
from orion_tpu.telemetry import TELEMETRY, current_trace_context
from orion_tpu.utils.exceptions import (
    AlgorithmExhausted,
    DuplicateKeyError,
    SampleTimeout,
)

log = logging.getLogger(__name__)


def _base_register_suggestion():
    """The BaseAlgorithm no-op ``register_suggestion`` (lazy import: the
    algo package is heavier than this module and not otherwise needed)."""
    from orion_tpu.algo.base import BaseAlgorithm

    return BaseAlgorithm.register_suggestion


def _observe_accepts_cube(algo):
    """True when the algorithm's ``observe`` takes the columnar ``cube``
    kwarg (the BaseAlgorithm contract).  Pre-columnar third-party plugins
    that override ``observe(params_list, results)`` keep working through
    the dict path."""
    try:
        sig = inspect.signature(type(algo).observe)
    except (TypeError, ValueError):  # pragma: no cover - exotic callables
        return False
    return any(
        p.name == "cube" or p.kind is inspect.Parameter.VAR_KEYWORD
        for p in sig.parameters.values()
    )


class Producer:
    #: Minimum seconds between metrics-snapshot upserts: _flush_timings
    #: runs from both update() and produce(), and the snapshot (every
    #: histogram's full bucket array) is the heaviest telemetry write —
    #: a q-round's worth of freshness is plenty for `orion-tpu info`.
    METRICS_FLUSH_INTERVAL = 2.0

    def __init__(self, experiment, max_idle_time=None, pipeline_depth=None):
        from orion_tpu.core.experiment import (
            DEFAULT_MAX_IDLE_TIME,
            DEFAULT_PIPELINE_DEPTH,
        )

        if max_idle_time is None:
            max_idle_time = DEFAULT_MAX_IDLE_TIME
        # Pipeline depth resolution: explicit arg > experiment worker-level
        # knob > ORION_TPU_PIPELINE_DEPTH env > default 1 (the pre-ring
        # single-slot behavior, differentially pinned).
        if pipeline_depth is None:
            pipeline_depth = getattr(experiment, "pipeline_depth", None)
        if pipeline_depth is None:
            pipeline_depth = os.environ.get("ORION_TPU_PIPELINE_DEPTH")
        self.pipeline_depth = max(
            1, int(pipeline_depth or DEFAULT_PIPELINE_DEPTH)
        )
        if experiment.algorithm is None:
            raise RuntimeError("Experiment not instantiated (call instantiate())")
        self.experiment = experiment
        self.algorithm = experiment.algorithm
        self.strategy = experiment.strategy
        self.max_idle_time = max_idle_time
        self.naive_algorithm = None
        self._observed_ids = set()  # replaces reference TrialsHistory dedup
        self._leaf_ids = []  # lineage: children of observed DAG (trials_history.py)
        # Columnar observe cache: trial id -> (D,) float32 unit-cube row
        # (Space.params_to_cube encoding).  Lies re-observe every in-flight
        # trial every round; without this each round re-parses O(in-flight)
        # param dicts through the codec.  Keyed by the STORAGE trial id —
        # a stored string on fetched trials, so cache lookups never pay the
        # md5-over-params hash_params would recompute per access.  Rows are
        # evicted once their trial completes and feeds the real algorithm
        # (never needed again — _observed_ids gates re-observation) and
        # swept for stopped trials.
        self._cube_cache = {}
        # Third-party plugins may predate the columnar contract and override
        # observe(params_list, results) without the cube kwarg — detect once
        # and fall back to the dict path for them (same semantics, slower).
        # Algorithms that declare uses_observe_cube=False (purely dict-keyed
        # observation handling, e.g. ASHA rung bookkeeping) skip the cube
        # build/cache too — it would be pure waste for them.
        self._observe_takes_cube = getattr(
            self.algorithm, "uses_observe_cube", True
        ) and _observe_accepts_cube(self.algorithm)
        self.failure_count = 0
        self._backoff_policy = RetryPolicy(
            base_delay=0.01, max_delay=0.5, jitter=0.5, deadline=None
        )
        self._n_in_flight = 0  # status == reserved (someone is executing)
        self._n_reservable = 0  # new/suspended/interrupted (worker can consume)
        self._pending_timings = []
        # Telemetry span entries buffered per round and booked in ONE
        # record_spans_batch call at flush time — the per-sample
        # record_span each paid a lock round-trip inside the hot loop.
        self._pending_spans = []
        # One optimization-health record per produce round (orion_tpu
        # .health), built at round end and flushed through the storage
        # health channel next to the spans/metrics.
        self._pending_health = None
        self._round_index = 0
        self._last_metrics_flush = float("-inf")
        self._n_completed_seen = 0
        self._update_epoch = 0
        # The speculative ring: up to ``pipeline_depth`` in-flight rounds,
        # oldest first.  Each entry is ``(handle, algo, t0, ctx)`` — the
        # unforced device handle, the naive copy that dispatched it, and
        # the per-entry open ``device.dispatch`` telemetry window
        # (perf_counter at dispatch + the ambient TraceContext of the
        # round that dispatched it; both None when telemetry is off).
        # Round k's storage commit, codec work and telemetry flush all run
        # while rounds k+1..k+N sit here — the depth-1 configuration is
        # behaviorally identical to the pre-ring single-slot pipeline
        # (tests/unit/test_producer_pipeline.py pins the storage op
        # sequence and the suggestion bit-stream).
        self._spec_ring = deque()
        # Whether the algorithm actually implements register_suggestion:
        # the per-slot call is a per-point plugin API, and paying a q-row
        # dict materialization per round to invoke the base no-op would
        # defeat the columnar commit.  Re-resolved at the top of every
        # produce round (_refresh_register_suggestion_gate) so
        # instance-assigned hooks and post-construction monkeypatches keep
        # firing exactly as the pre-gate code's dynamic call did.
        self._needs_register_suggestion = True
        self._refresh_register_suggestion_gate()
        # Trial ids already conditioned (register_suggestion + lie) onto the
        # CURRENT naive copy by _dispatch_speculative: the pipelined commit
        # may re-invoke it on the same instance (mid-loop dispatch opted
        # out, post-loop retry), and re-observing the same lies would skew
        # opt-in model-based speculation.  Reset whenever the naive copy is
        # rebuilt.
        self._spec_conditioned = set()
        # Probe the EVC family ONCE: walking the tree costs extra collection
        # scans per round (each a full lock/unpickle on the file backend),
        # which an un-branched experiment should never pay.  A branch
        # appearing mid-run is picked up by the next worker process.
        self._has_evc_family = bool(experiment.refers.get("parent_id")) or bool(
            experiment.storage.fetch_experiments(
                {"refers.parent_id": experiment.id}, projection={"_id": 1}
            )
        )
        # Incremental tree fetcher: topology + adapted family trials cached,
        # only changed trials re-read/re-adapted each round (VERDICT r1 #7).
        self._tree_fetcher = None
        if self._has_evc_family:
            from orion_tpu.evc.experiment import TreeTrialsFetcher

            self._tree_fetcher = TreeTrialsFetcher(experiment)

    # --- observation --------------------------------------------------------
    def update(self):
        """Sync algorithm state with storage (reference `producer.py:103-132`).

        Trials come through the EVC tree: a branched child warm-starts from
        its ancestors' completed trials, adapted hop by hop (reference
        `evc/experiment.py:154-226` — the point of branching).

        The round's snapshot comes from storage.fetch_update_view, which
        count-gates the completed history on capable backends (update()
        runs every produce round AND every backoff; re-reading the whole
        completed history each time costs O(trials) per call) and keeps
        the single full fetch elsewhere — see its docstring for the
        consistency and ordering contract."""
        if self._tree_fetcher is not None:
            trials = self._tree_fetcher.fetch()
        else:
            # Every 16th sync forces the gate open: the count gate assumes
            # the completed count only grows, which a concurrent
            # db-level remove of a completed trial (offset by a fresh
            # completion) could violate — the periodic full read bounds
            # that staleness window instead of trusting the invariant
            # forever.
            self._update_epoch += 1
            known = self._n_completed_seen if self._update_epoch % 16 else -1
            trials, self._n_completed_seen = (
                self.experiment.storage.fetch_update_view(self.experiment, known)
            )
        completed = [t for t in trials if t.status == "completed" and t.objective]
        incomplete = [t for t in trials if not t.is_stopped]
        # Exhaustion/backoff accounting counts THIS experiment's trials only:
        # the EVC tree fetch includes the family's trials, which this worker
        # can never reserve and whose completions feed ancestors, not us.
        own_id = self.experiment.id
        own = [t for t in trials if t.experiment == own_id]
        self._n_in_flight = sum(t.status == "reserved" for t in own)
        self._n_reservable = sum(t.status in RESERVABLE_STATUSES for t in own)
        self._update_algorithm(completed)
        # Bound the columnar cache: stopped trials are never lied about
        # again, so their rows are dead weight.  Completed-with-objective
        # trials were just observed (and evicted) above; this sweep covers
        # broken / interrupted / objective-less terminals, which would
        # otherwise leak one row per failed trial forever.  (A resumed
        # interrupted trial simply re-encodes on its next cache miss.)
        if self._cube_cache:
            for t in trials:
                if t.is_stopped:
                    self._cube_cache.pop(t.id, None)
        self._update_naive_algorithm(incomplete)
        self._flush_timings()

    def _update_algorithm(self, completed):
        fresh = [t for t in completed if t.id not in self._observed_ids]
        if fresh:
            params = [t.params for t in fresh]
            results = [_trial_results(t) for t in fresh]
            cube = self._cube_rows_for(fresh)
            t0 = time.perf_counter()
            if cube is not None:
                self.algorithm.observe(params, results, cube=cube)
            else:  # pre-columnar plugin signature
                self.algorithm.observe(params, results)
            self._record_timing("observe", time.perf_counter() - t0, len(fresh))
            self.strategy.observe(params, results)
            for t in fresh:
                self._observed_ids.add(t.id)
                self._cube_cache.pop(t.id, None)
            self._leaf_ids = [t.id for t in fresh]

    def _cube_rows_for(self, trials):
        """(n, D) columnar rows for ``trials`` — cache hits plus ONE bulk
        ``params_to_cube`` call for the misses.  Bit-identical to the
        per-call dict encode the algorithms would otherwise run (same
        single pipeline, row-independent codec), so the columnar and dict
        observe paths cannot diverge.  Returns None (dict fallback) for
        pre-columnar plugin algorithms."""
        if not self._observe_takes_cube:
            return None
        space = self.algorithm.space
        # lint: disable=PERF001 -- one dict probe per row against the id
        # cache (no codec work); misses below encode in ONE bulk call.
        rows = [self._cube_cache.get(t.id) for t in trials]
        missing = [i for i, r in enumerate(rows) if r is None]
        if missing:
            encoded = space.params_to_cube([trials[i].params for i in missing])
            for j, i in enumerate(missing):
                # Copy each row out: a view into `encoded` would pin the
                # whole (n_missing, D) batch for as long as any one row
                # survives in the cache.
                row = np.array(encoded[j])
                self._cube_cache[trials[i].id] = row
                rows[i] = row
        if not rows:
            return None
        return np.stack(rows)

    def _record_timing(self, op, duration, count):
        """Buffer a timing sample; flushed once per produce()/update() round
        so telemetry never adds a storage write inside the hot retry loop.

        The same sample also feeds the process-wide telemetry registry as a
        ``producer.{op}`` span + histogram entry — BUFFERED like the
        storage samples and booked in one ``record_spans_batch`` call at
        flush time, so the hot loop pays no registry lock per sample (the
        saved host µs are what bench.py's ``telemetry_us_saved`` reports).
        The span start is captured here (now - duration) so batching does
        not shift the record on the trace timeline."""
        self._pending_timings.append((op, duration, count))
        # Guarded: the span name f-string and args dict must not be
        # allocated per sample when telemetry is off — this runs inside
        # every produce()/update() round.  The ambient TraceContext is
        # captured NOW (fifth element): the batch flushes at round end,
        # when the ambient may already belong to the next round.
        if TELEMETRY.enabled:
            self._pending_spans.append(
                (
                    f"producer.{op}",
                    time.perf_counter() - duration,
                    duration,
                    {"count": count},
                    current_trace_context(),
                )
            )

    def _flush_timings(self, force_metrics=False):
        """Telemetry must never break the run (SURVEY §5 timing hooks).

        Flushes the buffered timing samples through the legacy storage
        channel AND, when the telemetry registry is enabled, this worker's
        new span records (drained once each) + a metrics snapshot upsert —
        so ``orion-tpu info``/``trace`` aggregate across worker processes.
        The snapshot upsert is time-gated (METRICS_FLUSH_INTERVAL): this
        runs from update() AND produce(), and re-upserting an
        all-histograms snapshot twice per round would tax the very storage
        hot path the pipelined commit freed.  ``force_metrics`` (the
        end-of-run flush) bypasses the gate so final totals always land."""
        samples, self._pending_timings = self._pending_timings, []
        if not samples and not TELEMETRY.enabled and not FLIGHT.enabled:
            return
        # Book the round's buffered producer spans in one registry call
        # BEFORE draining, so they ride this very flush to storage.
        if self._pending_spans:
            pending, self._pending_spans = self._pending_spans, []
            TELEMETRY.record_spans_batch(pending)
        try:
            if samples:
                self.experiment.storage.record_timings(self.experiment, samples)
            spans = TELEMETRY.drain_spans() if TELEMETRY.enabled else []
            if FLIGHT.enabled:
                # Mirror drained flight events into the spans channel as
                # flight.* records, so `orion-tpu flight-record -n NAME`
                # can reconstruct this worker's recent history.
                spans = spans + flight_events_as_spans(FLIGHT.drain())
            if spans:
                self.experiment.storage.record_spans(self.experiment, spans)
            health, self._pending_health = self._pending_health, None
            if health:
                self.experiment.storage.record_health(self.experiment, health)
            if TELEMETRY.enabled:
                now = time.monotonic()
                if (
                    force_metrics
                    or now - self._last_metrics_flush >= self.METRICS_FLUSH_INTERVAL
                ):
                    # Device-memory/compile-cache gauges ride the same
                    # low-frequency gate (rate-limited again inside), so a
                    # snapshot never ships stale memory numbers.
                    sample_memory(force=force_metrics)
                    self._sample_serve_placement()
                    self.experiment.storage.record_metrics(
                        self.experiment, TELEMETRY.snapshot()
                    )
                    self._last_metrics_flush = now
        except Exception:  # pragma: no cover - read-only/remote storage quirks
            log.debug("could not record telemetry", exc_info=True)

    def _sample_serve_placement(self):
        """Mirror the remote algorithm's fleet placement into gauges
        (fleet-served experiments only — ``placement()`` is None for
        local algorithms and single-gateway tenants).  Rides the metrics
        snapshot flush, so `orion-tpu top`/`info` show which gateway this
        worker's tenant lives on and how often it failed over."""
        placement = getattr(self.algorithm, "placement", None)
        if placement is None:
            return
        try:
            record = placement()
        except Exception:  # pragma: no cover - observability never breaks
            return
        if not record:
            return
        TELEMETRY.set_gauge(
            "serve.client.fleet_epoch", float(record.get("epoch") or 0)
        )
        TELEMETRY.set_gauge(
            "serve.client.fleet_members", float(record.get("members") or 0)
        )
        TELEMETRY.set_gauge(
            "serve.client.failovers", float(record.get("failovers") or 0)
        )
        TELEMETRY.set_gauge(
            "serve.client.adoptions", float(record.get("adoptions") or 0)
        )

    def _update_naive_algorithm(self, incomplete):
        """Naive algo = deepcopy of real + lies for in-flight trials
        (reference `producer.py:159-174`)."""
        self.naive_algorithm = copy.deepcopy(self.algorithm)
        self._spec_conditioned.clear()  # fresh copy: nothing conditioned yet
        lying = self._produce_lies(incomplete)
        # The lies observed right below ARE conditioning: seed the set with
        # their source ids, or a mid-round backoff (rebuild here, then the
        # next iteration's speculative dispatch) would observe the same
        # in-flight trials' lies a second time on this very copy.
        self._spec_conditioned.update(src.id for src, _ in lying)
        if lying:
            params = [lt.params for _, lt in lying]
            results = [{"objective": lt.lie.value} for _, lt in lying]
            # Columnar: lies re-feed every in-flight point every round, so
            # this is the hottest dict->cube boundary in the loop — row
            # cache + one bulk encode for first-seen points.  Keyed by the
            # SOURCE trial (its storage id is a stored string; the lying
            # twin's id would be a fresh md5 per access AND would never
            # match the eviction sweep's keys).
            cube = self._cube_rows_for([src for src, _ in lying])
            if cube is not None:
                self.naive_algorithm.observe(params, results, cube=cube)
            else:  # pre-columnar plugin signature
                self.naive_algorithm.observe(params, results)

    def _produce_lies(self, incomplete):
        """(source_trial, lying_trial) pairs for every liable in-flight
        trial — the source carries the storage identity, the lying twin the
        fantasy result."""
        lying = []
        for trial in incomplete:
            lie = self.strategy.lie(trial)
            if lie is None or lie.value is None:
                continue
            lying_trial = Trial(
                experiment=trial.experiment,
                params=dict(trial.params),
                results=[Result(lie.name, "lie", lie.value)],
            )
            try:
                self.experiment.register_lie(lying_trial)
            except DuplicateKeyError:
                pass  # lie already registered in a previous round
            lying.append((trial, lying_trial))
        return lying

    # --- production ---------------------------------------------------------
    def produce(self, pool_size=None, own_in_flight=0):
        """Register `pool_size` new trials (reference `producer.py:69-101`).

        The round's storage commit is PIPELINED: once the final batch is
        built, the next round's device suggest is dispatched first and the
        batched register (one transaction / one wire request) runs while
        that computation is in flight — storage latency and device latency
        overlap instead of adding up.

        ``own_in_flight``: how many of the experiment's reserved trials THE
        CALLER itself is holding.  An opt-out normally backs off while
        reserved trials exist (their completions can revive the algorithm),
        but waiting on the caller's own reservations would deadlock the
        caller against itself (``ExperimentClient.suggest`` holding a
        partial batch) — so the wait only applies when reserved trials
        beyond the caller's own exist."""
        # root=True: every produce round IS one distributed trace — the
        # storage commits, wire hops and server-side applies it causes all
        # stamp this round's trace_id, which is what `orion-tpu trace
        # --attribute` buckets the round's wall time by.
        with TELEMETRY.span("producer.round", root=True):
            return self._produce(pool_size, own_in_flight)

    def _refresh_register_suggestion_gate(self):
        """Resolve whether ``register_suggestion`` must be invoked per slot.

        Looked up on the INSTANCE (not the class) and refreshed every
        produce round: a plugin assigning the hook in ``__init__`` or a
        test monkeypatching it after construction must keep receiving the
        per-point callbacks, exactly like the pre-gate dynamic call."""
        hook = getattr(self.algorithm, "register_suggestion", None)
        self._needs_register_suggestion = (
            hook is not None
            and getattr(hook, "__func__", hook)
            is not _base_register_suggestion()
        )

    def _effective_pipeline_depth(self, algo):
        """Ring depth actually used for ``algo``.

        Deep rings are provably free ONLY for algorithms that declare
        ``speculation_safe`` at the CLASS level (observation-independent:
        random, grid — any depth is bit-identical to depth 1).  Opt-in
        model-based speculation (`speculative_suggest=True` sets the flag
        per-INSTANCE) keeps the async-BO contract "each in-flight round is
        conditioned on the previous one's lies", which a burst of N
        dispatches from one posterior would break — every extra entry
        would re-sample the same optimum, and the resulting duplicate
        slots would discard the whole ring every round.  Such algorithms
        stay 1-deep regardless of the knob."""
        if getattr(type(algo), "speculation_safe", False):
            return self.pipeline_depth
        return 1

    def _produce(self, pool_size, own_in_flight):
        pool_size = pool_size or self.experiment.pool_size
        self._refresh_register_suggestion_gate()
        registered = 0
        start = time.time()
        speculative = self._take_speculative(pool_size)
        registered_trials = []
        while registered < pool_size:
            if time.time() - start > self.max_idle_time:
                raise SampleTimeout(
                    f"algorithm produced no new unique point in {self.max_idle_time}s"
                )
            t0 = time.perf_counter()
            if speculative is not None:
                # Already timed by _take_speculative (the residual transfer).
                suggested, speculative = speculative, None
            else:
                # Columnar flow: the suggestion crosses the boundary as a
                # (q, d) array; batch.params is a LAZY ParamBatch — the
                # storage documents build straight from its columns below,
                # and per-point dicts only materialize at plugin-compat
                # boundaries (register_suggestion overrides, lie strategy).
                batch = self.naive_algorithm.suggest_batch(
                    pool_size - registered
                )
                suggested = batch.params if batch is not None else None
                # Advance ONLY the real algo's RNG stream, never its full
                # state: the naive copy has observed fantasy lies, and
                # syncing its whole state_dict would permanently inject
                # those rows into the real algorithm (compounding every
                # round).
                self.algorithm.rng_key = self.naive_algorithm.rng_key
                if suggested is not None:
                    self._record_timing(
                        "suggest", time.perf_counter() - t0, len(suggested)
                    )
            if suggested is None:
                log.debug("algorithm opted out of suggesting")
                # Re-sync first: the opt-out may come from a stale view.
                self.update()
                if registered or self._n_reservable:
                    # The worker can make progress without new points —
                    # consume what is already registered (this round's
                    # partial batch or a concurrent producer's); exhaustion
                    # re-fires on the next dry production round.
                    break
                if self._n_in_flight > own_in_flight:
                    # Executing trials beyond the caller's own exist; their
                    # completions may change the algorithm's state — wait.
                    self._sleep_backoff()
                    continue
                t0 = time.perf_counter()
                batch = self.naive_algorithm.suggest_batch(
                    pool_size - registered
                )
                suggested = batch.params if batch is not None else None
                self.algorithm.rng_key = self.naive_algorithm.rng_key
                if suggested is None:
                    # Nothing pending, nothing running, and a fresh-state
                    # retry still opts out: no observation can ever arrive,
                    # so the state producing this opt-out is final.
                    raise AlgorithmExhausted(
                        "algorithm opted out of suggesting with no trials "
                        "in flight; the search space is exhausted"
                    )
                self._record_timing(
                    "suggest", time.perf_counter() - t0, len(suggested)
                )
            # Columnar commit: the round's chunk stays a lazy ParamBatch
            # (or a host scheduler's dict list) wrapped by a TrialBatch —
            # ids and storage documents are built in ONE columnar pass
            # (core.trial), never q Trial constructions.  Trial objects
            # materialize only at the plugin-compat boundary below
            # (speculative lie conditioning, register_suggestion overrides).
            batch = TrialBatch(suggested[: pool_size - registered])
            # Pipelined commit (the storage twin of speculative suggest):
            # when this batch fills the round, stamp identities now —
            # freezing ids, so the speculative lie path and cube cache key
            # correctly — top the speculative ring up to pipeline_depth
            # in-flight rounds, and only then write storage, so the commit
            # overlaps jax async dispatch instead of serializing with it.
            # Presuming the batch registers is safe: a slot that turns out
            # duplicate IS durably registered (by whoever won the race), so
            # the speculative conditioning stays truthful; the ring is
            # discarded below if any slot fails to register.
            prepared = registered + len(batch) >= pool_size
            overlapped = False
            if prepared:
                self.experiment.prepare_trial_batch(batch, parents=self._leaf_ids)
                if getattr(self.naive_algorithm, "speculation_safe", False):
                    overlapped = self._dispatch_speculative(
                        pool_size, registered_trials + batch.trials()
                    )
            # Batch registration: ONE storage round — a single transaction
            # on SQL backends, one wire request on the network driver
            # (q=4096 would otherwise pay q serialized RTTs); per-trial
            # DuplicateKeyError comes back as that slot's outcome.
            t0 = time.perf_counter()
            try:
                outcomes = self.experiment.register_trial_batch(
                    batch, parents=self._leaf_ids, prepared=prepared
                )
            except Exception:
                if overlapped:
                    # Transport-level commit failure (no per-slot outcomes):
                    # the batch's fate is unknown, so every ring entry
                    # conditioned on it must go — same contract as the
                    # per-slot discard below.
                    self._discard_spec_ring()
                raise
            self._record_timing("register", time.perf_counter() - t0, len(batch))
            had_duplicate = False
            batch_error = None
            spec_capable = getattr(self.naive_algorithm, "speculation_safe", False)
            # lint: disable=PERF001 -- per-slot outcome handling: the
            # register_suggestion hook is a per-point plugin API (gated to
            # algorithms that actually override it), everything else here
            # is O(1) bookkeeping per slot.
            for slot, outcome in enumerate(outcomes):
                if isinstance(outcome, DuplicateKeyError):
                    # The point IS durably registered (by us earlier or by a
                    # concurrent worker) — the algorithm must still learn it
                    # is consumed, or it will re-suggest it forever.
                    if self._needs_register_suggestion:
                        self.algorithm.register_suggestion(batch.params[slot])
                    log.debug("duplicate suggestion %s", batch.ids[slot])
                    had_duplicate = True
                elif isinstance(outcome, Exception):
                    # Remember but keep walking the outcomes: later slots of
                    # the same pipelined round trip WERE durably registered,
                    # and skipping their register_suggestion would make the
                    # algorithm re-suggest them all next round.
                    batch_error = batch_error or outcome
                else:
                    if self._needs_register_suggestion:
                        self.algorithm.register_suggestion(batch.params[slot])
                    registered += 1
                    # Trial views only materialize for the speculative
                    # conditioning path; their ids ride the columnar batch
                    # (no md5 recomputation — the cube cache keys on them).
                    if spec_capable:
                        registered_trials.append(batch.trial_at(slot))
            if overlapped and (had_duplicate or batch_error is not None):
                # The speculative copies were conditioned on slots that did
                # not register; drop the whole ring — the post-loop dispatch
                # (or the next round's) redoes it from the true set.
                self._discard_spec_ring()
            if batch_error is not None:
                raise batch_error
            if had_duplicate:
                self.backoff()
        self._round_index += 1
        if TELEMETRY.enabled:
            # One optimization-health record per round (orion_tpu.health):
            # the naive copy ran this round's fused suggest (its GPState
            # carries the packed device health), the REAL algorithm holds
            # the honest host truth (no fantasy lies in its incumbent) —
            # merge with the real instance's fields winning.
            self._pending_health = self._build_health(registered)
        if FLIGHT.enabled:
            FLIGHT.record(
                "producer.round",
                args={"round": self._round_index, "registered": registered},
            )
        self._flush_timings()
        if len(self._spec_ring) < self._effective_pipeline_depth(
            self.naive_algorithm
        ):
            self._dispatch_speculative(pool_size, registered_trials)
        return registered

    def _build_health(self, registered):
        """Merge naive-copy device health over real-instance host truth
        into one per-round record; never raises (observability must not
        break a run) and returns None for algorithms that report nothing
        (plugins without the BaseAlgorithm contract included)."""
        try:
            record = {}
            naive = self.naive_algorithm
            if naive is not None:
                record.update(
                    getattr(naive, "health_record", lambda: None)() or {}
                )
            record.update(
                getattr(self.algorithm, "health_record", lambda: None)() or {}
            )
            if not record:
                return None
            record["round"] = self._round_index
            record["registered"] = int(registered)
            record["time"] = time.time()
            # Device-memory stamp (orion_tpu.devmem publishes the gauge,
            # rate-limited): gauges are last-write-wins, so the health
            # record is the ONLY stored time series — the doctor's
            # memory-growth trend rule (DX044) reads it from here.
            mem = TELEMETRY.gauge_value("memory.device_live_bytes")
            if mem is not None:
                record["mem_bytes"] = float(mem)
            return record
        except Exception:  # pragma: no cover - observability never breaks a run
            log.debug("could not build health record", exc_info=True)
            return None

    # --- speculative overlap ------------------------------------------------
    @property
    def _speculative(self):
        """Oldest in-flight speculative round as a ``(handle, algo)`` pair,
        or None — the pre-ring single-slot surface, kept for the
        speculation-contract tests and external introspection."""
        if not self._spec_ring:
            return None
        handle, algo, _t0, _ctx = self._spec_ring[0]
        return (handle, algo)

    def _close_entry_window(self, t0, ctx, outcome):
        """Close one ring entry's ``device.dispatch`` span: the async
        device work window from speculative dispatch to finalize/discard."""
        # t0 is only ever stamped with telemetry enabled, but the args dict
        # below must provably not allocate on the disabled path, so the
        # guard is explicit (it also closes the window cleanly if the
        # registry was disabled mid-run).
        if t0 is not None and TELEMETRY.enabled:
            TELEMETRY.record_span(
                "device.dispatch", start=t0, args={"outcome": outcome},
                parent_ctx=ctx,
            )

    def _discard_spec_ring(self):
        """Drop every in-flight speculative round (commit failure, duplicate
        slots, naive-copy invalidation): their conditioning presumed a
        registration set that did not hold, so none may be consumed."""
        while self._spec_ring:
            _handle, _algo, t0, ctx = self._spec_ring.popleft()
            self._close_entry_window(t0, ctx, "discarded")

    def _dispatch_speculative(self, pool_size, registered_trials):
        """Top the speculative ring up to ``pipeline_depth`` in-flight
        rounds before this round's trials execute (VERDICT r2 #3: the
        small-batch presets were pinned to one blocking ~100ms
        host<->device round trip per round; ISSUE 13 generalizes the
        single slot to a depth-N ring).

        Only algorithms declaring ``speculation_safe`` are speculated.
        Observation-independent algorithms (random search) declare it by
        class — zero regret cost by construction, and dispatching N rounds
        ahead consumes the SAME rng/cursor stream the synchronous path
        would, in the same order (rounds are finalized oldest-first), so
        any depth is bit-identical to depth 1.  Model-based algorithms opt
        in (`speculative_suggest=True`, async-BO semantics): the naive
        copy first observes constant-liar lies for the just-registered
        batch, so the in-flight round is conditioned like an async
        worker's round would be — and such algorithms are CAPPED at an
        effective depth of 1 (_effective_pipeline_depth): N dispatches
        from one posterior would violate that conditioning contract and
        re-sample the same optimum N times.  Lie conditioning happens
        ONCE per registered batch (``_spec_conditioned``).
        jax's async dispatch runs the computations and transfers while the
        host executes trials; successive produce() calls drain the ring.

        Returns True when at least one speculative round is in flight
        after the call — the pipelined commit path uses this to know the
        storage write it is about to issue overlaps live device work."""
        algo = self.naive_algorithm
        if algo is None or not getattr(algo, "speculation_safe", False):
            # A non-speculative algorithm must never leave stale handles
            # behind (the pre-ring code reset its slot unconditionally).
            self._discard_spec_ring()
            return False
        t_dispatch = time.perf_counter() if TELEMETRY.enabled else None
        dispatched = 0
        try:
            # Condition each trial onto this naive copy AT MOST ONCE: the
            # pipelined commit may re-invoke this on the same instance
            # (mid-loop dispatch opted out, post-loop retry), and
            # re-observing the same lies would double-count fantasies for
            # opt-in model-based speculation.  The set resets with every
            # naive rebuild (_update_naive_algorithm).
            # lint: disable=PERF001 -- plugin-compat boundary: the lie
            # strategy and register_suggestion hooks are per-point APIs;
            # this path only runs for speculation-safe algorithms.
            fresh = [
                t for t in registered_trials
                if t.id not in self._spec_conditioned
            ]
            if fresh:
                # The dispatch copy predates this round's registrations (it
                # was deepcopied in update()): mark the just-registered
                # points consumed on IT too, or cursor-based algorithms
                # (grid) would speculatively re-suggest the exact batch just
                # written and pay a round of DuplicateKeyError + backoff.
                for trial in fresh:
                    if self._needs_register_suggestion:
                        algo.register_suggestion(trial.params)
                    self._spec_conditioned.add(trial.id)
                lie_trials, lie_results = [], []
                for trial in fresh:
                    lie = self.strategy.lie(trial)
                    if lie is not None and lie.value is not None:
                        lie_trials.append(trial)
                        lie_results.append({"objective": lie.value})
                if lie_trials:
                    lie_params = [dict(t.params) for t in lie_trials]
                    lie_cube = self._cube_rows_for(lie_trials)
                    if lie_cube is not None:
                        algo.observe(lie_params, lie_results, cube=lie_cube)
                    else:  # pre-columnar plugin signature
                        algo.observe(lie_params, lie_results)
            depth = self._effective_pipeline_depth(algo)
            while len(self._spec_ring) < depth:
                t0 = time.perf_counter() if TELEMETRY.enabled else None
                handle = algo.dispatch_suggest(pool_size)
                if handle is None:
                    break
                ctx = current_trace_context() if t0 is not None else None
                self._spec_ring.append((handle, algo, t0, ctx))
                dispatched += 1
        except Exception:  # pragma: no cover - speculation must never break a run
            log.debug("speculative dispatch failed", exc_info=True)
            return bool(self._spec_ring)
        if t_dispatch is not None:
            # Host-side cost of conditioning + async dispatch (the span the
            # issue calls ``speculative_dispatch``); the device-work windows
            # are the per-entry open ``device.dispatch`` spans above.
            TELEMETRY.record_span(
                "producer.speculative_dispatch",
                start=t_dispatch,
                args={"dispatched": dispatched},
            )
        if dispatched:
            # Keep the real algo's rng stream ahead of the speculative
            # draws, or the next naive copy would replay the same keys and
            # duplicate them.
            self.algorithm.rng_key = algo.rng_key
        return bool(self._spec_ring)

    def _take_speculative(self, pool_size):
        if not self._spec_ring:
            return None
        handle, algo, t0, ctx = self._spec_ring.popleft()
        try:
            t_fin = time.perf_counter()
            out = algo.finalize_suggest_batch(handle).params[:pool_size]
            # Timed as "suggest": what remains of the device round trip
            # after the overlap (ideally just the residual transfer).
            self._record_timing("suggest", time.perf_counter() - t_fin, len(out))
            self._close_entry_window(t0, ctx, "finalized")
            return out
        except Exception:  # pragma: no cover - speculation must never break a run
            log.debug("speculative finalize failed", exc_info=True)
            self._close_entry_window(t0, ctx, "failed")
            # Later entries share the failed handle's lineage (same naive
            # copy, same device stream) — discard rather than trust them.
            self._discard_spec_ring()
            return None

    def backoff(self):
        """Re-sync with storage + jittered sleep (reference `producer.py:61-67`)."""
        self.update()
        self._sleep_backoff()

    def _sleep_backoff(self):
        # The unified backoff policy (storage/retry.py): exponential from
        # 10ms, capped at the same 0.5s ceiling the old gaussian sleep
        # had, jittered so concurrent producers de-synchronize.
        self._backoff_policy.sleep(
            self.failure_count, op="producer.backoff", span="producer.backoff"
        )
        self.failure_count += 1


def _trial_results(trial):
    out = {"objective": trial.objective.value if trial.objective else None}
    if trial.gradient is not None:
        out["gradient"] = trial.gradient.value
    if trial.constraints:
        out["constraint"] = [c.value for c in trial.constraints]
    return out
