"""Experiment: the DB-backed facade every subsystem talks to.

Capability parity: reference `src/orion/core/worker/experiment.py` — load by
(name, version) with latest-version resolution, trial operations delegated to
storage (atomic reservation + lost-trial sweep, registration with submit
time, lies, completed updates), `is_done`/`is_broken` from DB counts, stats,
and `configure()` with race-condition handling.  Branching/conflict logic
lives in `orion_tpu.evc` and is invoked from the builder, not here.
"""

import logging
import time

from orion_tpu.algo.base import create_algo
from orion_tpu.core.strategy import create_strategy
from orion_tpu.core.trial import ID_SCHEMES, Trial, compute_scheme_ids
from orion_tpu.space.dsl import build_space
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import (
    DuplicateKeyError,
    FailedUpdate,
    RaceCondition,
)

log = logging.getLogger(__name__)

#: Worker-level defaults (reference `core/__init__.py:52-105`).
DEFAULT_HEARTBEAT = 120.0
DEFAULT_MAX_BROKEN = 3
DEFAULT_MAX_IDLE_TIME = 60.0
DEFAULT_POOL_SIZE = 1
DEFAULT_PIPELINE_DEPTH = 1


class Experiment:
    """One named, versioned optimization run over a search space."""

    def __init__(self, storage, config):
        self._storage = storage
        self.name = config["name"]
        self.version = config.get("version", 1)
        self._id = config.get("_id")
        self.metadata = dict(config.get("metadata", {}))
        self.max_trials = config.get("max_trials", float("inf"))
        self.max_broken = config.get("max_broken", DEFAULT_MAX_BROKEN)
        self.heartbeat = config.get("heartbeat", DEFAULT_HEARTBEAT)
        self.max_idle_time = config.get("max_idle_time", DEFAULT_MAX_IDLE_TIME)
        self.pool_size = config.get("pool_size", DEFAULT_POOL_SIZE)
        # Worker-level knob (never stored identity, like heartbeat): how many
        # speculative rounds the producer keeps in flight (docs/performance.md
        # "Wall ≈ device").  None = unset — the Producer resolves it through
        # ORION_TPU_PIPELINE_DEPTH down to DEFAULT_PIPELINE_DEPTH (1, the
        # pre-ring behavior, pinned in tests/unit/test_producer_pipeline.py).
        self.pipeline_depth = config.get("pipeline_depth")
        self.working_dir = config.get("working_dir")
        self.algo_config = config.get("algorithms", "random")
        self.strategy_config = config.get("strategy", "MaxParallelStrategy")
        self.refers = dict(config.get("refers", {}))
        # Trial identity scheme — STORED identity (unlike heartbeat): every
        # consumer must compute the same ids, so the scheme rides the
        # experiment doc.  Absent = md5, which keeps every pre-existing
        # experiment resuming byte-identically; `db migrate-ids` flips it.
        self.id_scheme = config.get("id_scheme") or "md5"
        if self.id_scheme not in ID_SCHEMES:
            raise ValueError(
                f"Unknown id_scheme {self.id_scheme!r}; one of {ID_SCHEMES}"
            )
        self._last_lost_sweep = float("-inf")
        self.priors = dict(config.get("priors") or config.get("metadata", {}).get("priors", {}))
        self.space = build_space(self.priors) if self.priors else None
        self.algorithm = None
        self.strategy = None
        # Worker-level serving knob (never stored identity): a ``serve:``
        # config section — {"address": "host:port", ...} — makes
        # instantiate() build a gateway-backed RemoteAlgorithm instead of
        # a local instance (orion_tpu.serve, docs/serving.md).  Set by the
        # CLI bootstrap next to heartbeat/max_idle_time.
        self.serve_config = config.get("serve")

    # --- instantiation ------------------------------------------------------
    def instantiate(self, seed=None):
        """Build the algorithm + strategy from config (reference
        `experiment.py:562-614`).

        With a ``serve_config`` attached, the algorithm is a
        :class:`~orion_tpu.serve.client.RemoteAlgorithm` driving a tenant
        on the shared suggest gateway — same ``BaseAlgorithm`` surface, so
        the producer/worker stack is untouched.  The tenant is keyed by
        (name, version, host:pid) — one gateway-side instance PER WORKER,
        exactly mirroring local semantics: each worker's producer observes
        the full completed history from storage into its own instance, so
        worker restarts and multi-worker experiments never double-feed a
        shared model (coalescing still amortizes across workers AND
        experiments — signatures, not tenants, group dispatches; a dead
        worker's tenant ages out via the gateway's idle eviction)."""
        if self.space is None:
            raise ValueError(f"Experiment {self.name} has no search space")
        if self.serve_config:
            import os
            import socket

            from orion_tpu.serve.client import connect_remote_algorithm

            worker = f"{socket.gethostname()}:{os.getpid()}"
            self.algorithm = connect_remote_algorithm(
                self.space,
                self.priors,
                self.algo_config,
                self.serve_config,
                tenant=f"{self.name}-v{self.version}@{worker}",
                seed=seed,
            )
        else:
            self.algorithm = create_algo(self.space, self.algo_config, seed=seed)
        self.strategy = create_strategy(self.strategy_config)
        return self

    @property
    def id(self):
        return self._id

    @property
    def storage(self):
        return self._storage

    def configuration(self):
        out = {
            "name": self.name,
            "version": self.version,
            "metadata": self.metadata,
            "max_trials": self.max_trials,
            "max_broken": self.max_broken,
            "pool_size": self.pool_size,
            "working_dir": self.working_dir,
            "algorithms": self.algo_config,
            "strategy": self.strategy_config,
            "priors": self.priors,
            "refers": self.refers,
        }
        if self.id_scheme != "md5":
            # Conditional so default-scheme experiments' configuration stays
            # byte-for-byte what every earlier release produced (EVC conflict
            # detection and stored-config comparisons ride this dict).
            out["id_scheme"] = self.id_scheme
        return out

    # --- trial operations ---------------------------------------------------
    def fix_lost_trials(self):
        """Sweep reserved trials with stale heartbeats back to reservable
        (the elastic-recovery story; reference `experiment.py:217-232`)."""
        self._last_lost_sweep = time.monotonic()
        TELEMETRY.count("experiment.lost_trial_sweeps")
        for trial in self._storage.fetch_lost_trials(self._id, self.heartbeat):
            try:
                self._storage.set_trial_status(trial, "interrupted", was="reserved")
                log.info("Recovered lost trial %s", trial.id)
                TELEMETRY.count("experiment.lost_trials_recovered")
            except FailedUpdate:
                pass  # another worker got there first — fine

    def fix_lost_trials_throttled(self, interval=None):
        """Sweep unless one already ran within ``interval`` seconds (default
        heartbeat/4); returns True when a sweep actually ran.  Rate limiting
        matters on the reservation hot path: a trial cannot become lost
        faster than the heartbeat window, so sweeping a q=4096 reservation
        burst 4096 times is pure collection-scan overhead."""
        if interval is None:
            interval = max(1.0, self.heartbeat / 4.0)
        if time.monotonic() - self._last_lost_sweep < interval:
            return False
        self.fix_lost_trials()
        return True

    def reserve_trial(self):
        swept = self.fix_lost_trials_throttled()
        trial = self._storage.reserve_trial(self._id)
        if trial is None and not swept:
            # Miss guarantee: a dead worker's trial must be recoverable on
            # ANY reservation attempt (reference `experiment.py:217-232`),
            # so force the sweep the throttle skipped — but never twice in
            # the same call.
            self.fix_lost_trials()
            trial = self._storage.reserve_trial(self._id)
        if trial is not None:
            trial.working_dir = self.working_dir
        return trial

    def reserve_trials(self, num):
        """Batch reservation: up to ``num`` trials in one storage round trip
        (pipelined on the network backend).  Same lost-trial sweep guarantee
        as :meth:`reserve_trial`."""
        swept = self.fix_lost_trials_throttled()
        trials = self._storage.reserve_trials(self._id, num)
        if not trials and not swept:
            self.fix_lost_trials()
            trials = self._storage.reserve_trials(self._id, num)
        for trial in trials:
            trial.working_dir = self.working_dir
        return trials

    def _stamp_scheme_ids(self, trials, lie=False):
        """Freeze each trial's id under this experiment's ``id_scheme``.

        md5 needs no stamp (the ``Trial.id`` property computes it lazily);
        cube_hash ids ride ``_id_override`` so every creation path —
        single-trial registration, lies, the columnar batch — emits ids
        under ONE scheme.  A mixed-scheme experiment would silently defeat
        the duplicate-point unique index."""
        if self.id_scheme == "md5" or not trials:
            return trials
        ids = compute_scheme_ids(
            self._id,
            [trial.params for trial in trials],
            lie=lie,
            id_scheme=self.id_scheme,
            space=self.space,
        )
        for trial, _id in zip(trials, ids):
            trial._id_override = _id
        return trials

    def register_trial(self, trial, parents=()):
        trial.experiment = self._id
        trial.parents = list(parents)
        trial.submit_time = time.time()
        self._stamp_scheme_ids([trial])
        self._storage.register_trial(trial)
        return trial

    def prepare_trials(self, trials, parents=()):
        """Stamp the identity fields (experiment, lineage parents, submit
        time) WITHOUT writing storage.  This finalizes each trial's id
        (the scheme hash covers experiment + params), so a caller may key
        caches or dispatch device work against the real ids BEFORE the
        storage commit — the producer's pipelined commit path does exactly
        that."""
        now = time.time()
        for trial in trials:
            trial.experiment = self._id
            trial.parents = list(parents)
            trial.submit_time = now
        return self._stamp_scheme_ids(trials)

    def register_trials(self, trials, parents=(), prepared=False):
        """Batch registration; returns per-trial outcomes (the trial, or its
        DuplicateKeyError) — one storage round (single transaction / wire
        request on capable backends).  ``prepared=True`` skips re-stamping
        trials already passed through :meth:`prepare_trials`."""
        if not prepared:
            self.prepare_trials(trials, parents)
        return self._storage.register_trials(trials)

    def prepare_trial_batch(self, batch, parents=()):
        """Columnar twin of :meth:`prepare_trials`: stamp a
        :class:`~orion_tpu.core.trial.TrialBatch`'s identity fields and
        freeze its ids WITHOUT writing storage."""
        return batch.prepare(
            self._id,
            parents=parents,
            id_scheme=self.id_scheme,
            space=self.space,
        )

    def register_trial_batch(self, batch, parents=(), prepared=False):
        """Columnar batch registration: the round's documents are built in
        one pass (``TrialBatch.to_docs``) and fed straight to the storage
        batch primitive — no per-trial ``Trial``/``to_dict`` round trips.
        Returns per-slot outcomes (exception instances for failed slots,
        ``DuplicateKeyError`` for an already-taken point).  Storage
        protocols that predate ``register_trial_docs`` transparently fall
        back to the Trial-object path (identical write sequence)."""
        if not prepared:
            self.prepare_trial_batch(batch, parents)
        register_docs = getattr(self._storage, "register_trial_docs", None)
        if register_docs is not None:
            return register_docs(batch.to_docs())
        return self._storage.register_trials(batch.trials())

    def register_lie(self, trial):
        trial.experiment = self._id
        self._stamp_scheme_ids([trial], lie=True)
        self._storage.register_lie(trial)
        return trial

    def update_completed_trial(self, trial, results):
        return self._storage.update_completed_trial(trial, results)

    def update_completed_trials(self, pairs):
        return self._storage.update_completed_trials(pairs)

    def set_trial_status(self, trial, status, was=None):
        return self._storage.set_trial_status(trial, status, was=was)

    def update_heartbeat(self, trial):
        self._storage.update_heartbeat(trial)

    def fetch_trials(self, with_evc_tree=False):
        if with_evc_tree:
            # Roots have empty refers but may still have children — the tree
            # walk itself discovers both directions.
            from orion_tpu.evc.experiment import fetch_tree_trials

            return fetch_tree_trials(self)
        return self._storage.fetch_trials(uid=self._id)

    def fetch_trials_by_status(self, status):
        return self._storage.fetch_trials_by_status(self._id, status)

    def fetch_lies(self):
        return self._storage.fetch_lies(self._id)

    def fetch_noncompleted_trials(self):
        return self._storage.fetch_noncompleted_trials(self._id)

    # --- termination --------------------------------------------------------
    @property
    def is_done(self):
        """Completed-trial budget reached, or the algorithm says so."""
        if self._storage.count_completed_trials(self._id) >= self.max_trials:
            return True
        return bool(self.algorithm is not None and self.algorithm.is_done)

    @property
    def is_broken(self):
        return self._storage.count_broken_trials(self._id) >= self.max_broken

    def audit(self, lost_timeout=None):
        """Run the storage invariant auditor over this experiment's trials
        (``orion_tpu.storage.audit``); the orphaned-reservation threshold
        defaults to this experiment's heartbeat window."""
        from orion_tpu.storage.audit import audit_experiment

        return audit_experiment(
            self._storage, self, lost_timeout=lost_timeout
        )

    # --- stats --------------------------------------------------------------
    def stats(self):
        """Best trial + counts + duration (reference `experiment.py:419-467`)."""
        completed = self.fetch_trials_by_status("completed")
        out = {
            "trials_completed": len(completed),
            "best_trials_id": None,
            "best_evaluation": None,
            "start_time": self.metadata.get("timestamp"),
            "finish_time": None,
            "duration": None,
        }
        best = None
        finish = None
        for trial in completed:
            obj = trial.objective
            if obj is None:
                continue
            if best is None or obj.value < best.objective.value:
                best = trial
            if trial.end_time is not None:
                finish = max(finish or trial.end_time, trial.end_time)
        if best is not None:
            out["best_trials_id"] = best.id
            out["best_evaluation"] = best.objective.value
            out["best_params"] = dict(best.params)
        if finish is not None:
            out["finish_time"] = finish
            if out["start_time"] is not None:
                out["duration"] = finish - out["start_time"]
        return out


class ExperimentView:
    """Non-writable experiment façade (reference `experiment.py:673-744`).

    Wraps a built :class:`Experiment`, whitelists read-only attributes, and
    swaps its storage handle for a :class:`ReadOnlyStorage` so even the
    allowed methods cannot mutate anything.  Used by the info/status/list
    CLI paths.
    """

    __slots__ = ("_experiment",)

    valid_attributes = frozenset(
        # attributes
        ["name", "version", "metadata", "refers", "max_trials", "max_broken",
         "pool_size", "working_dir", "algo_config", "strategy_config",
         "priors", "heartbeat", "max_idle_time"]
        # properties
        + ["id", "space", "is_done", "is_broken", "stats", "storage"]
        # methods
        + ["configuration", "fetch_trials", "fetch_trials_by_status",
           "get_trial"]
    )

    def __init__(self, experiment):
        from orion_tpu.storage.base import ReadOnlyStorage

        experiment._storage = ReadOnlyStorage(experiment.storage)
        object.__setattr__(self, "_experiment", experiment)

    def __getattr__(self, name):
        if name not in self.valid_attributes:
            raise AttributeError(
                f"Cannot access attribute {name!r} on view-only experiments."
            )
        return getattr(self._experiment, name)

    def __setattr__(self, name, value):
        raise AttributeError("ExperimentView is read-only")

    def __repr__(self):
        return (
            f"ExperimentView(name={self.name}, version={self.version})"
        )


def build_experiment(
    storage,
    name,
    version=None,
    user=None,
    priors=None,
    branch_config=None,
    **config,
):
    """Create-or-resume an experiment (reference `experiment_builder.py:224-288`).

    Resolution: fetch latest (or requested) version from storage; if absent,
    create version 1 with the given config.  If present and the new config
    conflicts with the stored one, delegate to EVC branching (a version bump
    child experiment) — `orion_tpu.evc.builder.branch_experiment`.
    Races on concurrent creation retry once (RaceCondition semantics).
    """
    config = {k: v for k, v in config.items() if v is not None}
    for attempt in range(2):
        existing = _fetch_config(storage, name, version, user=user)
        if existing is None:
            # Non-mutating read of metadata: on a lost creation race the SAME
            # config dict feeds the resume path below, where popped metadata
            # would silently disable code/CLI conflict detection.
            full = {
                "name": name,
                "version": version or 1,
                "priors": dict(priors or {}),
                "metadata": {
                    "timestamp": time.time(),
                    **(config.get("metadata") or {}),
                },
                **{k: v for k, v in config.items() if k != "metadata"},
            }
            full.setdefault("algorithms", "random")
            full.setdefault("strategy", "MaxParallelStrategy")
            full["_id"] = full.get("_id") or experiment_id(
                name, full["version"], full["metadata"].get("user")
            )
            try:
                created = storage.create_experiment(full)
                return Experiment(storage, created)
            except DuplicateKeyError:
                if attempt:
                    raise RaceCondition(
                        f"lost creation race for experiment {name!r} twice"
                    )
                continue  # someone else created it — reload
        # Resume path.  Branch when anything identity-bearing changed: the
        # search space, an explicitly-given algorithm config (an omitted
        # algorithms key means "resume as stored", never a silent downgrade
        # to the default), the user script's VCS state, its config file
        # hash, or its non-prior command line.  The same detector drives the
        # branch itself, so the gate and the branching can never disagree.
        exp = Experiment(storage, existing)
        from orion_tpu.evc.builder import branch_experiment
        from orion_tpu.evc.conflicts import detect_conflicts

        candidate = {
            "name": name,
            "priors": dict(priors) if priors else dict(exp.priors),
            "algorithms": config.get("algorithms"),
            "metadata": config.get("metadata") or {},
        }
        if detect_conflicts(exp.configuration(), candidate).conflicts:
            return branch_experiment(
                storage,
                exp,
                candidate["priors"],
                branch_config=branch_config,
                **config,
            )
        for key in ("max_trials", "pool_size", "working_dir", "max_broken"):
            if key in config and config[key] is not None:
                setattr(exp, key, config[key])
        return exp
    raise RaceCondition(f"could not build experiment {name!r}")


def experiment_id(name, version, user=None):
    """Deterministic experiment identity.

    The user is part of the key: two users may own same-named experiments
    (per-user namespacing), and a name+version-only id would collide on the
    unique index at creation.  ``user=None`` keeps the historical formula so
    pre-existing databases resume unchanged.
    """
    key = {"v": version}
    if user:
        key["u"] = user
    return Trial.compute_id(name, key)


def _fetch_config(storage, name, version=None, user=None):
    query = {"name": name}
    if version is not None:
        query["version"] = version
    if user is not None:
        # -u/--user namespacing: an explicit user only sees (and resumes)
        # their own experiments; same name under another user is free.
        query["metadata.user"] = user
    docs = storage.fetch_experiments(query)
    if not docs:
        return None
    return max(docs, key=lambda d: d.get("version", 1))
