"""The worker main loop.

Capability parity: reference `src/orion/core/worker/__init__.py` — `workon`
creates a Producer and Consumer and loops `worker_trials` times (infinite by
default): stop when the experiment is done or broken; reserve a trial
(producing new ones when the queue is dry); consume it; report stats at the
end.  Many workers running this loop against one shared storage is the
framework's data-parallel execution model over DCN; on-device parallelism
lives inside each algorithm's jitted suggest step.
"""

import io
import logging

from orion_tpu.core.consumer import Consumer
from orion_tpu.core.experiment import DEFAULT_HEARTBEAT, DEFAULT_MAX_IDLE_TIME
from orion_tpu.core.producer import Producer
from orion_tpu.utils.exceptions import (
    AlgorithmExhausted,
    BrokenExperiment,
    SampleTimeout,
    WaitingForTrials,
)

log = logging.getLogger(__name__)


def reserve_trial(experiment, producer, _depth=0):
    """Reserve a trial, producing a fresh batch when none is pending
    (reference `worker/__init__.py:24-39`)."""
    trial = experiment.reserve_trial()
    if trial is not None:
        return trial
    if _depth >= 10:
        raise WaitingForTrials(
            "no trial could be reserved after repeated production rounds"
        )
    log.debug("no pending trials; producing a new batch")
    producer.update()
    producer.produce()
    return reserve_trial(experiment, producer, _depth=_depth + 1)


def workon(
    experiment,
    cmdline_parser,
    worker_trials=None,
    max_idle_time=DEFAULT_MAX_IDLE_TIME,
    heartbeat_interval=DEFAULT_HEARTBEAT / 2.0,
    on_error=None,
):
    """Run the optimization loop for up to `worker_trials` trials."""
    if worker_trials is None or worker_trials < 0:
        worker_trials = float("inf")
    producer = Producer(experiment, max_idle_time=max_idle_time)
    consumer = Consumer(
        experiment, cmdline_parser, heartbeat_interval=heartbeat_interval
    )

    iterations = 0
    try:
        iterations = _workon_loop(
            experiment, producer, consumer, worker_trials, on_error
        )
    finally:
        # Final telemetry flush: the last round's spans/metrics (including
        # the closing producer.round span) would otherwise die with the
        # process instead of reaching the storage channel `orion-tpu
        # info`/`trace` aggregate from.  Fire-and-forget by contract;
        # force_metrics bypasses the per-round upsert gate so the worker's
        # final counter totals always land.
        producer._flush_timings(force_metrics=True)
    if experiment.is_broken:
        # The budget may be exhausted on the very last worker iteration —
        # still a broken experiment, not a clean exit.
        raise BrokenExperiment(
            f"experiment {experiment.name} has too many broken trials"
        )
    return iterations


def _workon_loop(experiment, producer, consumer, worker_trials, on_error):
    iterations = 0
    while iterations < worker_trials:
        if experiment.is_broken:
            log.error(
                "Experiment %s is broken (>= %s broken trials); stopping.",
                experiment.name,
                experiment.max_broken,
            )
            raise BrokenExperiment(f"experiment {experiment.name} has too many broken trials")
        if experiment.is_done:
            log.info("Experiment %s is done.", experiment.name)
            break
        try:
            trial = reserve_trial(experiment, producer)
        except AlgorithmExhausted:
            # A finite algorithm ran out of points with nothing in flight:
            # every registered trial is consumed and no observation can
            # change that — a clean end of the hunt, reached in milliseconds
            # instead of idling out max_idle_time.
            log.info(
                "Algorithm for experiment %s is exhausted; stopping.",
                experiment.name,
            )
            break
        except (SampleTimeout, WaitingForTrials):
            if experiment.is_done:
                break
            raise
        log.debug("Consuming trial %s", trial.id)
        success = consumer.consume(trial)
        if not success and on_error is not None:
            on_error(trial)
        iterations += 1
    return iterations


def format_stats(experiment):
    """Human-readable end-of-run summary (reference `worker/__init__.py:66-88`)."""
    stats = experiment.stats()
    out = io.StringIO()
    out.write("RESULTS\n=======\n")
    out.write(f"experiment: {experiment.name} (v{experiment.version})\n")
    out.write(f"trials completed: {stats['trials_completed']}\n")
    if stats.get("best_evaluation") is not None:
        out.write(f"best objective: {stats['best_evaluation']}\n")
        out.write(f"best trial: {stats['best_trials_id']}\n")
        out.write("best params:\n")
        for name, value in sorted(stats.get("best_params", {}).items()):
            out.write(f"  {name}: {value}\n")
    return out.getvalue()
