"""The worker main loop.

Capability parity: reference `src/orion/core/worker/__init__.py` — `workon`
creates a Producer and Consumer and loops `worker_trials` times (infinite by
default): stop when the experiment is done or broken; reserve a trial
(producing new ones when the queue is dry); consume it; report stats at the
end.  Many workers running this loop against one shared storage is the
framework's data-parallel execution model over DCN; on-device parallelism
lives inside each algorithm's jitted suggest step.
"""

import io
import logging
import time

from orion_tpu.core.consumer import Consumer
from orion_tpu.core.experiment import DEFAULT_HEARTBEAT, DEFAULT_MAX_IDLE_TIME
from orion_tpu.core.producer import Producer
from orion_tpu.health import FLIGHT
from orion_tpu.storage.retry import RetryPolicy, is_transient
from orion_tpu.utils.exceptions import (
    AlgorithmExhausted,
    BrokenExperiment,
    DatabaseError,
    SampleTimeout,
    WaitingForTrials,
)

log = logging.getLogger(__name__)

#: Production rounds reserve_trial attempts before declaring the queue dry.
MAX_RESERVE_ROUNDS = 10


def reserve_trial(experiment, producer, max_rounds=MAX_RESERVE_ROUNDS, policy=None):
    """Reserve a trial, producing a fresh batch when none is pending
    (reference `worker/__init__.py:24-39`).

    Iterative, not recursive: a production storm (concurrent workers
    stealing every produced batch) used to build a depth-10 recursion
    whose traceback pointed at the recursion instead of the contention —
    and retried back-to-back with no spacing.  The loop retries up to
    ``max_rounds`` production rounds with the unified backoff policy
    between empty-handed rounds, so contention storms thin out instead of
    stampeding."""
    if policy is None:
        policy = RetryPolicy(
            max_attempts=max_rounds + 1, base_delay=0.01, max_delay=0.5,
            deadline=None,
        )
    for attempt in range(max_rounds + 1):
        trial = experiment.reserve_trial()
        if trial is not None:
            return trial
        if attempt >= max_rounds:
            break
        if attempt:
            # First empty round just produces (the common cold-start);
            # repeated ones mean contention — space them out.
            policy.sleep(attempt - 1, op="reserve_trial", span="worker.backoff")
        log.debug("no pending trials; producing a new batch")
        producer.update()
        producer.produce()
    raise WaitingForTrials(
        f"no trial could be reserved after {max_rounds} production rounds"
    )


def workon(
    experiment,
    cmdline_parser,
    worker_trials=None,
    max_idle_time=DEFAULT_MAX_IDLE_TIME,
    heartbeat_interval=DEFAULT_HEARTBEAT / 2.0,
    on_error=None,
):
    """Run the optimization loop for up to `worker_trials` trials."""
    if worker_trials is None or worker_trials < 0:
        worker_trials = float("inf")
    # Pull-based metrics plane (orion_tpu.metrics): a worker opts in via
    # the ORION_TPU_METRICS_PORT env var (or the `metrics_port:` config
    # key, which cli/base.py resolves to the same call) — idempotent, one
    # daemon /metrics + /healthz server per process, failures logged not
    # raised.
    from orion_tpu.metrics import ensure_worker_metrics_server

    ensure_worker_metrics_server()
    # Self-diagnosis watchdog (orion_tpu.diagnosis): when the
    # ORION_TPU_DOCTOR_INTERVAL env var (or the `doctor_interval:` config
    # key, resolved to the same spelling by cli/base.py) asks for one, a
    # daemon thread periodically joins this experiment's telemetry planes,
    # evaluates the doctor rule catalog, and publishes findings as
    # `flight.alert` events + the doctor.findings.* gauges the /metrics
    # and /healthz planes export.  None when not requested; never raises.
    from orion_tpu.diagnosis.watch import maybe_start_watchdog

    watchdog = maybe_start_watchdog(experiment)
    producer = Producer(experiment, max_idle_time=max_idle_time)
    consumer = Consumer(
        experiment, cmdline_parser, heartbeat_interval=heartbeat_interval
    )

    iterations = 0
    try:
        iterations = _workon_loop(
            experiment, producer, consumer, worker_trials, on_error
        )
    except BaseException as exc:
        # Crash flight record (orion_tpu.health): dump the bounded ring of
        # recent structured events (round boundaries, retries, reconnects,
        # status transitions) as a JSONL artifact next to the crash, so
        # the post-mortem starts with a timeline instead of a bare
        # traceback.  None when the recorder is disabled; dump_crash never
        # raises.
        path = FLIGHT.dump_crash(experiment.name, exc)
        if path:
            log.error("worker crashed; flight record written to %s", path)
        raise
    finally:
        if watchdog is not None:
            watchdog.stop()
        # Final telemetry flush: the last round's spans/metrics (including
        # the closing producer.round span) would otherwise die with the
        # process instead of reaching the storage channel `orion-tpu
        # info`/`trace` aggregate from.  Fire-and-forget by contract;
        # force_metrics bypasses the per-round upsert gate so the worker's
        # final counter totals always land.
        producer._flush_timings(force_metrics=True)
    if experiment.is_broken:
        # The budget may be exhausted on the very last worker iteration —
        # still a broken experiment, not a clean exit.
        raise BrokenExperiment(
            f"experiment {experiment.name} has too many broken trials"
        )
    return iterations


def _workon_loop(experiment, producer, consumer, worker_trials, on_error):
    iterations = 0
    # Graceful degradation under storage hiccups: a transient failure that
    # exhausted the storage layer's own retry policy backs the WORKER off
    # (up to max_idle_time of consecutive failure) instead of crashing it —
    # a worker that dies on a 20s storage blip abandons its reserved trial
    # to the lost-trial sweep and shrinks the fleet.  Fatal (semantic)
    # errors still raise immediately; the window resets on any success.
    degrade_policy = RetryPolicy(
        max_attempts=10**9, base_delay=0.1, max_delay=5.0, deadline=None
    )
    degrade_state = {"since": None, "count": 0}

    def _degrade(exc, where):
        """Absorb one transient failure (backoff + True) or decide it must
        raise (False): fatal errors, or a failure streak past
        max_idle_time.  Only DatabaseError-family transients qualify:
        every backend wraps its infrastructure failures in DatabaseError,
        while a raw OSError here is NOT storage — it is the user's script
        failing to launch (FileNotFoundError from Popen) and must crash
        with its real traceback, not be retried as a 'storage blip'."""
        if not (isinstance(exc, DatabaseError) and is_transient(exc)):
            return False
        now = time.monotonic()
        since = degrade_state["since"] or now
        degrade_state["since"] = since
        if now - since > producer.max_idle_time:
            log.error(
                "storage has been failing for %.1fs (> max_idle_time); "
                "giving up: %s",
                now - since,
                exc,
            )
            return False
        log.warning(
            "transient storage failure during %s (attempt %d, backing off): %s",
            where,
            degrade_state["count"] + 1,
            exc,
        )
        degrade_policy.sleep(
            degrade_state["count"], op=f"worker.{where}", span="worker.backoff"
        )
        degrade_state["count"] += 1
        return True
    while iterations < worker_trials:
        # The status reads are storage round trips too: during an outage the
        # degrade path above would absorb a reserve failure only for the
        # next loop-top is_broken/is_done read to crash the worker anyway.
        try:
            broken = experiment.is_broken
            done = False if broken else experiment.is_done
        except Exception as exc:
            if not _degrade(exc, "status"):
                raise
            continue
        if broken:
            log.error(
                "Experiment %s is broken (>= %s broken trials); stopping.",
                experiment.name,
                experiment.max_broken,
            )
            raise BrokenExperiment(f"experiment {experiment.name} has too many broken trials")
        if done:
            log.info("Experiment %s is done.", experiment.name)
            break
        try:
            trial = reserve_trial(experiment, producer)
            degrade_state["since"] = None
            degrade_state["count"] = 0
        except AlgorithmExhausted:
            # A finite algorithm ran out of points with nothing in flight:
            # every registered trial is consumed and no observation can
            # change that — a clean end of the hunt, reached in milliseconds
            # instead of idling out max_idle_time.
            log.info(
                "Algorithm for experiment %s is exhausted; stopping.",
                experiment.name,
            )
            break
        except (SampleTimeout, WaitingForTrials) as dry:
            try:
                if experiment.is_done:
                    break
            except Exception as exc:
                if not _degrade(exc, "status"):
                    raise
                continue
            raise dry
        except Exception as exc:
            if not _degrade(exc, "reserve"):
                raise
            continue
        log.debug("Consuming trial %s", trial.id)
        try:
            success = consumer.consume(trial)
        except Exception as exc:
            # An observe-side storage failure (pushing results/status) that
            # outlived the storage policy: the trial stays reserved and the
            # lost-trial sweep will recover it — back the worker off rather
            # than killing it (the observation is re-earned by the re-run,
            # never silently dropped).  KeyboardInterrupt and semantic
            # errors propagate as before.
            if not _degrade(exc, "consume"):
                raise
            continue
        degrade_state["since"] = None
        degrade_state["count"] = 0
        if not success and on_error is not None:
            on_error(trial)
        iterations += 1
    return iterations


def format_stats(experiment):
    """Human-readable end-of-run summary (reference `worker/__init__.py:66-88`)."""
    stats = experiment.stats()
    out = io.StringIO()
    out.write("RESULTS\n=======\n")
    out.write(f"experiment: {experiment.name} (v{experiment.version})\n")
    out.write(f"trials completed: {stats['trials_completed']}\n")
    if stats.get("best_evaluation") is not None:
        out.write(f"best objective: {stats['best_evaluation']}\n")
        out.write(f"best trial: {stats['best_trials_id']}\n")
        out.write("best params:\n")
        for name, value in sorted(stats.get("best_params", {}).items()):
            out.write(f"  {name}: {value}\n")
    return out.getvalue()
