"""Trial entity: the unit of optimization work.

Capability parity: reference `src/orion/core/worker/trial.py` (status machine
``new -> reserved -> completed | interrupted | broken | suspended``, nested
Param/Result values, md5 identity over params+experiment+lie flag, single-
objective accessors).  Host-only code — trials are the coordination currency
between workers; device code never sees them (it sees the flat arrays the
Space codec produces from their params).
"""

import hashlib
import time
from dataclasses import dataclass


ALL_STATUSES = (
    "new",
    "reserved",
    "suspended",
    "completed",
    "interrupted",
    "broken",
)

#: Trial identity schemes an experiment may select (``id_scheme`` config
#: field, default ``"md5"`` so every pre-existing experiment resumes
#: unchanged).  ``cube_hash`` hashes the canonical cube-row bytes instead
#: of assembling a params repr per trial — same uniqueness contract (the
#: storage unique index on ``_id``), ~an order of magnitude cheaper per
#: point.  `orion-tpu db migrate-ids` rewrites an existing experiment
#: from one scheme to the other (docs/multi_node.md).
ID_SCHEMES = ("md5", "cube_hash")

#: Statuses a worker may atomically reserve from (reference `legacy.py:253-273`).
RESERVABLE_STATUSES = ("new", "suspended", "interrupted")

#: Statuses meaning the trial will make no further progress.
STOPPED_STATUSES = ("completed", "interrupted", "broken")

RESULT_TYPES = ("objective", "constraint", "gradient", "statistic", "lie")
PARAM_TYPES = ("integer", "real", "categorical", "fidelity")


_PLAIN_SCALARS = frozenset((str, int, float, bool, type(None)))


def _canonical(value):
    """Print-independent canonical form of a param value for hashing.

    ``repr`` of numpy arrays is truncated by print options, so distinct large
    arrays would collide; normalize array-likes to full nested lists first.
    Plain python scalars (the overwhelmingly common case — one call per param
    per trial-id computation) shortcut straight to ``repr``, which is exactly
    what the general path returns for them, so stored trial ids are unchanged.
    """
    if type(value) in _PLAIN_SCALARS:
        return repr(value)
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            return repr(value.tolist())
        if isinstance(value, np.generic):
            return repr(value.item())
    except ImportError:  # pragma: no cover
        pass
    if isinstance(value, (list, tuple)):
        # Keep list/tuple distinguishable while canonicalizing elements.
        inner = ",".join(_canonical(v) for v in value)
        return ("[%s]" if isinstance(value, list) else "(%s)") % inner
    return repr(value)


def validate_status(status):
    if status is not None and status not in ALL_STATUSES:
        raise ValueError(f"Invalid trial status {status!r}; one of {ALL_STATUSES}")
    return status


@dataclass
class Result:
    """One reported value: ``{"name", "type", "value"}``."""

    name: str
    type: str
    value: object

    def __post_init__(self):
        if self.type not in RESULT_TYPES:
            raise ValueError(f"Invalid result type {self.type!r}; one of {RESULT_TYPES}")

    def to_dict(self):
        return {"name": self.name, "type": self.type, "value": self.value}


class Trial:
    """A single evaluation of the user's black box at one point of the space."""

    __slots__ = (
        "experiment",
        "_status",
        "params",
        "results",
        "worker",
        "submit_time",
        "start_time",
        "end_time",
        "heartbeat",
        "working_dir",
        "parents",
        "_id_override",
    )

    def __init__(
        self,
        experiment=None,
        status="new",
        params=None,
        results=None,
        worker=None,
        submit_time=None,
        start_time=None,
        end_time=None,
        heartbeat=None,
        working_dir=None,
        parents=None,
        _id=None,
        **_ignored,
    ):
        self.experiment = experiment
        self._status = validate_status(status) or "new"
        self.params = dict(params or {})
        self.results = [r if isinstance(r, Result) else Result(**r) for r in (results or [])]
        self.worker = worker
        self.submit_time = submit_time
        self.start_time = start_time
        self.end_time = end_time
        self.heartbeat = heartbeat
        self.working_dir = working_dir
        self.parents = list(parents or [])
        self._id_override = _id

    # --- status machine ---------------------------------------------------
    @property
    def status(self):
        return self._status

    @status.setter
    def status(self, value):
        self._status = validate_status(value)

    @property
    def is_stopped(self):
        return self._status in STOPPED_STATUSES

    # --- identity ---------------------------------------------------------
    @property
    def id(self):
        """Deterministic md5 identity (reference `trial.py:293-309`).

        Hash of experiment + sorted params (+ a lie marker), so the same point
        registered twice collides on the storage unique index — which is how
        duplicate suggestions are detected across concurrent producers.
        """
        if self._id_override is not None:
            return self._id_override
        return self.compute_id(self.experiment, self.params, lie=bool(self.lie))

    @staticmethod
    def compute_id(experiment, params, lie=False):
        payload = repr(
            (
                str(experiment),
                sorted((str(k), _canonical(v)) for k, v in params.items()),
                bool(lie),
            )
        )
        return hashlib.md5(payload.encode("utf-8")).hexdigest()

    @property
    def hash_params(self):
        """Identity of the parameter point alone (used for cross-status dedup)."""
        return Trial.compute_id(self.experiment, self.params, lie=False)

    # --- results accessors (single-objective, reference `trial.py:311-333`) ---
    def _fetch_one(self, rtype):
        for result in self.results:
            if result.type == rtype:
                return result
        return None

    @property
    def objective(self):
        return self._fetch_one("objective")

    @property
    def lie(self):
        return self._fetch_one("lie")

    @property
    def gradient(self):
        return self._fetch_one("gradient")

    @property
    def constraints(self):
        return [r for r in self.results if r.type == "constraint"]

    @property
    def statistics(self):
        return [r for r in self.results if r.type == "statistic"]

    # --- serialization ------------------------------------------------------
    def to_dict(self):
        return {
            "_id": self.id,
            "experiment": self.experiment,
            "status": self._status,
            "params": dict(self.params),
            "results": [r.to_dict() for r in self.results],
            "worker": self.worker,
            "submit_time": self.submit_time,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "heartbeat": self.heartbeat,
            "working_dir": self.working_dir,
            "parents": list(self.parents),
        }

    @classmethod
    def from_dict(cls, doc):
        doc = dict(doc)
        doc.pop("exp_working_dir", None)
        return cls(**doc)

    # --- misc ---------------------------------------------------------------
    @property
    def duration(self):
        if self.start_time is None:
            return 0.0
        end = self.end_time if self.end_time is not None else time.time()
        return end - self.start_time

    def params_repr(self, sep=","):
        return sep.join(f"{k}:{v}" for k, v in sorted(self.params.items()))

    def __eq__(self, other):
        return isinstance(other, Trial) and self.id == other.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return (
            f"Trial(experiment={self.experiment!r}, status={self._status!r}, "
            f"params={self.params_repr()})"
        )


def compute_batch_ids(experiment, params_rows, lie=False):
    """Vectorized :meth:`Trial.compute_id` over a whole q-round.

    Bit-identical md5s by construction: ``repr`` of the canonical tuple is
    assembled directly from per-part ``repr`` calls (``repr((a, [b, c], d))``
    IS ``"(" + repr(a) + ", [" + repr(b) + ", " + repr(c) + "], " + repr(d)
    + ")"``), with the experiment prefix, the sorted key order, and each
    key's own ``repr`` hoisted out of the per-row work — the per-trial
    ``sorted()`` + generator-tuple build was the single largest host cost
    of a q=1024 registration round.  Rows whose keys differ from the first
    row's (or are not name-sortable the way ``sorted`` on (str(k), value)
    pairs orders them) fall back to :meth:`Trial.compute_id` — correctness
    never depends on the fast path applying.

    Pinned differentially against ``Trial.compute_id`` in
    tests/unit/test_trial_batch.py.
    """
    n = len(params_rows)
    if n == 0:
        return []
    first = params_rows[0]
    keys = list(first)
    fast = all(type(k) is str for k in keys)
    if fast:
        order = sorted(keys)
        key_reprs = [repr(k) for k in order]
        prefix = f"({str(experiment)!r}, ["
        suffix = "], True)" if lie else "], False)"
        key_set = frozenset(order)
    ids = []
    md5 = hashlib.md5
    # lint: disable=PERF001 -- the md5 identity is per-trial by contract
    # (it IS the storage unique index); everything row-invariant (sort
    # order, key reprs, experiment prefix) is hoisted above, leaving one
    # string assembly + hash per row.
    for params in params_rows:
        if fast and params.keys() == key_set:
            parts = ", ".join(
                f"({kr}, {_canonical(params[k])!r})"
                for k, kr in zip(order, key_reprs)
            )
            ids.append(md5((prefix + parts + suffix).encode("utf-8")).hexdigest())
        else:
            ids.append(Trial.compute_id(experiment, params, lie=lie))
    return ids


def compute_cube_ids(experiment, cube_rows, lie=False):
    """Byte-hash trial identity (``id_scheme: "cube_hash"``): one 16-byte
    blake2b per row over ``experiment-prefix | canonical cube-row bytes |
    lie marker``.

    The cube rows MUST come from the canonical params→cube codec
    (``Space.params_to_cube`` — one vectorized encode pass per q-round),
    never from a raw suggestion cube: decode→re-encode is the id's
    canonical form, so the identity is a pure function of the params a
    consumer can always recompute.  Rows canonicalize to contiguous
    little-endian float32 (``<f4``) so the digest is platform-independent;
    the per-row work is one hasher copy + one memoryview slice — no string
    assembly, no repr, which is the entire speedup over the md5 scheme
    (gated ≥ 4× at q=1024 in ``bench.py --smoke``).
    """
    import numpy as np

    rows = np.ascontiguousarray(np.asarray(cube_rows, dtype="<f4"))
    if rows.ndim == 1:
        rows = rows.reshape(1, -1)
    n, width = rows.shape
    if n == 0:
        return []
    base = hashlib.blake2b(
        str(experiment).encode("utf-8") + (b"|L" if lie else b"|P"),
        digest_size=16,
    )
    stride = width * 4
    view = memoryview(rows).cast("B")
    ids = []
    # The identity is per-trial by contract (it IS the storage unique
    # index); everything row-invariant (experiment prefix, lie marker) is
    # folded into the copied base hasher, leaving one update + hexdigest
    # per row.
    for start in range(0, n * stride, stride):
        h = base.copy()
        h.update(view[start:start + stride])
        ids.append(h.hexdigest())
    return ids


def compute_scheme_ids(experiment, params_rows, lie=False, id_scheme="md5",
                       space=None):
    """Batch ids under the experiment's selected ``id_scheme``.

    ``cube_hash`` needs the experiment's :class:`~orion_tpu.space.space
    .Space` to encode params to canonical cube rows; without one — or for
    rows the codec cannot encode (params outside the space: legacy docs,
    plugin-injected points) — the md5 scheme answers instead, so
    correctness never depends on the fast scheme applying.  The fallback
    is deterministic per point (the same params always fail the encode the
    same way), which keeps the duplicate-detection contract intact.
    """
    if id_scheme == "cube_hash" and space is not None and len(params_rows):
        try:
            cube = space.params_to_cube(params_rows)
        except Exception:
            pass
        else:
            return compute_cube_ids(experiment, cube, lie=lie)
    return compute_batch_ids(experiment, params_rows, lie=lie)


class TrialBatch:
    """One q-round of trials in columnar form — the storage-document edge.

    Wraps the round's param rows (a lazy
    :class:`~orion_tpu.space.params.ParamBatch` or a plain dict list) and
    builds the q storage documents in ONE pass (:meth:`to_docs`), ids
    included, instead of q :class:`Trial` constructions + ``to_dict``
    round trips.  Real ``Trial`` objects exist only behind :meth:`trials`,
    for the plugin-compat boundary (the producer's speculative
    lie-conditioning, loop-fallback storage protocols) — they carry the
    precomputed ids, so materializing them never re-pays the md5.
    """

    __slots__ = ("params", "experiment", "parents", "submit_time", "ids",
                 "_trials")

    def __init__(self, params):
        self.params = params
        self.experiment = None
        self.parents = []
        self.submit_time = None
        self.ids = None
        self._trials = None

    def __len__(self):
        return len(self.params)

    def prepare(self, experiment, parents=(), submit_time=None,
                id_scheme="md5", space=None):
        """Stamp the identity fields and freeze the ids (the columnar twin
        of ``Experiment.prepare_trials``): after this, callers may key
        caches or dispatch device work against the real ids BEFORE the
        storage commit.  ``id_scheme``/``space`` select the experiment's
        identity scheme (:func:`compute_scheme_ids`); the default is the
        historical md5 so direct callers are unchanged."""
        self.experiment = experiment
        self.parents = list(parents)
        self.submit_time = time.time() if submit_time is None else submit_time
        self.ids = compute_scheme_ids(
            experiment, self.params, id_scheme=id_scheme, space=space
        )
        self._trials = None
        return self

    @property
    def prepared(self):
        return self.ids is not None

    def to_docs(self):
        """The q raw trial documents, key-for-key what ``Trial.to_dict``
        emits for a freshly prepared trial — fed straight to the storage
        batch primitive (``apply_batch``).  Backends copy/serialize on
        write, so handing out the live param row dicts is safe."""
        experiment = self.experiment
        submit_time = self.submit_time
        parents = list(self.parents)
        # lint: disable=PERF001 -- the storage-document edge: one JSON doc
        # per trial IS the output shape; everything inside is O(1) per row.
        return [
            {
                "_id": _id,
                "experiment": experiment,
                "status": "new",
                "params": params,
                "results": [],
                "worker": None,
                "submit_time": submit_time,
                "start_time": None,
                "end_time": None,
                "heartbeat": None,
                "working_dir": None,
                "parents": parents,
            }
            for _id, params in zip(self.ids, self.params)
        ]

    def trials(self):
        """Materialized :class:`Trial` views (cached) — the plugin-compat
        boundary.  Ids ride along as overrides; no md5 is recomputed."""
        if self._trials is None:
            ids = self.ids or [None] * len(self.params)
            # lint: disable=PERF001 -- plugin-compat boundary: per-point
            # Trial objects only materialize for per-point plugin APIs.
            self._trials = [
                Trial(
                    experiment=self.experiment,
                    params=params,
                    submit_time=self.submit_time,
                    parents=self.parents,
                    _id=_id,
                )
                for _id, params in zip(ids, self.params)
            ]
        return self._trials

    def trial_at(self, index):
        return self.trials()[index]
