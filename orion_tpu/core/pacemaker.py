"""Heartbeat thread for running trials.

Capability parity: reference `src/orion/core/worker/trial_pacemaker.py` —
a daemon thread bumping the trial's heartbeat every `wait_time` seconds while
it stays reserved; stops itself when the trial reaches a stopped status or
the update fails (meaning another actor transitioned it).

Failure accounting (robustness subsystem, docs/robustness.md): the storage
write itself already rides the unified retry policy inside
``DocumentStorage.update_heartbeat``, so an exception reaching this thread
means a whole policy's worth of backoff was exhausted.  Each such beat
books a ``pacemaker.beats_failed`` counter tick, and after
``max_failed_beats`` CONSECUTIVE failures the cause is logged loudly (and
re-logged every further ``max_failed_beats`` beats) — a silently dead
heartbeat is exactly how a live trial gets swept as lost and re-executed
by another worker.  The thread keeps beating regardless: the next
successful write is what saves the trial.
"""

import logging
import os
import threading
import time

from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import FailedUpdate

log = logging.getLogger(__name__)

DEFAULT_WAIT_TIME = 60.0

#: Consecutive failed beats before the pacemaker starts warning (env knob
#: ORION_TPU_PACEMAKER_MAX_FAILED_BEATS, or the constructor parameter).
DEFAULT_MAX_FAILED_BEATS = 3


class TrialPacemaker(threading.Thread):
    def __init__(self, storage, trial, wait_time=DEFAULT_WAIT_TIME,
                 max_failed_beats=None):
        super().__init__(daemon=True)
        self.storage = storage
        self.trial = trial
        self.wait_time = wait_time
        if max_failed_beats is None:
            try:
                max_failed_beats = int(
                    os.environ.get("ORION_TPU_PACEMAKER_MAX_FAILED_BEATS", "")
                    or DEFAULT_MAX_FAILED_BEATS
                )
            except ValueError:
                max_failed_beats = DEFAULT_MAX_FAILED_BEATS
        self.max_failed_beats = max(1, int(max_failed_beats))
        self.consecutive_failures = 0
        self._stop_event = threading.Event()

    def stop(self):
        self._stop_event.set()

    def run(self):
        beat_due = time.perf_counter() + self.wait_time
        while not self._stop_event.wait(self.wait_time):
            # Heartbeat lag: how far past the scheduled beat this one fires
            # (event-wait jitter + the PREVIOUS beat's storage-write time —
            # beat_due is re-anchored at wake, before this beat's write, so
            # a slow/flapping storage backend shows up in the next wake's
            # lag instead of being absorbed).  A lag approaching the
            # lost-trial sweep threshold means live trials are at risk of
            # being recovered as lost — exported as a gauge so `orion-tpu
            # info` surfaces it per worker fleet.
            now = time.perf_counter()
            TELEMETRY.set_gauge(
                "pacemaker.heartbeat_lag_s", max(0.0, now - beat_due)
            )
            beat_due = now + self.wait_time
            try:
                self.storage.update_heartbeat(self.trial)
                self.consecutive_failures = 0
            except FailedUpdate:
                break  # trial no longer reserved — our work here is done
            except Exception as exc:
                # The storage layer's retry policy already backed off and
                # gave up; swallow the beat but NEVER silently — count it,
                # and warn once per max_failed_beats streak with the cause
                # so a dying heartbeat is visible before the lost-trial
                # sweep reclaims a live trial.
                self.consecutive_failures += 1
                TELEMETRY.count("pacemaker.beats_failed")
                if self.consecutive_failures % self.max_failed_beats == 0:
                    log.warning(
                        "heartbeat for trial %s has failed %d consecutive "
                        "time(s) (latest cause: %s); the trial will be swept "
                        "as lost if this persists past the experiment "
                        "heartbeat window",
                        self.trial.id,
                        self.consecutive_failures,
                        exc,
                    )
                continue
