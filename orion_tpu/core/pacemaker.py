"""Heartbeat thread for running trials.

Capability parity: reference `src/orion/core/worker/trial_pacemaker.py` —
a daemon thread bumping the trial's heartbeat every `wait_time` seconds while
it stays reserved; stops itself when the trial reaches a stopped status or
the update fails (meaning another actor transitioned it).
"""

import threading

from orion_tpu.utils.exceptions import FailedUpdate

DEFAULT_WAIT_TIME = 60.0


class TrialPacemaker(threading.Thread):
    def __init__(self, storage, trial, wait_time=DEFAULT_WAIT_TIME):
        super().__init__(daemon=True)
        self.storage = storage
        self.trial = trial
        self.wait_time = wait_time
        self._stop_event = threading.Event()

    def stop(self):
        self._stop_event.set()

    def run(self):
        while not self._stop_event.wait(self.wait_time):
            try:
                self.storage.update_heartbeat(self.trial)
            except FailedUpdate:
                break  # trial no longer reserved — our work here is done
            except Exception:  # pragma: no cover - storage hiccup; retry next beat
                continue
