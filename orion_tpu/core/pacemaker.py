"""Heartbeat thread for running trials.

Capability parity: reference `src/orion/core/worker/trial_pacemaker.py` —
a daemon thread bumping the trial's heartbeat every `wait_time` seconds while
it stays reserved; stops itself when the trial reaches a stopped status or
the update fails (meaning another actor transitioned it).
"""

import threading
import time

from orion_tpu.telemetry import TELEMETRY
from orion_tpu.utils.exceptions import FailedUpdate

DEFAULT_WAIT_TIME = 60.0


class TrialPacemaker(threading.Thread):
    def __init__(self, storage, trial, wait_time=DEFAULT_WAIT_TIME):
        super().__init__(daemon=True)
        self.storage = storage
        self.trial = trial
        self.wait_time = wait_time
        self._stop_event = threading.Event()

    def stop(self):
        self._stop_event.set()

    def run(self):
        beat_due = time.perf_counter() + self.wait_time
        while not self._stop_event.wait(self.wait_time):
            # Heartbeat lag: how far past the scheduled beat this one fires
            # (event-wait jitter + the PREVIOUS beat's storage-write time —
            # beat_due is re-anchored at wake, before this beat's write, so
            # a slow/flapping storage backend shows up in the next wake's
            # lag instead of being absorbed).  A lag approaching the
            # lost-trial sweep threshold means live trials are at risk of
            # being recovered as lost — exported as a gauge so `orion-tpu
            # info` surfaces it per worker fleet.
            now = time.perf_counter()
            TELEMETRY.set_gauge(
                "pacemaker.heartbeat_lag_s", max(0.0, now - beat_due)
            )
            beat_due = now + self.wait_time
            try:
                self.storage.update_heartbeat(self.trial)
            except FailedUpdate:
                break  # trial no longer reserved — our work here is done
            except Exception:  # pragma: no cover - storage hiccup; retry next beat
                continue
