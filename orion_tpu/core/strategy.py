"""Parallel strategies: fantasized objectives ("lies") for in-flight trials.

Capability parity: reference `src/orion/core/worker/strategy.py` — the
constant-liar family keeping concurrent batch suggestion diverse: without a
fantasy value for incomplete trials, a batch/parallel optimizer would re-pick
the same point.  Strategies observe the full trial stream and produce a lie
result for each incomplete trial; the producer feeds lies to a *naive* copy
of the algorithm (reference `producer.py:134-174`).
"""

from orion_tpu.core.trial import Result
from orion_tpu.utils.registry import Registry

strategy_registry = Registry("strategy")


class BaseParallelStrategy:
    """Observe completed trials; fantasize objectives for incomplete ones."""

    def observe(self, params_list, results):
        """Digest completed evaluations (objective values)."""
        raise NotImplementedError

    def lie(self, trial):
        """Return a fake Result of type 'lie' for an incomplete trial, or None.

        If the trial already carries a lie (re-registered), reuse it —
        reference `strategy.py:89-101`.
        """
        existing = trial.lie
        if existing is not None:
            return existing
        return self._lie_value(trial)

    def _lie_value(self, trial):
        raise NotImplementedError

    @property
    def configuration(self):
        return type(self).__name__


@strategy_registry.register("NoParallelStrategy")
class NoParallelStrategy(BaseParallelStrategy):
    """Never lie — incomplete trials are invisible to the naive algo."""

    def observe(self, params_list, results):
        pass

    def _lie_value(self, trial):
        return None


@strategy_registry.register("StubParallelStrategy")
class StubParallelStrategy(BaseParallelStrategy):
    """Constant lie value (None by default) for every incomplete trial."""

    def __init__(self, stub_value=None):
        self.stub_value = stub_value

    def observe(self, params_list, results):
        pass

    def _lie_value(self, trial):
        return Result(name="lie", type="lie", value=self.stub_value)

    @property
    def configuration(self):
        if self.stub_value is None:
            return type(self).__name__
        return {type(self).__name__: {"stub_value": self.stub_value}}


@strategy_registry.register("MaxParallelStrategy")
class MaxParallelStrategy(BaseParallelStrategy):
    """Lie with the worst (max) completed objective — the default
    (reference `experiment.py:611-612`); pessimistic fantasies repel the
    optimizer from in-flight regions without assuming success."""

    def __init__(self, default_result=float("inf")):
        self.default_result = default_result
        self.max_result = None

    def observe(self, params_list, results):
        objectives = [
            float(r["objective"]) for r in results if r.get("objective") is not None
        ]
        if objectives:
            top = max(objectives)
            self.max_result = top if self.max_result is None else max(self.max_result, top)

    def _lie_value(self, trial):
        value = self.max_result if self.max_result is not None else self.default_result
        # Never emit a non-finite lie (round-1 verdict weak #5): before any
        # completion the inf default would NaN any model-based algorithm
        # that forgets to clamp.  No lie at all is the safe fantasy then.
        if value is None or not float("-inf") < value < float("inf"):
            return None
        return Result(name="lie", type="lie", value=value)


@strategy_registry.register("MeanParallelStrategy")
class MeanParallelStrategy(BaseParallelStrategy):
    """Lie with the mean completed objective."""

    def __init__(self, default_result=float("inf")):
        self.default_result = default_result
        self._sum = 0.0
        self._count = 0

    def observe(self, params_list, results):
        for r in results:
            if r.get("objective") is not None:
                self._sum += float(r["objective"])
                self._count += 1

    def _lie_value(self, trial):
        value = self._sum / self._count if self._count else self.default_result
        if value is None or not float("-inf") < value < float("inf"):
            return None  # see MaxParallelStrategy._lie_value
        return Result(name="lie", type="lie", value=value)


def create_strategy(config=None):
    """``"MaxParallelStrategy"`` or ``{"StubParallelStrategy": {...}}``."""
    config = config or "MaxParallelStrategy"
    if isinstance(config, str):
        return strategy_registry.create(config)
    name, kwargs = next(iter(config.items()))
    return strategy_registry.create(name, **(kwargs or {}))
