"""Domain model: trials, experiments, worker runtime."""
