"""Config-file converters: parse user script config templates.

Capability parity: reference `src/orion/core/io/convert.py` — YAML and JSON
converters plus a generic regex-based templater for arbitrary text configs,
selected by file extension.  A converter turns a config file into a flat
``{namespace: value}`` dict and can regenerate a concrete file from one.
"""

import json
import os
import re

import yaml


def _flatten_ns(nested, prefix=""):
    """Flatten nested config into /-namespaced keys (reference convention)."""
    out = {}
    for key, value in nested.items():
        full = f"{prefix}/{key}"
        if isinstance(value, dict) and value:
            out.update(_flatten_ns(value, prefix=full))
        else:
            out[full] = value
    return out


def _unflatten_ns(flat):
    # Split on "/" directly — keys containing a literal "." (e.g. "opt.lr")
    # must survive the round trip unrestructured.
    out = {}
    for key, value in flat.items():
        parts = key.lstrip("/").split("/")
        node = out
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return out


class YAMLConverter:
    extensions = (".yml", ".yaml")

    def parse(self, path):
        with open(path) as handle:
            data = yaml.safe_load(handle) or {}
        return _flatten_ns(data)

    def generate(self, path, flat):
        with open(path, "w") as handle:
            yaml.safe_dump(_unflatten_ns(flat), handle, default_flow_style=False)


class JSONConverter:
    extensions = (".json",)

    def parse(self, path):
        with open(path) as handle:
            data = json.load(handle)
        return _flatten_ns(data)

    def generate(self, path, flat):
        with open(path, "w") as handle:
            json.dump(_unflatten_ns(flat), handle, indent=2)


class GenericConverter:
    """Regex templating over arbitrary text configs.

    Finds ``name~prior`` occurrences (reference `convert.py` GenericConverter),
    remembers the surrounding text as a template, and substitutes concrete
    values on generate.
    """

    extensions = ()
    # Expression alternatives, first match wins: a (possibly marked) call
    # form whose parentheses may contain spaces/quotes
    # (``lr~loguniform(1e-4, 1e-1)``, ``act~+choices(['relu', 'tanh'])``),
    # the remove marker ``x~-``, the rename marker ``x~>new_name``, or a
    # bare token.  Truncating at whitespace (the previous rule) silently
    # dropped everything after the first space inside the parentheses —
    # the reference's regex (`convert.py:158`) deliberately spans to the
    # closing parenthesis for the same reason.
    # The marker alternatives need boundaries: a bare "-" must not eat the
    # front of "-5" (old bare-token capture), and ">name" must span
    # hyphenated names or "m~>new-name" would template a dangling "-name".
    # The call-form parentheses allow ONE level of nesting
    # (``choices([(1, 2), (3, 4)])``) instead of stopping at the first ``)``;
    # a fully greedy ``\(.*\)`` (the reference's rule, `convert.py:158`)
    # would instead swallow a second ``name~prior(...)`` on the same line.
    PRIOR_RE = re.compile(
        r"([\w\.\-/]+)~([+]?[\w.]+\((?:[^()]|\([^()]*\))*\)|-(?![\w.\-])|>[\w.\-]+|[^\s'\"]+)"
    )

    def __init__(self):
        self._template = None

    def parse(self, path):
        with open(path) as handle:
            text = handle.read()
        flat = {}

        def repl(match):
            name, expr = match.groups()
            ns = "/" + name.lstrip("/")
            flat[ns] = "~" + expr
            return "{" + ns + "}"

        self._template = self.PRIOR_RE.sub(repl, text)
        return flat

    def generate(self, path, flat):
        if self._template is None:
            raise RuntimeError("GenericConverter.generate before parse")
        text = self._template
        for ns, value in flat.items():
            text = text.replace("{" + ns + "}", str(value))
        with open(path, "w") as handle:
            handle.write(text)


def infer_converter(path):
    ext = os.path.splitext(path)[1].lower()
    for cls in (YAMLConverter, JSONConverter):
        if ext in cls.extensions:
            return cls()
    return GenericConverter()
