"""Host-side IO: user-commandline parsing, config converters, templating."""
