"""User-commandline prior extraction and templating.

Capability parity: reference `src/orion/core/io/orion_cmdline_parser.py` +
`cmdline_parser.py`: extract priors from the user's command
(``-x~'uniform(-50, 50)'`` becomes namespace ``/x``) and from a config file
referenced by ``--config`` (templated YAML/JSON/generic), keep an
order-preserving template of the whole command, and regenerate the concrete
argv for a given trial — including per-trial instantiated config files and
``{trial.id}`` / ``{trial.working_dir}`` / ``{exp.name}`` placeholders.
"""

import copy
import os
import re

from orion_tpu.io.convert import infer_converter


class CommandLineParser:
    """Parse once at experiment creation; format per trial forever after."""

    def __init__(self, config_prefix="config"):
        self.config_prefix = config_prefix
        self.template = []  # tokens: literals or {"ns": "/x"} placeholders
        self.priors = {}  # namespace -> prior expr (markers preserved)
        self.config_file_path = None
        self._config_template = {}  # namespace -> literal or prior placeholder
        self._converter = None

    # --- parsing ------------------------------------------------------------
    def parse(self, args):
        args = list(args or [])
        i = 0
        while i < len(args):
            token = args[i]
            consumed = self._parse_config_flag(args, i)
            if consumed:
                i += consumed
                continue
            self._parse_token(token)
            i += 1
        return self.priors

    def _parse_config_flag(self, args, i):
        """Handle ``--config path`` / ``-c path`` / ``--config=path``."""
        token = args[i]
        names = {f"--{self.config_prefix}", f"-{self.config_prefix[0]}"}
        path = None
        used = 0
        if token in names and i + 1 < len(args):
            path, used = args[i + 1], 2
            self.template.extend([token, {"config": True}])
        elif token.startswith(f"--{self.config_prefix}="):
            path, used = token.split("=", 1)[1], 1
            self.template.append({"config": True, "eq_flag": f"--{self.config_prefix}"})
        if path is None:
            return 0
        if self.config_file_path is not None:
            raise ValueError("Only one --config file is supported")
        self.config_file_path = os.path.abspath(path)
        self._parse_config_file(self.config_file_path)
        return used

    def _parse_config_file(self, path):
        self._converter = infer_converter(path)
        flat = self._converter.parse(path)
        for ns, value in flat.items():
            if isinstance(value, str) and value.startswith("~"):
                expr = value[1:]
                if ns in self.priors:
                    raise ValueError(f"Duplicate prior for {ns}")
                self.priors[ns] = expr
                self._config_template[ns] = {"ns": ns}
            else:
                self._config_template[ns] = value

    _NAME_RE = re.compile(r"[\w\.\-/]+")

    def _parse_token(self, token):
        """Classify one arg: dashed prior (``-x~'uniform(0,1)'``, with or
        without ``=``), positional prior (``x~prior``), or literal."""
        if "~" not in token:
            self.template.append(token)
            return
        if token.startswith("-"):
            dashes = "-" * (len(token) - len(token.lstrip("-")))
            rest = token.lstrip("-")
            left, expr = rest.split("~", 1)
            eq = left.endswith("=")
            name = left[:-1] if eq else left
            if name and self._NAME_RE.fullmatch(name):
                self._add_prior("/" + name, expr, flag=dashes + name, eq=eq)
            else:
                self.template.append(token)
            return
        left, expr = token.split("~", 1)
        if left and self._NAME_RE.fullmatch(left):
            self._add_prior("/" + left, expr, flag=None, eq=False)
        else:
            self.template.append(token)

    def _add_prior(self, ns, expr, flag=None, eq=False):
        if ns in self.priors:
            raise ValueError(f"Duplicate prior for {ns}")
        self.priors[ns] = expr
        self.template.append({"ns": ns, "flag": flag, "eq": eq})

    # --- state --------------------------------------------------------------
    def state_dict(self):
        return {
            "config_prefix": self.config_prefix,
            "template": copy.deepcopy(self.template),
            "priors": dict(self.priors),
            "config_file_path": self.config_file_path,
            "config_template": copy.deepcopy(self._config_template),
        }

    @classmethod
    def from_state(cls, state):
        parser = cls(config_prefix=state.get("config_prefix", "config"))
        parser.template = copy.deepcopy(state["template"])
        parser.priors = dict(state["priors"])
        parser.config_file_path = state.get("config_file_path")
        parser._config_template = copy.deepcopy(state.get("config_template", {}))
        if parser.config_file_path:
            parser._converter = infer_converter(parser.config_file_path)
            if hasattr(parser._converter, "PRIOR_RE") and os.path.exists(
                parser.config_file_path
            ):
                parser._converter.parse(parser.config_file_path)
        return parser

    # --- formatting ---------------------------------------------------------
    def format(self, trial, experiment=None, config_path=None):
        """Concrete argv for one trial (reference `orion_cmdline_parser.py:359`)."""
        out = []
        for token in self.template:
            if isinstance(token, str):
                out.append(self._substitute(token, trial, experiment))
                continue
            if token.get("config"):
                if config_path is None:
                    raise ValueError("Trial needs an instantiated config file path")
                if token.get("eq_flag"):
                    out.append(f"{token['eq_flag']}={config_path}")
                else:
                    out.append(config_path)
                continue
            ns = token["ns"]
            value = trial.params[ns]
            if token.get("flag") and token.get("eq"):
                out.append(f"{token['flag']}={value}")
            elif token.get("flag"):
                out.extend([token["flag"], str(value)])
            else:
                out.append(str(value))
        return out

    def generate_config(self, path, trial):
        """Write the per-trial concrete config file."""
        if self._converter is None:
            raise RuntimeError("No config file was parsed")
        flat = {}
        for ns, value in self._config_template.items():
            if isinstance(value, dict) and "ns" in value:
                flat[ns] = trial.params[value["ns"]]
            else:
                flat[ns] = value
        self._converter.generate(path, flat)

    @staticmethod
    def _substitute(token, trial, experiment):
        if "{" not in token:
            return token
        mapping = {
            "trial.id": getattr(trial, "id", ""),
            "trial.working_dir": getattr(trial, "working_dir", "") or "",
            "trial.hash_params": getattr(trial, "hash_params", ""),
            "exp.name": getattr(experiment, "name", "") if experiment else "",
        }
        for key, value in mapping.items():
            token = token.replace("{" + key + "}", str(value))
        return token

    @property
    def has_config_file(self):
        return self.config_file_path is not None
