"""VCS + script-config metadata capture for experiment identity.

Capability parity: reference `src/orion/core/io/resolve_config.py:249-289`
(`infer_versioning_metadata`: HEAD sha, dirty flag, active branch, diff sha
of the user script's git repository).  Implemented over subprocess git — no
gitpython dependency — and degrades to None outside a repository, so
experiments on unversioned scripts simply never raise CodeConflict.

The captured dict feeds `orion_tpu.evc.conflicts.detect_conflicts`: a changed
``HEAD_sha`` (or a changed dirty-diff sha) between two hunts of the same
experiment raises CodeConflict -> branch; a changed script-config content
hash raises ScriptConfigConflict.
"""

import hashlib
import logging
import os
import subprocess

log = logging.getLogger(__name__)

_GIT_TIMEOUT = 10.0
#: Untracked files whose CONTENT feeds the code-identity hash (code only —
#: data/log/checkpoint files change during a hunt without being code changes).
_CODE_SUFFIXES = (".py", ".sh", ".yaml", ".yml", ".json", ".toml", ".cfg", ".ini")
_MAX_HASHED_FILE = 1 << 20  # 1 MiB


def _git(repo_dir, *argv):
    """Run git in ``repo_dir``; returns stripped stdout or None on failure."""
    try:
        result = subprocess.run(
            ["git", "-C", repo_dir, *argv],
            capture_output=True,
            text=True,
            timeout=_GIT_TIMEOUT,
        )
    except (OSError, subprocess.TimeoutExpired) as exc:
        log.debug("git %s failed: %s", argv, exc)
        return None
    if result.returncode != 0:
        return None
    return result.stdout.strip()


def infer_versioning_metadata(script_path):
    """Describe the git state of the repository containing ``script_path``.

    Returns ``{"type": "git", "is_dirty", "HEAD_sha", "active_branch",
    "diff_sha"}`` or None when the script is not inside a git repository (or
    git is unavailable).  ``diff_sha`` hashes the uncommitted diff so two
    dirty checkouts at the same HEAD still compare differently when their
    edits differ (reference `resolve_config.py:270-282`).
    """
    repo_dir = os.path.dirname(os.path.abspath(script_path)) or "."
    if _git(repo_dir, "rev-parse", "--is-inside-work-tree") != "true":
        return None
    head_sha = _git(repo_dir, "rev-parse", "HEAD")
    if head_sha is None:  # fresh repo without commits
        head_sha = ""
    branch = _git(repo_dir, "rev-parse", "--abbrev-ref", "HEAD")
    status = _git(repo_dir, "status", "--porcelain")
    diff = _git(repo_dir, "diff", "HEAD") if head_sha else _git(repo_dir, "diff")
    # The working-tree hash covers the tracked diff, the status listing, AND
    # the CONTENT of untracked *code* files next to the script: `git diff
    # HEAD` is blind to untracked files and the status listing only names
    # them, but an edited untracked helper the script imports is still a
    # code change.  Only small source files are content-hashed — untracked
    # logs/checkpoints the script WRITES during a hunt must not churn the
    # code identity and force a spurious branch on every resume.
    parts = [diff or "", status or ""]
    untracked = _git(repo_dir, "ls-files", "--others", "--exclude-standard")
    for rel in (untracked or "").splitlines():
        if not rel.endswith(_CODE_SUFFIXES):
            continue
        path = os.path.join(repo_dir, rel)
        try:
            if os.path.getsize(path) > _MAX_HASHED_FILE:
                continue
            with open(path, "rb") as handle:
                parts.append(rel + hashlib.sha256(handle.read()).hexdigest())
        except OSError:
            parts.append(rel)
    dirty_state = "\0".join(parts)
    diff_sha = (
        hashlib.sha256(dirty_state.encode()).hexdigest()
        if dirty_state.strip("\0")
        else None
    )
    return {
        "type": "git",
        "is_dirty": bool(status),
        "HEAD_sha": head_sha,
        "active_branch": branch,
        "diff_sha": diff_sha,
    }


def hash_config_file(path):
    """Content hash of the user's script config file (templated YAML/JSON/...).

    Feeds ScriptConfigConflict detection: editing the config template between
    hunts must branch the experiment (reference `conflicts.py:1334`).
    """
    try:
        with open(path, "rb") as handle:
            return hashlib.sha256(handle.read()).hexdigest()
    except OSError:
        return None
