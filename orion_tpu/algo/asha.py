"""ASHA — Asynchronous Successive Halving.

Capability parity: reference `src/orion/algo/asha.py` — brackets of rungs
with geometric budgets; `suggest` first tries to promote the top
1/reduction_factor of a filled rung to the next rung, else samples a new
point at the bracket's bottom-rung fidelity (bracket chosen by softmax over
negative rung occupancy); points dedup by hash of their non-fidelity params;
`observe` records objectives into rungs; done when the top rungs are filled.

TPU split: rung bookkeeping is inherently sequential, pointer-chasing host
logic and stays host-side (as in the reference); *sampling* new points is the
device path — one jitted uniform draw through the Space codec, so an ASHA
sweep at q=4096 (BASELINE config #5) costs one kernel launch per round.
"""

import hashlib
import logging

import jax
import numpy as np

from orion_tpu.algo.base import BaseAlgorithm, algo_registry

log = logging.getLogger(__name__)


def _geometric_budgets(low, high, factor, num_rungs=None):
    budgets = []
    b = low
    while b < high:
        budgets.append(int(b))
        b *= factor
    budgets.append(int(high))
    if num_rungs is not None and len(budgets) > num_rungs:
        # Keep the extremes, thin the middle evenly.
        idx = np.linspace(0, len(budgets) - 1, num_rungs).round().astype(int)
        budgets = [budgets[i] for i in sorted(set(idx.tolist()))]
    return budgets


class Bracket:
    """One successive-halving ladder (reference `asha.py:259-365`)."""

    def __init__(self, budgets, reduction_factor):
        self.rungs = [{"resources": b, "results": {}} for b in budgets]
        self.reduction_factor = reduction_factor

    def register(self, point_hash, params, objective, fidelity):
        for rung in self.rungs:
            if rung["resources"] == fidelity:
                rung["results"][point_hash] = (objective, params)
                return True
        return False

    def get_candidate(self, rung_index):
        """Top-1/rf point of rung not yet present in the next rung."""
        rung = self.rungs[rung_index]["results"]
        next_rung = self.rungs[rung_index + 1]["results"]
        scored = [(h, o, p) for h, (o, p) in rung.items() if o is not None]
        scored.sort(key=lambda t: t[1])
        k = len(rung) // self.reduction_factor
        for h, _objective, params in scored[:k]:
            if h not in next_rung:
                return h, params
        return None, None

    def promote(self):
        """Find a promotable point; returns (hash, params, next_fidelity)."""
        for i in range(len(self.rungs) - 1):
            point_hash, params = self.get_candidate(i)
            if point_hash is not None:
                # Reserve the slot so concurrent suggests don't double-promote.
                self.rungs[i + 1]["results"][point_hash] = (None, params)
                return point_hash, params, self.rungs[i + 1]["resources"]
        return None, None, None

    def holds(self, point_hash):
        return any(point_hash in rung["results"] for rung in self.rungs)

    @property
    def is_filled(self):
        return len(self.rungs[0]["results"]) >= self.reduction_factor ** (
            len(self.rungs) - 1
        )

    @property
    def is_done(self):
        # Pending slots (objective None, promotion reserved or in flight) do
        # NOT finish a bracket — the top-fidelity trial must be evaluated.
        return any(
            entry[0] is not None for entry in self.rungs[-1]["results"].values()
        )

    def state(self):
        return [
            {"resources": r["resources"], "results": dict(r["results"])}
            for r in self.rungs
        ]

    def __deepcopy__(self, memo):
        """Naive-copy support (producer lie fantasization): rung ENTRIES are
        immutable-by-rebinding — `register`/`promote` always assign whole
        `(objective, params)` tuples, never mutate one in place — so the
        clone only needs fresh results DICTS (its inserts must not leak
        back), sharing the entries.  A true deepcopy walked ~325k dict
        nodes per produce round at 2048 trials (~0.25 s/round)."""
        cls = type(self)
        clone = cls.__new__(cls)
        memo[id(self)] = clone
        clone.reduction_factor = self.reduction_factor
        clone.rungs = self.state()
        return clone


@algo_registry.register("asha")
class ASHA(BaseAlgorithm):
    requires_fidelity = True

    # Rung bookkeeping is dict-keyed; observe() ignores the columnar rows,
    # so the producer must not waste an encode+cache per trial on them.
    # Model-based subclasses that DO consume cube (asha_bo, bohb) flip
    # this back on.
    uses_observe_cube = False

    # str -> int with immutable values; the naive copy only needs its own
    # dict so clone-side assignments don't leak back (base _share_dicts).
    _share_dicts = ("_bracket_of",)

    def __init__(
        self,
        space,
        seed=None,
        num_rungs=None,
        num_brackets=1,
        reduction_factor=None,
    ):
        super().__init__(
            space,
            seed=seed,
            num_rungs=num_rungs,
            num_brackets=num_brackets,
            reduction_factor=reduction_factor,
        )
        fid = space.fidelity
        if fid is None:
            raise RuntimeError(
                "ASHA requires a fidelity dimension (e.g. epochs~fidelity(1, 81, 3))"
            )
        self.fidelity_name = fid.name
        rf = int(reduction_factor or max(fid.base, 2))
        if rf < 2:
            raise ValueError(f"reduction_factor must be >= 2, got {rf}")
        self.reduction_factor = rf
        budgets = _geometric_budgets(fid.low, fid.high, rf, num_rungs)
        # Bracket s skips the s lowest rungs (ASHA paper); reference asha.py:125-134.
        num_brackets = min(num_brackets, len(budgets))
        self.brackets = [
            Bracket(budgets[s:], rf) for s in range(num_brackets)
        ]
        # point_hash -> bracket index.  A fidelity alone cannot identify the
        # bracket with num_brackets > 1 (bracket s's rungs are budgets[s:], a
        # subset of bracket 0's), so assignment is tracked at suggest time.
        self._bracket_of = {}

    # --- health --------------------------------------------------------------
    def rung_occupancy(self):
        """Per-bracket rung fill: ``[[(resources, occupied, evaluated),
        ...], ...]`` — ``occupied`` counts every slot (pending promotions
        included), ``evaluated`` only slots holding a real objective.  The
        optimization-health signal for fidelity schedulers: a rung whose
        occupancy stalls is where the ladder is starved."""
        # Lists, not tuples: these land verbatim in storage documents, and
        # the JSON-codec backends round-trip lists only.
        return [
            [
                [
                    rung["resources"],
                    len(rung["results"]),
                    sum(
                        1
                        for entry in rung["results"].values()
                        if entry[0] is not None
                    ),
                ]
                for rung in bracket.rungs
            ]
            for bracket in self.brackets
        ]

    def health_record(self):
        """Host-side health snapshot (orion_tpu.health): rung occupancy +
        the best evaluated objective across rungs.  GP-backed subclasses
        (asha_bo) extend this with the device GP/acquisition fields."""
        best = None
        for bracket in self.brackets:
            for rung in bracket.rungs:
                for objective, _params in rung["results"].values():
                    if objective is not None and (best is None or objective < best):
                        best = objective
        record = {
            "algo": type(self).__name__.lower(),
            "n_obs": int(self._n_observed),
            "rung_occupancy": self.rung_occupancy(),
        }
        if best is not None:
            record["best_y"] = float(best)
        return record

    # --- identity ------------------------------------------------------------
    def _point_hash(self, params):
        """md5 over non-fidelity params (reference `asha.py:204-210`).

        One C-level ``repr`` of the sorted item tuples — a python-level
        ``repr(v)`` per value was ~0.5 s of a 2048-trial ackley50 sweep
        (51 dims x every observe/sample).  Dedup semantics are unchanged:
        two params hash equal iff their sorted (name, value) reprs match.
        Sorted by KEY only: param names are unique strings, and letting
        ``sorted`` fall through to comparing values would raise TypeError
        on heterogeneous (non-string) values."""
        items = sorted(
            ((k, v) for k, v in params.items() if k != self.fidelity_name),
            key=lambda kv: kv[0],
        )
        return hashlib.md5(repr(items).encode()).hexdigest()

    # --- suggest/observe -------------------------------------------------------
    def suggest(self, num=1):
        """Promotions first, then new points batched in ONE device draw —
        an ASHA sweep at q=4096 (BASELINE config #5) costs a single kernel
        launch for sampling, not 4096."""
        out = []
        while len(out) < num:
            promoted = self._promote_one()
            if promoted is None:
                break
            out.append(promoted)
        remaining = num - len(out)
        if remaining:
            out.extend(self._sample_new(remaining))
        return out or None

    def _resolve_bracket(self, point_hash, fidelity):
        """Bracket for a point: tracked assignment, else the bracket already
        holding it, else — for an unknown point (e.g. suggested by a
        concurrent worker) — the bracket whose BOTTOM rung is this fidelity
        (fresh points always enter at a bracket's bottom), else the first
        bracket with any rung at this fidelity."""
        if point_hash in self._bracket_of:
            return self.brackets[self._bracket_of[point_hash]]
        for i, bracket in enumerate(self.brackets):
            if bracket.holds(point_hash):
                self._bracket_of[point_hash] = i
                return bracket
        for i, bracket in enumerate(self.brackets):
            if bracket.rungs[0]["resources"] == fidelity:
                self._bracket_of[point_hash] = i
                return bracket
        for i, bracket in enumerate(self.brackets):
            if any(r["resources"] == fidelity for r in bracket.rungs):
                self._bracket_of[point_hash] = i
                return bracket
        return None

    def _promote_one(self):
        for bracket_idx, bracket in enumerate(self.brackets):
            point_hash, params, fidelity = bracket.promote()
            if params is not None:
                self._bracket_of[point_hash] = bracket_idx
                promoted = dict(params)
                promoted[self.fidelity_name] = fidelity
                return promoted
        return None

    def _new_cube(self, num):
        """Unit-cube rows for fresh bottom-rung points — ONE batched device
        draw here; the model-based subclass (`asha_bo`) overrides this with a
        GP acquisition over a fidelity-augmented posterior."""
        key = self.next_key()
        return np.asarray(jax.random.uniform(key, (num, self.space.n_cols)))

    def _sample_new(self, num):
        # RNG order is part of the bit-stream contract: the bracket-softmax
        # key is drawn BEFORE `_new_cube`'s sampling key, exactly as the
        # fused-plan path (`asha_bo.fused_step_plan`) stashes it before
        # building its plan — both routes consume the stream identically.
        bracket_key = self.next_key()
        u = self._new_cube(num)
        return self._assign_new_points(u, bracket_key)

    def _assign_new_points(self, u, bracket_key):
        """Decode fresh bottom-rung cube rows into full params: softmax
        over negative bottom-rung occupancy chooses a bracket per point
        (reference `asha.py:191-198`, vectorized host-side), the bracket's
        bottom fidelity is stamped on, and the slot is pre-registered
        (objective pending) so the point is never re-suggested.  Shared by
        the host sampling path (`_sample_new`) and the gateway's fused
        demux (`asha_bo.finish_fused_rows`) — one assignment path, so
        coalesced and standalone suggests cannot drift."""
        num = len(u)
        sizes = np.asarray(
            [len(b.rungs[0]["results"]) for b in self.brackets], dtype=np.float64
        )
        logits = -sizes  # fewer points -> more likely
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        draws = np.asarray(jax.random.uniform(bracket_key, (num,)))
        bracket_ids = np.minimum(
            np.searchsorted(np.cumsum(probs), draws), len(self.brackets) - 1
        )
        arrays = self.space.decode_flat_np(u)
        out = []
        for i, params in enumerate(self.space.arrays_to_params(arrays)):
            bracket_idx = int(bracket_ids[i])
            bracket = self.brackets[bracket_idx]
            fidelity = bracket.rungs[0]["resources"]
            params[self.fidelity_name] = fidelity
            point_hash = self._point_hash(params)
            self._bracket_of[point_hash] = bracket_idx
            # Pre-register the slot (objective pending) to avoid re-suggesting.
            bracket.register(point_hash, params, None, fidelity)
            out.append(params)
        return out

    def register_suggestion(self, params):
        """Mark a durably-registered point as pending in its rung so a future
        producer round (with a fresh naive copy) cannot re-promote it."""
        fidelity = int(params.get(self.fidelity_name, 0))
        point_hash = self._point_hash(params)
        bracket = self._resolve_bracket(point_hash, fidelity)
        if bracket is None:
            return
        for rung in bracket.rungs:
            if rung["resources"] == fidelity and point_hash not in rung["results"]:
                rung["results"][point_hash] = (None, dict(params))
                return

    def observe(self, params_list, results, cube=None):
        # ``cube`` (the columnar fast path) is accepted for contract parity
        # with BaseAlgorithm.observe; rung bookkeeping is dict-keyed (see
        # uses_observe_cube=False on the class — the producer doesn't even
        # build the rows for plain ASHA/Hyperband).
        for params, result in zip(params_list, results):
            objective = result["objective"]
            fidelity = int(params.get(self.fidelity_name, 0))
            point_hash = self._point_hash(params)
            bracket = self._resolve_bracket(point_hash, fidelity)
            if bracket is None or not bracket.register(
                point_hash, dict(params), objective, fidelity
            ):
                log.debug(
                    "Observed point with unknown fidelity %s; no rung matched",
                    fidelity,
                )
            self._n_observed += 1

    @property
    def is_done(self):
        return all(b.is_done for b in self.brackets)

    # --- state -------------------------------------------------------------
    def state_dict(self):
        out = super().state_dict()
        out["brackets"] = [b.state() for b in self.brackets]
        out["bracket_of"] = dict(self._bracket_of)
        return out

    def set_state(self, state):
        super().set_state(state)
        for bracket, saved in zip(self.brackets, state["brackets"]):
            bracket.rungs = [
                {"resources": r["resources"], "results": dict(r["results"])}
                for r in saved
            ]
        self._bracket_of = dict(state.get("bracket_of", {}))
