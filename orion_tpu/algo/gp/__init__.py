"""Device-resident Gaussian-process machinery for the batched BO engine.

No counterpart exists in the reference (Oríon v0.1.7 ships only random search
and ASHA); this package is the TPU-native optimizer core that BASELINE.json's
north star specifies: GP posterior (Cholesky), marginal-likelihood fitting,
and vmapped EI/UCB/Thompson acquisitions — all jitted, static-shape, and
HBM-resident.
"""

from orion_tpu.algo.gp.gp import GPState, fit_gp, posterior
from orion_tpu.algo.gp.kernels import kernel_matrix

__all__ = ["GPState", "fit_gp", "posterior", "kernel_matrix"]
