"""Acquisition functions over candidate sets — the vmapped hot loop.

All acquisitions consume the GP posterior over an (m, d) candidate matrix in
one shot (m in the thousands); q-batch selection strategies:

- ``thompson``: q independent posterior draws over the candidate set, argmax
  each — naturally diverse batches, embarrassingly parallel, the q-batch
  mechanism BASELINE config #3 names.  Draws use the *marginal* posterior by
  default (O(m) per draw) with an optional joint mode (O(m^3) Cholesky of the
  candidate covariance) for small m.
- ``ei`` / ``ucb``: score all candidates, take the top-q distinct ones.
  Batch diversity beyond top-q comes from the producer's lie fantasization
  (constant-liar), mirroring how the reference composes strategies with any
  algorithm rather than baking diversity into each.
"""

import jax
import jax.numpy as jnp

from orion_tpu.algo.gp.gp import posterior_norm
from orion_tpu.algo.gp.kernels import cross_kernel_matrix

_SQRT2 = 1.4142135623730951


def _norm_cdf(z):
    return 0.5 * (1.0 + jax.lax.erf(z / _SQRT2))


def _norm_pdf(z):
    return jnp.exp(-0.5 * z * z) / jnp.sqrt(2.0 * jnp.pi)


def expected_improvement(mean, std, best):
    """EI for minimization, in normalized units."""
    z = (best - mean) / std
    return std * (z * _norm_cdf(z) + _norm_pdf(z))


def upper_confidence_bound(mean, std, beta=2.0):
    """Negated LCB for minimization (higher is better)."""
    return -(mean - beta * std)


def thompson_scores(key, mean, std, q):
    """(q, m) marginal posterior draws (negated: higher is better)."""
    eps = jax.random.normal(key, (q,) + mean.shape, dtype=mean.dtype)
    return -(mean[None, :] + std[None, :] * eps)


def rff_thompson(key, state, candidates, q, kind="matern52", n_features=512):
    """Correlated q-batch Thompson sampling via random Fourier features.

    Marginal TS over-explores as the candidate count grows (the max of m
    independent draws is dominated by high-variance points); joint TS needs an
    (m, m) Cholesky.  Weight-space sampling gets correlated draws at O(m*F):
    approximate the kernel with F cosine features, form the Bayesian linear
    regression posterior over feature weights (an (F, F) Cholesky), draw q
    weight vectors jointly, and score ALL candidates with one (m, F) x (F, q)
    matmul — MXU-shaped, scales to huge candidate sets and q=4096.

    Matern-5/2 spectral density = multivariate Student-t with 2*nu = 5 dof;
    RBF's is gaussian.
    """
    d = candidates.shape[1]
    ls = jnp.exp(state.hypers.log_lengthscales)
    amp = jnp.exp(state.hypers.log_amplitude)
    noise = jnp.exp(state.hypers.log_noise)

    k_w, k_g, k_b, k_theta = jax.random.split(key, 4)
    z = jax.random.normal(k_w, (n_features, d), dtype=jnp.float32)
    if kind == "matern52":
        df = 5.0
        g = 2.0 * jax.random.gamma(k_g, df / 2.0, (n_features, 1), dtype=jnp.float32)
        z = z * jnp.sqrt(df / g)
    w = z / ls[None, :]
    b = jax.random.uniform(k_b, (n_features,), dtype=jnp.float32, maxval=2.0 * jnp.pi)
    scale = jnp.sqrt(2.0 * amp / n_features)

    def features(x):
        return scale * jnp.cos(x @ w.T + b[None, :])

    y_norm = (state.y - state.y_mean) / state.y_std * state.mask
    phi = features(state.x) * state.mask[:, None]  # (n_pad, F)
    # Ridge floor 1e-3 keeps the f32 (F, F) Cholesky conditioned (a tiny
    # learned noise otherwise NaNs the factor and every draw argmins to 0).
    ridge = noise + 1e-3
    gram = jnp.matmul(phi.T, phi, precision=jax.lax.Precision.HIGHEST)
    a = gram + ridge * jnp.eye(n_features, dtype=jnp.float32)
    chol_a = jnp.linalg.cholesky(a)
    theta_mean = jax.scipy.linalg.cho_solve((chol_a, True), phi.T @ y_norm)
    # theta ~ N(theta_mean, ridge * A^-1):  theta = mean + sqrt(ridge) L^-T eps
    # (in data-null directions this preserves ~unit prior variance).
    eps = jax.random.normal(k_theta, (n_features, q), dtype=jnp.float32)
    delta = jax.scipy.linalg.solve_triangular(chol_a.T, eps, lower=False)
    thetas = theta_mean[:, None] + jnp.sqrt(ridge) * delta  # (F, q)

    scores = features(candidates) @ thetas  # (m, q)
    return jnp.argmin(scores, axis=0)  # minimization: best draw per sample


def select_q(scores, q):
    """Top-q candidate indices from an (m,) score vector."""
    _, idx = jax.lax.top_k(scores, q)
    return idx


def acquire(key, state, candidates, q, kind="matern52", acq="thompson", best=None, beta=2.0):
    """Pick q candidate indices by the requested acquisition."""
    if acq == "thompson":
        return rff_thompson(key, state, candidates, q, kind=kind)
    mean, std = posterior_norm(state, candidates, kind=kind)
    if acq == "marginal_thompson":
        draws = thompson_scores(key, mean, std, q)  # (q, m)
        return jnp.argmax(draws, axis=1)
    if acq == "ei":
        if best is None:
            best = jnp.min(
                jnp.where(state.mask > 0, (state.y - state.y_mean) / state.y_std, jnp.inf)
            )
        return select_q(expected_improvement(mean, std, best), q)
    if acq == "ucb":
        return select_q(upper_confidence_bound(mean, std, beta=beta), q)
    raise ValueError(f"unknown acquisition {acq!r}")


def joint_thompson(key, state, candidates, q, kind="matern52"):
    """Joint posterior Thompson draws (correlated): Cholesky of the full
    candidate covariance — use when m is small enough for an (m, m) factor."""
    inv_ls = jnp.exp(-state.hypers.log_lengthscales)
    amp = jnp.exp(state.hypers.log_amplitude)
    xq = candidates.astype(jnp.float32)
    kqx = cross_kernel_matrix(kind, xq, state.x, inv_ls, amp) * state.mask[None, :]
    mean = kqx @ state.alpha
    v = jax.scipy.linalg.solve_triangular(state.chol, kqx.T, lower=True)
    kqq = cross_kernel_matrix(kind, xq, xq, inv_ls, amp)
    cov = kqq - v.T @ v
    cov = cov + jnp.eye(cov.shape[0], dtype=cov.dtype) * 1e-5
    chol = jnp.linalg.cholesky(cov)
    eps = jax.random.normal(key, (q, candidates.shape[0]), dtype=mean.dtype)
    draws = -(mean[None, :] + eps @ chol.T)
    return jnp.argmax(draws, axis=1)
