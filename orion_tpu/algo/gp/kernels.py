"""GP kernels with ARD lengthscales, written for the MXU.

Pairwise distances are computed via the ||a-b||^2 = ||a||^2 + ||b||^2 - 2ab
expansion so the dominant cost is one (n, d) x (d, m) matmul that XLA tiles
onto the systolic array, instead of an O(n*m*d) broadcast-subtract that would
be HBM-bandwidth-bound.
"""

import jax
import jax.numpy as jnp


def sq_dists(xa, xb, inv_lengthscales):
    """Squared scaled euclidean distances, matmul-dominant.

    The cross term MUST run at full f32 precision: TPU's default bf16 matmul
    loses ~0.4% relative, which after the aa+bb-2ab cancellation shows up as
    k(x,x) != amplitude and an indefinite kernel matrix.
    """
    a = xa * inv_lengthscales
    b = xb * inv_lengthscales
    aa = jnp.sum(a * a, axis=-1)[:, None]
    bb = jnp.sum(b * b, axis=-1)[None, :]
    cross = jnp.matmul(a, b.T, precision=jax.lax.Precision.HIGHEST)
    return jnp.maximum(aa + bb - 2.0 * cross, 0.0)


def rbf(xa, xb, inv_lengthscales, amplitude):
    return amplitude * jnp.exp(-0.5 * sq_dists(xa, xb, inv_lengthscales))


def matern52(xa, xb, inv_lengthscales, amplitude):
    r2 = sq_dists(xa, xb, inv_lengthscales)
    # Double-where keeps d(sqrt)/d(r2) finite at r2=0 (the diagonal): without
    # it the 1/(2 sqrt(r2)) gradient is inf there and one MLL step NaNs every
    # hyperparameter.
    positive = r2 > 1e-12
    r = jnp.where(positive, jnp.sqrt(jnp.where(positive, r2, 1.0)), 0.0)
    sqrt5_r = jnp.sqrt(5.0) * r
    return amplitude * (1.0 + sqrt5_r + (5.0 / 3.0) * r2) * jnp.exp(-sqrt5_r)


_KERNELS = {"rbf": rbf, "matern52": matern52}

# Measured head-to-head on the real chip with the two-chain-length method
# (`python -m orion_tpu.benchmarks.runner --op gram`: per-op time =
# (t_1032ops - t_8ops)/1024 per dispatch, cancelling the ~75 ms tunnel
# round trip exactly; gram consumed by a matvec + elementwise-square
# reduction like the production posterior; table in docs/performance.md):
# the fused pallas gram wins 1.1-1.4x over XLA on every production shape,
# including the smallest (m=4096, n=256, d=8 -> work 8.4e6).  Round 2's
# "~5x" and an interim "parity" conclusion were both artifacts of
# tunnel-latency-dominated timing.  The threshold covers every shape
# measured to win; below it the dispatch is untested and XLA is kept.
_PALLAS_MIN_WORK = 8 * 10**6


def kernel_matrix(kind, xa, xb, inv_lengthscales, amplitude):
    return _KERNELS[kind](xa, xb, inv_lengthscales, amplitude)


def cross_kernel_matrix(kind, xa, xb, inv_lengthscales, amplitude):
    """Forward-only gram for candidate scoring: dispatches to the pallas
    fused kernel (`orion_tpu.ops.fused_gram`) on measured-to-win shapes
    when the runtime's compile/run probe passes (ORION_TPU_PALLAS=0 opts
    out — see _PALLAS_MIN_WORK note).  Never use under `jax.grad` — the
    pallas path defines no autodiff rule (the MLL fit's (n, n) kernel
    stays on `kernel_matrix`)."""
    m, d = xa.shape
    n = xb.shape[0]
    if m * n * max(d, 1) >= _PALLAS_MIN_WORK:
        from orion_tpu.ops import pallas_enabled

        if pallas_enabled():
            from orion_tpu.ops import fused_gram

            return fused_gram(xa, xb, inv_lengthscales, amplitude, kind=kind)
    return _KERNELS[kind](xa, xb, inv_lengthscales, amplitude)
