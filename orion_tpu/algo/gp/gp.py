"""Device-resident GP: masked static-shape buffers, Cholesky posterior,
marginal-likelihood fitting by a fixed (jit-friendly) number of adam steps.

Design notes (TPU-first):
- Trial history grows dynamically but jit needs static shapes: observations
  live in power-of-2 padded buffers with a validity mask.  Padded rows are
  made inert in the Cholesky by pinning their diagonal to 1 and off-diagonals
  to 0, and their targets to 0 — they then contribute nothing to the solve,
  the quad form, or the logdet (log 1 = 0).
- Everything is float32: the MXU path.  A jitter floor keeps Cholesky stable
  at that precision for histories in the thousands.
- Fitting is `lax.scan` over a fixed number of optimizer steps, so one
  compiled computation per buffer size, no Python-loop retrace.
"""

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from orion_tpu.algo.gp.kernels import cross_kernel_matrix, kernel_matrix
from orion_tpu.algo.sampling import masked_copula_transform

JITTER = 1e-5


class GPHypers(NamedTuple):
    log_lengthscales: jnp.ndarray  # (d,)
    log_amplitude: jnp.ndarray  # ()
    log_noise: jnp.ndarray  # ()


class GPState(NamedTuple):
    x: jnp.ndarray  # (n_pad, d) in the unit cube
    y: jnp.ndarray  # (n_pad,) raw objectives
    mask: jnp.ndarray  # (n_pad,) 1.0 for real rows
    hypers: GPHypers
    chol: jnp.ndarray  # (n_pad, n_pad) lower Cholesky of masked K + noise
    alpha: jnp.ndarray  # (n_pad,) chol^-T chol^-1 y_norm
    y_mean: jnp.ndarray  # ()
    y_std: jnp.ndarray  # ()
    # Optimization-health extras (orion_tpu.health): () marginal
    # log-likelihood per observation of the final fit, and the packed
    # per-round DEVICE_HEALTH_FIELDS vector the fused suggest step attaches
    # via _replace.  Optional (None) so ad-hoc constructions stay valid.
    mll: Optional[jnp.ndarray] = None  # ()
    health: Optional[jnp.ndarray] = None  # (len(DEVICE_HEALTH_FIELDS),)


def init_hypers(n_dims):
    return GPHypers(
        log_lengthscales=jnp.zeros(n_dims, dtype=jnp.float32) + jnp.log(0.3),
        log_amplitude=jnp.asarray(0.0, dtype=jnp.float32),
        log_noise=jnp.asarray(jnp.log(1e-3), dtype=jnp.float32),
    )


def _normalize_y(y, mask):
    n = jnp.maximum(jnp.sum(mask), 1.0)
    mean = jnp.sum(y * mask) / n
    var = jnp.sum(((y - mean) ** 2) * mask) / n
    std = jnp.sqrt(jnp.maximum(var, 1e-12))
    return (y - mean) * mask / std, mean, std


def _masked_kernel(kind, x, mask, hypers):
    inv_ls = jnp.exp(-hypers.log_lengthscales)
    amp = jnp.exp(hypers.log_amplitude)
    noise = jnp.exp(hypers.log_noise)
    k = kernel_matrix(kind, x, x, inv_ls, amp)
    outer = mask[:, None] * mask[None, :]
    eye = jnp.eye(x.shape[0], dtype=x.dtype)
    # Real block keeps K + noise*I; padded rows/cols become identity.  The
    # jitter scales with the amplitude: long-lengthscale fits make K nearly
    # rank-1 at magnitude `amp`, and an absolute 1e-5 is then below f32
    # resolution — the Cholesky NaNs.
    return k * outer + eye * (noise + JITTER * (1.0 + amp)) * mask + eye * (1.0 - mask)


def _neg_mll(hypers, kind, x, y_norm, mask):
    k = _masked_kernel(kind, x, mask, hypers)
    chol = jnp.linalg.cholesky(k)
    alpha = jax.scipy.linalg.cho_solve((chol, True), y_norm)
    quad = jnp.dot(y_norm, alpha)
    logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol)) * mask)
    n = jnp.maximum(jnp.sum(mask), 1.0)
    return 0.5 * (quad + logdet) / n


@partial(jax.jit, static_argnames=("kind", "n_steps", "y_transform"))
def fit_gp(x, y, mask, kind="matern52", n_steps=50, lr=0.08, init=None,
           y_transform="none"):
    """Fit hyperparameters by adam on the marginal likelihood; returns GPState
    with the posterior factorization cached (Cholesky + alpha).

    ``y_transform="copula"`` rank-Gaussianizes the masked targets ON DEVICE
    before normalization (see ``sampling.masked_copula_transform``); the
    returned ``GPState.y`` then holds the transformed targets, exactly as
    when callers pre-transformed on host."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    if y_transform == "copula":
        y = masked_copula_transform(y, mask)
    y_norm, y_mean, y_std = _normalize_y(y, mask)
    hypers = init if init is not None else init_hypers(x.shape[1])

    optimizer = optax.adam(lr)
    opt_state = optimizer.init(hypers)
    loss_grad = jax.value_and_grad(_neg_mll)

    def step(carry, _):
        hyp, opt = carry
        loss, grads = loss_grad(hyp, kind, x, y_norm, mask)
        # A transiently ill-conditioned Cholesky must not poison the whole fit.
        grads = jax.tree.map(jnp.nan_to_num, grads)
        updates, opt = optimizer.update(grads, opt)
        hyp = optax.apply_updates(hyp, updates)
        # Keep hypers in sane ranges (lengthscale in cube units, noise floor).
        hyp = GPHypers(
            log_lengthscales=jnp.clip(hyp.log_lengthscales, jnp.log(1e-3), jnp.log(1e2)),
            # Targets are normalized to unit variance; amplitudes far above 1
            # are the flat-function degeneracy (huge amp + huge lengthscale).
            log_amplitude=jnp.clip(hyp.log_amplitude, jnp.log(0.05), jnp.log(5.0)),
            # Noise floor 1e-4: duplicate-x rows (collapsed batches, lies)
            # otherwise drive noise to 0 and the f32 Cholesky off a cliff.
            log_noise=jnp.clip(hyp.log_noise, jnp.log(1e-4), jnp.log(1.0)),
        )
        return (hyp, opt), loss

    (hypers, _), _losses = jax.lax.scan(step, (hypers, opt_state), None, length=n_steps)

    k = _masked_kernel(kind, x, mask, hypers)
    chol = jnp.linalg.cholesky(k)
    alpha = jax.scipy.linalg.cho_solve((chol, True), y_norm)
    # Fit health for free: the final factorization already yields the
    # marginal likelihood terms (quad form + logdet) — a couple of vector
    # reductions, no extra Cholesky (orion_tpu.health, `gp_mll`).
    n = jnp.maximum(jnp.sum(mask), 1.0)
    quad = jnp.dot(y_norm, alpha)
    logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol)) * mask)
    mll = -0.5 * (quad + logdet) / n
    return GPState(
        x=x, y=y, mask=mask, hypers=hypers, chol=chol, alpha=alpha,
        y_mean=y_mean, y_std=y_std, mll=mll,
    )


def posterior(state, xq, kind="matern52"):
    """Predictive mean/std at query points ``xq`` (m, d) — vmap-free batched
    linear algebra: one (m, n) kernel matmul + one triangular solve."""
    inv_ls = jnp.exp(-state.hypers.log_lengthscales)
    amp = jnp.exp(state.hypers.log_amplitude)
    kqx = cross_kernel_matrix(kind, xq.astype(jnp.float32), state.x, inv_ls, amp)
    kqx = kqx * state.mask[None, :]
    mean_norm = kqx @ state.alpha
    v = jax.scipy.linalg.solve_triangular(state.chol, kqx.T, lower=True)
    var_norm = jnp.maximum(amp - jnp.sum(v * v, axis=0), 1e-10)
    mean = mean_norm * state.y_std + state.y_mean
    std = jnp.sqrt(var_norm) * state.y_std
    return mean, std


def posterior_norm(state, xq, kind="matern52"):
    """Predictive mean/std in normalized target units (for acquisitions)."""
    inv_ls = jnp.exp(-state.hypers.log_lengthscales)
    amp = jnp.exp(state.hypers.log_amplitude)
    kqx = cross_kernel_matrix(kind, xq.astype(jnp.float32), state.x, inv_ls, amp)
    kqx = kqx * state.mask[None, :]
    mean = kqx @ state.alpha
    v = jax.scipy.linalg.solve_triangular(state.chol, kqx.T, lower=True)
    var = jnp.maximum(amp - jnp.sum(v * v, axis=0), 1e-10)
    return mean, jnp.sqrt(var)
