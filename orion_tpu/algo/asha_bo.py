"""ASHA-BO: multi-fidelity Bayesian optimization under ASHA scheduling.

No reference counterpart (Oríon v0.1.7's ASHA samples new points uniformly,
`src/orion/algo/asha.py:191-198`); this is the round-1 verdict #10 stretch:
BASELINE config #5 (Ackley-50D, q=4096, fidelity rungs) runs model-based
instead of random-under-ASHA.

Design (BOHB-flavored, TPU-first):

- ASHA's bracket/rung machinery is inherited unchanged — promotion
  scheduling, dedup, bracket softmax all stay host-side.
- New bottom-rung points come from a GP fit on EVERY observation at EVERY
  fidelity, with the fidelity attached as one extra input column
  s = log(fid/low) / log(high/low) in [0, 1] (geometric rungs -> uniform in
  log space).  Low-fidelity evaluations are cheap, plentiful, and
  correlated with the truth; the learned lengthscale over s decides how
  much to trust them.
- Acquisition: random-Fourier-feature Thompson over a candidate set
  (global uniform + gaussian ball around the incumbent), scored at s = 1
  (max fidelity) — we select points by their predicted FULL-budget value.
  One fused jit per suggest round, same engine as `tpu_bo`.
"""

import logging

import numpy as np

from orion_tpu.algo.asha import ASHA
from orion_tpu.algo.base import algo_registry
from orion_tpu.algo.history import DeviceHistory, HostHistory, _next_pow2
from orion_tpu.algo.prewarm import DEFAULT_PREWARM_FILL, BucketPrewarmer
from orion_tpu.algo.sampling import clamp_objectives
from orion_tpu.algo.tpu_bo import (
    PlanPrepToken,
    make_fused_plan,
    maybe_prewarm_fused_step,
    run_fused_plan,
    tr_update_batch,
)
from orion_tpu.algo.sharding import mesh_health_fields
from orion_tpu.parallel import device_mesh

log = logging.getLogger(__name__)


@algo_registry.register("asha_bo")
class ASHABO(ASHA):
    """ASHA scheduling + fidelity-aware GP sampling.

    Parameters beyond ASHA's: ``n_init`` random bottom-rung points before
    the GP engages; ``n_candidates``, ``fit_steps``, ``kernel``, ``acq``,
    ``local_frac``/``local_sigma`` as in ``tpu_bo``.
    """

    # Unlike plain ASHA, observe() feeds the cube rows to the GP history.
    uses_observe_cube = True

    def __init__(
        self,
        space,
        seed=None,
        num_rungs=None,
        num_brackets=1,
        reduction_factor=None,
        n_init=32,
        n_candidates=8192,
        kernel="matern52",
        acq="thompson",
        fit_steps=40,
        refit_steps=None,
        beta=2.0,
        local_frac=0.5,
        local_sigma=0.1,
        y_transform="none",
        trust_region=False,
        tr_length_init=0.4,
        tr_length_min=0.5**7,
        tr_length_max=0.8,
        tr_succ_tol=3,
        tr_fail_tol=2,
        tr_improve_tol=1e-3,
        tr_local_m=512,
        tr_perturb_dims=20,
        tr_update_every=None,
        prewarm=True,
        prewarm_fill=DEFAULT_PREWARM_FILL,
        n_devices=None,
        use_mesh=False,
    ):
        super().__init__(
            space,
            seed=seed,
            num_rungs=num_rungs,
            num_brackets=num_brackets,
            reduction_factor=reduction_factor,
        )
        self._params.update(
            n_init=n_init, n_candidates=n_candidates, kernel=kernel, acq=acq,
            fit_steps=fit_steps, refit_steps=refit_steps, beta=beta,
            local_frac=local_frac, local_sigma=local_sigma,
            y_transform=y_transform, trust_region=trust_region,
            tr_length_init=tr_length_init, tr_length_min=tr_length_min,
            tr_length_max=tr_length_max, tr_succ_tol=tr_succ_tol,
            tr_fail_tol=tr_fail_tol, tr_improve_tol=tr_improve_tol,
            tr_local_m=tr_local_m, tr_perturb_dims=tr_perturb_dims,
            tr_update_every=tr_update_every, prewarm=prewarm,
            prewarm_fill=prewarm_fill,
        )
        self.n_init = n_init
        self.n_candidates = n_candidates
        self.kernel = kernel
        self.acq = acq
        self.fit_steps = fit_steps
        # None = warm refits also use fit_steps (run_suggest_step owns the
        # default); opt in where GP fitting genuinely dominates the round.
        self.refit_steps = refit_steps
        self.beta = beta
        self.local_frac = local_frac
        self.local_sigma = local_sigma
        self.y_transform = y_transform
        self.trust_region = trust_region
        self.tr_length_init = tr_length_init
        self.tr_length_min = tr_length_min
        self.tr_length_max = tr_length_max
        self.tr_succ_tol = tr_succ_tol
        self.tr_fail_tol = tr_fail_tol
        self.tr_improve_tol = tr_improve_tol
        self.tr_local_m = tr_local_m
        self.tr_perturb_dims = tr_perturb_dims
        self.tr_update_every = tr_update_every
        self.prewarm = bool(prewarm)
        self.prewarm_fill = float(prewarm_fill)
        # Same mesh semantics as TPUBO: shard the candidate axis of the fused
        # suggest step over the devices (BASELINE config #5 names q=4096 on a
        # v5e-8 — the model-based variant must scale the same way).
        self.use_mesh = use_mesh
        self._mesh = device_mesh(n_devices) if use_mesh else None
        self._tr_length = tr_length_init
        self._tr_succ = 0
        self._tr_fail = 0
        fid = space.fidelity
        self._log_low = float(np.log(max(fid.low, 1)))
        self._log_span = float(
            max(np.log(max(fid.high, 1)) - self._log_low, 1e-9)
        )
        d = space.n_cols
        # Host history of augmented rows [x | s] with objectives y:
        # amortized-growth buffers, O(batch) appends, incrementally-tracked
        # global incumbent (see HostHistory) — replaces the np.concatenate
        # `_mf_*` mirrors that cost O(n) host work per observe.
        self._host = HostHistory(d + 1)
        # Device-resident twin of the augmented history (the GP's actual
        # input columns), incrementally appended on observe — suggest reads
        # it in place (full history or on-device local subset), and the
        # copula transform runs in-jit, so no O(n) re-upload happens.
        self._hist = DeviceHistory(d + 1)
        self._gp_state = None
        self._prewarmer = BucketPrewarmer()
        self._last_q_bucket = None
        # Best observation at the highest observed fidelity tier, tracked
        # incrementally (O(batch) per observe; full rescan only when a new
        # top tier appears — once per rung level, not per round).
        self._s_top = -np.inf
        self._top_best_idx = -1
        self._top_best_y = np.inf
        # Trust-region-style local radius (TuRBO-lite): the GP's global
        # signal is weak in high dimensions, so progress rides the local
        # ball around the incumbent — expand it while improving, shrink it
        # when stalled.
        self._sigma = local_sigma
        self._best_seen = np.inf
        # Steady-path dispatch prep, as in TPUBO: the frozen part of
        # `_step_kw` and the resolved _PlanPrep ride per-instance caches
        # (the per-round variants — quantized local_sigma, tr_length — are
        # passed explicitly each round, see `_gp_plan`).
        self._step_kw_cache = None
        self._prep_token = PlanPrepToken()
        # Fused-round carry state (see `fused_step_plan`): promotions the
        # plan round already consumed (host-scheduled, no device work) and
        # the bracket-softmax key — drawn AFTER them and BEFORE the plan's
        # sampling key, preserving `suggest`'s exact RNG order.  Both are
        # consumed by `finish_fused_rows` after the gateway dispatch.
        self._pending_promoted = None
        self._pending_bracket_key = None

    # Naive-copy sharing (base __deepcopy__): the fitted GP state
    # (n_pad x n_pad Cholesky), the (uncopyable) mesh handle, and the
    # prewarmer (threads/locks; the jit cache it warms is process-wide).
    # `_hist`/`_host` are NOT shared by ref — their own __deepcopy__ does
    # copy-on-write of the buffers (see tpu_bo/history).  The step-kw
    # cache (never mutated after build) and prep token (atomic pinned
    # pair) are shared so naive clones ride the same warm prep.
    _share_by_ref = (
        "space", "_gp_state", "_mesh", "_prewarmer",
        "_step_kw_cache", "_prep_token",
    )

    # Back-compat views over the augmented host history (host consumers
    # and tests read these; appends go through `_host`).
    @property
    def _mf_x(self):
        return self._host.x[:, : self.space.n_cols]

    @property
    def _mf_s(self):
        return self._host.x[:, self.space.n_cols]

    @property
    def _mf_y(self):
        return self._host.y

    # --- observation ---------------------------------------------------------
    def _fid_norm(self, fidelity):
        return (np.log(max(float(fidelity), 1.0)) - self._log_low) / self._log_span

    def observe(self, params_list, results, cube=None):
        super().observe(params_list, results)  # rung bookkeeping
        valid, valid_idx, svals, yvals = [], [], [], []
        for i, (params, result) in enumerate(zip(params_list, results)):
            objective = result.get("objective")
            if objective is None:
                continue
            valid.append(params)
            valid_idx.append(i)
            svals.append(self._fid_norm(params.get(self.fidelity_name, 1)))
            yvals.append(float(objective))
        if not valid:
            return
        y = clamp_objectives(np.asarray(yvals, dtype=np.float64), self._mf_y)
        if y is None:
            return
        # One batched codec call for the whole batch (q can be 4096) —
        # per-point encode would cost O(batch * dims) python overhead.
        # The columnar fast path skips even that: the producer hands the
        # params_to_cube rows it already built.
        if cube is not None:
            rows = np.asarray(cube, dtype=np.float32)[valid_idx]
        else:
            rows = self.space.params_to_cube(valid)
        rows32 = np.asarray(rows, dtype=np.float32)
        s32 = np.asarray(svals, dtype=np.float32)
        y32 = y.astype(np.float32)
        prev_count = self._host.count
        aug = np.concatenate([rows32, s32[:, None]], axis=1)
        # O(batch) host append + O(batch) incremental device append of the
        # augmented rows [x | s] — no O(n) concatenate per observe.
        self._host.append(aug, y32)
        self._hist.append(aug, y32)
        self._update_top_tier(prev_count, s32, y32)
        prev_best = self._best_seen
        batch_best = float(np.min(y))
        if batch_best < self._best_seen - 1e-9:
            self._best_seen = batch_best
            self._sigma = min(self._sigma * 1.5, 0.4)
        else:
            self._sigma = max(self._sigma * 0.7, 0.005)
        # Trust-region bookkeeping (tr_update: the one TuRBO schedule),
        # counted on model rounds only; objectives are comparable across
        # fidelities for the box signal (a better low-fid value still marks
        # progress).
        if self.trust_region and prev_count >= self.n_init:
            # Default cadence here is ONE update per observe round (chunk =
            # whole batch), unlike TPUBO's batch-decoupled 8: a rung batch
            # mixes fidelities, and chunk-wise accounting over mixed-budget
            # objectives measurably thrashes the box (ackley50, 5 matched
            # seeds: every seed worse, median 8.83 -> 10.26 — r5 A/B in
            # BENCH_SEEDS/BASELINE).  tr_update_every stays available for
            # single-fidelity-ish ladders.
            # (the restart count is unused here: asha_bo's box rides the
            # fidelity context and re-centers through rung promotion)
            self._tr_length, self._tr_succ, self._tr_fail, _ = tr_update_batch(
                self._tr_length, self._tr_succ, self._tr_fail,
                prev_best, y, chunk=self.tr_update_every or max(1, len(y)),
                succ_tol=self.tr_succ_tol, fail_tol=self.tr_fail_tol,
                length_init=self.tr_length_init,
                length_min=self.tr_length_min,
                length_max=self.tr_length_max,
                improve_tol=self.tr_improve_tol,
            )
        # LAST, after the sigma/box updates above: the fused step's
        # local_sigma static is quantized from _sigma, and warming before
        # the update would compile a stale signature the boundary-crossing
        # suggest never hits.
        self._maybe_prewarm(batch=len(y32))

    def _update_top_tier(self, prev_count, s32, y32):
        """Incremental best-at-top-fidelity-tier tracking.

        Old path re-scanned the whole history per suggest
        (``s >= s.max() - 1e-6`` + masked argmin, O(n)).  Fidelity values
        are computed identically per rung, so tier membership is exact
        float equality in practice; a batch that RAISES the top tier
        triggers one full rescan (happens once per rung level over a run),
        anything else updates from the batch in O(batch)."""
        batch_top = float(np.max(s32))
        if batch_top > self._s_top + 1e-9:
            # New top tier: previous tier's best no longer qualifies.
            self._s_top = batch_top
            s_all, y_all = self._mf_s, self._mf_y
            pool = np.nonzero(s_all >= self._s_top - 1e-6)[0]
            at = pool[int(np.argmin(y_all[pool]))]
            self._top_best_idx = int(at)
            self._top_best_y = float(y_all[at])
            return
        in_tier = np.nonzero(s32 >= self._s_top - 1e-6)[0]
        if in_tier.size:
            at = in_tier[int(np.argmin(y32[in_tier]))]
            # Strict <: ties keep the earliest index, matching the old
            # full-scan argmin.
            if float(y32[at]) < self._top_best_y:
                self._top_best_y = float(y32[at])
                self._top_best_idx = prev_count + int(at)

    def _maybe_prewarm(self, batch=0):
        # Shared trigger (tpu_bo.maybe_prewarm_fused_step): fidelity rides
        # along as the fixed context column via _step_kw's fixed_tail_cols.
        maybe_prewarm_fused_step(self, batch=batch)

    # --- model-based sampling -----------------------------------------------
    def _step_kw(self):
        return dict(
            n_candidates=self.n_candidates,
            kernel=self.kernel,
            acq=self.acq,
            fit_steps=self.fit_steps,
            refit_steps=self.refit_steps,
            local_frac=self.local_frac,
            # Quantized to a pow-2 ladder: local_sigma is a STATIC arg of the
            # fused jit, and a freely-varying value would recompile per round.
            local_sigma=float(2.0 ** round(np.log2(self._sigma))),
            beta=self.beta,
            trust_region=self.trust_region,
            tr_length=self._tr_length,
            tr_perturb_dims=self.tr_perturb_dims,
            y_transform=self.y_transform,
            # Fidelity is context, pinned to s=1 when scoring: selection
            # optimizes predicted FULL-budget value; the rung machinery then
            # assigns the actual bottom-rung fidelity.
            fixed_tail_cols=1,
            mesh=self._mesh,
        )

    def _gp_plan(self, num):
        """This round's fidelity-augmented GP acquisition as a
        :class:`~orion_tpu.algo.tpu_bo.FusedPlan` — ONE builder behind the
        standalone dispatch (`_new_cube`) and the gateway's coalescing
        path (`fused_step_plan`), so their inputs cannot drift."""
        n = self._host.count
        self._last_q_bucket = _next_pow2(num, floor=8)
        if self.trust_region:
            # Global argmin: early TR rounds have almost nothing at the top
            # tier, and the s-lengthscale already decides how much to trust
            # low-fidelity values — the incumbent just centers the box.
            # O(1): tracked incrementally by HostHistory.
            best_row = self._host.best_idx
        else:
            # Best observation at the highest observed fidelity tier —
            # O(1) via the incremental tracker (see _update_top_tier).
            best_row = self._top_best_idx
        d = self.space.n_cols
        best_x = self._host.x[best_row, :d]
        step_kw = self._step_kw_cache
        if step_kw is None:
            # The per-round variants (traced tr_length, the quantized
            # local_sigma static) are passed explicitly below; everything
            # else is frozen at __init__, so the dict rides the instance
            # and is never mutated after build.
            step_kw = dict(self._step_kw())
            for name in ("tr_length", "local_sigma"):
                step_kw.pop(name, None)
            self._step_kw_cache = step_kw
        if self.trust_region and n > self.tr_local_m:
            # Local GP on the nearest observations (x-distance, fidelity
            # ignored): keeps lengthscales local, Cholesky small.  The
            # subset is gathered ON DEVICE from the resident augmented
            # buffers (dist_cols=d skips the s column) — no host distance
            # scan, gather, or upload.
            x_dev, y_dev, mask_dev, _ = self._hist.local_view(
                self._host.x[best_row], self.tr_local_m, dist_cols=d
            )
        else:
            # Full-history fast path: the augmented history already lives
            # on device (pow-2 bucketed buffers — DeviceHistory growth —
            # so two tenants in the same bucket produce shape-aligned,
            # hence coalescible, signatures), and the (rank-global) copula
            # transform, when enabled, runs in-jit — nothing history-sized
            # is rebuilt on host or shipped per round.
            x_dev, y_dev, mask_dev, _ = self._hist.fit_view()
        return make_fused_plan(
            self.next_key(), x_dev, y_dev, mask_dev, best_x,
            self._gp_state, num,
            tr_length=self._tr_length,
            # Quantized to a pow-2 ladder (a STATIC of the fused jit; a
            # freely-varying value would recompile per round).  The prep
            # token's fast key revalidates it, so a ladder move is a
            # correct token miss, not a stale plan.
            local_sigma=float(2.0 ** round(np.log2(self._sigma))),
            prep_token=self._prep_token,
            **step_kw,
        )

    def _new_cube(self, num):
        n = self._host.count
        if n < self.n_init:
            return super()._new_cube(num)
        plan = self._gp_plan(num)
        rows, state = run_fused_plan(plan, prewarmer=self._prewarmer)
        self._gp_state = state
        return rows

    # --- serve-gateway coalescing --------------------------------------------
    def suggest(self, num=1):
        # A fused round that fell back to the plain path after consuming
        # its promotions (all-promotion round, or a failed dispatch) must
        # serve the stash first — `_promote_one` already RESERVED those
        # next-rung slots, so dropping them would strand the slots pending
        # forever.  Stream-identical to a standalone round: the stash is
        # exactly the promotions `suggest` would have emitted first.
        stash, self._pending_promoted = self._pending_promoted, None
        self._pending_bracket_key = None
        if not stash:
            return super().suggest(num)
        out = list(stash)
        while len(out) < num:
            promoted = self._promote_one()
            if promoted is None:
                break
            out.append(promoted)
        remaining = num - len(out)
        if remaining:
            out.extend(self._sample_new(remaining))
        return out or None

    def fused_step_plan(self, num):
        """This round as a coalescible plan, or None when there is nothing
        to dispatch (random-init phase, or the round is promotions-only).
        Mirrors ``suggest``'s order exactly: pending promotions are
        consumed FIRST into a stash (host-scheduled rung pointer-chasing —
        no device work), then the remaining fresh bottom-rung samples
        become the fused plan.  Like TPUBO's, the plan is CONSUMING: it
        advances the RNG stream — the bracket-softmax key is stashed ahead
        of the plan's sampling key, preserving ``_sample_new``'s draw
        order — so a holder MUST dispatch it and feed the rows through
        :meth:`finish_fused_rows`.  A stash left over from a failed
        dispatch is re-served before anything new is consumed."""
        if self._host.count < self.n_init:
            return None
        promoted = self._pending_promoted
        if promoted is None:
            promoted = []
        while len(promoted) < num:
            point = self._promote_one()
            if point is None:
                break
            promoted.append(point)
        self._pending_promoted = promoted
        remaining = num - len(promoted)
        if remaining <= 0:
            # Promotions-only round: no device work — the gateway's plain
            # path (our `suggest` override) serves the stash.
            return None
        self._pending_bracket_key = self.next_key()
        return self._gp_plan(remaining)

    def consume_fused_step(self, state):
        """Accept the GPState a fused-plan dispatch produced (warm-start
        source for the next round's fit + packed device health)."""
        self._gp_state = state

    def finish_fused_rows(self, rows):
        """Demux hook for the gateway: turn dispatched cube rows into full
        params — bracket assignment (stashed softmax key), fidelity stamp,
        rung pre-registration via the same `_assign_new_points` the host
        sampling path uses (raw cube rows would bypass all three) — with
        the round's stashed promotions prepended, exactly where
        ``suggest`` would have put them."""
        key, self._pending_bracket_key = self._pending_bracket_key, None
        promoted, self._pending_promoted = self._pending_promoted, None
        if key is None:
            raise RuntimeError(
                "finish_fused_rows without a pending fused_step_plan"
            )
        return list(promoted or ()) + self._assign_new_points(
            np.asarray(rows), key
        )

    # --- health --------------------------------------------------------------
    def health_record(self):
        """ASHA's rung occupancy plus the GP side (orion_tpu.health):
        incumbent over the augmented history, trust-region box, and the
        device GP/acquisition fields the last fused step attached to its
        GPState (ready data — no device sync)."""
        from orion_tpu.health import unpack_device_health

        record = super().health_record()
        record.update(
            tr_length=float(self._tr_length),
            tr_succ=int(self._tr_succ),
            tr_fail=int(self._tr_fail),
        )
        if self._host.count:
            record["best_y"] = float(self._host.best_y)
            record["n_obs"] = int(self._host.count)
        if self._mesh is not None:
            sample = () if self._gp_state is None else (self._gp_state.chol,)
            record.update(mesh_health_fields(self._mesh, *sample))
        state = self._gp_state
        if state is not None and state.health is not None:
            record.update(unpack_device_health(state.health))
        return record

    # --- state ---------------------------------------------------------------
    def state_dict(self):
        out = super().state_dict()
        out["mf_x"] = self._mf_x.tolist()
        out["mf_s"] = self._mf_s.tolist()
        out["mf_y"] = self._mf_y.tolist()
        out["sigma"] = self._sigma
        out["best_seen"] = (
            None if np.isinf(self._best_seen) else self._best_seen
        )
        out["tr"] = [self._tr_length, self._tr_succ, self._tr_fail]
        return out

    def set_state(self, state):
        super().set_state(state)
        d = self.space.n_cols
        mf_x = np.asarray(state.get("mf_x", []), dtype=np.float32).reshape(-1, d)
        mf_s = np.asarray(state.get("mf_s", []), dtype=np.float32)
        mf_y = np.asarray(state.get("mf_y", []), dtype=np.float32)
        aug = np.concatenate([mf_x, mf_s[:, None]], axis=1)
        # Rebuild host (incumbent tracking resumes) and the device-resident
        # augmented history with one bulk upload each.
        self._host = HostHistory.from_host(aug, mf_y)
        self._hist = DeviceHistory.from_host(aug, mf_y)
        # Rebuild the top-tier incumbent tracker from scratch.
        self._s_top = -np.inf
        self._top_best_idx = -1
        self._top_best_y = np.inf
        if mf_s.size:
            self._update_top_tier(0, mf_s, mf_y)
        self._sigma = state.get("sigma", self.local_sigma)
        best = state.get("best_seen")
        self._best_seen = np.inf if best is None else float(best)
        tr = state.get("tr")
        if tr is not None:
            self._tr_length, self._tr_succ, self._tr_fail = tr[0], int(tr[1]), int(tr[2])
        self._gp_state = None
