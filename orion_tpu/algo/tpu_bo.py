"""TPU-native batched Bayesian optimizer — the framework's flagship.

No reference counterpart (Oríon v0.1.7 has only random search + ASHA); this
implements BASELINE.json's north star: `suggest`/`observe` as jitted batched
device code — GP posterior via masked Cholesky on power-of-2 padded buffers,
acquisition (Thompson/EI/UCB) vmapped over thousands of candidates, q-batch
selection in a single compiled call, optionally sharded across a device mesh
(`orion_tpu.parallel`).

The producer's lie fantasization (constant-liar strategies) composes on top:
lies arrive through `observe` like real results, which is exactly the
fantasize-don't-refit design SURVEY.md §7 calls for — the naive-algo copy
refits its posterior with fantasy rows instead of waiting on stragglers.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.algo.base import BaseAlgorithm, algo_registry
from orion_tpu.algo.gp.acquisition import acquire, joint_thompson
from orion_tpu.algo.gp.gp import fit_gp
from orion_tpu.algo.sampling import clamp_objectives, reflect_unit
from orion_tpu.parallel import device_mesh, shard_candidates


def _next_pow2(n, floor=64):
    out = floor
    while out < n:
        out *= 2
    return out


@algo_registry.register("tpu_bo")
class TPUBO(BaseAlgorithm):
    """Batched GP-BO on device.

    Parameters
    ----------
    n_init: random (prior) points before the GP engages.
    n_candidates: candidate-set size per suggest call (split between global
        uniform exploration and gaussian perturbations around incumbents).
    acq: "thompson" (default; diverse q-batches), "joint_thompson", "ei", "ucb".
    kernel: "matern52" (default) or "rbf".
    fit_steps: adam steps on the marginal likelihood per (re)fit.
    local_frac: fraction of candidates drawn around the current best point.
    n_devices: shard candidates over this many devices (None = all visible).
    """

    def __init__(
        self,
        space,
        seed=None,
        n_init=16,
        n_candidates=8192,
        acq="thompson",
        kernel="matern52",
        fit_steps=50,
        beta=2.0,
        local_frac=0.5,
        local_sigma=0.1,
        n_devices=None,
        use_mesh=False,
    ):
        super().__init__(
            space,
            seed=seed,
            n_init=n_init,
            n_candidates=n_candidates,
            acq=acq,
            kernel=kernel,
            fit_steps=fit_steps,
            beta=beta,
            local_frac=local_frac,
            local_sigma=local_sigma,
        )
        self.n_init = n_init
        self.n_candidates = n_candidates
        self.acq = acq
        self.kernel = kernel
        self.fit_steps = fit_steps
        self.beta = beta
        self.local_frac = local_frac
        self.local_sigma = local_sigma
        self.use_mesh = use_mesh
        self._mesh = device_mesh(n_devices) if use_mesh else None
        d = space.n_cols
        self._x = np.zeros((0, d), dtype=np.float32)
        self._y = np.zeros((0,), dtype=np.float32)
        self._gp_state = None
        self._gp_dirty = True

    def __deepcopy__(self, memo):
        """Producer deepcopies the algorithm each round for the naive copy;
        share the mesh handle (not copyable) and the immutable GP state."""
        import copy as _copy

        cls = type(self)
        clone = cls.__new__(cls)
        memo[id(self)] = clone
        for key, value in self.__dict__.items():
            if key in ("_mesh", "_gp_state", "space"):
                setattr(clone, key, value)
            else:
                setattr(clone, key, _copy.deepcopy(value, memo))
        return clone

    # --- observation --------------------------------------------------------
    def observe_arrays(self, cube, objectives, params_list=None, fidelities=None):
        objectives = clamp_objectives(objectives, self._y)
        if objectives is None:
            return
        self._x = np.concatenate([self._x, np.asarray(cube, dtype=np.float32)])
        self._y = np.concatenate([self._y, np.asarray(objectives, dtype=np.float32)])
        self._gp_dirty = True

    # --- suggestion ---------------------------------------------------------
    def _suggest_cube(self, num):
        n = self._x.shape[0]
        if n < self.n_init:
            return jax.random.uniform(self.next_key(), (num, self.space.n_cols))
        state = self._fit()
        key_cand, key_acq = jax.random.split(self.next_key())
        best_x = self._x[int(np.argmin(self._y))]
        candidates = _make_candidates(
            key_cand,
            self.n_candidates,
            self.space.n_cols,
            jnp.asarray(best_x),
            self.local_frac,
            self.local_sigma,
        )
        if self._mesh is not None:
            candidates = shard_candidates(candidates, self._mesh)
        if self.acq == "joint_thompson":
            idx = _acquire_joint(key_acq, state, candidates, num, self.kernel)
        else:
            idx = _acquire(key_acq, state, candidates, num, self.kernel, self.acq, self.beta)
        idx = self._dedup_fill(idx, state, candidates, num)
        return jnp.take(candidates, jnp.asarray(idx), axis=0)

    def _dedup_fill(self, idx, state, candidates, num):
        """A confident posterior makes all Thompson draws argmin at the same
        candidate; q duplicate suggestions would spin the producer on
        DuplicateKeyError.  Keep first occurrences, fill the rest with the
        top distinct candidates by EI."""
        seen, out = set(), []
        for i in np.asarray(idx).tolist():
            if i not in seen:
                seen.add(i)
                out.append(i)
        if len(out) < num:
            ranked = np.asarray(
                _acquire(
                    self.next_key(), state, candidates,
                    min(4 * num, candidates.shape[0]), self.kernel, "ei", self.beta,
                )
            )
            for i in ranked.tolist():
                if i not in seen:
                    seen.add(i)
                    out.append(i)
                    if len(out) == num:
                        break
        return out[:num]

    def _fit(self):
        if self._gp_state is not None and not self._gp_dirty:
            return self._gp_state
        n = self._x.shape[0]
        n_pad = _next_pow2(n)
        x = np.zeros((n_pad, self.space.n_cols), dtype=np.float32)
        y = np.zeros((n_pad,), dtype=np.float32)
        mask = np.zeros((n_pad,), dtype=np.float32)
        x[:n] = self._x
        y[:n] = self._y
        mask[:n] = 1.0
        warm = self._gp_state.hypers if self._gp_state is not None else None
        self._gp_state = fit_gp(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask),
            kind=self.kernel, n_steps=self.fit_steps, init=warm,
        )
        self._gp_dirty = False
        return self._gp_state

    # --- state --------------------------------------------------------------
    def state_dict(self):
        out = super().state_dict()
        out["x"] = self._x.tolist()
        out["y"] = self._y.tolist()
        return out

    def set_state(self, state):
        super().set_state(state)
        d = self.space.n_cols
        self._x = np.asarray(state["x"], dtype=np.float32).reshape(-1, d)
        self._y = np.asarray(state["y"], dtype=np.float32)
        self._gp_dirty = True


@partial(jax.jit, static_argnums=(1, 2, 4))
def _make_candidates(key, n_candidates, n_dims, best_x, local_frac, local_sigma):
    """Candidate set: global uniform + gaussian ball around the incumbent.

    Boundary handling is reflection, not clipping — clipping would pile local
    candidates onto the exact floats 0.0/1.0 whenever the incumbent sits near
    an edge, producing duplicate suggestions (see sampling.reflect_unit)."""
    k1, k2 = jax.random.split(key)
    n_local = int(n_candidates * local_frac)
    n_global = n_candidates - n_local
    global_c = jax.random.uniform(k1, (n_global, n_dims))
    local_c = best_x[None, :] + local_sigma * jax.random.normal(k2, (n_local, n_dims))
    return jnp.concatenate([global_c, reflect_unit(local_c)], axis=0)


@partial(jax.jit, static_argnums=(3, 4, 5))
def _acquire(key, state, candidates, q, kernel, acq, beta):
    return acquire(key, state, candidates, q, kind=kernel, acq=acq, beta=beta)


@partial(jax.jit, static_argnums=(3, 4))
def _acquire_joint(key, state, candidates, q, kernel):
    return joint_thompson(key, state, candidates, q, kind=kernel)
