"""TPU-native batched Bayesian optimizer — the framework's flagship.

No reference counterpart (Oríon v0.1.7 has only random search + ASHA); this
implements BASELINE.json's north star: `suggest`/`observe` as jitted batched
device code — GP posterior via masked Cholesky on power-of-2 padded buffers,
acquisition (Thompson/EI/UCB) vmapped over thousands of candidates, q-batch
selection in a single compiled call, optionally sharded across a device mesh
(`orion_tpu.parallel`).

The producer's lie fantasization (constant-liar strategies) composes on top:
lies arrive through `observe` like real results, which is exactly the
fantasize-don't-refit design SURVEY.md §7 calls for — the naive-algo copy
refits its posterior with fantasy rows instead of waiting on stragglers.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.algo.base import BaseAlgorithm, algo_registry
from orion_tpu.algo.gp.acquisition import (
    acquire,
    expected_improvement,
    joint_thompson,
    select_q,
)
from orion_tpu.algo.gp.gp import fit_gp, init_hypers, posterior_norm
from orion_tpu.algo.sampling import clamp_objectives, reflect_unit
from orion_tpu.parallel import candidate_sharding, device_mesh


def _next_pow2(n, floor=64):
    out = floor
    while out < n:
        out *= 2
    return out


@algo_registry.register("tpu_bo")
class TPUBO(BaseAlgorithm):
    """Batched GP-BO on device.

    Parameters
    ----------
    n_init: random (prior) points before the GP engages.
    n_candidates: candidate-set size per suggest call (split between global
        uniform exploration and gaussian perturbations around incumbents).
    acq: "thompson" (default; diverse q-batches), "joint_thompson", "ei", "ucb".
    kernel: "matern52" (default) or "rbf".
    fit_steps: adam steps on the marginal likelihood for the FIRST fit.
    refit_steps: steps for warm-started refits (default: fit_steps).  Each
        round resumes from the previous round's hyperparameters, so fewer
        refit steps are viable where GP fitting dominates the round.
    local_frac: fraction of candidates drawn around the current best point.
    n_devices: shard candidates over this many devices (None = all visible).
    """

    def __init__(
        self,
        space,
        seed=None,
        n_init=16,
        n_candidates=8192,
        acq="thompson",
        kernel="matern52",
        fit_steps=50,
        refit_steps=None,
        beta=2.0,
        local_frac=0.5,
        local_sigma=0.1,
        n_devices=None,
        use_mesh=False,
    ):
        super().__init__(
            space,
            seed=seed,
            n_init=n_init,
            n_candidates=n_candidates,
            acq=acq,
            kernel=kernel,
            fit_steps=fit_steps,
            refit_steps=refit_steps,
            beta=beta,
            local_frac=local_frac,
            local_sigma=local_sigma,
        )
        self.n_init = n_init
        self.n_candidates = n_candidates
        self.acq = acq
        self.kernel = kernel
        self.fit_steps = fit_steps
        # None = warm refits also use fit_steps (run_suggest_step owns the
        # default); opt in where GP fitting genuinely dominates the round.
        self.refit_steps = refit_steps
        self.beta = beta
        self.local_frac = local_frac
        self.local_sigma = local_sigma
        self.use_mesh = use_mesh
        self._mesh = device_mesh(n_devices) if use_mesh else None
        d = space.n_cols
        self._x = np.zeros((0, d), dtype=np.float32)
        self._y = np.zeros((0,), dtype=np.float32)
        self._gp_state = None

    # Naive-copy sharing (base __deepcopy__): the mesh handle is not
    # copyable and the fitted GP state / observation buffers are
    # immutable-by-rebinding.
    _share_by_ref = ("space", "_mesh", "_gp_state", "_x", "_y")

    # --- observation --------------------------------------------------------
    def observe_arrays(self, cube, objectives, params_list=None, fidelities=None):
        objectives = clamp_objectives(objectives, self._y)
        if objectives is None:
            return
        self._x = np.concatenate([self._x, np.asarray(cube, dtype=np.float32)])
        self._y = np.concatenate([self._y, np.asarray(objectives, dtype=np.float32)])

    # --- suggestion ---------------------------------------------------------
    def _suggest_cube(self, num):
        n = self._x.shape[0]
        if n < self.n_init:
            return jax.random.uniform(self.next_key(), (num, self.space.n_cols))
        # Single fused jit call: warm-started GP refit + candidate generation
        # + acquisition + on-device dedup/EI-fill + gather.  One dispatch and
        # one (q, d) transfer per suggest — dispatch latency otherwise
        # dominates (each host->device round trip costs ~ms).  With a mesh,
        # the same compiled step shards the candidate axis over it (SPMD
        # collectives inserted by XLA, see orion_tpu.parallel).
        best_x = self._x[int(np.argmin(self._y))]
        rows, state = run_suggest_step(
            self.next_key(),
            self._x,
            self._y,
            best_x,
            self._gp_state,
            num,
            n_candidates=self.n_candidates,
            kernel=self.kernel,
            acq=self.acq,
            fit_steps=self.fit_steps,
            refit_steps=self.refit_steps,
            local_frac=self.local_frac,
            local_sigma=self.local_sigma,
            beta=self.beta,
            mesh=self._mesh,
        )
        self._gp_state = state
        return rows

    # --- state --------------------------------------------------------------
    def state_dict(self):
        out = super().state_dict()
        out["x"] = self._x.tolist()
        out["y"] = self._y.tolist()
        return out

    def set_state(self, state):
        super().set_state(state)
        d = self.space.n_cols
        self._x = np.asarray(state["x"], dtype=np.float32).reshape(-1, d)
        self._y = np.asarray(state["y"], dtype=np.float32)
        self._gp_state = None  # refit (cold) on the next suggest


@partial(jax.jit, static_argnums=(1, 2, 4))
def _make_candidates(key, n_candidates, n_dims, best_x, local_frac, local_sigma):
    """Candidate set: global uniform + gaussian ball around the incumbent.

    Boundary handling is reflection, not clipping — clipping would pile local
    candidates onto the exact floats 0.0/1.0 whenever the incumbent sits near
    an edge, producing duplicate suggestions (see sampling.reflect_unit)."""
    k1, k2 = jax.random.split(key)
    n_local = int(n_candidates * local_frac)
    n_global = n_candidates - n_local
    global_c = jax.random.uniform(k1, (n_global, n_dims))
    local_c = best_x[None, :] + local_sigma * jax.random.normal(k2, (n_local, n_dims))
    return jnp.concatenate([global_c, reflect_unit(local_c)], axis=0)


def run_suggest_step(
    key,
    x_obs,
    y_obs,
    best_x,
    warm_state,
    num,
    *,
    n_candidates,
    kernel,
    acq,
    fit_steps,
    refit_steps=None,
    local_frac,
    local_sigma,
    beta,
    fixed_tail_cols=0,
    mesh=None,
):
    """Host wrapper around the fused jit: pow-2 pad the observation buffers,
    warm-start from a previous GPState (warm refits run ``refit_steps``
    optimizer steps, cold first fits ``fit_steps``), bucket q (a static arg
    — the producer's retry loop shrinks its request per round and each
    distinct q would otherwise recompile the whole graph), and slice the
    rows back.  Shared by ``tpu_bo`` and the multi-fidelity ``asha_bo``.
    """
    n, width = np.asarray(x_obs).shape
    n_pad = _next_pow2(n)
    x = np.zeros((n_pad, width), dtype=np.float32)
    y = np.zeros((n_pad,), dtype=np.float32)
    mask = np.zeros((n_pad,), dtype=np.float32)
    x[:n] = x_obs
    y[:n] = y_obs
    mask[:n] = 1.0
    warm = warm_state.hypers if warm_state is not None else init_hypers(width)
    if warm_state is not None and refit_steps is not None:
        fit_steps = refit_steps
    rows, state = _suggest_step(
        key,
        jnp.asarray(x),
        jnp.asarray(y),
        jnp.asarray(mask),
        jnp.asarray(best_x),
        warm,
        q=_next_pow2(num, floor=8),
        n_candidates=n_candidates,
        kernel=kernel,
        acq=acq,
        fit_steps=fit_steps,
        local_frac=local_frac,
        local_sigma=local_sigma,
        beta=beta,
        fixed_tail_cols=fixed_tail_cols,
        mesh=mesh,
    )
    # Dedup ordered unique draws first, so the first `num` rows are the ones
    # the un-padded call would have returned.
    return np.asarray(rows)[:num], state


def _dedup_fill_device(idx, ei_rank, q):
    """On-device first-occurrence dedup of ``idx`` with EI-ranked backfill.

    Sort-by-priority-key trick, all static shapes: unique draws keep their
    draw position as key, duplicates and already-drawn fill candidates get
    pushed past everything usable, EI fills slot in after the draws.  If the
    distinct pool is exhausted the tail recycles duplicates (storage
    dedup/DuplicateKeyError rejects them downstream, as before).
    """
    k = ei_rank.shape[0]
    pos_q = jnp.arange(q)
    pos_k = jnp.arange(k)
    is_dup = jnp.any(
        (idx[:, None] == idx[None, :]) & (pos_q[:, None] > pos_q[None, :]), axis=1
    )
    is_member = jnp.any(ei_rank[:, None] == idx[None, :], axis=1)
    big = q + k + 1
    key_draws = jnp.where(is_dup, big + pos_q, pos_q)
    key_fills = jnp.where(is_member, big + q + pos_k, q + pos_k)
    all_idx = jnp.concatenate([idx, ei_rank])
    order = jnp.argsort(jnp.concatenate([key_draws, key_fills]))
    return all_idx[order][:q]


@partial(
    jax.jit,
    static_argnames=(
        "q",
        "n_candidates",
        "kernel",
        "acq",
        "fit_steps",
        "local_frac",
        "local_sigma",
        "beta",
        "fixed_tail_cols",
        "mesh",
    ),
)
def _suggest_step(
    key,
    x,
    y,
    mask,
    best_x,
    warm_hypers,
    *,
    q,
    n_candidates,
    kernel,
    acq,
    fit_steps,
    local_frac,
    local_sigma,
    beta,
    fixed_tail_cols=0,
    mesh=None,
):
    """The whole GP-BO suggest round as ONE compiled computation.

    ``fixed_tail_cols``: the last k input columns are context, not free
    variables — candidates are generated over the leading columns only and
    the tail is pinned to 1.0 when scoring (multi-fidelity BO pins the
    fidelity column to max budget so selection optimizes the predicted
    FULL-budget value).  Returned rows include only the free columns.
    """
    state = fit_gp(x, y, mask, kind=kernel, n_steps=fit_steps, init=warm_hypers)
    k_cand, k_acq = jax.random.split(key)
    d_free = x.shape[1] - fixed_tail_cols
    free_candidates = _make_candidates(
        k_cand, n_candidates, d_free, best_x[:d_free], local_frac, local_sigma
    )
    if mesh is not None:
        # Data-parallel over the candidate axis: XLA's SPMD partitioner
        # splits generation+scoring per shard and inserts the ICI
        # collectives for the cross-candidate argmin/top-k reductions.
        free_candidates = jax.lax.with_sharding_constraint(
            free_candidates, candidate_sharding(mesh)
        )
    if fixed_tail_cols:
        candidates = jnp.concatenate(
            [
                free_candidates,
                jnp.ones((n_candidates, fixed_tail_cols), free_candidates.dtype),
            ],
            axis=1,
        )
    else:
        candidates = free_candidates
    y_norm = (state.y - state.y_mean) / state.y_std
    if fixed_tail_cols:
        # Candidates are scored at max context (tail pinned to 1), so the EI
        # incumbent must be the best observation AT the top context tier — a
        # lucky low-fidelity value would otherwise be unattainable for every
        # candidate and flatten EI to ~0.
        s_col = x[:, -1]
        s_max = jnp.max(jnp.where(mask > 0, s_col, -jnp.inf))
        top = (mask > 0) & (s_col >= s_max - 1e-6)
        best = jnp.min(jnp.where(top, y_norm, jnp.inf))
    else:
        best = jnp.min(jnp.where(state.mask > 0, y_norm, jnp.inf))
    if acq == "joint_thompson":
        idx = joint_thompson(k_acq, state, candidates, q, kind=kernel)
    else:
        idx = acquire(
            k_acq, state, candidates, q, kind=kernel, acq=acq, best=best, beta=beta
        )
    mean, std = posterior_norm(state, candidates, kind=kernel)
    ei_rank = select_q(
        expected_improvement(mean, std, best), min(4 * q, n_candidates)
    )
    final_idx = _dedup_fill_device(idx, ei_rank, q)
    return jnp.take(free_candidates, final_idx, axis=0), state

