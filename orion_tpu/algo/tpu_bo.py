"""TPU-native batched Bayesian optimizer — the framework's flagship.

No reference counterpart (Oríon v0.1.7 has only random search + ASHA); this
implements BASELINE.json's north star: `suggest`/`observe` as jitted batched
device code — GP posterior via masked Cholesky on power-of-2 padded buffers,
acquisition (Thompson/EI/UCB) vmapped over thousands of candidates, q-batch
selection in a single compiled call, optionally sharded across a device mesh
(`orion_tpu.parallel`).

The producer's lie fantasization (constant-liar strategies) composes on top:
lies arrive through `observe` like real results, which is exactly the
fantasize-don't-refit design SURVEY.md §7 calls for — the naive-algo copy
refits its posterior with fantasy rows instead of waiting on stragglers.
"""

import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.compiler_plane import (
    COMPILE_REGISTRY,
    fields_from_plan_signature,
    lowered_analysis_fn,
    signature_fields,
)
from orion_tpu.telemetry import TELEMETRY

from orion_tpu.algo.base import BaseAlgorithm, algo_registry
from orion_tpu.algo.gp.acquisition import (
    acquire,
    expected_improvement,
    joint_thompson,
    select_q,
)
from orion_tpu.algo.gp.gp import GPHypers, fit_gp, init_hypers, posterior_norm
from orion_tpu.algo.history import (
    DeviceHistory,
    HostHistory,
    _next_pow2,
    prewarm_local_subset,
)
from orion_tpu.algo.prewarm import (
    DEFAULT_PREWARM_FILL,
    BucketPrewarmer,
    completed_prewarm_count,
    plan_fused_step_bucket,
    plan_next_bucket,
)
from orion_tpu.algo.sampling import clamp_objectives, reflect_unit
from orion_tpu.algo.sharding import mesh_health_fields
from orion_tpu.parallel import candidate_sharding, device_mesh, replicated


class WarmStart(NamedTuple):
    """Restored GP warm-start carrier: quacks like the slice of GPState the
    suggest path reads before the first post-restore fit lands (``hypers``
    for the refit init; ``mll``/``health`` absent)."""

    hypers: "GPHypers"
    mll: None = None
    health: None = None


def copula_transform(y):
    """Rank -> normal quantile on host (monotone: argmin preserved).

    The HOT path no longer runs this: the fused suggest step applies the
    same transform on device (``sampling.masked_copula_transform``, routed
    through ``fit_gp(y_transform="copula")``), so the full-history y is
    never re-ranked on host or re-uploaded per round.  This host twin
    remains the parity reference (``tests/unit/test_copula_device.py``
    pins device == host within float32 tolerance) and the entry point for
    host-side consumers.  The inner sort is ``kind="stable"`` so duplicate
    objectives get first-occurrence ranks — the tie order jax's (stable)
    ``argsort`` uses on device."""
    from scipy.special import ndtri

    order = np.argsort(np.argsort(y, kind="stable"))
    return ndtri((order + 0.5) / y.shape[0]).astype(np.float32)


def local_subset_indices(x, center, m):
    """Indices of the m nearest rows to ``center`` (local-GP selection).

    Host reference implementation; the algorithms now gather the subset on
    device (``DeviceHistory.local_view``) so the fit set never crosses the
    host boundary."""
    d2 = ((x - center[None, :]) ** 2).sum(axis=1)
    return np.argpartition(d2, m)[:m]


def tr_update(length, succ, fail, improved, *, succ_tol, fail_tol,
              length_init, length_min, length_max):
    """One trust-region bookkeeping step (TuRBO schedule), shared by every
    algorithm hosting a box: expand after ``succ_tol`` consecutive improving
    rounds, halve after ``fail_tol`` stagnating ones, restart wide on
    collapse (history is kept — only the box resets).  Returns
    ``(length, succ, fail, restarted)``."""
    if improved:
        succ, fail = succ + 1, 0
    else:
        succ, fail = 0, fail + 1
    if succ >= succ_tol:
        length, succ = min(2.0 * length, length_max), 0
    elif fail >= fail_tol:
        length, fail = length / 2.0, 0
    restarted = length < length_min
    if restarted:
        length, succ, fail = length_init, 0, 0
    return length, succ, fail, restarted


def tr_update_batch(length, succ, fail, prev_best, objectives, *, chunk,
                    succ_tol, fail_tol, length_init, length_min, length_max,
                    improve_tol):
    """Run the TuRBO schedule over ONE observe round, splitting a batch
    larger than ``chunk`` into sequential sub-rounds (arrival order, running
    incumbent).

    The schedule's unit of evidence is a *round* of samples from the current
    box — but its cadence must not be coupled to the caller's batch size
    (VERDICT r4 weak #2): at q=256 a once-per-round update gives the box 4
    adaptations over a 1024-trial run, vs the 128 that made batch-8 TuRBO
    match CMA-ES on rosenbrock20.  Sub-rounds approximate the small-batch
    schedule — later chunks came from the same (not-yet-shrunk) box, so the
    box only lacks the per-chunk *sampling* feedback, not the success/failure
    signal.  Batches ≤ ``chunk`` keep the exact one-update-per-round
    behavior."""
    y = np.asarray(objectives, dtype=np.float64).ravel()
    best = float(prev_best)
    n_restarts = 0
    for i in range(0, y.shape[0], chunk):
        chunk_best = float(np.min(y[i : i + chunk]))
        improved = chunk_best < best - improve_tol * abs(best)
        length, succ, fail, restarted = tr_update(
            length, succ, fail, improved,
            succ_tol=succ_tol, fail_tol=fail_tol, length_init=length_init,
            length_min=length_min, length_max=length_max,
        )
        n_restarts += restarted
        best = min(best, chunk_best)
    return length, succ, fail, n_restarts


@algo_registry.register("tpu_bo")
class TPUBO(BaseAlgorithm):
    """Batched GP-BO on device.

    Parameters
    ----------
    n_init: random (prior) points before the GP engages.
    n_candidates: candidate-set size per suggest call (split between global
        uniform exploration and gaussian perturbations around incumbents).
    acq: "thompson" (default; diverse q-batches), "joint_thompson", "ei", "ucb".
    kernel: "matern52" (default) or "rbf".
    fit_steps: adam steps on the marginal likelihood for the FIRST fit.
    refit_steps: steps for warm-started refits (default: fit_steps).  Each
        round resumes from the previous round's hyperparameters, so fewer
        refit steps are viable where GP fitting dominates the round.
    local_frac: fraction of candidates drawn around the current best point.
    y_transform: "copula" (default) rank-Gaussianizes objectives before the
        GP fit (ranks mapped through the normal quantile function).
        Monotone, so acquisition order is preserved — but the GP sees a
        unit-scale, outlier-free target even when raw objectives span
        orders of magnitude (Rosenbrock-class landscapes), which is exactly
        where raw-y GPs go blind: the valley floor normalizes to one flat
        value and every gradient signal lives in the first percentile.
        "none" fits raw objectives (useful when their scale itself is the
        signal, e.g. already-standardized targets).
    trust_region: TuRBO-style local BO (Eriksson et al. 2019), ON by
        default — measured on the chip it is what keeps the default config
        robust on ill-conditioned landscapes (rosenbrock20 regret ~700-1100
        vs 1.3e4 for the global-candidate scheme, VERDICT r3 weak #2) while
        holding Hartmann6 parity (0.129-0.143 over 3 seeds, anchor 0.187).
        The trust box starts at most of the cube (0.8) and expands to
        super-global (1.6) while improving, so easy landscapes degrade
        gracefully to near-global search.  The local
        candidate fraction is drawn from a box around the incumbent whose
        per-dimension side lengths follow the fitted GP lengthscales; the
        box expands after ``tr_succ_tol`` consecutive improving rounds,
        halves after ``tr_fail_tol`` stagnating ones, and restarts at
        ``tr_length_init`` when it collapses below ``tr_length_min``.  This
        is what lets the GP concentrate samples inside high-D curved
        valleys (Rosenbrock-class landscapes) where a global-uniform +
        fixed-sigma-ball scheme plateaus.
    prewarm: background-compile the next pow-2 history bucket's fused
        suggest step before the history crosses the boundary, so mid-run
        bucket growth costs a jit-cache hit instead of a synchronous
        multi-second compile stall (docs/performance.md, "The
        zero-reupload round").  ``prewarm_fill`` is the bucket-fill
        fraction that triggers the compile (default 0.75).
    tr_update_every: the box adaptation cadence in *observations*, not
        rounds — an observe round larger than this is split into
        sequential sub-rounds for the TuRBO schedule (tr_update_batch),
        so q=256 users get ~32 adaptations per round instead of 1 and the
        default config stays robust at any batch size.
    n_devices: shard candidates over this many devices (None = all visible).
    """

    supports_async_suggest = True

    def __init__(
        self,
        space,
        seed=None,
        n_init=16,
        n_candidates=8192,
        acq="thompson",
        kernel="matern52",
        fit_steps=50,
        refit_steps=None,
        beta=2.0,
        local_frac=0.5,
        local_sigma=0.1,
        y_transform="copula",
        trust_region=True,
        tr_length_init=0.8,
        tr_length_min=0.5**7,
        tr_length_max=1.6,
        tr_succ_tol=3,
        tr_fail_tol=4,
        tr_improve_tol=1e-3,
        tr_local_m=256,
        tr_perturb_dims=20,
        tr_update_every=8,
        speculative_suggest=False,
        prewarm=True,
        prewarm_fill=DEFAULT_PREWARM_FILL,
        n_devices=None,
        use_mesh=False,
    ):
        super().__init__(
            space,
            seed=seed,
            n_init=n_init,
            n_candidates=n_candidates,
            acq=acq,
            kernel=kernel,
            fit_steps=fit_steps,
            refit_steps=refit_steps,
            beta=beta,
            local_frac=local_frac,
            local_sigma=local_sigma,
            y_transform=y_transform,
            trust_region=trust_region,
            tr_length_init=tr_length_init,
            tr_length_min=tr_length_min,
            tr_length_max=tr_length_max,
            tr_succ_tol=tr_succ_tol,
            tr_fail_tol=tr_fail_tol,
            tr_improve_tol=tr_improve_tol,
            tr_local_m=tr_local_m,
            tr_perturb_dims=tr_perturb_dims,
            tr_update_every=tr_update_every,
            speculative_suggest=speculative_suggest,
            prewarm=prewarm,
            prewarm_fill=prewarm_fill,
        )
        self.n_init = n_init
        self.n_candidates = n_candidates
        self.acq = acq
        self.kernel = kernel
        self.fit_steps = fit_steps
        # None = warm refits also use fit_steps (run_suggest_step owns the
        # default); opt in where GP fitting genuinely dominates the round.
        self.refit_steps = refit_steps
        self.beta = beta
        self.local_frac = local_frac
        self.local_sigma = local_sigma
        self.y_transform = y_transform
        self.trust_region = trust_region
        self.tr_length_init = tr_length_init
        self.tr_length_min = tr_length_min
        self.tr_length_max = tr_length_max
        self.tr_succ_tol = tr_succ_tol
        self.tr_fail_tol = tr_fail_tol
        self.tr_improve_tol = tr_improve_tol
        self.tr_local_m = tr_local_m
        self.tr_perturb_dims = tr_perturb_dims
        self.tr_update_every = tr_update_every
        # Opt-in async-BO semantics: let the producer dispatch next round's
        # suggest conditioned on constant-liar fantasies for the in-flight
        # batch.  Hides the device round trip behind trial execution, at the
        # one-round-stale conditioning cost every async multi-worker setup
        # already accepts (measured on Hartmann6: regret 0.13 -> 0.21).
        self.speculation_safe = bool(speculative_suggest)
        self.prewarm = bool(prewarm)
        self.prewarm_fill = float(prewarm_fill)
        self.use_mesh = use_mesh
        self._mesh = device_mesh(n_devices) if use_mesh else None
        d = space.n_cols
        # Host history: amortized-growth capped buffers with O(batch)
        # appends and an incrementally-tracked incumbent — the old
        # np.concatenate mirrors cost O(n) host work per observe.  Only
        # bookkeeping that genuinely needs host floats reads it
        # (trust-region schedule, restart-center scans, state_dict).
        self._host = HostHistory(d)
        # Device-resident twin: incrementally appended on observe so the
        # suggest path never re-uploads rows the device already holds
        # (docs/algorithms.md, "Device-resident history").  The copula
        # y-transform and local-subset selection run on these buffers
        # in-jit, so a steady-state round's upload is O(batch) rows.
        self._hist = DeviceHistory(d)
        self._gp_state = None
        # Shape-bucket AOT prewarm: compiles the next pow-2 bucket's fused
        # step on a background thread before the history crosses the
        # boundary (docs/performance.md, "The zero-reupload round").
        self._prewarmer = BucketPrewarmer()
        self._last_q_bucket = None
        self._tr_length = tr_length_init
        self._tr_succ = 0
        self._tr_fail = 0
        # Fresh-restart override: row index the trust box centers on after a
        # collapse with no progress (None = the global incumbent).
        self._tr_center = None
        # Steady-path dispatch prep (docs/performance.md, "Attributing the
        # round"): the statics part of `_step_kw` and the resolved
        # _PlanPrep ride per-instance caches so a steady round skips the
        # dict rebuild and the 16-tuple prep-key probe entirely.
        self._step_kw_cache = None
        self._prep_token = PlanPrepToken()

    # Naive-copy sharing (base __deepcopy__): the mesh handle and the
    # prewarmer's threads/locks are not copyable (and the jit cache they
    # warm is process-wide — one warm covers every clone); the fitted GP
    # state is immutable-by-rebinding.  `_hist` and `_host` are
    # deliberately NOT here: their own __deepcopy__ implements
    # copy-on-write sharing of the buffers (a plain by-ref share would let
    # the clone's in-place appends clobber the real algorithm's history).
    # `_step_kw_cache` (never mutated after build) and `_prep_token`
    # (atomic-by-rebinding pinned pair) ARE shared: a naive clone prepares
    # the same signatures, so it should ride the same warm caches — and a
    # deepcopy of either would walk the mesh handle / device scalars.
    _share_by_ref = (
        "space", "_mesh", "_gp_state", "_prewarmer",
        "_step_kw_cache", "_prep_token",
    )

    # Back-compat views of the observation history (tests and host-side
    # consumers read these; appends go through `_host`).
    @property
    def _x(self):
        return self._host.x

    @property
    def _y(self):
        return self._host.y

    # --- observation --------------------------------------------------------
    def observe_arrays(self, cube, objectives, params_list=None, fidelities=None):
        objectives = clamp_objectives(objectives, self._y)
        if objectives is None:
            return
        prev_n = self._host.count
        prev_best = self._host.best_y  # O(1): tracked incrementally
        rows32 = np.asarray(cube, dtype=np.float32)
        y32 = np.asarray(objectives, dtype=np.float32)
        # O(batch) host append + O(batch) device append: only the new rows
        # cross the boundary, and no O(n) concatenate/argmin runs on host.
        self._host.append(rows32, y32)
        self._hist.append(rows32, y32)
        self._maybe_prewarm(batch=y32.shape[0])
        # Trust-region bookkeeping counts MODEL rounds only: observations of
        # the random init phase say nothing about the local model's quality.
        if self.trust_region and prev_n >= self.n_init:
            # Decoupled from batch size: a big observe round is split into
            # tr_update_every-sized sub-rounds (see tr_update_batch) so the
            # box gets the same adaptation count a small-batch run would.
            (self._tr_length, self._tr_succ, self._tr_fail,
             n_restarts) = tr_update_batch(
                self._tr_length, self._tr_succ, self._tr_fail,
                prev_best, objectives, chunk=self.tr_update_every,
                succ_tol=self.tr_succ_tol, fail_tol=self.tr_fail_tol,
                length_init=self.tr_length_init,
                length_min=self.tr_length_min,
                length_max=self.tr_length_max,
                improve_tol=self.tr_improve_tol,
            )
            new_best = self._host.best_y
            if new_best < prev_best - self.tr_improve_tol * abs(prev_best):
                # Progress: the box belongs back on the true incumbent.
                self._tr_center = None
            elif n_restarts:
                # Collapse without progress: re-centering the fresh box on
                # the SAME stuck incumbent replays the failed search (the
                # round-4 tail diagnosis — the worst seed's box cycled
                # 0.4 -> 0.0125 -> restart four times around one point).
                # Restart around the best observation that is at least an
                # average-distance/4 away instead.
                self._tr_center = self._fresh_restart_center()

    def _fresh_restart_center(self):
        """Index of the best observation usefully FAR from the incumbent
        (>= a quarter of the mean distance to it); None when nothing
        qualifies (early runs whose points all cluster).  The O(n) distance
        scan only runs on a box collapse without progress — a rare event,
        not steady-state observe cost."""
        best_idx = self._host.best_idx
        d = np.sqrt(((self._x - self._x[best_idx]) ** 2).sum(axis=1))
        far = d >= max(float(d.mean()) / 4.0, 1e-6)
        if not far.any():
            return None
        candidates = np.where(far)[0]
        return int(candidates[np.argmin(self._y[candidates])])

    # --- suggestion ---------------------------------------------------------
    def _step_kw(self):
        return dict(
            n_candidates=self.n_candidates,
            kernel=self.kernel,
            acq=self.acq,
            fit_steps=self.fit_steps,
            refit_steps=self.refit_steps,
            local_frac=self.local_frac,
            local_sigma=self.local_sigma,
            beta=self.beta,
            trust_region=self.trust_region,
            tr_length=self._tr_length,
            tr_perturb_dims=self.tr_perturb_dims,
            y_transform=self.y_transform,
            mesh=self._mesh,
        )

    def _maybe_prewarm(self, batch=0):
        maybe_prewarm_fused_step(self, batch=batch)

    def fused_step_plan(self, num):
        """This round's fused suggest step as a :class:`FusedPlan`, or None
        while the random-init phase is still running (nothing fused to
        dispatch).  The plan is CONSUMING: it advances the RNG stream and
        stamps the q bucket exactly as a direct suggest would, so a caller
        holding a plan MUST run it (standalone via :func:`run_fused_plan`,
        or stacked with other tenants' same-signature plans through the
        serve gateway's coalescer) and feed the resulting GPState back via
        :meth:`consume_fused_step` — which is precisely what
        ``_suggest_cube`` does.  One prep path for both the standalone and
        the coalesced dispatch is what makes them bit-identical."""
        n = self._host.count
        if n < self.n_init:
            return None
        self._last_q_bucket = _next_pow2(num, floor=8)
        center_idx = (
            self._tr_center
            if self._tr_center is not None and self._tr_center < n
            else self._host.best_idx  # O(1): tracked incrementally
        )
        best_x = self._host.x[center_idx]
        step_kw = self._step_kw_cache
        if step_kw is None:
            # tr_length is the per-round traced input (passed explicitly
            # below); every other `_step_kw` entry is frozen at __init__,
            # so the dict rides the instance and is never mutated after
            # build (shared by ref with naive clones).
            step_kw = dict(self._step_kw())
            step_kw.pop("tr_length", None)
            self._step_kw_cache = step_kw
        if self.trust_region and n > self.tr_local_m:
            # LOCAL GP (the TuRBO design): fit only the tr_local_m nearest
            # observations to the incumbent.  A global fit has to average
            # lengthscales over the whole landscape, washing out exactly the
            # local structure the trust region is trying to exploit — and a
            # 4x smaller buffer makes the per-round Cholesky ~64x cheaper.
            # The subset is gathered ON DEVICE from the resident buffers
            # (masked top_k, DeviceHistory.local_view): no O(n·d) host
            # distance scan, no host gather, no upload — only the center
            # row crosses the boundary.
            x_dev, y_dev, mask_dev, _ = self._hist.local_view(
                best_x, self.tr_local_m
            )
        else:
            # Full-history fast path: the fit set IS the full history,
            # which already lives on device — no O(n) re-pad or re-upload.
            # The copula transform (whose ranks change globally with every
            # new observation) runs in-jit over the masked device y, so
            # nothing history-sized crosses the boundary here either.
            x_dev, y_dev, mask_dev, _ = self._hist.fit_view()
        return make_fused_plan(
            self.next_key(), x_dev, y_dev, mask_dev, best_x,
            self._gp_state, num, tr_length=self._tr_length,
            prep_token=self._prep_token, **step_kw,
        )

    def consume_fused_step(self, state):
        """Accept the GPState a fused-plan dispatch produced (warm-start
        source for the next round's fit + packed device health)."""
        self._gp_state = state

    def _suggest_cube(self, num):
        plan = self.fused_step_plan(num)
        if plan is None:
            return jax.random.uniform(self.next_key(), (num, self.space.n_cols))
        # Single fused jit call: warm-started GP refit + on-device copula
        # y-transform + candidate generation + acquisition + on-device
        # dedup/EI-fill + gather.  One dispatch and one (q, d) transfer per
        # suggest — dispatch latency otherwise dominates (each host->device
        # round trip costs ~ms).  With a mesh, the same compiled step
        # shards the candidate axis over it (SPMD collectives inserted by
        # XLA, see orion_tpu.parallel).
        rows, state = run_fused_plan(plan, prewarmer=self._prewarmer)
        self.consume_fused_step(state)
        return rows

    # --- health -------------------------------------------------------------
    def health_record(self):
        """Per-round optimization health (orion_tpu.health): incumbent +
        trust-region box from the host trackers (all O(1) reads), GP fit /
        acquisition / dedup fields unpacked from the packed device vector
        the last fused step attached to its GPState (already computed —
        reading it transfers ready data, it does not sync the device)."""
        from orion_tpu.health import unpack_device_health

        record = {
            "algo": type(self).__name__.lower(),
            "n_obs": int(self._host.count),
            "tr_length": float(self._tr_length),
            "tr_succ": int(self._tr_succ),
            "tr_fail": int(self._tr_fail),
        }
        if self._host.count:
            record["best_y"] = float(self._host.best_y)
        if self._mesh is not None:
            # serve_width-style placement fields: device count always;
            # measured per-device byte fractions once a fused round has
            # produced sharded state to read placement from (metadata-only,
            # no transfers — see sharding.placement_fractions).
            sample = () if self._gp_state is None else (self._gp_state.chol,)
            record.update(mesh_health_fields(self._mesh, *sample))
        state = self._gp_state
        if state is not None and state.health is not None:
            record.update(unpack_device_health(state.health))
        return record

    # --- state --------------------------------------------------------------
    def state_dict(self):
        out = super().state_dict()
        out["x"] = self._x.tolist()
        out["y"] = self._y.tolist()
        out["tr"] = [self._tr_length, self._tr_succ, self._tr_fail]
        out["tr_center"] = self._tr_center
        # GP warm start: the fitted hyperparameters the next round's refit
        # resumes from.  Without them a restored instance cold-fits from
        # init_hypers and the suggestion stream FORKS at the restore point
        # — the serve gateway's --persist restart pins bit-identical
        # continuation on exactly this field (tests/unit/test_serve.py).
        if self._gp_state is not None:
            hypers = self._gp_state.hypers
            out["gp_hypers"] = [
                np.asarray(hypers.log_lengthscales).tolist(),
                float(hypers.log_amplitude),
                float(hypers.log_noise),
            ]
        return out

    def set_state(self, state):
        super().set_state(state)
        d = self.space.n_cols
        x = np.asarray(state["x"], dtype=np.float32).reshape(-1, d)
        y = np.asarray(state["y"], dtype=np.float32)
        # Rebuild the host buffers (incumbent tracking resumes) and the
        # device-resident twin with ONE bulk upload; incremental appends
        # resume from here.
        self._host = HostHistory.from_host(x, y)
        self._hist = DeviceHistory.from_host(x, y)
        saved = state.get("gp_hypers")
        if saved is not None:
            # Warm-restart shim: only .hypers feeds the next fused plan
            # (the fit rebuilds chol/alpha on device); .health/.mll absent
            # until the first restored round replaces this with a full
            # GPState via consume_fused_step.
            self._gp_state = WarmStart(
                hypers=GPHypers(
                    log_lengthscales=jnp.asarray(saved[0], jnp.float32),
                    log_amplitude=jnp.asarray(saved[1], jnp.float32),
                    log_noise=jnp.asarray(saved[2], jnp.float32),
                )
            )
        else:
            self._gp_state = None  # refit (cold) on the next suggest
        tr = state.get("tr")
        if tr is not None:
            self._tr_length, self._tr_succ, self._tr_fail = tr[0], int(tr[1]), int(tr[2])
        center = state.get("tr_center")
        self._tr_center = int(center) if center is not None else None


@algo_registry.register("turbo")
class TuRBO(TPUBO):
    """Trust-region GP-BO: :class:`TPUBO` with TuRBO candidate generation on
    by default and a 90/10 local/global candidate split.  Same fused-jit
    suggest step, same public API — only the candidate scheme and its
    host-side box bookkeeping differ."""

    def __init__(self, space, seed=None, **kwargs):
        kwargs.setdefault("trust_region", True)
        kwargs.setdefault("local_frac", 0.9)
        kwargs.setdefault("y_transform", "copula")
        super().__init__(space, seed=seed, **kwargs)


@partial(jax.jit, static_argnums=(1, 2, 4))
def _make_candidates(key, n_candidates, n_dims, best_x, local_frac, local_sigma):
    """Candidate set: global uniform + gaussian ball around the incumbent.

    Boundary handling is reflection, not clipping — clipping would pile local
    candidates onto the exact floats 0.0/1.0 whenever the incumbent sits near
    an edge, producing duplicate suggestions (see sampling.reflect_unit)."""
    k1, k2 = jax.random.split(key)
    n_local = int(n_candidates * local_frac)
    n_global = n_candidates - n_local
    global_c = jax.random.uniform(k1, (n_global, n_dims))
    local_c = best_x[None, :] + local_sigma * jax.random.normal(k2, (n_local, n_dims))
    return jnp.concatenate([global_c, reflect_unit(local_c)], axis=0)


def _topk_cov_chol(x, y, mask, n_dims, k=64):
    """Cholesky factor of the covariance of the k best observed points.

    The elite set's spread tracks the local geometry of the descent (a
    curved valley stretches it along the valley's direction), giving a
    ROTATED sampling distribution that an axis-aligned trust box cannot
    express — the same signal CMA-ES distills into its covariance, read
    directly off the history instead of adapted generation by generation."""
    y_sorted_idx = jnp.argsort(jnp.where(mask > 0, y, jnp.inf))
    elite = jnp.take(x, y_sorted_idx[:k], axis=0)
    # CMA-style log weights: best points dominate the estimate.  Padded
    # buffer rows sort last but can still land inside the top k when fewer
    # than k real observations exist — zero their weight or the (0,...,0)
    # padding rows drag mu toward the origin and the covariance toward the
    # padding geometry.
    w = jnp.log(k + 0.5) - jnp.log(jnp.arange(1, k + 1, dtype=x.dtype))
    w = w * jnp.take(mask, y_sorted_idx[:k])
    w = w / jnp.maximum(jnp.sum(w), 1e-12)
    mu = jnp.sum(elite * w[:, None], axis=0)
    centered = elite - mu[None, :]
    cov = (centered * w[:, None]).T @ centered
    # Ridge: elite sets collapsed to a subspace (or duplicates) must still
    # factorize; 1e-6 in cube units is far below any useful step.
    chol = jnp.linalg.cholesky(cov + 1e-6 * jnp.eye(n_dims, dtype=x.dtype))
    return chol, mu


def _tr_box(center, tr_length, lengthscales):
    """Trust-box bounds: per-dimension half-widths follow the GP
    lengthscales normalized to geometric mean 1, clipped to the cube."""
    scale = lengthscales / jnp.exp(jnp.mean(jnp.log(lengthscales)))
    half = 0.5 * tr_length * scale
    lb = jnp.clip(center - half, 0.0, 1.0)
    ub = jnp.clip(center + half, 0.0, 1.0)
    return lb, ub


def _polish_candidates(
    state, kernel, starts, lb, ub, n_steps=30, lr=0.02, fixed_tail_cols=0
):
    """Multi-start adam descent on the GP posterior mean, box-clipped every
    step — in-jit acquisition optimization.  Random candidates locate the
    posterior's basins; 30 gradient steps walk the floor of the basin, which
    random sampling cannot hit in high D.  The polished points join the
    candidate pool; acquisition still chooses the batch, so this sharpens
    exploitation without giving up Thompson's batch diversity."""
    import optax

    def mean_of(x_free):
        x_full = x_free
        if fixed_tail_cols:
            x_full = jnp.concatenate(
                [x_free, jnp.ones((fixed_tail_cols,), x_free.dtype)]
            )
        m, _ = posterior_norm(state, x_full[None, :], kind=kernel)
        return m[0]

    grad_fn = jax.grad(mean_of)
    opt = optax.adam(lr)

    def run_one(x0):
        def step(carry, _):
            x_cur, opt_state = carry
            g = jnp.nan_to_num(grad_fn(x_cur))
            updates, opt_state = opt.update(g, opt_state)
            x_cur = jnp.clip(optax.apply_updates(x_cur, updates), lb, ub)
            return (x_cur, opt_state), None

        (x_fin, _), _ = jax.lax.scan(step, (x0, opt.init(x0)), None, length=n_steps)
        return x_fin

    return jax.vmap(run_one)(starts)


def _make_tr_candidates(
    key, n_candidates, n_dims, center, tr_length, lengthscales, local_frac,
    cov_chol, elite_mu, perturb_dims=20,
):
    """TuRBO-style candidates: the local fraction split between the trust
    box and elite-covariance gaussian steps, the remainder global uniform
    (restart-free exploration floor).

    The box's per-dimension half-widths follow the fitted GP lengthscales
    normalized to geometric mean 1 (long-lengthscale = flat direction = wide
    box side), clipped to the unit cube.  Each box candidate perturbs a
    random ~min(20, d)-dim subset of coordinates and inherits the incumbent
    elsewhere — in high D, moving every coordinate at once almost surely
    leaves the valley (TuRBO's perturbation mask).  The covariance source
    samples ``center + L_elite z`` — rotated steps along the elite set's
    principal directions (see _topk_cov_chol), which is what actually walks
    curved valleys.  Traced on ``tr_length``/``cov_chol`` so box resizing
    and covariance updates never recompile."""
    k1, k2, k3, k4, k5, k6, k7 = jax.random.split(key, 7)
    n_local = int(n_candidates * local_frac)
    n_cov = n_local // 6
    n_dir = n_local // 6
    n_cem = n_local // 6
    n_box = n_local - n_cov - n_dir - n_cem
    n_global = n_candidates - n_local
    lb, ub = _tr_box(center, tr_length, lengthscales)
    u = jax.random.uniform(k1, (n_box, n_dims))
    box = lb[None, :] + u * (ub - lb)[None, :]
    p_perturb = min(1.0, perturb_dims / n_dims)
    if p_perturb < 1.0:
        mask = jax.random.bernoulli(k2, p_perturb, (n_box, n_dims))
        # Guarantee at least one perturbed coordinate per candidate.
        forced = (
            jax.nn.one_hot(
                jax.random.randint(k3, (n_box,), 0, n_dims), n_dims
            )
            > 0
        )
        mask = jnp.where(jnp.any(mask, axis=1, keepdims=True), mask, forced)
        box = jnp.where(mask, box, center[None, :])
    z = jax.random.normal(k4, (n_cov, n_dims))
    # Half unit-scale steps, half double — the elite spread lags the true
    # local scale while the search is still descending.
    sigma = jnp.where(jnp.arange(n_cov)[:, None] % 2 == 0, 1.0, 2.0)
    cov_c = reflect_unit(center[None, :] + sigma * (z @ cov_chol.T))
    # Directional extrapolation: the elite mean trails the incumbent while
    # the search descends, so (center - mu) spans the descent path — step
    # at assorted magnitudes BOTH ways (t symmetric: valley landscapes
    # reward pushing past the incumbent, basin landscapes reward stepping
    # back toward the elite mean; acquisition judges which) with a little
    # covariance-shaped noise (the momentum CMA-ES gets from moving its
    # recombination mean).
    t = jax.random.normal(k5, (n_dir, 1)) * 2.0
    zd = jax.random.normal(k6, (n_dir, n_dims))
    dir_c = reflect_unit(
        center[None, :] + t * (center - elite_mu)[None, :] + 0.5 * (zd @ cov_chol.T)
    )
    # CEM-style recombination: samples around the elite MEAN.  Averaging the
    # top-k concentrates each coordinate ~sqrt(k)x tighter than any single
    # elite point, so on basin landscapes mu sits far closer to the optimum
    # than the incumbent — a move no incumbent-centered source can make.
    zc = jax.random.normal(k7, (n_cem, n_dims))
    cem_c = reflect_unit(elite_mu[None, :] + zc @ cov_chol.T)
    global_c = jax.random.uniform(jax.random.fold_in(k1, 1), (n_global, n_dims))
    return jnp.concatenate([global_c, box, cov_c, dir_c, cem_c], axis=0)


def maybe_prewarm_fused_step(algo, batch=0):
    """Observe-side prewarm trigger shared by the GP algorithms (`tpu_bo`,
    `asha_bo` — any algorithm exposing the `_host`/`_hist`/`_step_kw`
    surface): when the history nears the next pow-2 bucket, background-
    compile that bucket's fused step so the crossing costs a jit-cache hit
    instead of a synchronous multi-second stall.  O(1) planning per
    observe; needs one prior suggest to know the q bucket.

    In the local-TR regime (``count > tr_local_m``) the fused step's fit
    shape is pinned, but the on-device subset gather still re-buckets with
    the history — its (much smaller) compile is prewarmed instead; the
    approach INTO the regime warms the gather's first shape the same
    way."""
    if not algo.prewarm or algo._last_q_bucket is None:
        return
    count = algo._host.count
    if count < algo.n_init:
        return

    def warm_gather(m_hist):
        width = algo._hist.n_cols
        dist_cols = width - algo._step_kw().get("fixed_tail_cols", 0)
        floor = algo._hist.floor
        m = algo.tr_local_m
        algo._prewarmer.maybe_start(
            ("local_subset", m_hist, width, m, dist_cols),
            lambda: prewarm_local_subset(
                m_hist, width, m, dist_cols, floor=floor
            ),
        )

    if algo.trust_region and count > algo.tr_local_m:
        target_m = plan_next_bucket(
            count, floor=algo._hist.floor, fill=algo.prewarm_fill,
            batch=batch,
        )
        if target_m is not None:
            warm_gather(target_m)
        return
    if algo.trust_region and (
        count >= algo.prewarm_fill * algo.tr_local_m
        or count + batch > algo.tr_local_m
    ):
        # Approaching the full->local switch (by fill, or because one more
        # batch of this size lands past it): the first local_view call
        # feeds the gather an x of shape next_pow2 of that landing count —
        # warm that first signature too.
        warm_gather(
            _next_pow2(
                max(algo.tr_local_m + 1, count + batch),
                floor=algo._hist.floor,
            )
        )
    target_m = plan_fused_step_bucket(
        count,
        floor=algo._hist.floor,
        fill=algo.prewarm_fill,
        batch=batch,
        trust_region=algo.trust_region,
        tr_local_m=algo.tr_local_m,
    )
    if target_m is not None:
        start_bucket_prewarm(
            algo._prewarmer,
            target_m,
            algo._hist.n_cols,
            algo._last_q_bucket,
            algo._step_kw(),
            warm_refit=algo._gp_state is not None,
        )


def start_bucket_prewarm(prewarmer, target_m, width, q_bucket, step_kw, *,
                         warm_refit=False, fixed_tail_cols=0):
    """Hand the prewarmer a compile closure replaying the fused step's
    EXACT static-arg signature at the ``(target_m, width)`` bucket.  The
    dedup key is built from the same statics, so each signature compiles
    at most once per prewarmer.  ``warm_refit``: steady-state boundary
    calls run ``refit_steps`` when configured (the refit path is warm), so
    the prewarm signature must bake that in or it warms the wrong cache
    entry.  Shared by ``tpu_bo`` and ``asha_bo``."""
    kw = dict(step_kw)
    kw.pop("tr_length", None)
    fixed_tail_cols = kw.pop("fixed_tail_cols", fixed_tail_cols)
    refit_steps = kw.pop("refit_steps", None)
    if warm_refit and refit_steps is not None:
        kw["fit_steps"] = refit_steps
    key = (
        target_m,
        width,
        q_bucket,
        fixed_tail_cols,
        tuple(sorted((k, str(v)) for k, v in kw.items())),
    )

    def compile_and_record():
        t0 = time.perf_counter()
        prewarm_suggest_step(
            target_m, width, q_bucket, fixed_tail_cols=fixed_tail_cols, **kw
        )
        if TELEMETRY.enabled:
            # Compiler plane: record the EXACT signature this warm covers
            # (built from the same statics `make_fused_plan` hashes into
            # the plan signature, split-fit adjustment included) — a later
            # retrace at this signature is a prewarm bug (DX052).
            statics = dict(kw, q=q_bucket, fixed_tail_cols=fixed_tail_cols)
            mesh = statics.get("mesh")
            if mesh is not None and mesh.devices.size > 1:
                statics["fit_steps"] = 0
            COMPILE_REGISTRY.record_prewarm(
                "fused_plan",
                signature_fields((target_m, width), statics),
                seconds=time.perf_counter() - t0,
            )

    return prewarmer.maybe_start(key, compile_and_record)


def prewarm_suggest_step(
    m,
    width,
    q_bucket,
    *,
    n_candidates,
    kernel,
    acq,
    fit_steps,
    local_frac,
    local_sigma,
    beta,
    trust_region=False,
    tr_perturb_dims=20,
    y_transform="none",
    fixed_tail_cols=0,
    mesh=None,
):
    """Compile the fused suggest step for the ``(m, width)`` buffer bucket
    by CALLING the jitted function on zero dummies — the call populates the
    jit cache (AOT ``lower().compile()`` would not), so the first real call
    at this bucket is a cache hit.  Runs on the prewarmer's background
    thread; XLA compilation releases the GIL, so the main thread keeps
    producing rounds.  Deliberately bypasses ``run_suggest_step_arrays``:
    a prewarm compile must never book a ``jax.retraces`` sample (that
    counter reports the synchronous stalls a suggest actually paid)."""
    zeros = jnp.zeros((m, width), jnp.float32)
    split_fit = mesh is not None and mesh.devices.size > 1
    if split_fit:
        # Multi-device mesh plans split the hyper-opt into `_fit_gp_host`
        # and run the fused step solve-only (make_fused_plan); warm BOTH
        # entries, each at the signature the real round will hit.
        _fit_gp_host(
            zeros, zeros[:, 0], zeros[:, 0], init_hypers(width),
            kernel=kernel, fit_steps=fit_steps, y_transform=y_transform,
        )
    rows, _ = _suggest_step(
        jax.random.PRNGKey(0),
        zeros,
        zeros[:, 0],
        zeros[:, 0],
        # best_x carries only the FREE columns: multi-fidelity callers pass
        # the incumbent without the context tail, and jit caches on shape —
        # a (width,) dummy would warm an entry the real call never hits.
        jnp.zeros((width - fixed_tail_cols,), jnp.float32),
        init_hypers(width),
        jnp.asarray(1.0, jnp.float32),
        q=q_bucket,
        n_candidates=n_candidates,
        kernel=kernel,
        acq=acq,
        fit_steps=0 if split_fit else fit_steps,
        local_frac=local_frac,
        local_sigma=local_sigma,
        beta=beta,
        trust_region=trust_region,
        tr_perturb_dims=tr_perturb_dims,
        y_transform=y_transform,
        fixed_tail_cols=fixed_tail_cols,
        mesh=mesh,
    )
    # No block_until_ready: the first call compiles SYNCHRONOUSLY (the
    # cache insert happens before it returns); only the dummy's execution
    # is async, and waiting on it would hold the prewarmer's completed
    # bookkeeping tens of ms past the insert — exactly the window in which
    # the retrace detector would misread the growth.
    del rows


def run_suggest_step(
    key,
    x_obs,
    y_obs,
    best_x,
    warm_state,
    num,
    *,
    n_candidates,
    kernel,
    acq,
    fit_steps,
    refit_steps=None,
    local_frac,
    local_sigma,
    beta,
    trust_region=False,
    tr_length=None,
    tr_perturb_dims=20,
    y_transform="none",
    fixed_tail_cols=0,
    mesh=None,
):
    """Host wrapper around the fused jit: pow-2 pad the observation buffers
    on host, upload, and delegate to :func:`run_suggest_step_arrays`.

    No longer on the algorithms' hot path — both the full-history and the
    local-subset (trust-region) fit sets now come straight off the
    device-resident :class:`DeviceHistory` buffers (``fit_view`` /
    ``local_view``), so nothing history-sized is re-padded or re-uploaded
    per round.  This entry remains for host-array callers and as the
    re-upload REFERENCE the bit-equality regression tests compare the
    resident path against (``tests/unit/test_device_history.py``,
    ``tests/unit/test_host_history.py``).
    """
    n, width = np.asarray(x_obs).shape
    n_pad = _next_pow2(n)
    x = np.zeros((n_pad, width), dtype=np.float32)
    y = np.zeros((n_pad,), dtype=np.float32)
    mask = np.zeros((n_pad,), dtype=np.float32)
    x[:n] = x_obs
    y[:n] = y_obs
    mask[:n] = 1.0
    return run_suggest_step_arrays(
        key,
        jnp.asarray(x),
        jnp.asarray(y),
        jnp.asarray(mask),
        best_x,
        warm_state,
        num,
        n_candidates=n_candidates,
        kernel=kernel,
        acq=acq,
        fit_steps=fit_steps,
        refit_steps=refit_steps,
        local_frac=local_frac,
        local_sigma=local_sigma,
        beta=beta,
        trust_region=trust_region,
        tr_length=tr_length,
        tr_perturb_dims=tr_perturb_dims,
        y_transform=y_transform,
        fixed_tail_cols=fixed_tail_cols,
        mesh=mesh,
    )


class FusedPlan(NamedTuple):
    """One prepared (not yet dispatched) fused suggest step.

    ``arrays`` holds the traced inputs of ``_suggest_step`` in call order
    (key, x, y, mask, best_x, warm hypers, tr_length); ``statics`` its
    exact static-arg kwargs — warm-vs-cold fit_steps and the pow-2 q bucket
    already folded in, so two plans with equal ``signature`` are guaranteed
    to hit the SAME jit entry and can be stacked along a leading tenant
    axis and dispatched as ONE device call (``orion_tpu.serve.coalesce``).
    ``signature`` is that grouping key: buffer shapes + every static.
    """

    signature: tuple
    arrays: tuple
    statics: dict
    num: int


class _PlanPrep(NamedTuple):
    """The signature-invariant part of a :class:`FusedPlan`, cached per
    distinct (shape bucket, statics) so the steady suggest path skips
    rebuilding it every round (the statics dict, the stringified
    signature — ``str(mesh)`` formats the whole device array — the cold
    ``init_hypers`` leaves, and the default tr_length upload were the
    largest host lines inside the bench's ``dispatch`` stage)."""

    statics: dict
    signature: tuple
    cold_hypers: object
    default_tr: object
    #: Multi-device mesh mode: the hyper-opt loop runs in its own
    #: single-device jit (`_fit_gp_host`) and the plan's in-step fit is the
    #: solve-only ``fit_steps=0`` — see :func:`make_fused_plan`.
    split_fit: bool
    host_fit_steps: int


_PLAN_PREP_CACHE = {}
_PLAN_PREP_STATS = {"hits": 0, "misses": 0, "hit_ns": 0, "miss_ns": 0}


def plan_prep_stats():
    """Aggregate prep-cache effect for the bench breakdown: measured mean
    prep cost on a miss vs a hit, and the µs the cache saved overall."""
    hits = _PLAN_PREP_STATS["hits"]
    misses = _PLAN_PREP_STATS["misses"]
    hit_us = _PLAN_PREP_STATS["hit_ns"] / 1e3 / hits if hits else 0.0
    miss_us = _PLAN_PREP_STATS["miss_ns"] / 1e3 / misses if misses else 0.0
    return {
        "hits": hits,
        "misses": misses,
        "hit_us_mean": hit_us,
        "miss_us_mean": miss_us,
        "saved_us": hits * max(0.0, miss_us - hit_us),
    }


def reset_plan_prep_stats():
    _PLAN_PREP_STATS.update(hits=0, misses=0, hit_ns=0, miss_ns=0)


#: Distinct trust-region lengths a token's device-scalar cache may hold.
#: The TuRBO schedule walks a short halving/doubling ladder, so a runaway
#: set means a caller feeds free-form floats — the cache resets rather
#: than grow without bound.
_TR_CACHE_MAX = 64


class PlanPrepToken:
    """Per-algorithm-instance steady-path dispatch-prep cache.

    ``_PLAN_PREP_CACHE`` already skips re-deriving the signature-invariant
    plan leaves, but *probing* it still costs building the 16-element
    ``prep_key`` (hashing the mesh handle included) plus the ``_step_kw``
    statics-dict rebuild, every round.  A token pins the resolved
    :class:`_PlanPrep` for ONE instance and revalidates only what that
    instance can change between rounds — the history shape bucket, the q
    bucket, warm-vs-cold, the quantized ``local_sigma`` ladder
    (``asha_bo``), and the fit-step knobs; every other static is frozen at
    ``__init__``.  A caller that mutates a frozen static mid-run must drop
    the token (``algo._prep_token = PlanPrepToken()``).

    ``pinned`` is the ``(fast_key, prep)`` pair, swapped as ONE tuple:
    immutable-by-rebinding, so a concurrent reader (gateway dispatch
    thread vs producer clone sharing the token by ref) can never observe a
    torn key/prep mix.  ``tr_cache`` re-uses the uploaded device scalar
    per distinct trust-region length.  Donation safety: ``_suggest_step``
    declares no ``donate_argnums``, so re-passing the same device buffer
    (tr scalar, ``default_tr``, ``cold_hypers``) round after round can
    never alias a donated input — the same COW discipline as
    ``DeviceHistory._append_donating``, where a buffer handed to a
    donating jit is never re-entered.
    """

    __slots__ = ("pinned", "tr_cache")

    def __init__(self):
        self.pinned = None
        self.tr_cache = {}

    def __deepcopy__(self, memo):
        # Fallback for algos that don't share the token by ref: a true
        # deepcopy would walk device buffers; a clone starting cold only
        # costs one full prep probe.
        return type(self)()


_DISPATCH_PREP_STATS = {"hits": 0, "misses": 0, "hit_ns": 0, "miss_ns": 0}


def dispatch_prep_stats():
    """Steady-path dispatch-prep effect for the bench breakdown
    (``dispatch_us_saved``): measured mean prep cost on the token fast
    path vs the full prep-key probe, and the µs the token saved overall."""
    hits = _DISPATCH_PREP_STATS["hits"]
    misses = _DISPATCH_PREP_STATS["misses"]
    hit_us = _DISPATCH_PREP_STATS["hit_ns"] / 1e3 / hits if hits else 0.0
    miss_us = _DISPATCH_PREP_STATS["miss_ns"] / 1e3 / misses if misses else 0.0
    return {
        "hits": hits,
        "misses": misses,
        "hit_us_mean": hit_us,
        "miss_us_mean": miss_us,
        "saved_us": hits * max(0.0, miss_us - hit_us),
    }


def reset_dispatch_prep_stats():
    _DISPATCH_PREP_STATS.update(hits=0, misses=0, hit_ns=0, miss_ns=0)


def _finish_plan(
    prep,
    key,
    x,
    y,
    mask,
    best_x,
    warm_state,
    warm_is_none,
    num,
    tr_length,
    tr_cache,
    kernel,
    y_transform,
):
    """Per-round plan tail shared by the token fast path and the full prep
    path — ONE implementation, so a token hit is bit-identical by
    construction to the plan the full path would have built."""
    warm = prep.cold_hypers if warm_is_none else warm_state.hypers
    if prep.split_fit:
        warm = _fit_gp_host(
            x, y, mask, warm,
            kernel=kernel,
            fit_steps=prep.host_fit_steps,
            y_transform=y_transform,
        )
    # tr_length is dynamic (traced) so success/failure box resizing never
    # recompiles; always an array — jit caches on dtype, not value.  The
    # token's tr_cache skips the per-round host->device upload for lengths
    # the TuRBO ladder already visited (safe to re-pass: no donation, see
    # PlanPrepToken).
    if tr_length is None:
        tr = prep.default_tr
    else:
        tr = tr_cache.get(tr_length) if tr_cache is not None else None
        if tr is None:
            tr = jnp.asarray(tr_length, jnp.float32)
            if tr_cache is not None:
                if len(tr_cache) >= _TR_CACHE_MAX:
                    tr_cache.clear()
                tr_cache[tr_length] = tr
    arrays = (key, x, y, mask, jnp.asarray(best_x), warm, tr)
    return FusedPlan(prep.signature, arrays, prep.statics, int(num))


def make_fused_plan(
    key,
    x,
    y,
    mask,
    best_x,
    warm_state,
    num,
    *,
    n_candidates,
    kernel,
    acq,
    fit_steps,
    refit_steps=None,
    local_frac,
    local_sigma,
    beta,
    trust_region=False,
    tr_length=None,
    tr_perturb_dims=20,
    y_transform="none",
    fixed_tail_cols=0,
    mesh=None,
    prep_token=None,
):
    """Fold the per-round dynamics (warm refit steps, q bucket, tr_length
    boxing) into a :class:`FusedPlan`.  This is THE prep path — the
    standalone dispatch (:func:`run_fused_plan`) and the gateway's
    coalesced dispatch both consume plans built here, so their inputs
    cannot drift.

    The signature-invariant leaves (statics dict, stringified signature,
    cold-start hypers, default tr_length array) are cached per
    :class:`_PlanPrep` key: on the steady path every round re-requests the
    same bucket, and re-deriving them was the largest host line in the
    bench's ``dispatch`` stage.  The cache key folds in everything the
    cached values depend on — including ``warm_state is None`` (fit-steps
    selection) — so a hit can never change the plan that would have been
    built.

    ``prep_token`` (a :class:`PlanPrepToken` private to one algorithm
    instance) layers the steady-path shortcut on top: when the token's
    pinned fast key still matches, the 16-element ``prep_key`` build and
    cache probe are skipped entirely and the round goes straight to the
    shared plan tail (:func:`_finish_plan`) — the ``dispatch_us_saved``
    line of the bench breakdown.  The fast key deliberately omits the
    instance-frozen statics; see :class:`PlanPrepToken` for the contract.
    """
    t0 = time.perf_counter_ns()
    warm_is_none = warm_state is None
    fast_key = None
    if prep_token is not None:
        fast_key = (
            tuple(x.shape),
            _next_pow2(num, floor=8),
            warm_is_none,
            local_sigma,
            fit_steps,
            refit_steps,
        )
        pinned = prep_token.pinned
        if pinned is not None and pinned[0] == fast_key:
            plan = _finish_plan(
                pinned[1], key, x, y, mask, best_x, warm_state,
                warm_is_none, num, tr_length, prep_token.tr_cache,
                kernel, y_transform,
            )
            _DISPATCH_PREP_STATS["hits"] += 1
            _DISPATCH_PREP_STATS["hit_ns"] += time.perf_counter_ns() - t0
            return plan
    width = x.shape[1]
    prep_key = (
        tuple(x.shape),
        _next_pow2(num, floor=8),
        warm_is_none,
        n_candidates,
        kernel,
        acq,
        fit_steps,
        refit_steps,
        local_frac,
        local_sigma,
        beta,
        trust_region,
        tr_perturb_dims,
        y_transform,
        fixed_tail_cols,
        mesh,
    )
    prep = _PLAN_PREP_CACHE.get(prep_key)
    if prep is None:
        steps = fit_steps
        if not warm_is_none and refit_steps is not None:
            steps = refit_steps
        # Multi-device mesh: the marginal-likelihood hyper-opt LOOP moves to
        # a separate single-device jit (`_fit_gp_host`) and the fused step
        # keeps only the solve (fit_steps=0).  XLA's SPMD pipeline compiles
        # the loop's reductions differently per mesh size — even fully
        # replicated — so an in-step loop breaks the sharded gate's
        # bit-match-or-fail contract, while the solve is bit-stable across
        # module variants (verified by the parity pins).  On a 1-device
        # mesh nothing splits, keeping the sharded path bit-identical to
        # the unsharded single-jit round.
        split_fit = mesh is not None and mesh.devices.size > 1
        statics = dict(
            q=_next_pow2(num, floor=8),
            n_candidates=n_candidates,
            kernel=kernel,
            acq=acq,
            fit_steps=0 if split_fit else steps,
            local_frac=local_frac,
            local_sigma=local_sigma,
            beta=beta,
            trust_region=trust_region,
            tr_perturb_dims=tr_perturb_dims,
            y_transform=y_transform,
            fixed_tail_cols=fixed_tail_cols,
            mesh=mesh,
        )
        # The exact coalescing key (prewarm.start_bucket_prewarm builds its
        # dedup key from the same statics): fit-buffer shape bucket + q
        # bucket + every static arg.  Plans whose signatures match compile
        # to the same jit entry, so stacking them is safe; anything else
        # must not coalesce.
        signature = (
            tuple(x.shape),
            tuple(sorted((k, str(v)) for k, v in statics.items())),
        )
        prep = _PlanPrep(
            statics,
            signature,
            init_hypers(width) if warm_is_none else None,
            jnp.asarray(1.0, jnp.float32),
            split_fit,
            steps,
        )
        _PLAN_PREP_CACHE[prep_key] = prep
        _PLAN_PREP_STATS["misses"] += 1
        _PLAN_PREP_STATS["miss_ns"] += time.perf_counter_ns() - t0
        hit = False
    else:
        hit = True
    if prep_token is not None:
        # One-tuple swap: a concurrent fast-path reader sees either the old
        # or the new (key, prep) pair, never a torn mix.
        prep_token.pinned = (fast_key, prep)
    plan = _finish_plan(
        prep, key, x, y, mask, best_x, warm_state, warm_is_none, num,
        tr_length, prep_token.tr_cache if prep_token is not None else None,
        kernel, y_transform,
    )
    if prep_token is not None:
        _DISPATCH_PREP_STATS["misses"] += 1
        _DISPATCH_PREP_STATS["miss_ns"] += time.perf_counter_ns() - t0
    if hit:
        _PLAN_PREP_STATS["hits"] += 1
        _PLAN_PREP_STATS["hit_ns"] += time.perf_counter_ns() - t0
    return plan


def run_suggest_step_arrays(
    key,
    x,
    y,
    mask,
    best_x,
    warm_state,
    num,
    *,
    n_candidates,
    kernel,
    acq,
    fit_steps,
    refit_steps=None,
    local_frac,
    local_sigma,
    beta,
    trust_region=False,
    tr_length=None,
    tr_perturb_dims=20,
    y_transform="none",
    fixed_tail_cols=0,
    mesh=None,
    prewarmer=None,
):
    """Device-array entry to the fused jit: ``(x, y, mask)`` are already
    pow-2-padded device (or device-ready) buffers — typically
    ``DeviceHistory.fit_view`` slices, so no O(n) host re-pad or re-upload
    happens here.  Warm-starts from a previous GPState (warm refits run
    ``refit_steps`` optimizer steps, cold first fits ``fit_steps``) and
    buckets q (a static arg — the producer's retry loop shrinks its request
    per round and each distinct q would otherwise recompile the whole
    graph).  Shared by ``tpu_bo`` and the multi-fidelity ``asha_bo``.
    """
    plan = make_fused_plan(
        key,
        x,
        y,
        mask,
        best_x,
        warm_state,
        num,
        n_candidates=n_candidates,
        kernel=kernel,
        acq=acq,
        fit_steps=fit_steps,
        refit_steps=refit_steps,
        local_frac=local_frac,
        local_sigma=local_sigma,
        beta=beta,
        trust_region=trust_region,
        tr_length=tr_length,
        tr_perturb_dims=tr_perturb_dims,
        y_transform=y_transform,
        fixed_tail_cols=fixed_tail_cols,
        mesh=mesh,
    )
    return run_fused_plan(plan, prewarmer=prewarmer)


def run_fused_plan(plan, prewarmer=None):
    """Dispatch ONE prepared :class:`FusedPlan` through the fused jit,
    with the retrace-vs-cache-hit telemetry bracket.  Returns
    ``(rows[:num], state)`` exactly as the pre-plan entry did."""
    num = plan.num
    x = plan.arrays[1]
    # Telemetry: jax dispatch is asynchronous, so this span is the HOST
    # cost of the fused step — tracing + lowering + compile on a cache
    # miss, ~argument-handling microseconds on a hit.  The jit cache size
    # before/after distinguishes the two (a growth IS a retrace), which is
    # how `orion-tpu info` counts recompiles a production hunt paid.
    tel_t0 = cache_size = None
    if TELEMETRY.enabled:
        cache_size = getattr(_suggest_step, "_cache_size", None)
        try:
            tel_before = cache_size() if cache_size is not None else -1
        except Exception:  # private jax API — degrade, never raise into suggest
            cache_size, tel_before = None, -1
        # Background prewarm compiles insert cache entries too: sample the
        # completed-prewarm count around the dispatch so a prewarm landing
        # mid-window is not booked as a synchronous retrace (jax.retraces
        # must report only the stalls THIS call paid).  Scoped to the
        # caller's own prewarmer when given — only ITS compiles share
        # these jit signatures; the process-global fallback would let an
        # unrelated instance's warm mask a genuine retrace here.
        tel_completed = (
            prewarmer.completed_count
            if prewarmer is not None
            else completed_prewarm_count
        )
        tel_prewarms_before = tel_completed()
        tel_t0 = time.perf_counter()
    rows, state = _suggest_step(*plan.arrays, **plan.statics)
    if tel_t0 is not None:
        try:
            retraced = (
                cache_size is not None
                and cache_size() > tel_before
                # A prewarm that completed during this window explains the
                # growth; classify as a cached dispatch (conservative: a
                # genuine retrace coinciding with a completing prewarm
                # goes uncounted rather than a cache hit being booked as a
                # stall).  Prewarm compiles are synchronous inside the
                # jitted call and bookkeeping follows within microseconds
                # (no block_until_ready on the dummy), so the completed
                # delta is a tight proxy for "an insert landed here" — a
                # blanket in-flight check would instead blind the counter
                # to genuine retraces for the whole life of a compile.
                and tel_completed() == tel_prewarms_before
            )
        except Exception:  # private jax API — degrade, never raise into suggest
            retraced = False
        TELEMETRY.record_span(
            "jax.suggest_step.compile" if retraced else "jax.suggest_step.dispatch",
            start=tel_t0,
            args={"q": int(num), "n": int(x.shape[0])},
        )
        if retraced:
            TELEMETRY.count("jax.retraces")
            # Compiler-plane attribution (orion_tpu.compiler_plane): the
            # registry diffs this signature against the nearest prior one
            # in the fused_plan family, emits the flight `jax.retrace`
            # event naming the changed statics (the timeline entry a crash
            # post-mortem wants), and keeps a lazy cost/memory closure —
            # shape specs only, never the arrays — for cold-path analysis
            # (bench's compiler block, `orion-tpu profile`).
            COMPILE_REGISTRY.record_retrace(
                "fused_plan",
                fields_from_plan_signature(plan.signature),
                seconds=time.perf_counter() - tel_t0,
                analysis_fn=lowered_analysis_fn(
                    _suggest_step, plan.arrays, plan.statics
                ),
            )
    # Dedup ordered unique draws first, so the first `num` rows are the ones
    # the un-padded call would have returned.  Rows come back as a DEVICE
    # array slice: jax dispatch is asynchronous, so callers that defer the
    # host transfer (BaseAlgorithm.suggest's np.asarray, or the producer's
    # speculative prefetch) overlap the ~100ms tunnel round trip with host
    # work instead of blocking here.
    return rows[:num], state


@partial(jax.jit, static_argnames=("kernel", "fit_steps", "y_transform"))
def _fit_gp_host(x, y, mask, warm, *, kernel, fit_steps, y_transform):
    """The hyper-opt loop as its OWN single-device jit (multi-device mesh
    mode only).  Dispatched by :func:`make_fused_plan` right before the
    sharded fused step; only the fitted hypers cross into the plan — the
    posterior factorization is re-solved inside the step (bit-stable), so
    the warm-start chain through ``consume_fused_step`` is unchanged."""
    return fit_gp(
        x, y, mask, kind=kernel, n_steps=fit_steps, init=warm,
        y_transform=y_transform,
    ).hypers


def _dedup_fill_device(idx, ei_rank, q):
    """On-device first-occurrence dedup of ``idx`` with EI-ranked backfill.

    Sort-by-priority-key trick, all static shapes: unique draws keep their
    draw position as key, duplicates and already-drawn fill candidates get
    pushed past everything usable, EI fills slot in after the draws.  If the
    distinct pool is exhausted the tail recycles duplicates (storage
    dedup/DuplicateKeyError rejects them downstream, as before).
    """
    k = ei_rank.shape[0]
    pos_q = jnp.arange(q)
    pos_k = jnp.arange(k)
    # Sort-based dup/membership tests: the O(q^2) pairwise masks (and the
    # O(q*k) membership mask, k = 4q) cap q around 4k before the mask alone
    # outweighs the candidate pool — at q=64k they would materialize
    # multi-GB booleans.  A stable sort puts equal draws adjacent with the
    # FIRST occurrence first, so "has an earlier equal" is one neighbor
    # compare scattered back; membership is a searchsorted probe into the
    # same sorted order.  Both produce booleans identical to the pairwise
    # masks, so the keys — and therefore the returned q-batch — stay
    # bit-identical at every q.
    sort_perm = jnp.argsort(idx, stable=True)
    sorted_idx = idx[sort_perm]
    dup_sorted = jnp.concatenate(
        [jnp.zeros((1,), bool), sorted_idx[1:] == sorted_idx[:-1]]
    )
    is_dup = jnp.zeros((q,), bool).at[sort_perm].set(dup_sorted)
    probe = jnp.searchsorted(sorted_idx, ei_rank)
    is_member = sorted_idx[jnp.clip(probe, 0, q - 1)] == ei_rank
    big = q + k + 1
    key_draws = jnp.where(is_dup, big + pos_q, pos_q)
    key_fills = jnp.where(is_member, big + q + pos_k, q + pos_k)
    all_idx = jnp.concatenate([idx, ei_rank])
    order = jnp.argsort(jnp.concatenate([key_draws, key_fills]))
    return all_idx[order][:q]


@partial(
    jax.jit,
    static_argnames=(
        "q",
        "n_candidates",
        "kernel",
        "acq",
        "fit_steps",
        "local_frac",
        "local_sigma",
        "beta",
        "trust_region",
        "tr_perturb_dims",
        "y_transform",
        "fixed_tail_cols",
        "mesh",
    ),
)
def _suggest_step(
    key,
    x,
    y,
    mask,
    best_x,
    warm_hypers,
    tr_length=None,  # required (traced scalar) when trust_region=True
    *,
    q,
    n_candidates,
    kernel,
    acq,
    fit_steps,
    local_frac,
    local_sigma,
    beta,
    trust_region=False,
    tr_perturb_dims=20,
    y_transform="none",
    fixed_tail_cols=0,
    mesh=None,
):
    """The whole GP-BO suggest round as ONE compiled computation.

    ``y_transform="copula"`` rank-Gaussianizes the masked targets in-jit
    (``fit_gp`` applies ``masked_copula_transform``); ``y`` arrives RAW, so
    the device-resident buffers feed this step directly with no per-round
    host transform or y re-upload.  The transform is monotone, so every
    rank-based consumer below (elite covariance, EI incumbent) is
    unaffected by reading raw ``y``.

    ``fixed_tail_cols``: the last k input columns are context, not free
    variables — candidates are generated over the leading columns only and
    the tail is pinned to 1.0 when scoring (multi-fidelity BO pins the
    fidelity column to max budget so selection optimizes the predicted
    FULL-budget value).  Returned rows include only the free columns.
    """
    if mesh is not None:
        # Pin the fit side REPLICATED before anything touches it: sharding
        # propagation from the candidate constraint below would otherwise
        # partition the O(n^2) GP fit too, re-ordering its reductions — the
        # fit is tiny next to the O(m·F) candidate work, and replicating it
        # keeps every device computing the bit-identical single-device fit
        # (the sharded gate's bit-match-or-fail contract).
        rep = replicated(mesh)
        x, y, mask, best_x, warm_hypers = jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(a, rep),
            (x, y, mask, best_x, warm_hypers),
        )
    state = fit_gp(
        x, y, mask, kind=kernel, n_steps=fit_steps, init=warm_hypers,
        y_transform=y_transform,
    )
    if mesh is not None:
        state = jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(a, rep), state
        )
    k_cand, k_acq = jax.random.split(key)
    d_free = x.shape[1] - fixed_tail_cols
    if trust_region:
        cov_chol, elite_mu = _topk_cov_chol(
            x[:, :d_free], y, mask, d_free, k=min(64, x.shape[0])
        )
        lengthscales = jnp.exp(state.hypers.log_lengthscales[:d_free])
        free_candidates = _make_tr_candidates(
            k_cand,
            n_candidates,
            d_free,
            best_x[:d_free],
            tr_length,
            lengthscales,
            local_frac,
            cov_chol,
            elite_mu,
            perturb_dims=tr_perturb_dims,
        )
        # Gradient-polish a handful of elite-covariance-jittered incumbent
        # copies on the posterior mean and splice them over the pool's tail
        # (keeps the pool size, and with it the candidates-divide-mesh
        # invariant, unchanged) — acquisition still judges them against the
        # random candidates, so exploitation sharpens without another full
        # posterior pass over the pool.
        k_polish = jax.random.fold_in(k_cand, 7)
        lb, ub = _tr_box(best_x[:d_free], tr_length, lengthscales)
        # Scale the exploiter count with the batch: at q=512 eight polished
        # points would be a rounding error in the pool.  Clamped to half the
        # pool — a small-n_candidates config must not have the splice eat the
        # whole pool (changing the candidate count breaks the
        # candidates-divide-mesh invariant and select_q's k <= pool assert).
        n_polish = max(1, min(64, max(8, q // 16), n_candidates // 2))
        starts = jnp.clip(
            best_x[None, :d_free]
            + 0.5 * jax.random.normal(k_polish, (n_polish, d_free)) @ cov_chol.T,
            lb,
            ub,
        )
        if mesh is not None:
            # Pin the polish segment REPLICATED on both sides.  Without the
            # pins, the candidate constraint below back-propagates into this
            # tail-of-pool computation and XLA compiles the tiny start
            # matmul and the 30-step descent scan per-partition — with a
            # different float association than the single-device module
            # (measured: the splice rows drift by ulps, which moves
            # suggestion rows AND acq_ei_mean).  Pinned, the segment
            # compiles once, replicated, bit-identical to unsharded.
            rep = replicated(mesh)
            starts = jax.lax.with_sharding_constraint(starts, rep)
        polished = _polish_candidates(
            state, kernel, starts, lb, ub, fixed_tail_cols=fixed_tail_cols
        )
        if mesh is not None:
            polished = jax.lax.with_sharding_constraint(polished, rep)
        free_candidates = jnp.concatenate(
            [free_candidates[:-n_polish], polished], axis=0
        )
    else:
        free_candidates = _make_candidates(
            k_cand, n_candidates, d_free, best_x[:d_free], local_frac, local_sigma
        )
    if mesh is not None:
        # Data-parallel over the candidate axis: XLA's SPMD partitioner
        # splits generation+scoring per shard and inserts the ICI
        # collectives for the cross-candidate argmin/top-k reductions.
        free_candidates = jax.lax.with_sharding_constraint(
            free_candidates, candidate_sharding(mesh)
        )
    if fixed_tail_cols:
        candidates = jnp.concatenate(
            [
                free_candidates,
                jnp.ones(
                    (free_candidates.shape[0], fixed_tail_cols),
                    free_candidates.dtype,
                ),
            ],
            axis=1,
        )
    else:
        candidates = free_candidates
    y_norm = (state.y - state.y_mean) / state.y_std
    if fixed_tail_cols:
        # Candidates are scored at max context (tail pinned to 1), so the EI
        # incumbent must be the best observation AT the top context tier — a
        # lucky low-fidelity value would otherwise be unattainable for every
        # candidate and flatten EI to ~0.
        s_col = x[:, -1]
        s_max = jnp.max(jnp.where(mask > 0, s_col, -jnp.inf))
        top = (mask > 0) & (s_col >= s_max - 1e-6)
        best = jnp.min(jnp.where(top, y_norm, jnp.inf))
    else:
        best = jnp.min(jnp.where(state.mask > 0, y_norm, jnp.inf))
    if acq == "joint_thompson":
        idx = joint_thompson(k_acq, state, candidates, q, kind=kernel)
    else:
        idx = acquire(
            k_acq, state, candidates, q, kind=kernel, acq=acq, best=best, beta=beta
        )
    mean, std = posterior_norm(state, candidates, kind=kernel)
    ei = expected_improvement(mean, std, best)
    ei_rank = select_q(ei, min(4 * q, n_candidates))
    if trust_region:
        # Guarantee one pure-exploitation member per batch: the pool's
        # posterior-mean minimizer (usually a gradient-polished point).
        # Thompson noise rarely selects it, yet it is the single highest
        # expected payoff — CMA-style descent wants it evaluated every round.
        # UNLESS it is already observed: once the box has converged, polish
        # lands on the incumbent bit-for-bit every round, and injecting it
        # again would re-suggest a stored point each batch — the producer
        # then loops on DuplicateKeyError until SampleTimeout (small pools
        # hit this within two rounds).
        exploit_idx = jnp.argmin(mean)
        exploit_cand = jnp.take(free_candidates, exploit_idx, axis=0)
        d2_obs = jnp.sum((x[:, :d_free] - exploit_cand[None, :]) ** 2, axis=1)
        already_observed = jnp.any((d2_obs < 1e-12) & (mask > 0))
        injected = jnp.where(already_observed, idx[0], exploit_idx)
        idx = jnp.concatenate([injected[None], idx])[:q]
    final_idx = _dedup_fill_device(idx, ei_rank, q)
    # Packed per-round health vector (health.DEVICE_HEALTH_FIELDS), built
    # entirely from intermediates this step already computed — a handful of
    # reductions, attached to the returned state so no signature changes
    # and no extra device->host syncs (the vector is read lazily after the
    # q-row transfer already materialized the round).
    ls = jnp.exp(state.hypers.log_lengthscales[:d_free])
    sorted_idx = jnp.sort(final_idx)
    n_unique = 1.0 + jnp.sum((sorted_idx[1:] != sorted_idx[:-1]).astype(ls.dtype))
    ei_stats = ei
    if mesh is not None:
        # Health-only copy of the EI vector, gathered replicated: a mean
        # over the SHARDED axis is per-shard partials + all-reduce, whose
        # float association (and so the last ulp of acq_ei_mean) would vary
        # with the mesh size.  The gather pins the reduction to the
        # single-device association — one all-gather of m floats on the
        # health path, nothing on the selection path.
        ei_stats = jax.lax.with_sharding_constraint(ei, replicated(mesh))
    health = jnp.stack(
        [
            state.mll,
            jnp.min(ls),
            jnp.mean(ls),
            jnp.max(ls),
            jnp.exp(state.hypers.log_noise),
            jnp.max(ei_stats),
            jnp.mean(ei_stats),
            n_unique / q,
        ]
    ).astype(jnp.float32)
    state = state._replace(health=health)
    return jnp.take(free_candidates, final_idx, axis=0), state

