"""Asynchronous pow-2 shape-bucket prewarming for the fused suggest step.

The GP history lives in power-of-2-padded buffers: when the observation
count crosses a bucket boundary (64 -> 65 means the fit shape jumps 64 ->
128), the fused suggest jit sees a new input shape and pays a synchronous
trace+lower+compile — a multi-second dispatch stall in the middle of a run,
exactly the cliff the fused-step design otherwise avoids.

The fix is to compile the NEXT bucket before the history gets there: when
``count`` nears the boundary (``prewarm_fill`` of the current bucket), the
algorithm hands a zero-arg compile closure — a dummy call of the jitted
step at the next bucket's exact shapes and static-arg signature — to a
:class:`BucketPrewarmer`, which runs it on a background daemon thread.
Calling the jitted function itself (rather than AOT ``lower().compile()``,
which would NOT populate the jit call cache) makes the eventual real call a
cache hit.  XLA compilation releases the GIL, so the main thread keeps
producing rounds while the compile runs.

Honest accounting: prewarm compiles are counted under the ``jax.prewarms``
telemetry counter and the ``jax.prewarm.compile`` span — NEVER under
``jax.retraces``, which keeps counting only the synchronous retraces a
suggest call actually paid (the channel the boundary-crossing test and
``orion-tpu info`` read).
"""

import logging
import threading
import time
import weakref

# Module-scope on purpose (cycle-free: history.py imports nothing from this
# package): the bucket planners below AND the serve gateway's coalescer both
# key on pow-2 buckets, and a per-call function-level import was pure
# overhead once a second subsystem started planning buckets.
from orion_tpu.algo.history import _next_pow2
from orion_tpu.analysis.sanitizer import TSAN
from orion_tpu.health import FLIGHT
from orion_tpu.telemetry import TELEMETRY

log = logging.getLogger(__name__)

#: Fraction of the current bucket the history must fill before the next
#: bucket's compile is kicked off (early enough that multi-second compiles
#: finish before the crossing, late enough not to warm buckets short runs
#: never reach).
DEFAULT_PREWARM_FILL = 0.75

# Process-wide prewarm activity, sampled by the retrace detector in
# run_suggest_step_arrays (as a fallback when no per-instance prewarmer is
# passed): a jit-cache growth observed in a window where this count moved
# came from a background prewarm landing, not from a synchronous retrace
# the suggest paid.  The compile is synchronous inside the prewarm's
# jitted dummy call and this bookkeeping follows within microseconds (the
# dummy's async execution is NOT waited on), so the delta tightly brackets
# the cache insert.
_completed_lock = threading.Lock()
_completed_count = 0


def completed_prewarm_count():
    """Monotonic count of finished prewarm compile attempts (success or
    failure — either may have inserted a jit-cache entry)."""
    with _completed_lock:
        TSAN.read("prewarm._completed_count")
        return _completed_count


# Live-prewarmer registry (weak) feeding the device-memory sampler
# (orion_tpu.devmem): the prewarm INVENTORY — how many distinct signatures
# have been launched across every live prewarmer, next to the process-wide
# completed count.
_prewarmers_lock = threading.Lock()
_prewarmers = weakref.WeakSet()


def prewarm_inventory():
    """``{"started", "completed"}``: distinct signatures launched across
    every live :class:`BucketPrewarmer`, and compiles finished
    process-wide."""
    with _prewarmers_lock:
        live = list(_prewarmers)
    return {
        "started": sum(p.started_count() for p in live),
        "completed": completed_prewarm_count(),
    }


def _note_prewarm_completed():
    global _completed_count
    with _completed_lock:
        TSAN.write("prewarm._completed_count")
        _completed_count += 1


def plan_next_bucket(count, *, floor, fill=DEFAULT_PREWARM_FILL, batch=0,
                     next_pow2=_next_pow2):
    """The bucket worth prewarming for a history at ``count`` rows, or None.

    Two triggers, whichever fires first:

    - **batch anticipation**: if one more observe of the size just seen
      (``batch``) would cross the current bucket, warm the bucket that
      observe LANDS in (``next_pow2(count + batch)`` — possibly several
      buckets ahead: a q=1024 round at bucket 2048 jumps straight to
      4096).  Without this, any batch larger than ``(1-fill) * bucket``
      steps over the fill window and the crossing pays the compile anyway.
    - **fill**: the current bucket is at least ``fill`` full — covers
      drifting/small arrival sizes.

    Pure planning — callers decide which jit signature that shape feeds
    (full-history vs local-subset paths differ; a path whose fit shape is
    pinned, like the subset pad, has nothing to prewarm at history
    boundaries)."""
    if count <= 0:
        return None
    m = next_pow2(count, floor=floor)
    if batch and count + batch > m:
        return next_pow2(count + batch, floor=floor)
    if count < fill * m:
        return None
    return 2 * m


def plan_fused_step_bucket(count, *, floor, fill=DEFAULT_PREWARM_FILL,
                           batch=0, trust_region=False, tr_local_m=None):
    """Target fit shape for the GP algorithms' fused suggest step, or None.

    Folds in the local-subset switch: once the history is past
    ``tr_local_m`` the FUSED STEP's fit shape is pinned at
    ``next_pow2(tr_local_m)`` — no fused-step boundary left to warm (the
    small local-subset gather jit still re-buckets with the history; the
    trigger warms it separately).  A crossing that LANDS past the switch
    targets the subset pad instead of the raw next bucket — unless that
    pad is at most the current fit shape, which every suggest since the
    last boundary already compiled: warming it again would be a no-op
    that still books a ``jax.prewarms`` count."""
    if trust_region and tr_local_m is not None and count > tr_local_m:
        return None
    target = plan_next_bucket(count, floor=floor, fill=fill, batch=batch)
    if target is None:
        return None
    if trust_region and tr_local_m is not None and target > tr_local_m:
        target = _next_pow2(tr_local_m, floor=floor)
        if target <= _next_pow2(count, floor=floor):
            return None  # the current fit shape — already compiled
    return target


class BucketPrewarmer:
    """Deduplicated background compile runner.

    One instance per algorithm (shared by-ref with its naive copies — the
    jit cache is process-wide, so warming once covers every clone).  Each
    distinct signature key compiles at most once; failures are logged and
    swallowed (a failed prewarm just means the boundary pays the compile it
    would have paid anyway)."""

    def __init__(self):
        self._started = set()
        self._threads = {}
        self._lock = threading.Lock()
        self._completed = 0
        with _prewarmers_lock:
            _prewarmers.add(self)

    def maybe_start(self, key, compile_fn):
        """Run ``compile_fn`` on a background thread unless ``key`` was
        already started.  Returns True when a new prewarm was launched."""
        with self._lock:
            TSAN.write("BucketPrewarmer._threads", self)
            if key in self._started:
                return False
            self._started.add(key)
            thread = threading.Thread(
                target=self._run,
                args=(key, compile_fn),
                name="orion-tpu-prewarm",
                daemon=True,
            )
            self._threads[key] = thread
        thread.start()
        return True

    def _run(self, key, compile_fn):
        t0 = time.perf_counter()
        try:
            compile_fn()
        except Exception:  # never raise out of a daemon thread
            log.debug("prewarm compile failed for %r", key, exc_info=True)
            return
        finally:
            _note_prewarm_completed()
            with self._lock:
                TSAN.write("BucketPrewarmer._threads", self)
                self._completed += 1
        TELEMETRY.count("jax.prewarms")
        TELEMETRY.record_span("jax.prewarm.compile", start=t0)
        # Flight event (orion_tpu.health): a background compile landing on
        # the timeline explains bucket crossings in a post-mortem.  Guarded
        # — the args dict must not allocate when the recorder is off.
        if FLIGHT.enabled:
            FLIGHT.record("jax.prewarm", args={"key": str(key)})

    def started_count(self):
        """Distinct signatures this instance has launched — the prewarm
        INVENTORY leg of :func:`prewarm_inventory`."""
        with self._lock:
            TSAN.read("BucketPrewarmer._threads", self)
            return len(self._started)

    def completed_count(self):
        """Prewarm attempts THIS instance finished (success or failure) —
        the per-algorithm twin of :func:`completed_prewarm_count`, so the
        retrace detector can scope its discount to the one prewarmer whose
        compiles share the caller's jit signatures instead of being
        blinded by unrelated instances' warms."""
        with self._lock:
            TSAN.read("BucketPrewarmer._threads", self)
            return self._completed

    def wait(self, timeout=None):
        """Join every launched prewarm thread (tests / deterministic
        boundary crossings).  ``timeout`` is per-thread.  The thread map is
        snapshotted under the lock — iterating it bare races maybe_start
        from another thread (found by the runtime sanitizer; joining
        happens outside the lock so a slow compile never blocks new
        prewarm launches)."""
        for thread in self._thread_snapshot():
            thread.join(timeout)

    @property
    def in_flight(self):
        """True while any prewarm compile is still running."""
        return any(t.is_alive() for t in self._thread_snapshot())

    def _thread_snapshot(self):
        with self._lock:
            TSAN.read("BucketPrewarmer._threads", self)
            return list(self._threads.values())
