"""Shared device-side sampling helpers for algorithms."""

import jax.numpy as jnp
import numpy as np


def reflect_unit(x):
    """Fold values back into [0, 1] by reflection at the boundaries.

    Hard clipping creates an atom at exactly 0.0/1.0 — a gaussian-perturbed
    candidate lands on the same float over and over, which the storage's
    unique trial index rejects until the producer times out.
    """
    r = jnp.mod(jnp.abs(x), 2.0)
    return jnp.where(r > 1.0, 2.0 - r, r)


def masked_copula_transform(y, mask):
    """Rank -> normal-quantile (copula) transform over the masked rows,
    entirely on device — the in-jit twin of ``tpu_bo.copula_transform``.

    Real rows (mask 1) get rank r in first-occurrence order (stable
    argsort, matching the host path's ``kind="stable"``) and map to
    ``ndtri((r + 0.5) / n)``; padded rows sort last (key +inf) and come
    back exactly 0.0, preserving the all-zeros-past-count buffer
    invariant.  Monotone, so the argmin row is preserved.  Running this
    inside the fused suggest step removes the per-round O(n) host
    transform and the (n_pad,) y re-upload — the ranks change globally
    with every observation, but the device already holds y."""
    from jax.scipy.special import ndtri

    n = jnp.maximum(jnp.sum(mask), 1.0)
    keyed = jnp.where(mask > 0, y, jnp.inf)
    rank = jnp.argsort(jnp.argsort(keyed))  # jnp.argsort is stable
    q = (rank.astype(jnp.float32) + 0.5) / n
    out = ndtri(jnp.clip(q, 1e-7, 1.0 - 1e-7))
    return jnp.where(mask > 0, out, 0.0).astype(jnp.float32)


def clamp_objectives(objectives, history):
    """Replace non-finite objectives with the worst finite value known.

    Lies may carry inf sentinels before any real completion; model-based
    algorithms need finite targets.  Returns None when nothing finite is
    known at all (caller should skip the batch).
    """
    objectives = np.asarray(objectives)
    finite = np.isfinite(objectives)
    if np.all(finite):
        return objectives
    if not np.any(finite) and history.size == 0:
        return None
    worst = (
        float(np.max(objectives[finite])) if np.any(finite) else float(np.max(history))
    )
    return np.where(finite, objectives, worst)
