"""Shared device-side sampling helpers for algorithms."""

import jax.numpy as jnp
import numpy as np


def reflect_unit(x):
    """Fold values back into [0, 1] by reflection at the boundaries.

    Hard clipping creates an atom at exactly 0.0/1.0 — a gaussian-perturbed
    candidate lands on the same float over and over, which the storage's
    unique trial index rejects until the producer times out.
    """
    r = jnp.mod(jnp.abs(x), 2.0)
    return jnp.where(r > 1.0, 2.0 - r, r)


def clamp_objectives(objectives, history):
    """Replace non-finite objectives with the worst finite value known.

    Lies may carry inf sentinels before any real completion; model-based
    algorithms need finite targets.  Returns None when nothing finite is
    known at all (caller should skip the batch).
    """
    objectives = np.asarray(objectives)
    finite = np.isfinite(objectives)
    if np.all(finite):
        return objectives
    if not np.any(finite) and history.size == 0:
        return None
    worst = (
        float(np.max(objectives[finite])) if np.any(finite) else float(np.max(history))
    )
    return np.where(finite, objectives, worst)
