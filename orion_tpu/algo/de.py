"""Differential evolution on device — rand/1/bin with crowding replacement.

No reference counterpart (Oríon v0.1.7 ships only random search + ASHA; its
plugin docs name evolutionary algorithms as the intended extension family,
cf. reference `docs/src/plugins/algorithms.rst`).  TPU-native take: the
whole proposal batch — base selection, differential mutation with per-vector
F dither, binomial crossover, boundary reflection — is one jitted gather/
arithmetic pass over the resident population, so a q-batch costs one
dispatch regardless of q.

Async contract: canonical DE is generational (propose one trial vector per
member, compare child i against parent i) but the producer delivers
observations in arbitrary dribs and the naive copy injects fantasy lies.
Pairwise parent/child bookkeeping would need every suggestion matched back
to its parent across that boundary; **crowding replacement** (Thomsen 2004)
needs none of it: each arriving observation replaces the NEAREST population
member iff it improves on it.  Any point — own proposal, another worker's,
a lie — integrates through the same rule, and niches are preserved by
construction (a child can only displace its own neighborhood).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.algo.base import BaseAlgorithm, algo_registry
from orion_tpu.algo.sampling import reflect_unit


@partial(jax.jit, static_argnums=(3, 4))
def _de_propose(key, pop, fit, num, mutation, f_lo, f_hi, cr):
    """One q-batch of trial vectors from the resident population.

    Targets cycle through the population from a random offset (num == P
    hits every member exactly once — the classic generation); r1/r2/r3 are
    drawn distinct from the target via the shift trick (an r2 == r3
    collision is rare and harmless: the mutant degenerates to x_r1).
    """
    P, d = pop.shape
    k0, k1, k2, k3, k4, k5, k6 = jax.random.split(key, 7)
    target = (jnp.arange(num) + jax.random.randint(k0, (), 0, P)) % P

    def pick(k):
        r = jax.random.randint(k, (num,), 0, P - 1)
        return r + (r >= target)

    r1, r2, r3 = pick(k1), pick(k2), pick(k3)
    if mutation == "best1":
        base = pop[jnp.argmin(fit)][None, :]
    else:  # rand/1
        base = pop[r1]
    F = jax.random.uniform(k4, (num, 1), minval=f_lo, maxval=f_hi)
    v = base + F * (pop[r2] - pop[r3])
    # Binomial crossover with one forced mutant coordinate per vector.
    mask = jax.random.bernoulli(k5, cr, (num, d))
    jrand = jax.random.randint(k6, (num,), 0, d)
    mask = mask | (jnp.arange(d)[None, :] == jrand[:, None])
    u = jnp.where(mask, v, pop[target])
    return reflect_unit(u)


@algo_registry.register("de")
class DifferentialEvolution(BaseAlgorithm):
    """Differential evolution (rand/1/bin) with crowding replacement.

    Parameters
    ----------
    popsize: population size (default ``min(max(16, 5·d), 128)``).  The
        first ``popsize`` observations seed the population; after that each
        observation competes against its nearest member (crowding).
    f_lo, f_hi: per-vector dither range for the differential weight F
        (Das & Suganthan 2011 recommend dithering over a fixed F).
    cr: binomial crossover rate; high values suit non-separable landscapes.
    mutation: ``"rand1"`` (default, robust) or ``"best1"`` (greedy —
        faster on unimodal landscapes, premature elsewhere).
    tol_pop: declare ``is_done`` when every member sits within this
        distance of the best (collapsed population: all difference vectors
        are ~0, every future mutant repeats the incumbent).
    """

    supports_async_suggest = True

    def __init__(
        self,
        space,
        seed=None,
        popsize=None,
        f_lo=0.5,
        f_hi=1.0,
        cr=0.9,
        mutation="rand1",
        tol_pop=1e-6,
    ):
        d = space.n_cols
        if popsize is None:
            popsize = min(max(16, 5 * d), 128)
        popsize = max(int(popsize), 4)
        if mutation not in ("rand1", "best1"):
            raise ValueError(f"mutation must be 'rand1' or 'best1', got {mutation!r}")
        super().__init__(
            space, seed=seed, popsize=popsize, f_lo=f_lo, f_hi=f_hi, cr=cr,
            mutation=mutation, tol_pop=tol_pop,
        )
        self.popsize = popsize
        self.f_lo = float(f_lo)
        self.f_hi = float(f_hi)
        self.cr = float(cr)
        self.mutation = mutation
        # The population is float32 (ulp ~6e-8 at 0.5) and crowding demands
        # strict improvement, so members freeze a few ulps apart once the
        # objective plateaus — a tolerance below ~1e-6 could never fire;
        # clamp instead of silently dead-ending is_done (cmaes' tol_sigma
        # treatment).
        self.tol_pop = max(float(tol_pop), 1e-6)
        self._pop = np.zeros((popsize, d), dtype=np.float32)
        self._fit = np.zeros((popsize,), dtype=np.float32)
        self._n_filled = 0

    # --- suggestion ---------------------------------------------------------
    def _suggest_cube(self, num):
        if self._n_filled < self.popsize:
            # Population still seeding: propose prior samples.
            return jax.random.uniform(self.next_key(), (int(num), self.space.n_cols))
        return _de_propose(
            self.next_key(),
            jnp.asarray(self._pop),
            jnp.asarray(self._fit),
            int(num),
            self.mutation,
            self.f_lo,
            self.f_hi,
            self.cr,
        )

    # --- observation --------------------------------------------------------
    def observe_arrays(self, cube, objectives, params_list=None, fidelities=None):
        # Drop non-finite rows instead of clamping them (cmaes-style): a
        # clamped inf-sentinel lie would otherwise enter the POPULATION with
        # a fabricated fitness — and unlike cmaes' transient generation
        # buffer, population state persists it indefinitely (with
        # mutation='best1' it could even become the base vector).  An
        # "assume bad" lie can never win a crowding competition, so dropping
        # it is semantics-preserving.
        cube = np.asarray(cube, dtype=np.float32)
        # Filter on the INCOMING (float64) values — casting first would
        # overflow large finite objectives (big-M penalties ~1e39) to inf
        # and silently drop real evaluations; clip the survivors into
        # float32 range instead.
        objectives = np.asarray(objectives, dtype=np.float64)
        finite = np.isfinite(objectives)
        if not finite.all():
            cube, objectives = cube[finite], objectives[finite]
        if objectives.size == 0:
            return
        f32_max = float(np.finfo(np.float32).max)
        objectives = np.clip(objectives, -f32_max, f32_max).astype(np.float32)
        for row, y in zip(cube, objectives):
            if self._n_filled < self.popsize:
                self._pop[self._n_filled] = row
                self._fit[self._n_filled] = y
                self._n_filled += 1
                continue
            # Crowding: compete against the nearest member only.  Sequential
            # on purpose — an accepted replacement changes the neighborhoods
            # later rows in the same batch compete against.
            j = int(np.argmin(((self._pop - row[None, :]) ** 2).sum(axis=1)))
            if y < self._fit[j]:
                self._pop[j] = row
                self._fit[j] = y

    # --- lifecycle ----------------------------------------------------------
    @property
    def is_done(self):
        """Population collapse: every member within ``tol_pop`` of the best
        (all difference vectors ~0, so every future mutant is the incumbent
        — the producer would otherwise grind on duplicate suggestions until
        SampleTimeout, the exhausted-algorithm failure mode)."""
        if self._n_filled < self.popsize:
            return False
        spread = np.abs(self._pop - self._pop[np.argmin(self._fit)][None, :]).max()
        return float(spread) <= self.tol_pop

    # --- state --------------------------------------------------------------
    def state_dict(self):
        out = super().state_dict()
        out["pop"] = self._pop.tolist()
        out["fit"] = self._fit.tolist()
        out["n_filled"] = self._n_filled
        return out

    def set_state(self, state):
        super().set_state(state)
        d = self.space.n_cols
        self._pop = np.asarray(state["pop"], dtype=np.float32).reshape(-1, d)
        self._fit = np.asarray(state["fit"], dtype=np.float32)
        self._n_filled = int(state["n_filled"])
        # The restored arrays ARE the population: a state saved under a
        # different popsize config must not leave self.popsize pointing past
        # (or short of) the actual rows — the seeding phase writes at
        # self._pop[self._n_filled] and would IndexError past a smaller
        # restored population.
        if self._pop.shape[0] != self._fit.shape[0]:
            raise ValueError(
                "inconsistent DE state: pop has "
                f"{self._pop.shape[0]} rows but fit has {self._fit.shape[0]}"
            )
        self.popsize = self._pop.shape[0]
