"""Grid search: deterministic sweep over a cartesian lattice of the space.

No counterpart in the reference v0.1.7 (later Oríon versions add it); the
grid lives in the unit cube so every dimension type (real/int/categorical)
gets an even sweep through the codec's inverse-CDF decode.
"""

import itertools

import numpy as np

from orion_tpu.algo.base import BaseAlgorithm, algo_registry
from orion_tpu.space.dims import Categorical, Integer


@algo_registry.register("grid_search")
class GridSearch(BaseAlgorithm):
    """``n_values`` points per dimension (categoricals: one per category)."""

    # The sweep order never depends on observations, so a speculatively
    # dispatched batch is identical to a synchronous one (the producer
    # overlaps the next round's suggest with trial execution — BASELINE's
    # speculative-dispatch A/B).
    supports_async_suggest = True
    speculation_safe = True

    MAX_GRID = 1_000_000

    def __init__(self, space, n_values=10, seed=None):
        super().__init__(space, seed=seed, n_values=n_values)
        axes = []
        for dim in space:
            if dim.n_cols == 0:
                continue
            for _ in range(dim.n_cols):
                if isinstance(dim, Categorical):
                    k = dim.n_choices
                    axes.append((np.arange(k) + 0.5) / k)
                elif isinstance(dim, Integer):
                    k = min(n_values, int(dim.high - dim.low + 1))
                    axes.append((np.arange(k) + 0.5) / k)
                else:
                    axes.append((np.arange(n_values) + 0.5) / n_values)
        size = int(np.prod([len(a) for a in axes])) if axes else 0
        if size > self.MAX_GRID:
            raise ValueError(
                f"grid of {size} points exceeds MAX_GRID={self.MAX_GRID}; "
                "reduce n_values or the number of dimensions"
            )
        self._grid = np.asarray(list(itertools.product(*axes)), dtype=np.float32)
        self._cursor = 0

    def _suggest_cube(self, num):
        if self._cursor >= len(self._grid):
            return None
        batch = self._grid[self._cursor : self._cursor + num]
        self._cursor += len(batch)
        return batch

    def register_suggestion(self, params):
        """Advance the REAL algorithm's cursor past durably-registered grid
        points — suggestions come from the per-round naive deepcopy, whose
        cursor advance would otherwise be discarded and the producer would
        re-suggest grid[0:pool] forever (DuplicateKeyError -> SampleTimeout)."""
        arrays = self.space.params_to_arrays([params])
        cube = np.asarray(self.space.encode_flat(arrays))[0]
        idx = int(np.argmin(np.sum((self._grid - cube) ** 2, axis=1)))
        self._cursor = max(self._cursor, idx + 1)

    @property
    def is_done(self):
        return self._cursor >= len(self._grid) and self.n_observed >= len(self._grid)

    def state_dict(self):
        out = super().state_dict()
        out["cursor"] = self._cursor
        return out

    def set_state(self, state):
        super().set_state(state)
        self._cursor = state["cursor"]
