"""Device-resident observation history with incremental in-place appends.

The GP algorithms (`tpu_bo`, `asha_bo`) fit on the full observation history
every round.  Re-padding that history on host and re-uploading it with
``jnp.asarray`` per suggest costs O(n) transfer per round — O(n²) cumulative
over an experiment — for rows the device has already seen.  This module
keeps the history in preallocated power-of-2-padded device buffers owned by
the algorithm and appends each observe batch in place with one small
``dynamic_update_slice`` jit whose input buffers are donated (XLA aliases
them, so no copy of the resident history is made).  Only the new rows cross
the host→device boundary.

Invariants (what makes the incremental path bit-equal to a full re-upload):

- Buffer capacity is a power of 2 (floor 64, the GP pad floor) and only
  grows; every row at index >= ``count`` is exactly 0.0 in x and y with
  mask 0.0 — identical to the zero-padding a host re-pad produces.
- :meth:`fit_view` returns views sliced to ``_next_pow2(count)``, the exact
  shape the host re-upload path pads to, so the fused suggest jit sees the
  same shapes, same values, same jit bucket — and therefore returns
  bit-identical suggestions (the regression test in
  ``tests/unit/test_device_history.py`` pins this across a pow-2 growth
  boundary).

Naive-copy discipline (the producer deepcopies the algorithm every round to
fantasize lies): donation would invalidate a buffer the clone still
references, so ``__deepcopy__`` hands the clone the same buffers and marks
BOTH sides copy-on-write — the next append on either side runs the
non-donating twin of the update jit (the other side's view survives), after
which the appender exclusively owns its fresh buffers and donation resumes.
A bench- or client-driven algorithm that is never cloned donates on every
append.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _next_pow2(n, floor=64):
    out = floor
    while out < n:
        out *= 2
    return out


#: Append batches are padded to a power of 2 (floor 8) so the update jit
#: compiles once per (capacity, batch-bucket) pair instead of once per
#: distinct batch size (the producer's retry loop shrinks its request).
_BATCH_FLOOR = 8


def _donation_supported():
    # CPU ignores buffer donation and warns per compile; skip it there (the
    # tests run JAX_PLATFORMS=cpu).  Accelerator backends — including this
    # image's remote tunnel — take the alias.
    return jax.default_backend() != "cpu"


def _append_impl(x, y, mask, rows, ys, mvals, n):
    x = jax.lax.dynamic_update_slice(x, rows, (n, jnp.int32(0)))
    y = jax.lax.dynamic_update_slice(y, ys, (n,))
    mask = jax.lax.dynamic_update_slice(mask, mvals, (n,))
    return x, y, mask


# Donating twin: in-place update of the resident buffers (no O(capacity)
# copy per observe).  Copying twin: used under copy-on-write and on CPU.
_append_donating = jax.jit(_append_impl, donate_argnums=(0, 1, 2))
_append_copying = jax.jit(_append_impl)


@partial(jax.jit, static_argnames=("new_cap",))
def _grow(x, y, mask, new_cap):
    pad = new_cap - x.shape[0]
    return (
        jnp.pad(x, ((0, pad), (0, 0))),
        jnp.pad(y, (0, pad)),
        jnp.pad(mask, (0, pad)),
    )


class DeviceHistory:
    """Pow-2-padded device buffers ``(x, y, mask)`` for one observation set.

    ``append`` is the only mutator; ``fit_view`` is the only reader the hot
    path needs.  ``count`` is the number of real rows; everything past it is
    zero (see module docstring for why that exact invariant matters).
    """

    def __init__(self, n_cols, floor=64):
        self.n_cols = int(n_cols)
        self.floor = int(floor)
        self.count = 0
        self.cap = 0
        self._x = None
        self._y = None
        self._mask = None
        # True while the buffers may be visible to another DeviceHistory
        # (a naive-copy clone): the next append must not donate them.
        self._cow = False

    @classmethod
    def from_host(cls, x, y, floor=64):
        """Bulk-build from host mirrors (state restore / resume)."""
        x = np.asarray(x, dtype=np.float32)
        hist = cls(x.shape[1] if x.ndim == 2 else 0, floor=floor)
        if x.shape[0]:
            hist.append(x, np.asarray(y, dtype=np.float32))
        return hist

    def __deepcopy__(self, memo):
        clone = DeviceHistory.__new__(DeviceHistory)
        clone.__dict__.update(self.__dict__)
        # Both sides now share the device buffers: whichever appends first
        # must copy-on-write so the other side's rows survive.
        clone._cow = True
        self._cow = True
        memo[id(self)] = clone
        return clone

    def _ensure_capacity(self, need):
        new_cap = _next_pow2(need, floor=self.floor)
        if self._x is None:
            self._x = jnp.zeros((new_cap, self.n_cols), jnp.float32)
            self._y = jnp.zeros((new_cap,), jnp.float32)
            self._mask = jnp.zeros((new_cap,), jnp.float32)
        elif new_cap > self.cap:
            self._x, self._y, self._mask = _grow(
                self._x, self._y, self._mask, new_cap=new_cap
            )
        else:
            return
        self.cap = new_cap
        self._cow = False  # fresh buffers are exclusively ours

    def append(self, rows, ys):
        """Write an observe batch at ``count``; one device dispatch.

        The batch is zero-padded to a pow-2 bucket before upload; the
        padding rows land in the region past ``count`` with mask 0.0,
        preserving the all-zeros-past-count invariant.
        """
        rows = np.asarray(rows, dtype=np.float32).reshape(-1, self.n_cols)
        ys = np.asarray(ys, dtype=np.float32).reshape(-1)
        b = rows.shape[0]
        if b == 0:
            return
        b_pad = _next_pow2(b, floor=_BATCH_FLOOR)
        mvals = np.zeros((b_pad,), dtype=np.float32)
        mvals[:b] = 1.0
        if b_pad != b:
            rows = np.concatenate(
                [rows, np.zeros((b_pad - b, self.n_cols), np.float32)]
            )
            ys = np.concatenate([ys, np.zeros((b_pad - b,), np.float32)])
        # Capacity must cover the PADDED write: dynamic_update_slice clamps
        # out-of-range starts, which would silently shift the write onto
        # valid rows.
        self._ensure_capacity(self.count + b_pad)
        fn = (
            _append_donating
            if not self._cow and _donation_supported()
            else _append_copying
        )
        self._x, self._y, self._mask = fn(
            self._x,
            self._y,
            self._mask,
            jnp.asarray(rows),
            jnp.asarray(ys),
            jnp.asarray(mvals),
            jnp.int32(self.count),
        )
        self._cow = False
        self.count += b

    def fit_view(self):
        """``(x, y, mask, m)`` sliced to ``m = _next_pow2(count)`` — the
        exact padded shape the host re-upload path produces, regardless of
        how far capacity has grown ahead (growth is batch-bucket eager)."""
        m = _next_pow2(max(self.count, 1), floor=self.floor)
        if m == self.cap:
            return self._x, self._y, self._mask, m
        return self._x[:m], self._y[:m], self._mask[:m], m
