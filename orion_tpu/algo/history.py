"""Observation history buffers: device-resident pow-2 buffers with
incremental in-place appends (:class:`DeviceHistory`) and their
amortized-growth host twin (:class:`HostHistory`).

The GP algorithms (`tpu_bo`, `asha_bo`) fit on the full observation history
every round.  Re-padding that history on host and re-uploading it with
``jnp.asarray`` per suggest costs O(n) transfer per round — O(n²) cumulative
over an experiment — for rows the device has already seen.  This module
keeps the history in preallocated power-of-2-padded device buffers owned by
the algorithm and appends each observe batch in place with one small
``dynamic_update_slice`` jit whose input buffers are donated (XLA aliases
them, so no copy of the resident history is made).  Only the new rows cross
the host→device boundary.

Invariants (what makes the incremental path bit-equal to a full re-upload):

- Buffer capacity is a power of 2 (floor 64, the GP pad floor) and only
  grows; every row at index >= ``count`` is exactly 0.0 in x and y with
  mask 0.0 — identical to the zero-padding a host re-pad produces.
- :meth:`fit_view` returns views sliced to ``_next_pow2(count)``, the exact
  shape the host re-upload path pads to, so the fused suggest jit sees the
  same shapes, same values, same jit bucket — and therefore returns
  bit-identical suggestions (the regression test in
  ``tests/unit/test_device_history.py`` pins this across a pow-2 growth
  boundary).

Naive-copy discipline (the producer deepcopies the algorithm every round to
fantasize lies): donation would invalidate a buffer the clone still
references, so ``__deepcopy__`` hands the clone the same buffers and marks
BOTH sides copy-on-write — the next append on either side runs the
non-donating twin of the update jit (the other side's view survives), after
which the appender exclusively owns its fresh buffers and donation resumes.
A bench- or client-driven algorithm that is never cloned donates on every
append.
"""

import threading
import time
import weakref
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.compiler_plane import (
    COMPILE_REGISTRY,
    jit_cache_size,
    signature_fields,
)
from orion_tpu.telemetry import TELEMETRY


def _next_pow2(n, floor=64):
    out = floor
    while out < n:
        out *= 2
    return out


# --- memory accounting -------------------------------------------------------
# Live-instance registries (weak — registration must never extend a
# history's lifetime) feeding the device-memory sampler
# (orion_tpu.devmem): per-pow-2-bucket resident device bytes and the host
# mirror total.  Clones made by __deepcopy__ share buffers with their
# source and are deliberately NOT registered (they bypass __init__), so
# shared buffers are counted once.
_registry_lock = threading.Lock()
_device_histories = weakref.WeakSet()
_host_histories = weakref.WeakSet()


def history_memory_stats():
    """Resident observation-history bytes, introspected from every live
    (non-clone) history instance: ``device_buckets`` maps pow-2 capacity
    -> total device bytes at that bucket, ``device_bytes``/``host_bytes``
    the totals, ``device_count`` live DeviceHistory instances."""
    with _registry_lock:
        device = list(_device_histories)
        host = list(_host_histories)
    buckets = {}
    device_bytes = 0
    for hist in device:
        if not hist.cap or hist._x is None:
            continue
        nbytes = 0
        for buf in (hist._x, hist._y, hist._mask):
            try:
                nbytes += int(buf.nbytes)
            except Exception:  # pragma: no cover - deleted buffer mid-walk
                pass
        buckets[hist.cap] = buckets.get(hist.cap, 0) + nbytes
        device_bytes += nbytes
    host_bytes = sum(
        int(h._x.nbytes) + int(h._y.nbytes) for h in host
    )
    return {
        "device_buckets": buckets,
        "device_bytes": device_bytes,
        "host_bytes": host_bytes,
        "device_count": len(device),
    }


#: Append batches are padded to a power of 2 (floor 8) so the update jit
#: compiles once per (capacity, batch-bucket) pair instead of once per
#: distinct batch size (the producer's retry loop shrinks its request).
_BATCH_FLOOR = 8


def _donation_supported():
    # CPU ignores buffer donation and warns per compile; skip it there (the
    # tests run JAX_PLATFORMS=cpu).  Accelerator backends — including this
    # image's remote tunnel — take the alias.
    return jax.default_backend() != "cpu"


@partial(jax.jit, static_argnames=("m", "m_pad", "dist_cols"))
def _local_subset(x, y, mask, center, m, m_pad, dist_cols):
    """Gather the ``m`` nearest real rows to ``center`` (squared euclidean
    over the leading ``dist_cols`` columns), padded to ``m_pad`` — the
    device twin of the old host ``np.argpartition`` local-GP selection.
    Ties break by lowest index (``top_k``), deterministically."""
    d2 = jnp.sum((x[:, :dist_cols] - center[None, :dist_cols]) ** 2, axis=1)
    d2 = jnp.where(mask > 0, d2, jnp.inf)
    _, idx = jax.lax.top_k(-d2, m)
    xs = jnp.take(x, idx, axis=0)
    ys = jnp.take(y, idx)
    ms = jnp.ones((m,), x.dtype)
    if m_pad > m:
        xs = jnp.pad(xs, ((0, m_pad - m), (0, 0)))
        ys = jnp.pad(ys, (0, m_pad - m))
        ms = jnp.pad(ms, (0, m_pad - m))
    return xs, ys, ms


def prewarm_local_subset(m_hist, n_cols, m, dist_cols, floor=64):
    """Compile the device local-subset gather for the ``(m_hist, n_cols)``
    history bucket by calling it on zero dummies (populates the jit
    cache).  In the local-TR regime the fused step's fit shape is pinned,
    but this gather still re-buckets with the history — without a warm it
    would pay a (small) synchronous compile at every pow-2 growth."""
    x = jnp.zeros((int(m_hist), int(n_cols)), jnp.float32)
    # No block_until_ready — the compile (and with it the jit-cache
    # insert) completes synchronously before the call returns; see
    # tpu_bo.prewarm_suggest_step.
    _local_subset(
        x,
        x[:, 0],
        x[:, 0],
        jnp.zeros((int(n_cols),), jnp.float32),
        m=int(m),
        m_pad=_next_pow2(int(m), floor=floor),
        dist_cols=int(dist_cols),
    )


def _append_impl(x, y, mask, rows, ys, mvals, n):
    x = jax.lax.dynamic_update_slice(x, rows, (n, jnp.int32(0)))
    y = jax.lax.dynamic_update_slice(y, ys, (n,))
    mask = jax.lax.dynamic_update_slice(mask, mvals, (n,))
    return x, y, mask


# Donating twin: in-place update of the resident buffers (no O(capacity)
# copy per observe).  Copying twin: used under copy-on-write and on CPU.
_append_donating = jax.jit(_append_impl, donate_argnums=(0, 1, 2))
_append_copying = jax.jit(_append_impl)


@partial(jax.jit, static_argnames=("new_cap",))
def _grow(x, y, mask, new_cap):
    pad = new_cap - x.shape[0]
    return (
        jnp.pad(x, ((0, pad), (0, 0))),
        jnp.pad(y, (0, pad)),
        jnp.pad(mask, (0, pad)),
    )


class DeviceHistory:
    """Pow-2-padded device buffers ``(x, y, mask)`` for one observation set.

    ``append`` is the only mutator; ``fit_view`` is the only reader the hot
    path needs.  ``count`` is the number of real rows; everything past it is
    zero (see module docstring for why that exact invariant matters).
    """

    def __init__(self, n_cols, floor=64):
        self.n_cols = int(n_cols)
        self.floor = int(floor)
        self.count = 0
        self.cap = 0
        self._x = None
        self._y = None
        self._mask = None
        # True while the buffers may be visible to another DeviceHistory
        # (a naive-copy clone): the next append must not donate them.
        self._cow = False
        with _registry_lock:
            _device_histories.add(self)

    @classmethod
    def from_host(cls, x, y, floor=64):
        """Bulk-build from host mirrors (state restore / resume)."""
        x = np.asarray(x, dtype=np.float32)
        hist = cls(x.shape[1] if x.ndim == 2 else 0, floor=floor)
        if x.shape[0]:
            hist.append(x, np.asarray(y, dtype=np.float32))
        return hist

    def __deepcopy__(self, memo):
        clone = DeviceHistory.__new__(DeviceHistory)
        clone.__dict__.update(self.__dict__)
        # Both sides now share the device buffers: whichever appends first
        # must copy-on-write so the other side's rows survive.
        clone._cow = True
        self._cow = True
        memo[id(self)] = clone
        return clone

    def _ensure_capacity(self, need):
        new_cap = _next_pow2(need, floor=self.floor)
        if self._x is None:
            self._x = jnp.zeros((new_cap, self.n_cols), jnp.float32)
            self._y = jnp.zeros((new_cap,), jnp.float32)
            self._mask = jnp.zeros((new_cap,), jnp.float32)
        elif new_cap > self.cap:
            self._x, self._y, self._mask = _grow(
                self._x, self._y, self._mask, new_cap=new_cap
            )
        else:
            return
        self.cap = new_cap
        self._cow = False  # fresh buffers are exclusively ours

    def append(self, rows, ys):
        """Write an observe batch at ``count``; one device dispatch.

        The batch is zero-padded to a pow-2 bucket before upload; the
        padding rows land in the region past ``count`` with mask 0.0,
        preserving the all-zeros-past-count invariant.
        """
        rows = np.asarray(rows, dtype=np.float32).reshape(-1, self.n_cols)
        ys = np.asarray(ys, dtype=np.float32).reshape(-1)
        b = rows.shape[0]
        if b == 0:
            return
        b_pad = _next_pow2(b, floor=_BATCH_FLOOR)
        mvals = np.zeros((b_pad,), dtype=np.float32)
        mvals[:b] = 1.0
        if b_pad != b:
            rows = np.concatenate(
                [rows, np.zeros((b_pad - b, self.n_cols), np.float32)]
            )
            ys = np.concatenate([ys, np.zeros((b_pad - b,), np.float32)])
        # Capacity must cover the PADDED write: dynamic_update_slice clamps
        # out-of-range starts, which would silently shift the write onto
        # valid rows.
        self._ensure_capacity(self.count + b_pad)
        donated = not self._cow and _donation_supported()
        fn = _append_donating if donated else _append_copying
        # Donation-hit accounting (orion_tpu.devmem): how often the append
        # aliased the resident buffers vs paid an O(capacity) copy (CoW
        # after a naive clone, or a CPU backend).  Constant names, one
        # enabled check — hot-path clean.
        TELEMETRY.count(
            "history.appends.donated" if donated else "history.appends.copied"
        )
        # Compiler-plane bracket: the append twins compile once per
        # (capacity bucket, batch bucket, donation mode) — cache growth
        # during the call books a plain `append`-family compile.  NOT a
        # retrace: bucket crossings legitimately compile a fresh append jit
        # (no prewarm covers it by design — the compile is milliseconds,
        # far under the fused step's), and counting it as `jax.retraces`
        # would fail the bench's retraces_after_warm == 0 gate for a stall
        # the suggest path never paid.
        tel_t0 = tel_before = None
        if TELEMETRY.enabled:
            tel_before = jit_cache_size(fn)
            tel_t0 = time.perf_counter()
        self._x, self._y, self._mask = fn(
            self._x,
            self._y,
            self._mask,
            jnp.asarray(rows),
            jnp.asarray(ys),
            jnp.asarray(mvals),
            jnp.int32(self.count),
        )
        if tel_t0 is not None:
            after = jit_cache_size(fn)
            if tel_before is not None and after is not None and after > tel_before:
                COMPILE_REGISTRY.record_compile(
                    "append",
                    signature_fields(
                        (self.cap, self.n_cols),
                        {"donated": donated, "batch": b_pad},
                    ),
                    seconds=time.perf_counter() - tel_t0,
                )
        self._cow = False
        self.count += b

    def fit_view(self):
        """``(x, y, mask, m)`` sliced to ``m = _next_pow2(count)`` — the
        exact padded shape the host re-upload path produces, regardless of
        how far capacity has grown ahead (growth is batch-bucket eager)."""
        m = _next_pow2(max(self.count, 1), floor=self.floor)
        if m == self.cap:
            return self._x, self._y, self._mask, m
        return self._x[:m], self._y[:m], self._mask[:m], m

    def local_view(self, center, m, dist_cols=None):
        """``(x, y, mask, m_pad)`` of the ``m`` rows nearest to ``center``
        (x-distance over the leading ``dist_cols`` columns; default all),
        gathered ON DEVICE from the resident buffers and padded to
        ``m_pad = _next_pow2(m)`` — the local-GP (TuRBO subset) fit set
        without the O(n·d) host distance scan, host gather, or re-upload
        the old ``np.argpartition`` path paid per suggest.  Only ``center``
        (one row) crosses the boundary.  Requires ``count >= m``."""
        m = int(m)
        x, y, mask, _ = self.fit_view()
        xs, ys, ms = _local_subset(
            x,
            y,
            mask,
            jnp.asarray(np.asarray(center, dtype=np.float32)),
            m=m,
            m_pad=_next_pow2(m, floor=self.floor),
            dist_cols=int(dist_cols) if dist_cols is not None else self.n_cols,
        )
        return xs, ys, ms, _next_pow2(m, floor=self.floor)


class HostHistory:
    """Amortized-growth host mirrors ``(x, y)`` with O(batch) appends.

    The old mirrors were rebuilt by ``np.concatenate`` per observe — an
    O(n) copy per round, O(n²) cumulative.  This keeps capacity-doubling
    numpy buffers written in place at ``count``, so a steady-state observe
    costs O(batch) host work; ``x``/``y`` are zero-copy views sliced to
    ``count`` (bit-identical to what the concatenate path held — pinned in
    ``tests/unit/test_host_history.py``).

    The incumbent is tracked incrementally: ``best_idx``/``best_y`` are
    the FIRST-occurrence argmin/min over the history (exactly what
    ``np.argmin`` returns), updated in O(batch) per append — no O(n)
    argmin scan per suggest.

    Naive-copy discipline mirrors :class:`DeviceHistory`: ``__deepcopy__``
    shares the buffers and marks both sides copy-on-write, so a lie
    clone's fantasy rows can never clobber (or be clobbered by) the real
    history — the first append on either side after a clone copies its
    rows into fresh exclusively-owned buffers (one memcpy, the same cost
    the old concatenate paid every round)."""

    def __init__(self, n_cols, floor=64):
        self.n_cols = int(n_cols)
        self.floor = max(int(floor), 1)
        self.count = 0
        self._x = np.zeros((self.floor, self.n_cols), dtype=np.float32)
        self._y = np.zeros((self.floor,), dtype=np.float32)
        self._cow = False
        self.best_idx = -1
        self.best_y = np.inf
        with _registry_lock:
            _host_histories.add(self)

    @classmethod
    def from_host(cls, x, y, floor=64):
        """Bulk-build from materialized arrays (state restore / resume)."""
        x = np.asarray(x, dtype=np.float32)
        hist = cls(x.shape[1] if x.ndim == 2 else 0, floor=floor)
        if x.shape[0]:
            hist.append(x, np.asarray(y, dtype=np.float32))
        return hist

    @property
    def x(self):
        """(count, n_cols) view — rows [:count] are never mutated in place."""
        return self._x[: self.count]

    @property
    def y(self):
        return self._y[: self.count]

    def __deepcopy__(self, memo):
        clone = HostHistory.__new__(HostHistory)
        clone.__dict__.update(self.__dict__)
        clone._cow = True
        self._cow = True
        memo[id(self)] = clone
        return clone

    def _own_with_capacity(self, need):
        """Exclusively-owned buffers covering ``need`` rows (grow and/or
        copy-on-write in one memcpy)."""
        cap = self._x.shape[0]
        new_cap = _next_pow2(need, floor=cap)  # cap is always a pow-2
        if new_cap == cap and not self._cow:
            return
        x = np.zeros((new_cap, self.n_cols), dtype=np.float32)
        y = np.zeros((new_cap,), dtype=np.float32)
        x[: self.count] = self._x[: self.count]
        y[: self.count] = self._y[: self.count]
        self._x, self._y = x, y
        self._cow = False

    def append(self, rows, ys):
        rows = np.asarray(rows, dtype=np.float32).reshape(-1, self.n_cols)
        ys = np.asarray(ys, dtype=np.float32).reshape(-1)
        b = rows.shape[0]
        if b == 0:
            return
        self._own_with_capacity(self.count + b)
        self._x[self.count : self.count + b] = rows
        self._y[self.count : self.count + b] = ys
        batch_arg = int(np.argmin(ys))
        # Strict <: ties keep the earliest index, matching np.argmin over
        # the full concatenated history.
        if float(ys[batch_arg]) < self.best_y:
            self.best_y = float(ys[batch_arg])
            self.best_idx = self.count + batch_arg
        self.count += b
