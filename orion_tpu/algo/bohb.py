"""BOHB — per-budget TPE model under Hyperband bracket scheduling.

No reference counterpart (Oríon v0.1.7 ships ASHA only,
`src/orion/algo/asha.py`); this composes the two families the framework
already has the TPU machinery for: Hyperband's bracket/rung host logic and
TPE's jitted KDE-ratio suggestion (`orion_tpu.algo.tpe._tpe_suggest` — one
(m, n) pairwise-kernel matmul per density).  Classic recipe (Falkner et al.
2018): keep observations per budget tier, model with the HIGHEST tier that
has enough points (high-fidelity data is scarce but trustworthy), fall back
to random until any tier qualifies.
"""

import numpy as np

from orion_tpu.algo.base import algo_registry
from orion_tpu.algo.hyperband import Hyperband
from orion_tpu.algo.sampling import clamp_objectives
from orion_tpu.algo.tpe import _tpe_suggest, good_bad_split  # shared TPE core
from orion_tpu.parallel import device_mesh

import jax.numpy as jnp


@algo_registry.register("bohb")
class BOHB(Hyperband):
    """Hyperband scheduling + TPE sampling from the highest informative budget.

    Parameters beyond Hyperband's: ``gamma`` (good/bad split quantile),
    ``n_candidates`` (KDE-ratio candidate pool per suggest round), and
    ``min_points`` (observations a budget tier needs before it can be
    modeled; default ``dims + 2``).
    """

    # Unlike plain ASHA/Hyperband, observe() feeds cube rows to the KDE tiers.
    uses_observe_cube = True

    def __init__(
        self,
        space,
        seed=None,
        num_rungs=None,
        reduction_factor=None,
        gamma=0.25,
        n_candidates=1024,
        min_points=None,
        bw_factor=1.0,
        n_devices=None,
        use_mesh=False,
    ):
        super().__init__(
            space, seed=seed, num_rungs=num_rungs, reduction_factor=reduction_factor
        )
        d = space.n_cols
        self.gamma = float(gamma)
        self.n_candidates = int(n_candidates)
        self.min_points = int(min_points) if min_points is not None else d + 2
        self.bw_factor = float(bw_factor)
        self._params.update(
            gamma=self.gamma, n_candidates=self.n_candidates,
            min_points=self.min_points, bw_factor=self.bw_factor,
        )
        # Candidate-axis SPMD for the KDE-ratio matmuls (same mesh semantics
        # as tpu_bo/asha_bo; BASELINE config #5's q=4096 scaling story).
        self.use_mesh = use_mesh
        self._mesh = device_mesh(n_devices) if use_mesh else None
        # budget tier -> (x (n, d) unit-cube rows, y (n,)) observation arrays.
        self._tier_x = {}
        self._tier_y = {}

    # Naive-copy sharing (base __deepcopy__): the per-tier observation
    # arrays are append-only; the dicts holding them are shallow-copied so
    # the clone's key inserts don't leak back.  The mesh handle is not
    # copyable.
    _share_by_ref = ("space", "_mesh")
    _share_dicts = ("_tier_x", "_tier_y")

    # --- observation --------------------------------------------------------
    def observe(self, params_list, results, cube=None):
        super().observe(params_list, results)  # rung/promotion bookkeeping
        by_tier = {}
        for i, (params, result) in enumerate(zip(params_list, results)):
            objective = result.get("objective")
            if objective is None:
                continue
            tier = int(params.get(self.fidelity_name, 1))
            by_tier.setdefault(tier, ([], [], []))
            by_tier[tier][0].append(params)
            by_tier[tier][1].append(float(objective))
            by_tier[tier][2].append(i)
        for tier, (valid, yvals, idx) in by_tier.items():
            prev_y = self._tier_y.get(tier, np.zeros((0,), dtype=np.float32))
            y = clamp_objectives(np.asarray(yvals, dtype=np.float64), prev_y)
            if y is None:
                continue
            # Columnar fast path: reuse the producer's params_to_cube rows.
            if cube is not None:
                rows = np.asarray(cube, dtype=np.float32)[idx]
            else:
                rows = self.space.params_to_cube(valid)
            prev_x = self._tier_x.get(
                tier, np.zeros((0, self.space.n_cols), dtype=np.float32)
            )
            self._tier_x[tier] = np.concatenate(
                [prev_x, np.asarray(rows, dtype=np.float32)]
            )
            self._tier_y[tier] = np.concatenate([prev_y, y.astype(np.float32)])

    # --- model-based sampling -----------------------------------------------
    def _model_tier(self):
        """Highest budget whose observation count can support the KDE pair."""
        for tier in sorted(self._tier_y, reverse=True):
            if self._tier_y[tier].shape[0] >= self.min_points:
                return tier
        return None

    def _new_cube(self, num):
        tier = self._model_tier()
        if tier is None:
            return super()._new_cube(num)
        good, bad = good_bad_split(self._tier_x[tier], self._tier_y[tier], self.gamma)
        good = self._boost_top_rungs(tier, good)
        return np.asarray(
            _tpe_suggest(
                self.next_key(),
                jnp.asarray(good),
                jnp.asarray(bad),
                self.n_candidates,
                int(num),
                mesh=self._mesh,
                bw_factor=self.bw_factor,
            )
        )

    def _boost_top_rungs(self, tier, good):
        """Prepend the good splits of every budget ABOVE the model tier.

        The model tier is the highest with >= min_points, so higher tiers
        are exactly the promoted survivors — too few to model alone, but
        the most trustworthy evidence there is.  Prepending them best-first
        (highest budget first) puts them at the TOP of the rank-weighted
        good set, so the KDE concentrates on full-budget evidence instead
        of ignoring it (VERDICT r4 #5: classic single-tier BOHB wasted
        every observation above the model tier).  A config promoted through
        several budgets appears once per tier — the duplicate rows upweight
        survivors, which is the point."""
        boost = []
        for upper in sorted((t for t in self._tier_y if t > tier), reverse=True):
            ys = self._tier_y[upper]
            n_good = max(1, int(np.ceil(self.gamma * ys.shape[0])))
            order = np.argsort(ys, kind="stable")[:n_good]
            boost.append(self._tier_x[upper][order])
        if not boost:
            return good
        return np.concatenate(boost + [good])

    # --- health -------------------------------------------------------------
    def health_record(self):
        """Hyperband's rung occupancy plus the KDE side (orion_tpu.health):
        per-budget-tier observation counts, the tier currently modeled (or
        None while still random-sampling), and the incumbent over every
        tier."""
        record = super().health_record()
        if self._mesh is not None:
            from orion_tpu.algo.sharding import mesh_health_fields

            record.update(mesh_health_fields(self._mesh))
        tier = self._model_tier()
        record["model_tier"] = int(tier) if tier is not None else None
        record["tier_counts"] = {
            str(t): int(self._tier_y[t].shape[0]) for t in sorted(self._tier_y)
        }
        best = None
        for ys in self._tier_y.values():
            if ys.shape[0]:
                tier_best = float(np.min(ys))
                best = tier_best if best is None else min(best, tier_best)
        if best is not None:
            record["best_y"] = best
        return record

    # --- state --------------------------------------------------------------
    def state_dict(self):
        out = super().state_dict()
        out["tiers"] = {
            str(t): {"x": self._tier_x[t].tolist(), "y": self._tier_y[t].tolist()}
            for t in self._tier_y
        }
        return out

    def set_state(self, state):
        super().set_state(state)
        d = self.space.n_cols
        self._tier_x, self._tier_y = {}, {}
        for key, obs in state.get("tiers", {}).items():
            tier = int(key)
            self._tier_x[tier] = np.asarray(obs["x"], dtype=np.float32).reshape(-1, d)
            self._tier_y[tier] = np.asarray(obs["y"], dtype=np.float32)
