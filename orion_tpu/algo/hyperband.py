"""Hyperband: successive halving over ALL bracket offsets.

The reference ships ASHA only (`src/orion/algo/asha.py`); Hyperband is its
multi-bracket generalization (every rung offset gets a bracket, hedging the
unknown fidelity/quality correlation) and appears in later Oríon releases —
included here for a complete multi-fidelity family.  Same host-side rung
logic + device sampling split as ASHA.
"""

from orion_tpu.algo.asha import ASHA, _geometric_budgets
from orion_tpu.algo.base import algo_registry


@algo_registry.register("hyperband")
class Hyperband(ASHA):
    def __init__(self, space, seed=None, num_rungs=None, reduction_factor=None):
        fid = space.fidelity
        if fid is None:
            raise RuntimeError("Hyperband requires a fidelity dimension")
        rf = int(reduction_factor or max(fid.base, 2))
        n_brackets = len(_geometric_budgets(fid.low, fid.high, rf, num_rungs))
        super().__init__(
            space,
            seed=seed,
            num_rungs=num_rungs,
            num_brackets=n_brackets,
            reduction_factor=reduction_factor,
        )
