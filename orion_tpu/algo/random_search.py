"""Random search — the default algorithm.

Capability parity: reference `src/orion/algo/random.py` (sample the prior,
rng state in state_dict).  TPU-native: a suggestion batch of any size is one
jitted uniform draw on device; the prior shaping happens in the Space codec's
decode (inverse-CDF), so random search at q=4096 is a single kernel launch.
"""

from functools import partial

import jax

from orion_tpu.algo.base import BaseAlgorithm, algo_registry


@algo_registry.register("random")
class RandomSearch(BaseAlgorithm):
    """Uniform prior sampling; seeded, resumable."""

    supports_async_suggest = True
    speculation_safe = True  # suggestions ignore observations entirely

    def __init__(self, space, seed=None):
        super().__init__(space, seed=seed)

    def _suggest_cube(self, num):
        return _uniform(self.next_key(), num, self.space.n_cols)


@partial(jax.jit, static_argnums=(1, 2))
def _uniform(key, num, n_cols):
    return jax.random.uniform(key, (num, n_cols))
