"""Optimization algorithms.

Capability parity: reference `src/orion/algo/` — abstract suggest/observe
interface, plugin discovery, random search, ASHA — plus the TPU-native
batched Bayesian optimizer (`tpu_bo`) that is this framework's reason to
exist.  Algorithms operate on the Space's flat unit-cube codec so their hot
paths are jitted, batched jnp code; trials and storage never reach device.
"""

from orion_tpu.algo.base import BaseAlgorithm, algo_registry, create_algo

__all__ = ["BaseAlgorithm", "algo_registry", "create_algo"]
