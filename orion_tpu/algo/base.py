"""Algorithm interface.

Capability parity: reference `src/orion/algo/base.py` (BaseAlgorithm:
suggest/observe/is_done/score/judge/should_suspend/state_dict/set_state/
seed_rng, nested config instantiation, Factory plugin discovery).

TPU-first redesign: algorithms speak **flat unit-cube arrays**.  ``suggest``
produces a ``(num, D)`` array in [0,1]^D through jitted device code and the
framework decodes it to structured params via the Space codec; ``observe``
receives the encoded array plus an objective vector.  The stateful-RNG
contract of the reference (numpy RandomState in state_dict) becomes a JAX
PRNGKey threaded through state — seeding is explicit and resumable.
"""

from typing import NamedTuple, Optional

import numpy as np
import jax

from orion_tpu.space.space import Space
from orion_tpu.utils.registry import Registry

algo_registry = Registry("algo")


class SuggestionBatch(NamedTuple):
    """One suggest round in columnar form.

    ``params`` is the storage-document edge: the per-point dicts trials are
    registered from (built ONCE, by one vectorized ``decode_flat_np`` +
    bulk dict zip).  ``cube`` is the raw ``(n, D)`` unit-cube rows the
    device produced, or None for host-scheduled algorithms (ASHA
    promotions, grid cursors) that never had a cube.  Note ``cube`` is the
    SUGGEST-time encoding; the observe-side columnar rows are defined by
    ``Space.params_to_cube`` over the registered params (quantized dims
    decode lossily), which is what the producer caches and feeds back.
    """

    params: list
    #: Raw suggest-time rows, for array-native consumers (benchmarks,
    #: custom drivers that skip the dict edge entirely).  The producer
    #: registers trials from ``params`` and builds its observe-side rows
    #: via ``params_to_cube`` — it does NOT feed this cube back.
    cube: Optional[np.ndarray]


def _effective_share(cls):
    """Union of ``_share_by_ref`` / ``_share_dicts`` over the MRO, so a
    subclass's declaration extends rather than shadows its parents'.

    Cached on the class itself (not a module-level lru_cache, which would
    pin a strong reference to every algorithm class ever copied and keep
    dynamically created classes — plugin reloads, test subclasses — alive
    forever).  The ``cls.__dict__`` guard makes the cache per-class rather
    than inherited: a subclass must not reuse its parent's union."""
    cached = cls.__dict__.get("__effective_share__")
    if cached is not None:
        return cached
    ref, dicts = set(), set()
    for klass in cls.__mro__:
        ref.update(klass.__dict__.get("_share_by_ref", ()))
        dicts.update(klass.__dict__.get("_share_dicts", ()))
    out = (frozenset(ref), frozenset(dicts))
    cls.__effective_share__ = out
    return out


class BaseAlgorithm:
    """Base class for optimization algorithms.

    Subclasses implement ``_suggest_cube(num)`` returning a ``(num, D)``
    unit-cube array (or None to opt out this round, reference
    `base.py:142-163`) and may override ``observe_arrays``.
    """

    requires_fidelity = False

    # True for algorithms whose `_suggest_cube` returns an UNFORCED device
    # array (jax dispatch is asynchronous): the producer may then split
    # suggestion into dispatch_suggest/finalize_suggest and overlap the
    # device round trip with trial execution.  Algorithms that override
    # `suggest` itself with host-side scheduling (ASHA's promotions) or
    # compute on host must leave this False.
    supports_async_suggest = False

    # True ONLY when a suggestion conditioned on round-(k-1) state is
    # EXACTLY as good as one conditioned on round k (i.e. suggestions do
    # not depend on observations at all — random/grid).  The producer
    # speculatively dispatches the next round's suggest for such
    # algorithms.  Model-based algorithms must leave this False: measured
    # on Hartmann6, fantasy-conditioned speculation costs real regret
    # (0.13 -> 0.21) because constant-liar lies mark the previous batch's
    # genuinely-good region as bad.
    speculation_safe = False

    # True when observe() actually consumes the columnar ``cube`` rows.
    # Algorithms whose observation handling is purely dict-keyed (ASHA's
    # rung bookkeeping) set this False so the producer skips building and
    # caching cube rows it would only throw away.  Orthogonal to signature
    # compatibility: the producer ALSO sniffs the observe signature, so
    # pre-columnar plugin overrides fall back to the dict path either way.
    uses_observe_cube = True

    # The producer deepcopies the algorithm every round for its naive copy
    # (lie fantasization); these class attributes let subclasses exempt
    # fields from that copy without each reimplementing __deepcopy__:
    # - _share_by_ref: immutable-by-rebinding values (Space, fitted GP
    #   state, mesh handles, append-only observation arrays that are
    #   rebound via np.concatenate, never mutated).
    # - _share_dicts: dicts WHOSE VALUES follow that discipline but which
    #   are themselves mutated by key assignment — shallow-copied so the
    #   clone's inserts don't leak back.
    # The effective sets are the UNION over the class's MRO (see
    # _effective_share): a subclass declaring its own tuple extends its
    # parents' instead of silently shadowing them (bohb's tier dicts once
    # hid ASHA's _bracket_of exactly that way).
    _share_by_ref = ("space",)
    _share_dicts = ()

    def __deepcopy__(self, memo):
        import copy as _copy

        cls = type(self)
        share_ref, share_dicts = _effective_share(cls)
        clone = cls.__new__(cls)
        memo[id(self)] = clone
        for key, value in self.__dict__.items():
            if key in share_ref:
                setattr(clone, key, value)
            elif key in share_dicts:
                setattr(clone, key, dict(value))
            else:
                setattr(clone, key, _copy.deepcopy(value, memo))
        return clone

    def __init__(self, space, seed=None, **params):
        if not isinstance(space, Space):
            raise TypeError(f"space must be a Space, got {type(space)}")
        self.space = space
        self._params = dict(params)
        self._seed = seed
        if seed is None:
            # Each unseeded instance gets its own stream (as the reference's
            # unseeded numpy RandomState does): concurrent workers sharing a
            # fixed default key would all suggest the IDENTICAL point
            # sequence and grind on DuplicateKeyError until SampleTimeout.
            import os

            seed = int.from_bytes(os.urandom(4), "little")
        self.rng_key = jax.random.PRNGKey(seed)
        # Observation history, host-side mirrors of device state.
        self._n_observed = 0

    # --- RNG ---------------------------------------------------------------
    def seed_rng(self, seed):
        """Reset the algorithm's PRNG stream (reference `base.py:121-128`)."""
        self._seed = seed
        self.rng_key = jax.random.PRNGKey(seed)

    def next_key(self):
        """Split off a fresh subkey (functional replacement for RandomState)."""
        self.rng_key, sub = jax.random.split(self.rng_key)
        return sub

    # --- state -------------------------------------------------------------
    def state_dict(self):
        """Serializable snapshot; must capture everything ``set_state`` needs
        to resume identically (reference `base.py:130-140`)."""
        return {
            "rng_key": np.asarray(self.rng_key).tolist(),
            "n_observed": self._n_observed,
        }

    def set_state(self, state):
        self.rng_key = jax.numpy.asarray(np.asarray(state["rng_key"], dtype=np.uint32))
        self._n_observed = state["n_observed"]

    # --- core contract -----------------------------------------------------
    def _materialize_batch(self, cube):
        """Decode a device cube to a :class:`SuggestionBatch`: ONE bulk
        device->host transfer, then host-side decode — per-dimension device
        decode would pay a host<->device round trip per dim
        (orion_tpu.space.dims host codec mirror)."""
        cube = np.asarray(cube, dtype=np.float32)
        arrays = self.space.decode_flat_np(cube)
        params = self.space.arrays_to_params(
            arrays, fidelity_value=self._fidelity_for_new()
        )
        return SuggestionBatch(params, cube)

    def suggest(self, num=1):
        """Return ``num`` new points as a list of param dicts, or None to
        signal a temporary opt-out (producer backs off and retries).

        Deliberately does NOT route through :meth:`suggest_batch`: a
        subclass override of ``suggest`` that delegates to
        ``super().suggest()`` must reach this implementation directly
        (suggest_batch routes overriders back to ``self.suggest`` — going
        through it here would make that pattern infinitely recursive).
        """
        cube = self._suggest_cube(num)
        if cube is None:
            return None
        return self._materialize_batch(cube).params

    def suggest_batch(self, num=1):
        """Columnar twin of :meth:`suggest`: returns a
        :class:`SuggestionBatch` (params + the raw cube rows) or None on
        opt-out.  This is the producer's entry point — suggestions flow as
        arrays and the per-point dicts are built exactly once, at the
        storage-document edge.

        Algorithms that override ``suggest`` itself with host-side
        scheduling (ASHA's promotions, grid cursors, plugins) are routed
        through their override and yield ``cube=None``.
        """
        if type(self).suggest is not BaseAlgorithm.suggest:
            params = self.suggest(num)
            return SuggestionBatch(params, None) if params is not None else None
        cube = self._suggest_cube(num)
        if cube is None:
            return None
        return self._materialize_batch(cube)

    def _suggest_cube(self, num):
        raise NotImplementedError

    # --- asynchronous suggestion (device-overlap path) ----------------------
    def dispatch_suggest(self, num=1):
        """Start the device computation for ``num`` suggestions WITHOUT
        forcing the result to host.  Returns an opaque handle for
        :meth:`finalize_suggest`, or None (opt-out / unsupported).  The
        computation and the device->host transfer proceed in the background
        (jax async dispatch), so the caller can run trials, write storage,
        etc. before finalizing."""
        if not self.supports_async_suggest:
            return None
        cube = self._suggest_cube(num)
        if cube is None:
            return None
        return (num, cube)

    def finalize_suggest(self, handle):
        """Force a :meth:`dispatch_suggest` handle to concrete params.

        Like :meth:`suggest`, this is the direct implementation — it must
        not route through the batch twin, so subclass overrides delegating
        to ``super().finalize_suggest()`` cannot recurse."""
        num, cube = handle
        return self._materialize_batch(np.asarray(cube)[:num]).params

    def finalize_suggest_batch(self, handle):
        """Columnar finalize: force a :meth:`dispatch_suggest` handle to a
        :class:`SuggestionBatch` — the dict build happens here, at the
        storage edge, once.  Plugins that override ``finalize_suggest``
        itself (custom handles / post-processing) are routed through their
        override and yield ``cube=None``."""
        if type(self).finalize_suggest is not BaseAlgorithm.finalize_suggest:
            return SuggestionBatch(self.finalize_suggest(handle), None)
        num, cube = handle
        return self._materialize_batch(np.asarray(cube)[:num])

    def _fidelity_for_new(self):
        """Fidelity assigned to fresh points (max budget unless multi-fidelity
        algorithms override with rung budgets)."""
        fid = self.space.fidelity
        return fid.high if fid is not None else None

    def observe(self, params_list, results, cube=None):
        """Feed evaluated points back.

        ``results`` is a list of dicts with at least ``objective`` (reference
        `base.py:165-191`).  The default implementation encodes points to the
        unit cube and forwards to :meth:`observe_arrays`.

        ``cube`` is the columnar fast path: pre-encoded ``(n, D)`` unit-cube
        rows for ``params_list``, as produced by ``Space.params_to_cube``
        (the producer caches these per trial).  When given, the per-point
        dict parse + encode is skipped entirely; the rows MUST be the
        ``params_to_cube`` encoding — feeding anything else (e.g. raw
        suggest-time cube rows for quantized dims) would diverge from the
        dict path.
        """
        if not params_list:
            return
        if cube is None:
            cube = self.space.params_to_cube(params_list)
        else:
            cube = np.asarray(cube, dtype=np.float32)
            if cube.shape[0] != len(params_list):
                raise ValueError(
                    f"cube has {cube.shape[0]} rows for "
                    f"{len(params_list)} params"
                )
        objectives = np.asarray(
            [float(r["objective"]) for r in results], dtype=np.float64
        )
        fidelities = None
        fid = self.space.fidelity
        if fid is not None:
            from orion_tpu.space.params import ParamBatch

            if isinstance(params_list, ParamBatch) and params_list.has_column(
                fid.name
            ):
                # Columnar fast path: the fidelity column comes straight
                # out of the batch view — no per-trial dict probes.
                col = params_list.column(fid.name)
            else:
                col = [p[fid.name] for p in params_list]
            fidelities = np.asarray(col, dtype=np.int64)
        self.observe_arrays(cube, objectives, params_list=params_list, fidelities=fidelities)
        self._n_observed += len(params_list)

    def observe_arrays(self, cube, objectives, params_list=None, fidelities=None):
        """Device-facing observation hook; default is stateless."""

    def register_suggestion(self, params):
        """Called by the producer after a suggested point is durably
        registered as a trial.  Algorithms with in-flight bookkeeping (ASHA's
        pending rung slots) override this so state survives across producer
        rounds — the *naive* copy that produced the suggestion is discarded
        every round."""

    def health_record(self):
        """One optimization-health snapshot dict, or None when the
        algorithm has nothing to report (the default).

        Contract (orion_tpu.health): host-side truth only from the
        instance itself (incumbent value, observation count, trust-region
        box, rung occupancy), device-side GP/acquisition fields unpacked
        from the last fused step's packed health vector — reading it must
        never force a device sync beyond transferring already-computed
        values.  The producer merges the real instance's host fields over
        the naive copy's device fields (the copy is the one that actually
        suggested, but its host history contains fantasy lies) and flushes
        one record per round through ``storage.record_health``."""
        return None

    @property
    def n_observed(self):
        return self._n_observed

    @property
    def is_done(self):
        """True when the algo cannot improve further (reference `base.py:193-196`)."""
        return False

    def score(self, params):  # pragma: no cover - default
        """Prior preference score for a candidate point (reference `base.py:198-208`)."""
        return 0

    def judge(self, params, measurements):  # pragma: no cover - default
        """Online early-stopping hook (reference `base.py:210-232`)."""
        return None

    @property
    def should_suspend(self):  # pragma: no cover - default
        return False

    # --- configuration -----------------------------------------------------
    @property
    def configuration(self):
        """Dict form used for storage/EVC comparison (reference `base.py:241-256`)."""
        name = type(self).__name__.lower()
        cfg = dict(self._params)
        if self._seed is not None:
            cfg["seed"] = self._seed
        return {name: cfg}


_BUILTIN_MODULES = (
    "random_search",
    "asha",
    "asha_bo",
    "bohb",
    "cmaes",
    "de",
    "hyperband",
    "grid_search",
    "tpe",
    "tpu_bo",
)


def _import_builtins():
    """Register built-in algorithms (entry points cover third-party ones)."""
    import importlib

    for mod in _BUILTIN_MODULES:
        try:
            importlib.import_module(f"orion_tpu.algo.{mod}")
        except ImportError:  # pragma: no cover - during incremental build only
            pass


def create_algo(space, config=None, seed=None):
    """Instantiate an algorithm from config.

    ``config`` is either a name string (``"random"``) or a one-key dict
    ``{"asha": {...kwargs}}`` like the reference's nested instantiation
    (`base.py:104-119`).  Unknown names raise with available choices listed.
    """
    _import_builtins()
    # Every algorithm instantiation path funnels through here: turn on the
    # persistent XLA compilation cache so repeated processes (workers,
    # benches, tests) skip the tens-of-seconds TPU compile per jit bucket.
    from orion_tpu.utils.jit_cache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    config = config or "random"
    if isinstance(config, str):
        name, kwargs = config, {}
    elif isinstance(config, dict):
        if len(config) != 1:
            raise ValueError(f"Algorithm config must have exactly one key: {config}")
        name, kwargs = next(iter(config.items()))
        kwargs = dict(kwargs or {})
    else:
        raise TypeError(f"Bad algorithm config {config!r}")
    if seed is not None:
        kwargs.setdefault("seed", seed)
    return algo_registry.create(name, space, **kwargs)
