"""CMA-ES on device — covariance matrix adaptation evolution strategy.

No reference counterpart (Oríon v0.1.7 ships only random search + ASHA;
its plugin docs name evolutionary algorithms as the intended extension
family, cf. reference `docs/src/plugins/algorithms.rst`).  This is the
TPU-native take: the search distribution N(m, sigma^2 C) lives on device,
``suggest`` is one jitted draw of the whole q-batch (MXU matmul against the
covariance factor), and the rank-mu/rank-1 update is one jitted step whose
heavy op is a (d, d) eigendecomposition — all static shapes.

Async contract: the canonical algorithm is generational (ask lambda points,
tell lambda results) but the producer observes completed trials in arbitrary
dribs.  Observations therefore accumulate in a host-side buffer; every time
``popsize`` results are available one generation update runs on device.
Suggestions beyond ``popsize`` per round are extra i.i.d. draws from the
current distribution — valid, just not all used by the next update.
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.algo.base import BaseAlgorithm, algo_registry
from orion_tpu.algo.sampling import clamp_objectives, reflect_unit


@partial(jax.jit, static_argnums=(2,))
def _cma_sample(key, state, num):
    """Draw ``num`` candidates from N(m, sigma^2 C), reflected into [0,1]^d.

    C's eigendecomposition is part of the carried state (refreshed by the
    update step), so sampling is just z @ (B sqrt(D))^T — one matmul.
    """
    m, sigma, _C, B, D, _pc, _ps, _gen = state
    d = m.shape[0]
    z = jax.random.normal(key, (num, d))
    x = m[None, :] + sigma * (z * D[None, :]) @ B.T
    return reflect_unit(x)


def _init_state(d, sigma0):
    return (
        jnp.full((d,), 0.5, jnp.float32),     # m: mean
        jnp.float32(sigma0),                  # sigma: global step size
        jnp.eye(d, dtype=jnp.float32),        # C: covariance
        jnp.eye(d, dtype=jnp.float32),        # B: eigenvectors of C
        jnp.ones((d,), jnp.float32),          # D: sqrt eigenvalues of C
        jnp.zeros((d,), jnp.float32),         # p_c: covariance path
        jnp.zeros((d,), jnp.float32),         # p_sigma: step-size path
        jnp.int32(0),                         # generation counter
    )


@jax.jit
def _cma_update(state, X, y):
    """One generation: rank by objective, shift mean, adapt paths/C/sigma.

    Hansen's (mu/mu_w, lambda) update with rank-1 + rank-mu covariance
    adaptation; lambda = X.shape[0] is static, so the strategy constants
    fold into the compiled graph.
    """
    m, sigma, C, B, D, pc, ps, gen = state
    d = m.shape[0]
    lam = X.shape[0]
    mu = lam // 2
    # Recombination weights (positive half, log-linear).
    w = jnp.log(mu + 0.5) - jnp.log(jnp.arange(1, mu + 1, dtype=jnp.float32))
    w = w / jnp.sum(w)
    mueff = 1.0 / jnp.sum(w**2)

    # Strategy constants (Hansen 2016 tutorial defaults).
    cs = (mueff + 2.0) / (d + mueff + 5.0)
    ds = 1.0 + 2.0 * jnp.maximum(0.0, jnp.sqrt((mueff - 1.0) / (d + 1.0)) - 1.0) + cs
    cc = (4.0 + mueff / d) / (d + 4.0 + 2.0 * mueff / d)
    c1 = 2.0 / ((d + 1.3) ** 2 + mueff)
    cmu = jnp.minimum(
        1.0 - c1, 2.0 * (mueff - 2.0 + 1.0 / mueff) / ((d + 2.0) ** 2 + mueff)
    )
    chi_d = math.sqrt(d) * (1.0 - 1.0 / (4.0 * d) + 1.0 / (21.0 * d * d))

    order = jnp.argsort(y)
    X_mu = X[order[:mu]]                      # (mu, d) best points
    m_new = w @ X_mu
    shift = (m_new - m) / sigma               # (d,)

    # C^{-1/2} from the carried eigendecomposition.
    inv_sqrt = (B * (1.0 / D)[None, :]) @ B.T
    ps_new = (1.0 - cs) * ps + jnp.sqrt(cs * (2.0 - cs) * mueff) * (inv_sqrt @ shift)
    gen_new = gen + 1
    hs = (
        jnp.linalg.norm(ps_new)
        / jnp.sqrt(1.0 - (1.0 - cs) ** (2.0 * gen_new.astype(jnp.float32)))
        / chi_d
    ) < (1.4 + 2.0 / (d + 1.0))
    hs = hs.astype(jnp.float32)
    pc_new = (1.0 - cc) * pc + hs * jnp.sqrt(cc * (2.0 - cc) * mueff) * shift

    Y_mu = (X_mu - m[None, :]) / sigma        # (mu, d)
    rank_mu = (Y_mu * w[:, None]).T @ Y_mu    # MXU: weighted scatter matrix
    delta_hs = (1.0 - hs) * cc * (2.0 - cc)
    C_new = (
        (1.0 - c1 - cmu) * C
        + c1 * (jnp.outer(pc_new, pc_new) + delta_hs * C)
        + cmu * rank_mu
    )
    C_new = 0.5 * (C_new + C_new.T)

    sigma_new = sigma * jnp.exp((cs / ds) * (jnp.linalg.norm(ps_new) / chi_d - 1.0))
    # Keep the distribution inside sane bounds for the unit cube.
    sigma_new = jnp.clip(sigma_new, 1e-12, 1.0)

    eigval, B_new = jnp.linalg.eigh(C_new)
    D_new = jnp.sqrt(jnp.clip(eigval, 1e-20, None))
    return (
        m_new,
        sigma_new,
        C_new,
        B_new,
        D_new,
        pc_new,
        ps_new,
        gen_new,
    )


@algo_registry.register("cmaes")
class CMAES(BaseAlgorithm):
    """Covariance matrix adaptation evolution strategy on the unit cube.

    Parameters
    ----------
    popsize: generation size lambda (default ``4 + floor(3 ln d)``).  An
        update runs every time this many new observations have accumulated.
    sigma0: initial global step size (0.3 covers the unit cube well).
    tol_sigma: declare ``is_done`` when the step size collapses below this
        (the distribution has converged to a point).
    """

    def __init__(self, space, seed=None, popsize=None, sigma0=0.3, tol_sigma=1e-10):
        d = space.n_cols
        if popsize is None:
            popsize = 4 + int(3 * math.log(max(d, 2)))
        popsize = max(int(popsize), 4)
        super().__init__(
            space, seed=seed, popsize=popsize, sigma0=sigma0, tol_sigma=tol_sigma
        )
        self.popsize = popsize
        self.sigma0 = float(sigma0)
        # The update step clips sigma to >= 1e-12, so a tolerance below that
        # could never fire; clamp instead of silently dead-ending is_done.
        self.tol_sigma = max(float(tol_sigma), 1e-12)
        self._state = _init_state(d, self.sigma0)
        # Host-side generation buffer (async observations dribble in).
        self._buf_x = np.zeros((0, d), dtype=np.float32)
        self._buf_y = np.zeros((0,), dtype=np.float32)
        # Worst finite objective ever seen — clamp baseline for inf-sentinel
        # lies; the generation buffer is transient so it can't serve as the
        # history the way sibling algos' full observation arrays do.
        self._worst_finite = None

    # --- suggestion ---------------------------------------------------------
    def _suggest_cube(self, num):
        return _cma_sample(self.next_key(), self._state, int(num))

    # --- observation --------------------------------------------------------
    def observe_arrays(self, cube, objectives, params_list=None, fidelities=None):
        history = (
            np.asarray([self._worst_finite])
            if self._worst_finite is not None
            else np.zeros((0,))
        )
        objectives = clamp_objectives(objectives, history)
        if objectives is None:
            return
        batch_worst = float(np.max(objectives))
        if self._worst_finite is None or batch_worst > self._worst_finite:
            self._worst_finite = batch_worst
        self._buf_x = np.concatenate(
            [self._buf_x, np.asarray(cube, dtype=np.float32)]
        )
        self._buf_y = np.concatenate(
            [self._buf_y, np.asarray(objectives, dtype=np.float32)]
        )
        lam = self.popsize
        while self._buf_x.shape[0] >= lam:
            X = jnp.asarray(self._buf_x[:lam])
            y = jnp.asarray(self._buf_y[:lam])
            self._state = _cma_update(self._state, X, y)
            self._buf_x = self._buf_x[lam:]
            self._buf_y = self._buf_y[lam:]

    # --- lifecycle ----------------------------------------------------------
    @property
    def is_done(self):
        return float(self._state[1]) <= self.tol_sigma

    # --- state --------------------------------------------------------------
    def state_dict(self):
        out = super().state_dict()
        m, sigma, C, B, D, pc, ps, gen = self._state
        out["cma"] = {
            "m": np.asarray(m).tolist(),
            "sigma": float(sigma),
            "C": np.asarray(C).tolist(),
            "pc": np.asarray(pc).tolist(),
            "ps": np.asarray(ps).tolist(),
            "gen": int(gen),
        }
        out["buf_x"] = self._buf_x.tolist()
        out["buf_y"] = self._buf_y.tolist()
        out["worst_finite"] = self._worst_finite
        return out

    def set_state(self, state):
        super().set_state(state)
        cma = state["cma"]
        d = self.space.n_cols
        C = jnp.asarray(np.asarray(cma["C"], dtype=np.float32).reshape(d, d))
        eigval, B = jnp.linalg.eigh(C)
        self._state = (
            jnp.asarray(np.asarray(cma["m"], dtype=np.float32)),
            jnp.float32(cma["sigma"]),
            C,
            B,
            jnp.sqrt(jnp.clip(eigval, 1e-20, None)),
            jnp.asarray(np.asarray(cma["pc"], dtype=np.float32)),
            jnp.asarray(np.asarray(cma["ps"], dtype=np.float32)),
            jnp.int32(cma["gen"]),
        )
        self._buf_x = np.asarray(state["buf_x"], dtype=np.float32).reshape(-1, d)
        self._buf_y = np.asarray(state["buf_y"], dtype=np.float32)
        self._worst_finite = state.get("worst_finite")
