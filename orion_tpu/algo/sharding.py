"""Cached Mesh/NamedSharding helpers for the fused suggest hot path.

Every sharded dispatch in the engine — standalone `run_fused_plan`, the
producer ring, prewarm compiles, and the gateway's coalesced stacked step —
names its mesh and sharding specs from HERE, never by constructing them at
the call site.  Two reasons, both measured:

- ``Mesh(jax.devices(), ...)`` re-hashes the device list and re-derives the
  axis env on every construction; on the steady suggest path that is pure
  host tax (ROADMAP item 5's "wall ≈ device" budget).
- ``mesh`` rides the fused step's ``static_argnames``, so the *object* is
  part of the jit cache key.  Fresh per-call meshes that compare equal still
  pay ``__eq__``/``__hash__`` over the device array each lookup; a cached
  singleton makes the cache probe an identity hit.

Lint rule JIT004 (`orion_tpu/analysis/jit_rules.py`) enforces the contract:
per-call ``Mesh(...)``/``NamedSharding(...)`` construction inside a declared
hot-path function is a lint failure — the construction below happens once
per distinct topology, behind a cache.

Axis layout (docs/performance.md "Sharded suggest"):

- ``candidates`` — the throughput axis.  The fused step's candidate pool,
  EI scores, and q-batch dedup shard along it; GP fit state replicates.
- ``tenants`` — the gateway's stacked-lane axis.  Coalesced dispatches lay
  the stacked plan arrays out over it (2-D mesh, see `get_stacked_mesh`) so
  one dispatch spreads (tenant, candidate) work across chips.

This module deliberately imports only jax/numpy: `orion_tpu.parallel`
delegates here, and the algo modules import `orion_tpu.parallel`, so any
heavier import would cycle.
"""

import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

CANDIDATE_AXIS = "candidates"
TENANT_AXIS = "tenants"

# Cache writes happen once per distinct topology; the lock is a leaf (no
# other lock is ever taken while holding it).
_CACHE_LOCK = threading.Lock()
_MESH_CACHE = {}
_SPEC_CACHE = {}


def get_mesh(n_devices=None, axis_name=CANDIDATE_AXIS):
    """Cached 1-D mesh over the first ``n_devices`` devices (all by default).

    The cache key includes the resolved device tuple, so a changed backend
    (tests forcing a virtual CPU mesh in a subprocess, multi-host init
    growing ``jax.devices()``) can never serve a stale mesh.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    key = (tuple(d.id for d in devices), (axis_name,), None)
    with _CACHE_LOCK:
        mesh = _MESH_CACHE.get(key)
        if mesh is None:
            mesh = Mesh(np.asarray(devices), (axis_name,))
            _MESH_CACHE[key] = mesh
    return mesh


def get_stacked_mesh(n_tenants, n_devices=None):
    """Cached 2-D ``(tenants, candidates)`` mesh for coalesced dispatch.

    The tenant axis takes the largest power-of-2 lane count that divides
    both the padded tenant width and the device count; the rest of the
    devices go to the candidate axis.  With 8 devices and a 2-lane stack
    that is a (2, 4) mesh: stacked plan arrays lay out over ``tenants``,
    and each lane's candidate pool shards over ``candidates``.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    t = _gcd_pow2(max(1, int(n_tenants)), n)
    key = (tuple(d.id for d in devices), (TENANT_AXIS, CANDIDATE_AXIS), t)
    with _CACHE_LOCK:
        mesh = _MESH_CACHE.get(key)
        if mesh is None:
            mesh = Mesh(
                np.asarray(devices).reshape(t, n // t),
                (TENANT_AXIS, CANDIDATE_AXIS),
            )
            _MESH_CACHE[key] = mesh
    return mesh


def _gcd_pow2(a, b):
    """Largest power of 2 dividing both a and b (>= 1)."""
    g = 1
    while a % 2 == 0 and b % 2 == 0 and g < b:
        a //= 2
        b //= 2
        g *= 2
    return g


def _cached_spec(mesh, spec):
    key = (mesh, spec)
    with _CACHE_LOCK:
        out = _SPEC_CACHE.get(key)
        if out is None:
            out = NamedSharding(mesh, spec)
            _SPEC_CACHE[key] = out
    return out


def candidate_spec(mesh, axis_name=CANDIDATE_AXIS):
    """(m, d) candidate matrix: shard m, replicate d.

    On a 2-D stacked mesh the spec still names only the candidate axis —
    the array replicates over ``tenants`` (each lane scores its own pool).
    """
    return _cached_spec(mesh, PartitionSpec(axis_name, None))


def replicated_spec(mesh):
    """Fully replicated (GP fit state: O(n^2) vs the O(m·F) candidate work)."""
    return _cached_spec(mesh, PartitionSpec())


def tenant_spec(mesh):
    """Stacked plan leaves: shard the leading (tenant) axis, replicate rest."""
    return _cached_spec(mesh, PartitionSpec(TENANT_AXIS))


def shard_candidates(candidates, mesh, axis_name=CANDIDATE_AXIS):
    """Place a host candidate pool sharded over the mesh (one transfer per
    shard; the full pool is never materialized on any single device)."""
    return jax.device_put(candidates, candidate_spec(mesh, axis_name))


def gather_candidates(array):
    """Bring a (possibly sharded) device array back as one host ndarray."""
    return np.asarray(jax.device_get(array))


def clear_caches():
    """Drop cached meshes/specs (tests that swap backends mid-process)."""
    with _CACHE_LOCK:
        _MESH_CACHE.clear()
        _SPEC_CACHE.clear()


# --------------------------------------------------------------------------
# Placement introspection — the observability side of sharding.  All of it
# reads array *metadata* (shard device + nbytes); nothing transfers.


def placement_fractions(*arrays):
    """device id -> fraction of the arrays' bytes resident on that device.

    Replicated arrays contribute their full size to every holding device,
    sharded arrays one shard each — so a well-sharded dispatch shows near
    1/n fractions and a silently-unsharded one shows a single device at 1.0.
    """
    per_device = {}
    for array in arrays:
        shards = getattr(array, "addressable_shards", None)
        if shards:
            for shard in shards:
                nbytes = getattr(shard.data, "nbytes", 0)
                per_device[shard.device.id] = (
                    per_device.get(shard.device.id, 0) + nbytes
                )
        else:  # pragma: no cover - non-Array leaves (host numpy)
            continue
    total = sum(per_device.values())
    if not total:
        return {}
    return {dev: nbytes / total for dev, nbytes in per_device.items()}


def mesh_utilization(mesh, *arrays):
    """(min_frac, max_frac) byte fraction across the mesh's devices.

    Devices in the mesh holding nothing count as 0.0 — exactly the "one
    device doing all the work" signal doctor rule DX006 watches for.
    """
    fractions = placement_fractions(*arrays)
    device_ids = [d.id for d in mesh.devices.flat]
    per = [fractions.get(dev, 0.0) for dev in device_ids]
    return (min(per), max(per)) if per else (0.0, 0.0)


def mesh_fingerprint(mesh):
    """Compact, stable identity of a mesh for compiler-plane signature
    fields and span args — ``"4×candidates"`` instead of the multi-line
    ``str(Mesh)`` (signatures are diffed and rendered in tables; a
    verbose mesh repr would drown the one static that actually changed).
    None-safe: an unmeshed dispatch fingerprints as ``"none"``."""
    if mesh is None:
        return "none"
    try:
        axes = ",".join(str(name) for name in mesh.axis_names)
        return f"{int(mesh.devices.size)}×{axes}"
    except Exception:  # hostile/mock mesh — degrade to the repr
        return str(mesh)


def mesh_health_fields(mesh, *arrays):
    """Host-side health-record fields describing the mesh and, when sample
    arrays are given, the measured per-device placement (`serve_width`-style:
    merged into health records next to the packed device fields)."""
    fields = {"mesh_devices": int(mesh.devices.size)}
    if arrays:
        lo, hi = mesh_utilization(mesh, *arrays)
        fields["mesh_util_min_frac"] = float(lo)
        fields["mesh_util_max_frac"] = float(hi)
    return fields
