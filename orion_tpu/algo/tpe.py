"""TPE — Tree-structured Parzen Estimator, vectorized on device.

No counterpart in the reference v0.1.7 (later Oríon releases add TPE); the
classic algorithm (Bergstra et al. 2011): split observations at the gamma
quantile into good/bad sets, model each with a kernel density estimate, and
pick candidates maximizing l(x)/g(x).  TPU-native formulation: candidates are
sampled from the good-set KDE by perturbing good points, and both density
evaluations are one (m, n) pairwise-kernel matmul each under jit — no
per-dimension python loops.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.algo.base import BaseAlgorithm, algo_registry
from orion_tpu.algo.sampling import clamp_objectives, reflect_unit


@algo_registry.register("tpe")
class TPE(BaseAlgorithm):
    def __init__(self, space, seed=None, n_init=20, gamma=0.25, n_candidates=1024):
        super().__init__(
            space, seed=seed, n_init=n_init, gamma=gamma, n_candidates=n_candidates
        )
        self.n_init = n_init
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._x = np.zeros((0, space.n_cols), dtype=np.float32)
        self._y = np.zeros((0,), dtype=np.float32)

    def observe_arrays(self, cube, objectives, params_list=None, fidelities=None):
        objectives = clamp_objectives(objectives, self._y)
        if objectives is None:
            return
        self._x = np.concatenate([self._x, np.asarray(cube, dtype=np.float32)])
        self._y = np.concatenate([self._y, np.asarray(objectives, dtype=np.float32)])

    def _suggest_cube(self, num):
        n = len(self._y)
        if n < self.n_init:
            return jax.random.uniform(self.next_key(), (num, self.space.n_cols))
        good, bad = good_bad_split(self._x, self._y, self.gamma)
        return _tpe_suggest(
            self.next_key(),
            jnp.asarray(good),
            jnp.asarray(bad),
            self.n_candidates,
            num,
        )

    def state_dict(self):
        out = super().state_dict()
        out["x"] = self._x.tolist()
        out["y"] = self._y.tolist()
        return out

    def set_state(self, state):
        super().set_state(state)
        self._x = np.asarray(state["x"], dtype=np.float32).reshape(-1, self.space.n_cols)
        self._y = np.asarray(state["y"], dtype=np.float32)


def good_bad_split(x, y, gamma):
    """Split observations at the gamma quantile into (good, bad) sets; the
    bad set falls back to the good one when everything is good (shared by
    TPE and BOHB so the split semantics cannot diverge)."""
    n = y.shape[0]
    n_good = max(1, int(np.ceil(gamma * n)))
    order = np.argsort(y, kind="stable")
    good = x[order[:n_good]]
    bad = x[order[n_good:]]
    if len(bad) == 0:
        bad = good
    return good, bad


def _scott_bandwidth(points):
    n, d = points.shape
    std = jnp.maximum(jnp.std(points, axis=0), 1e-3)
    return std * (n ** (-1.0 / (d + 4)))


def _log_kde(x, points, bandwidth):
    """(m,) log density of a gaussian KDE.

    Bandwidth-scaled squared distances via the shared `sq_dists` expansion
    (gp kernels): the dominant cost becomes one (m, d) x (d, n)
    MXU matmul instead of materializing an (m, n, d) diff tensor in HBM.
    Inputs are centered on the KDE points first — late in a run the good
    set clusters tightly and Scott bandwidths shrink toward the 1e-3 floor,
    so un-centered scaled coordinates reach ~1e3 and the aa+bb-2ab
    cancellation would round at the same order as the true distances."""
    from orion_tpu.algo.gp.kernels import sq_dists

    center = jnp.mean(points, axis=0, keepdims=True)
    log_k = -0.5 * sq_dists(x - center, points - center, 1.0 / bandwidth)
    return jax.scipy.special.logsumexp(log_k, axis=1) - jnp.log(points.shape[0])


@partial(jax.jit, static_argnums=(3, 4))
def _tpe_suggest(key, good, bad, n_candidates, num):
    # top_k needs k <= pool size: q-batch requests can exceed the configured
    # candidate pool (q=4096 presets), so grow the pool to fit.
    n_candidates = max(n_candidates, num)
    k_pick, k_noise, k_mix = jax.random.split(key, 3)
    bw_good = _scott_bandwidth(good)
    # Candidates ~ good-KDE (pick a good point, jitter by its bandwidth),
    # mixed with 25% uniform exploration.
    idx = jax.random.randint(k_pick, (n_candidates,), 0, good.shape[0])
    noise = jax.random.normal(k_noise, (n_candidates, good.shape[1]))
    cands = reflect_unit(good[idx] + noise * bw_good[None, :])
    uniform = jax.random.uniform(k_mix, (n_candidates, good.shape[1]))
    take_uniform = (jnp.arange(n_candidates) % 4) == 3
    cands = jnp.where(take_uniform[:, None], uniform, cands)

    score = _log_kde(cands, good, bw_good) - _log_kde(cands, bad, _scott_bandwidth(bad))
    _, top = jax.lax.top_k(score, num)
    return cands[top]
