"""TPE — Tree-structured Parzen Estimator, vectorized on device.

No counterpart in the reference v0.1.7 (later Oríon releases add TPE); the
classic algorithm (Bergstra et al. 2011): split observations at the gamma
quantile into good/bad sets, model each with a kernel density estimate, and
pick candidates maximizing l(x)/g(x).  TPU-native formulation: candidates are
sampled from the good-set KDE by perturbing good points, and both density
evaluations are one (m, n) pairwise-kernel matmul each under jit — no
per-dimension python loops.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.algo.base import BaseAlgorithm, algo_registry
from orion_tpu.algo.sampling import clamp_objectives, reflect_unit
from orion_tpu.algo.sharding import mesh_health_fields
from orion_tpu.parallel import device_mesh


@algo_registry.register("tpe")
class TPE(BaseAlgorithm):
    def __init__(self, space, seed=None, n_init=20, gamma=0.25, n_candidates=1024,
                 bw_factor=1.0, n_devices=None, use_mesh=False):
        super().__init__(
            space, seed=seed, n_init=n_init, gamma=gamma,
            n_candidates=n_candidates, bw_factor=bw_factor
        )
        self.n_init = n_init
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.bw_factor = float(bw_factor)
        self.use_mesh = use_mesh
        self._mesh = device_mesh(n_devices) if use_mesh else None
        self._x = np.zeros((0, space.n_cols), dtype=np.float32)
        self._y = np.zeros((0,), dtype=np.float32)

    # Naive-copy sharing (base __deepcopy__): the mesh handle is not copyable.
    _share_by_ref = ("space", "_mesh", "_x", "_y")

    def observe_arrays(self, cube, objectives, params_list=None, fidelities=None):
        objectives = clamp_objectives(objectives, self._y)
        if objectives is None:
            return
        self._x = np.concatenate([self._x, np.asarray(cube, dtype=np.float32)])
        self._y = np.concatenate([self._y, np.asarray(objectives, dtype=np.float32)])

    def _suggest_cube(self, num):
        n = len(self._y)
        if n < self.n_init:
            return jax.random.uniform(self.next_key(), (num, self.space.n_cols))
        good, bad = good_bad_split(self._x, self._y, self.gamma)
        return _tpe_suggest(
            self.next_key(),
            jnp.asarray(good),
            jnp.asarray(bad),
            self.n_candidates,
            num,
            mesh=self._mesh,
            bw_factor=self.bw_factor,
        )

    def health_record(self):
        record = super().health_record()
        if self._mesh is not None:
            # serve_width-style placement field (BOHB inherits this, so the
            # mesh-mode KDE path reports its device count like the GP algos).
            record.update(mesh_health_fields(self._mesh))
        return record

    def state_dict(self):
        out = super().state_dict()
        out["x"] = self._x.tolist()
        out["y"] = self._y.tolist()
        return out

    def set_state(self, state):
        super().set_state(state)
        self._x = np.asarray(state["x"], dtype=np.float32).reshape(-1, self.space.n_cols)
        self._y = np.asarray(state["y"], dtype=np.float32)


def good_bad_split(x, y, gamma):
    """Split observations at the gamma quantile into (good, bad) sets; the
    bad set falls back to the good one when everything is good (shared by
    TPE and BOHB so the split semantics cannot diverge).  The good set is
    returned BEST-FIRST so rank weighting inside the sampler lines up."""
    n = y.shape[0]
    n_good = max(1, int(np.ceil(gamma * n)))
    order = np.argsort(y, kind="stable")
    good = x[order[:n_good]]
    bad = x[order[n_good:]]
    if len(bad) == 0:
        bad = good
    return good, bad


def _bandwidth_1d(points):
    """Per-dimension UNIVARIATE bandwidths: std_j * n^(-1/5).

    The d enters nowhere — TPE's density is a product of 1-D KDEs, and each
    univariate KDE takes the 1-D Scott rate.  A joint-KDE Scott factor
    n^(-1/(d+4)) goes to 1 as d grows (n=512, d=50: 0.89·std — no
    concentration at all), which silently degrades TPE to near-uniform
    sampling exactly in the high-D regimes the q-batch presets run."""
    n = points.shape[0]
    std = jnp.maximum(jnp.std(points, axis=0), 1e-3)
    return std * (n ** (-0.2))


def _rank_log_weights(n):
    """CMA-style log-rank weights (normalized), best-first order."""
    w = jnp.log(n + 0.5) - jnp.log(jnp.arange(1, n + 1, dtype=jnp.float32))
    return jnp.log(w / jnp.sum(w))


def _log_kde_product(x, points, bandwidth, log_w=None):
    """(m,) log density of the product-of-univariate-KDEs (classic TPE),
    optionally with per-point mixture weights (``log_w``, best-first rank
    weights for the good set — Optuna-flavored weighted TPE).

    Computed per dimension with a lax.scan so peak memory stays one (m, n)
    slab instead of an (m, n, d) tensor; inputs are centered on the KDE
    points first — late in a run the good set clusters tightly and
    bandwidths shrink toward the 1e-3 floor, so un-centered coordinates
    scaled by 1/bw reach ~1e3 and float32 squaring loses the distances."""
    center = jnp.mean(points, axis=0, keepdims=True)
    xc = (x - center).T  # (d, m)
    pc = (points - center).T  # (d, n)
    if log_w is None:
        log_w = jnp.zeros(points.shape[0], x.dtype) - jnp.log(
            jnp.asarray(points.shape[0], x.dtype)
        )

    def per_dim(acc, inputs):
        xj, pj, bwj = inputs
        log_k = (
            -0.5 * ((xj[:, None] - pj[None, :]) / bwj) ** 2
            - jnp.log(bwj)
            + log_w[None, :]
        )
        return acc + jax.scipy.special.logsumexp(log_k, axis=1), None

    init = jnp.zeros(x.shape[0], x.dtype)
    total, _ = jax.lax.scan(per_dim, init, (xc, pc, bandwidth))
    return total


@partial(jax.jit, static_argnames=("n_candidates", "num", "mesh", "bw_factor"))
def _tpe_suggest(key, good, bad, n_candidates, num, mesh=None, bw_factor=1.0):
    # top_k needs k <= pool size: q-batch requests can exceed the configured
    # candidate pool (q=4096 presets), so grow the pool to fit.
    n_candidates = max(n_candidates, num)
    if mesh is not None:
        # The candidate axis shards over the mesh; round the pool up so the
        # shards stay equal (XLA SPMD requires divisibility).
        n_shards = mesh.devices.size
        n_candidates = -(-n_candidates // n_shards) * n_shards
    k_pick, k_noise, k_mix = jax.random.split(key, 3)
    m, d = n_candidates, good.shape[1]
    # bw_factor < 1 sharpens the good-set KDE below the 1-D Scott rate —
    # an exploitation knob for high-D spaces where even univariate
    # bandwidths stay wide at realistic n.
    bw_good = _bandwidth_1d(good) * bw_factor
    # Candidates ~ the product KDE: each DIMENSION independently picks a
    # good point and jitters by that dimension's 1-D bandwidth.  Per-dim
    # independence both matches the density being scored and recombines
    # coordinates across good points (a candidate can take dim 0 from one
    # elite and dim 1 from another), mixed with 25% uniform exploration.
    log_w = _rank_log_weights(good.shape[0])
    idx = jax.random.categorical(k_pick, log_w, shape=(m, d))
    picked = jnp.take_along_axis(good.T, idx.T, axis=1).T  # (m, d)
    noise = jax.random.normal(k_noise, (m, d))
    cands = reflect_unit(picked + noise * bw_good[None, :])
    uniform = jax.random.uniform(k_mix, (m, d))
    take_uniform = (jnp.arange(m) % 4) == 3
    cands = jnp.where(take_uniform[:, None], uniform, cands)
    if mesh is not None:
        # Candidate-parallel SPMD, same layout as tpu_bo's fused step: the
        # (m, n) pairwise-kernel matmuls partition along m, the KDE points
        # replicate, and XLA inserts the top-k all-gather (orion_tpu.parallel).
        from orion_tpu.parallel import candidate_sharding

        cands = jax.lax.with_sharding_constraint(cands, candidate_sharding(mesh))

    score = _log_kde_product(cands, good, bw_good, log_w=log_w) - _log_kde_product(
        cands, bad, _bandwidth_1d(bad)
    )
    _, top = jax.lax.top_k(score, num)
    return cands[top]
