#!/usr/bin/env python
"""Multi-fidelity sweep example — BASELINE config #5's shape, runnable.

A training run whose cost scales with ``--epochs`` and whose validation
loss approaches the truth as epochs grow: exactly the structure a
``fidelity(...)`` dimension exploits.  ASHA (or its model-based variants)
evaluates many cheap low-epoch configurations and promotes only the
promising ones to full budget.

Run under the framework (any of asha / hyperband / asha_bo / bohb):

    orion-tpu hunt -n fid-sweep --storage-path db.sqlite --max-trials 60 \\
        -c <(echo 'algorithms: {asha_bo: {num_brackets: 2}}') \\
        examples/fidelity_sweep.py \\
        --lr~'loguniform(1e-4, 1e-1)' \\
        --width~'uniform(16, 256, discrete=True)' \\
        --epochs~'fidelity(1, 27, 3)'

Then inspect promotions: `orion-tpu info -n fid-sweep --storage-path
db.sqlite` — the same (lr, width) point re-appears at rising epochs.
"""

import argparse
import math

from orion_tpu.client import report_objective


def noisy_validation_loss(lr, width, epochs):
    """Stand-in for a real training curve: the asymptotic loss depends on
    the hyperparameters; finite epochs add an optimistic-bias term that
    shrinks as 1/epochs (the classic multi-fidelity correlation)."""
    asymptote = (math.log10(lr) + 2.0) ** 2 + (width - 96) ** 2 / 128.0**2
    finite_budget_bias = 0.5 / epochs
    return asymptote + finite_budget_bias


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--lr", type=float, required=True)
    parser.add_argument("--width", type=int, required=True)
    parser.add_argument("--epochs", type=int, required=True)
    args = parser.parse_args()
    report_objective(noisy_validation_loss(args.lr, args.width, args.epochs))


if __name__ == "__main__":
    main()
