#!/usr/bin/env python
"""Mixed Real/Integer/Categorical tuning example — BASELINE config #4.

Tunes a LeNet-style classifier over learning rate (real, log scale), batch
size (integer), width multiplier (integer), and activation (categorical).
The model is a small flax-free jax MLP-conv hybrid trained on a synthetic
MNIST-shaped dataset when torchvision data is unavailable (this image has no
network egress); plug in real MNIST tensors to reproduce the docs example.

Run under the framework:

    orion-tpu hunt -n lenet --storage-path db.pkl --max-trials 20 \\
        examples/mnist_lenet.py \\
        --lr~'loguniform(1e-4, 1e-1)' \\
        --batch-size~'uniform(32, 256, discrete=True)' \\
        --width~'uniform(1, 4, discrete=True)' \\
        --act~"choices(['relu', 'tanh', 'gelu'])"
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.client import report_objective

ACTS = {"relu": jax.nn.relu, "tanh": jnp.tanh, "gelu": jax.nn.gelu}


def synthetic_mnist(n=2048, seed=0):
    """Deterministic MNIST-shaped stand-in (28x28 images, 10 classes)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 28 * 28)).astype(np.float32)
    w_true = rng.normal(size=(28 * 28, 10)).astype(np.float32)
    y = np.argmax(x @ w_true + 0.5 * rng.normal(size=(n, 10)), axis=1)
    return x, y.astype(np.int32)


def train_eval(lr, batch_size, width, act_name, epochs=3, seed=0):
    x, y = synthetic_mnist()
    n_train = len(x) * 3 // 4
    act = ACTS[act_name]
    hidden = 32 * width
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    params = {
        "w1": jax.random.normal(k1, (784, hidden)) * (1.0 / 28.0),
        "b1": jnp.zeros(hidden),
        "w2": jax.random.normal(k2, (hidden, 10)) * (1.0 / jnp.sqrt(hidden)),
        "b2": jnp.zeros(10),
    }

    def forward(p, xb):
        h = act(xb @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"]

    def loss_fn(p, xb, yb):
        logits = forward(p, xb)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(logp[jnp.arange(len(yb)), yb])

    @jax.jit
    def step(p, xb, yb):
        grads = jax.grad(loss_fn)(p, xb, yb)
        return jax.tree.map(lambda a, g: a - lr * g, p, grads)

    xb_train, yb_train = jnp.asarray(x[:n_train]), jnp.asarray(y[:n_train])
    for _epoch in range(epochs):
        for i in range(0, n_train, batch_size):
            params = step(
                params, xb_train[i : i + batch_size], yb_train[i : i + batch_size]
            )
    logits = forward(params, jnp.asarray(x[n_train:]))
    acc = float(jnp.mean(jnp.argmax(logits, axis=1) == jnp.asarray(y[n_train:])))
    return 1.0 - acc  # minimize validation error


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--lr", type=float, required=True)
    parser.add_argument("--batch-size", type=int, required=True)
    parser.add_argument("--width", type=int, required=True)
    parser.add_argument("--act", required=True)
    args = parser.parse_args()
    error = train_eval(args.lr, args.batch_size, args.width, args.act)
    report_objective(error)


if __name__ == "__main__":
    main()
