#!/usr/bin/env python
"""Benchmark: the BASELINE.json north-star configuration.

Measures **suggestions/sec at q=1024 on Hartmann6** for the TPU-native
batched GP-BO engine (`tpu_bo`) **through the public algorithm API**
(`BaseAlgorithm.suggest`/`observe`, including unit-cube decode and host
param-dict construction), against the skopt-style anchor: a sequential CPU
GP-EI loop (sklearn GaussianProcessRegressor with a Matern-5/2 kernel and
MLL refit per suggestion + EI argmax — which is what skopt's `gp_minimize`
does internally; skopt itself is not installed in this image).

Also verifies simple-regret parity (the other half of the north star): both
optimizers run from the same 16-point initial design to an equal
192-evaluation budget, and the engine's simple regret must not exceed the
anchor's by more than the tolerance.  The check is a hard assert AND both
regrets are printed in the JSON line.

Prints ONE json line:
{"metric", "value", "unit", "vs_baseline", "regret", "anchor_regret",
 "wall_ms_per_round", "device_ms_per_round", "breakdown_ms"} — the last is
the per-stage host/device split of one steady-state round (encode, upload,
dispatch, wait_transfer, decode, dict_build, doc_build; see
bench_breakdown).  The steady-state host tax is gated against device time
(_check_host_budget: 1.25x factor from orion_tpu.hostbudget — the same
knob the doctor's DX004 rule and `orion-tpu top` read;
ORION_TPU_HOST_BUDGET_FACTOR overrides — hard SystemExit in --smoke,
warning on full runs).
"""

import json
import time
import warnings

import numpy as np


Q = 1024
N_INIT = 16
PARITY_BUDGET = 192
PARITY_Q = 16
REGRET_TOL = 0.10  # ours may trail the anchor's regret by at most 10%
GLOBAL_MIN = -3.32237  # Hartmann6
SEED = 0

#: Version of the emitted JSON payload (and of the compact
#: ``BENCH_history.jsonl`` records derived from it).  Bump when a payload
#: key is renamed/removed so cross-run consumers (the doctor's future
#: perf-trajectory rules, trend dashboards) can join records honestly —
#: today's BENCH_r*.json files carry no version and form no
#: machine-joinable series.
BENCH_SCHEMA_VERSION = 2


def _hartmann6_np(u):
    import jax.numpy as jnp

    import orion_tpu.benchmarks.functions as f

    return np.asarray(f.hartmann6(jnp.asarray(u)))


def _make_algo(seed=SEED, n_candidates=16384, fit_steps=40, prewarm=False):
    from orion_tpu.algo.base import create_algo
    from orion_tpu.space.dsl import build_space

    space = build_space({f"x{i}": "uniform(0, 1)" for i in range(6)})
    return create_algo(
        space,
        # local_frac 0.3 = the measured setting for smooth multimodal
        # landscapes (runner.py's hartmann6 preset comment has the A/B).
        # prewarm defaults OFF here (the production default is on): the
        # timed phases must not have a background XLA compile competing
        # for cores mid-measurement; bench_prewarm opts in explicitly to
        # measure the boundary-crossing contract itself.
        {"tpu_bo": {"n_init": N_INIT, "n_candidates": n_candidates,
                     "fit_steps": fit_steps, "local_frac": 0.3,
                     "prewarm": prewarm}},
        seed=seed,
    )


def _params_to_x(params_list):
    return np.asarray(
        [[p[f"x{i}"] for i in range(6)] for p in params_list], dtype=np.float32
    )


def _observe(algo, X, y):
    params = [{f"x{i}": float(row[i]) for i in range(6)} for row in np.asarray(X)]
    algo.observe(params, [{"objective": float(v)} for v in np.asarray(y)])


def bench_throughput():
    """suggestions/sec at q=1024 through the public suggest/observe API.

    Each timed round first observes a fresh batch (marking the GP stale), so
    the measured suggest() includes the full honest cycle: encode + refit +
    candidate generation + acquisition + dedup + decode to param dicts.
    History starts at 130 points so the padded GP size (256) is stable across
    rounds — no recompilation inside the timing loop.
    """
    rng = np.random.default_rng(SEED)
    algo = _make_algo()
    X = rng.uniform(size=(130, 6)).astype(np.float32)
    _observe(algo, X, _hartmann6_np(X))

    algo.suggest(Q)  # compile (fit at pad 256 + acquire at q=1024)
    times = []
    for _ in range(5):
        Xn = rng.uniform(size=(16, 6)).astype(np.float32)
        _observe(algo, Xn, _hartmann6_np(Xn))  # marks the GP stale
        t0 = time.perf_counter()
        out = algo.suggest(Q)
        times.append(time.perf_counter() - t0)
    assert len(out) == Q and set(out[0]) == {f"x{i}" for i in range(6)}
    return Q / float(np.median(times))


#: Seeds of the multi-seed regret-trajectory gate (seed 0 doubles as the
#: anchor-parity run).  Five seeds span both modes of Hartmann6's bimodal
#: seed distribution (BENCH_REGRET_BASELINE.json's justification).
GATE_SEEDS = (0, 1, 2, 3, 4)
REGRET_BASELINE_PATH = "BENCH_REGRET_BASELINE.json"


def run_regret_curve(seed, budget=PARITY_BUDGET, q=PARITY_Q, algo_kwargs=None):
    """One seeded bench regret trajectory: ``(curve, health_records)``.

    ``curve`` is the incumbent simple regret after the initial design and
    after every q-round; ``health_records`` one ``algo.health_record()``
    dict per GP round (regret stamped in) — the optimization-health series
    the gate and the emitted ``health`` payload are built from.  Seed
    ``SEED`` with default kwargs reproduces the historical single-seed
    regret number exactly."""
    rng = np.random.default_rng(seed)
    X0 = rng.uniform(size=(N_INIT, 6)).astype(np.float32)
    y0 = _hartmann6_np(X0)
    algo = _make_algo(seed=seed, **(algo_kwargs or {}))
    _observe(algo, X0, y0)
    best = float(np.min(y0))
    n_evals = len(y0)
    curve = [best - GLOBAL_MIN]
    health_records = []
    while n_evals < budget:
        step_q = min(q, budget - n_evals)
        params = algo.suggest(step_q)
        Xn = _params_to_x(params)
        yn = _hartmann6_np(Xn)
        algo.observe(params, [{"objective": float(v)} for v in yn])
        best = min(best, float(np.min(yn)))
        n_evals += step_q
        curve.append(best - GLOBAL_MIN)
        record = algo.health_record() or {}
        record["regret"] = best - GLOBAL_MIN
        record["round"] = len(health_records) + 1
        health_records.append(record)
    return curve, health_records


def _health_payload(curve, health_records):
    """The emitted ``health`` block: the per-round regret curve plus the
    GP/TR health series and the last full record (schema-pinned by
    tests/unit/test_bench_smoke.py)."""
    return {
        "regret_curve": [round(float(v), 6) for v in curve],
        "rounds": len(health_records),
        "gp_mll": [
            round(r["gp_mll"], 4) for r in health_records if r.get("gp_mll") is not None
        ],
        "tr_length": [
            round(r["tr_length"], 4)
            for r in health_records
            if r.get("tr_length") is not None
        ],
        "last": health_records[-1] if health_records else None,
    }


def _baseline_curves(baseline_path=REGRET_BASELINE_PATH):
    """Committed baseline curves, resolved next to this file when the cwd
    differs (the smoke test runs bench.py from the repo root either way)."""
    import os

    from orion_tpu.benchmarks.regret_gate import load_baseline

    path = baseline_path
    if not os.path.exists(path):
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), baseline_path
        )
    return load_baseline(path)


def bench_regret_gate(curves, baseline_path=REGRET_BASELINE_PATH):
    """Evaluate the multi-seed statistical gate against the committed
    baseline (orion_tpu.benchmarks.regret_gate); returns the verdict dict
    with the measured per-seed finals attached."""
    from orion_tpu.benchmarks.regret_gate import evaluate_regret_gate

    baseline = _baseline_curves(baseline_path)
    verdict = evaluate_regret_gate(curves, baseline)
    verdict["current_final"] = [round(float(c[-1]), 6) for c in curves]
    verdict["baseline_final"] = [round(float(c[-1]), 6) for c in baseline]
    return verdict


def run_anchor_regret(X0, y0):
    """Sequential skopt-style GP-EI on CPU from the same initial design.

    Returns (simple_regret, per-suggest times at history >= 128) so the
    anchor's suggestions/sec is measured at a history size comparable to the
    throughput bench (130+).
    """
    from scipy.stats import norm
    from sklearn.gaussian_process import GaussianProcessRegressor
    from sklearn.gaussian_process.kernels import (
        ConstantKernel,
        Matern,
        WhiteKernel,
    )

    rng = np.random.default_rng(SEED + 1)
    X = np.asarray(X0, dtype=np.float64)
    y = np.asarray(y0, dtype=np.float64)
    times = []
    while len(y) < PARITY_BUDGET:
        t0 = time.perf_counter()
        kernel = (
            ConstantKernel(1.0) * Matern(length_scale=np.ones(6), nu=2.5)
            + WhiteKernel(1e-4)
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            gpr = GaussianProcessRegressor(
                kernel=kernel,
                normalize_y=True,
                n_restarts_optimizer=1,
                random_state=SEED,
            )
            gpr.fit(X, y)
            cands = rng.uniform(size=(1000, 6))
            mu, std = gpr.predict(cands, return_std=True)
        z = (y.min() - mu) / np.maximum(std, 1e-12)
        ei = std * (z * norm.cdf(z) + norm.pdf(z))
        xn = cands[np.argmax(ei)]
        dt = time.perf_counter() - t0
        if len(y) >= 128:
            times.append(dt)
        yn = _hartmann6_np(xn[None].astype(np.float32))
        X = np.vstack([X, xn[None]])
        y = np.append(y, yn)
    return float(y.min()) - GLOBAL_MIN, times


def bench_storage(q=Q, rounds=3):
    """The storage edge of one producer round: register a q-trial batch
    through ``DocumentStorage.register_trials`` on the two backends that
    matter at scale — sqlite (the durable local default) and network (an
    in-process loopback server) — measuring wall ms per round AND the
    backend-level operation count (SQLite transactions / wire round
    trips).  The batched write path commits the whole round as ONE
    transaction / ONE wire request, so ``storage_ops_per_round`` must stay
    O(1) regardless of q; a regression back to per-trial commits shows up
    here as q, not 1.

    Returns ``(storage_ms, storage_ops_per_round)`` dicts keyed by
    backend."""
    import os
    import tempfile

    from orion_tpu.core.trial import Trial
    from orion_tpu.storage.base import DocumentStorage
    from orion_tpu.storage.netdb import DBServer, NetworkDB
    from orion_tpu.storage.sqlitedb import SQLiteDB

    rng = np.random.default_rng(SEED + 3)
    storage_ms, storage_ops = {}, {}

    def _run(name, db, ops_counter):
        storage = DocumentStorage(db)
        exp = storage.create_experiment(
            {"name": "bench-storage", "metadata": {"user": "bench"}}
        )
        times = []
        ops_before = ops_counter()
        for _ in range(rounds):
            trials = [
                Trial(
                    experiment=exp["_id"],
                    params={f"x{i}": float(v) for i, v in enumerate(row)},
                )
                for row in rng.uniform(size=(q, 6))
            ]
            t0 = time.perf_counter()
            outcomes = storage.register_trials(trials)
            times.append(time.perf_counter() - t0)
            assert not any(isinstance(o, Exception) for o in outcomes)
        storage_ms[name] = round(1e3 * float(np.median(times)), 3)
        storage_ops[name] = int(round((ops_counter() - ops_before) / rounds))

    with tempfile.TemporaryDirectory(prefix="orion-bench-storage-") as tmpdir:
        sqlite_db = SQLiteDB(os.path.join(tmpdir, "bench.sqlite"))
        try:
            _run("sqlite", sqlite_db, lambda: sqlite_db.txn_count)
        finally:
            sqlite_db.close()

    server = DBServer(port=0)
    host, port = server.serve_background()
    net_db = NetworkDB(host=host, port=port)
    try:
        _run("network", net_db, lambda: net_db.wire_requests)
    finally:
        net_db.close()
        server.shutdown()
        server.server_close()
    return storage_ms, storage_ops


def bench_breakdown(rounds=4, q=Q, algo=None, n_hist=130):
    """Median per-round host/device breakdown of the q=1024 boundary at the
    steady-state shape, one stage at a time (the stages algo.observe +
    algo.suggest run internally, replayed through the same public codec and
    suggest-step entry points):

    - encode:        observe-side dict -> unit-cube rows (params_to_cube)
    - upload:        observe-side device append (the incremental
                     DeviceHistory write — the only O(batch) transfer; the
                     history itself stays resident)
    - dispatch:      host prep + async dispatch of the fused suggest jit
                     (the copula transform runs in-jit over the resident
                     device buffers — nothing history-sized is rebuilt on
                     host or uploaded here)
    - wait_transfer: blocking on the device result + the (q, d) transfer
                     (device execution + this image's tunnel round trip)
    - decode:        cube -> per-dim host arrays (decode_flat_np)
    - dict_build:    per-dim arrays -> the round's ParamBatch
                     (arrays_to_params: vectorized column build; the
                     per-trial dicts are LAZY — they materialize exactly
                     once, inside the doc_build stage's columnar pass,
                     instead of eagerly here — docs/performance.md
                     "Wall ≈ device")
    - doc_build:     the columnar trial-document pass (TrialBatch.prepare
                     + to_docs — ids and storage docs for the whole
                     q-round, what the producer's commit feeds apply_batch)
    - health:        one ``algo.health_record()`` read (the per-round
                     optimization-health record, orion_tpu.health) —
                     measured AFTER wait_transfer so it reads ready device
                     data; ``main``/``--smoke`` hard-assert it stays under
                     1% of the round

    Everything except wait_transfer is host boundary tax; regressions in
    any stage show up in the JSON line.  ``storage_ms`` (the sqlite commit
    of one q-batch registration, measured by :func:`bench_storage`) is
    merged into this dict by ``main`` — the host stage the pipelined
    producer commit overlaps with the next round's dispatch.

    The FIRST loop round is a discarded warmup: the big fused-step compile
    is covered by the pre-loop ``suggest``, but the first in-loop round
    still pays the batch-16 observe-append jit compile (measured
    ``wait_transfer≈3306ms`` at ``rounds=3`` on CPU) — a median over few
    rounds must not carry that one-time cost as a steady-state number."""
    rng = np.random.default_rng(SEED + 2)
    if algo is None:
        algo = _make_algo(seed=SEED + 2)
    space = algo.space
    X = rng.uniform(size=(n_hist, 6)).astype(np.float32)
    _observe(algo, X, _hartmann6_np(X))
    algo.suggest(q)  # compile

    from orion_tpu.algo.tpu_bo import (
        dispatch_prep_stats,
        plan_prep_stats,
        reset_dispatch_prep_stats,
        reset_plan_prep_stats,
    )
    from orion_tpu.core.trial import TrialBatch

    # Plan-prep cache accounting over the measured rounds only: the µs the
    # per-signature cache saves inside the dispatch stage (statics dict +
    # signature + cold-hypers rebuilt on a miss, reused on a hit), and the
    # µs the per-instance prep token saves on top (skipping the prep-key
    # probe entirely on the steady path).
    reset_plan_prep_stats()
    reset_dispatch_prep_stats()

    stages = {k: [] for k in
              ("encode", "upload", "dispatch", "wait_transfer", "health",
               "decode", "dict_build", "doc_build")}
    for bench_round in range(rounds + 1):
        Xn = rng.uniform(size=(16, 6)).astype(np.float32)
        yn = _hartmann6_np(Xn)
        params = [{f"x{i}": float(r[i]) for i in range(6)} for r in Xn]
        t0 = time.perf_counter()
        cube = space.params_to_cube(params)
        t1 = time.perf_counter()
        algo.observe(params, [{"objective": float(v)} for v in yn], cube=cube)
        t2 = time.perf_counter()
        rows = algo._suggest_cube(q)
        t3 = time.perf_counter()
        out = np.asarray(rows)
        t4 = time.perf_counter()
        algo.health_record()
        t_health = time.perf_counter()
        arrays = space.decode_flat_np(out)
        t5 = time.perf_counter()
        batch = space.arrays_to_params(arrays)
        t6 = time.perf_counter()
        TrialBatch(batch).prepare("bench-breakdown", submit_time=0.0).to_docs()
        t7 = time.perf_counter()
        if bench_round == 0:
            continue  # discarded warmup round (append-jit compiles)
        for key, dt in zip(stages, (t1 - t0, t2 - t1, t3 - t2, t4 - t3,
                                    t_health - t4, t5 - t_health,
                                    t6 - t5, t7 - t6)):
            stages[key].append(dt)
    out = {k: round(1e3 * float(np.median(v)), 3) for k, v in stages.items()}
    # SAVINGS reports like telemetry_us_saved, not stages: the dispatch
    # medians above already CONTAIN the cache-hit prep, so the saved µs must
    # be excluded from every host_ms sum (test_bench_smoke pins this).
    out["prep_us_saved"] = plan_prep_stats()["saved_us"]
    out["dispatch_us_saved"] = dispatch_prep_stats()["saved_us"]
    return out


def bench_telemetry_batching(samples_per_round=4, rounds=400):
    """Host µs per round saved by the producer's batched span bookkeeping
    (``Telemetry.record_spans_batch`` vs one ``record_span`` per sample) —
    the ROADMAP-item-2 down-payment number reported as
    ``breakdown_ms["telemetry_us_saved"]``.  Measured on a PRIVATE enabled
    registry so the timed bench phases keep their disabled-path default."""
    import time as _time

    from orion_tpu.telemetry import Telemetry

    tel = Telemetry(enabled=True, span_capacity=8192)
    args = {"count": 16}
    t0 = _time.perf_counter()
    for _ in range(rounds):
        for _s in range(samples_per_round):
            tel.record_span("bench.tel.single", duration=1e-4, args=args)
    per_call = _time.perf_counter() - t0
    tel.reset()
    entries = [("bench.tel.batch", None, 1e-4, args)] * samples_per_round
    t0 = _time.perf_counter()
    for _ in range(rounds):
        tel.record_spans_batch(entries)
    batched = _time.perf_counter() - t0
    return round((per_call - batched) / rounds * 1e6, 2)


def bench_id_hash(q=1024, reps=5):
    """Trial-identity cost at the bench batch size: the md5 path
    (per-trial repr assembly + md5, ``compute_batch_ids``) vs the
    ``cube_hash`` scheme (ONE vectorized pass over the canonical cube-row
    bytes, ``compute_scheme_ids``) — the ~6.4µs/trial repr+md5 floor was
    the last per-trial host line of the registration tail (ROADMAP item
    5).  Returns per-trial µs for both paths, the speedup, and a
    ``distinct_ok`` collision check over the q-batch; ``--smoke``
    hard-gates ``speedup >= 4`` at q=1024."""
    from orion_tpu.core.trial import compute_batch_ids, compute_scheme_ids
    from orion_tpu.space.dsl import build_space

    space = build_space({f"x{i}": "uniform(0, 1)" for i in range(6)})
    rng = np.random.default_rng(SEED + 3)
    cube = rng.uniform(size=(q, 6)).astype(np.float32)
    arrays = space.decode_flat_np(cube)
    params = space.arrays_to_params(arrays)
    exp_id = "bench-id-hash"
    md5_times, cube_times = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        md5_ids = compute_batch_ids(exp_id, params)
        md5_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        cube_ids = compute_scheme_ids(
            exp_id, params, id_scheme="cube_hash", space=space
        )
        cube_times.append(time.perf_counter() - t0)
    md5_us = float(np.median(md5_times)) / q * 1e6
    cube_us = float(np.median(cube_times)) / q * 1e6
    return {
        "q": q,
        "md5_us_per_trial": round(md5_us, 3),
        "cube_hash_us_per_trial": round(cube_us, 3),
        "speedup": round(md5_us / cube_us, 2) if cube_us else None,
        "distinct_ok": len(set(cube_ids)) == q and len(set(md5_ids)) == q,
    }


def bench_prewarm(q=16):
    """The pow-2 boundary-crossing contract, asserted on every bench run:
    grow a small history across a bucket boundary with prewarm enabled and
    measure (via telemetry) how many synchronous retraces the post-warm
    suggest rounds paid — MUST be zero (the background compile turned the
    crossing into a jit-cache hit; docs/performance.md, "The zero-reupload
    round").  Returns ``{"retraces_after_warm", "prewarms"}``; retrace
    introspection rides a private jax accessor, so the fields are None
    (skipped, not failed) when it is unavailable."""
    from orion_tpu import telemetry as tel
    from orion_tpu.algo.tpu_bo import _suggest_step

    if not hasattr(_suggest_step, "_cache_size"):
        return {"retraces_after_warm": None, "prewarms": None}
    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    try:
        rng = np.random.default_rng(SEED + 5)
        # Distinct static signature (n_candidates) from the other bench
        # phases so their compiled buckets cannot mask the measurement.
        algo = _make_algo(seed=SEED + 5, n_candidates=192, fit_steps=2,
                          prewarm=True)

        def observe(batch):
            Xn = rng.uniform(size=(batch, 6)).astype(np.float32)
            _observe(algo, Xn, _hartmann6_np(Xn))

        observe(40)          # bucket 64, under the fill threshold
        algo.suggest(q)      # compiles the 64-bucket, records the q bucket
        observe(16)          # count 56 >= 48: prewarm of bucket 128 starts
        algo._prewarmer.wait()
        base = tel.TELEMETRY.counter_value("jax.retraces")
        observe(16)          # count 72: crosses 64 -> 128
        algo.suggest(q)      # post-warm round — must be a cache hit
        return {
            "retraces_after_warm":
                tel.TELEMETRY.counter_value("jax.retraces") - base,
            "prewarms": tel.TELEMETRY.counter_value("jax.prewarms"),
        }
    finally:
        if not was_enabled:
            tel.TELEMETRY.disable()


def bench_serve(m_tenants=2, rounds=4, q=8, window=0.4, n_candidates=256,
                fit_steps=4, storage=None, algorithms=None, priors=None,
                name_prefix="bench-serve"):
    """The multi-tenant suggest gateway, full stack (orion_tpu.serve):
    M concurrent experiments — each a REAL producer/worker loop over one
    shared sqlite store, its algorithm a gateway-backed RemoteAlgorithm —
    drive one in-process GatewayServer with barrier-synchronized rounds so
    concurrent suggest traffic actually lands in the coalescing window.

    Hard asserts (the serving contract, ISSUE 8):

    - **coalescing happened**: at least one dispatch stacked >= 2 tenants
      (``max_width >= 2``), and device dispatches per suggest < 1 — M
      suggests cost fewer than M device calls;
    - **storage invariants hold**: `orion-tpu audit` is clean for every
      tenant experiment after the run (served rounds register/complete
      trials exactly like local ones).

    Returns the ``serve`` payload block: coalesce width stats, device
    dispatches per suggest, per-tenant request p50/p99 (from the gateway's
    per-tenant telemetry histograms), backpressure/eviction counts.

    ``algorithms``/``priors`` parametrize the tenants' experiments (default:
    6-dim Hartmann6 under tpu_bo) — the ``--serve --smoke`` asha_bo leg
    reuses this same harness with a fidelity dimension added; the objective
    is always Hartmann6 over the ``x*`` parameters, so a fidelity column
    simply rides along unscored."""
    import os
    import tempfile
    import threading

    from orion_tpu import telemetry as tel
    from orion_tpu.client.experiment import ExperimentClient
    from orion_tpu.core.experiment import build_experiment
    from orion_tpu.serve.gateway import GatewayServer
    from orion_tpu.storage.audit import audit_experiment
    from orion_tpu.storage.base import create_storage
    from orion_tpu.telemetry import histogram_percentile

    if priors is None:
        priors = {f"x{j}": "uniform(0, 1)" for j in range(6)}
    if algorithms is None:
        algorithms = {
            "tpu_bo": {
                "n_init": q,
                "n_candidates": n_candidates,
                "fit_steps": fit_steps,
            }
        }
    x_names = sorted(
        (k for k in priors if k.startswith("x")), key=lambda k: int(k[1:])
    )
    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    server = GatewayServer(window=window, max_width=max(2, m_tenants))
    host, port = server.serve_background()
    barrier = threading.Barrier(m_tenants)
    errors, reports = [], {}
    try:
        with tempfile.TemporaryDirectory(prefix="orion-bench-serve-") as tmp:
            if storage is None:
                storage = create_storage(
                    {"type": "sqlite", "path": os.path.join(tmp, "serve.sqlite")}
                )

            def run_tenant(index):
                try:
                    experiment = build_experiment(
                        storage,
                        f"{name_prefix}-{index}",
                        priors=priors,
                        algorithms=algorithms,
                        pool_size=q,
                        metadata={"user": "bench"},
                    )
                    experiment.serve_config = {"address": f"{host}:{port}"}
                    experiment.instantiate(seed=SEED + index)
                    client = ExperimentClient(experiment)
                    for _ in range(rounds):
                        # Round barrier: the gateway's coalescing window is
                        # small; the bench must present genuinely
                        # concurrent traffic, as M live workers would.
                        barrier.wait(timeout=120)
                        trials = client.suggest(q)
                        X = np.asarray(
                            [
                                [t.params[name] for name in x_names]
                                for t in trials
                            ],
                            dtype=np.float32,
                        )
                        client.observe_all(
                            trials, [float(v) for v in _hartmann6_np(X)]
                        )
                    reports[index] = audit_experiment(storage, experiment)
                except Exception as exc:  # surfaced after join
                    errors.append(exc)
                    barrier.abort()

            threads = [
                threading.Thread(target=run_tenant, args=(i,), daemon=True)
                for i in range(m_tenants)
            ]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join(timeout=500)
            assert not errors, f"serve bench tenant failed: {errors[0]!r}"
            stats = server.stats_snapshot()
            snapshot = tel.TELEMETRY.snapshot()
    finally:
        server.shutdown()
        server.server_close()
        if not was_enabled:
            tel.TELEMETRY.disable()

    assert all(r.ok for r in reports.values()), {
        i: r.summary() for i, r in reports.items() if not r.ok
    }
    assert stats["max_width"] >= 2, (
        f"no coalescing happened: width stats {stats['widths']}"
    )
    ratio = stats["dispatches_per_suggest"]
    assert ratio is not None and ratio < 1.0, (
        f"device dispatches per suggest = {ratio} (must be < 1 for "
        f"M={m_tenants} tenants): {stats}"
    )
    per_tenant = {}
    for name, hist in (snapshot.get("histograms") or {}).items():
        prefix, suffix = "serve.tenant.", ".request"
        if name.startswith(prefix) and name.endswith(suffix) and hist.get("count"):
            tenant = name[len(prefix):-len(suffix)]
            per_tenant[tenant] = {
                "requests": hist["count"],
                "p50_ms": round(histogram_percentile(hist, 50) * 1e3, 3),
                "p99_ms": round(histogram_percentile(hist, 99) * 1e3, 3),
            }
    return {
        "tenants": m_tenants,
        "rounds": rounds,
        "q": q,
        "suggests": stats["suggests"],
        "device_dispatches": stats["dispatches"],
        "dispatches_per_suggest": ratio,
        "coalesced_dispatches": stats["coalesced_dispatches"],
        "coalesce_max_width": stats["max_width"],
        "coalesce_widths": stats["widths"],
        "backpressure": stats["backpressure"],
        "evictions": stats["evictions"],
        "per_tenant": per_tenant,
        "audit_violations": sum(
            len(r.violations) for r in reports.values()
        ),
    }


def bench_serve_fleet(m_gateways=3, n_tenants=6, rounds=4, q=8, window=0.4,
                      n_candidates=256, fit_steps=4, priors=None,
                      algorithms=None, name_prefix="bench-fleet"):
    """The gateway FLEET leg (ISSUE 19): K tenants ring-routed over M
    gateway processes with one member killed mid-stream.

    Two passes with identical seeds: a single-gateway reference run, then
    the fleet run — M members sharing a per-tenant snapshot store, every
    client routing by the consistent-hash ring (``serve.addresses``), and
    the member owning the MOST tenants killed (simulated crash, no
    farewell snapshot) at the mid-stream round barrier while suggests are
    in flight.  Hard gates (SystemExit, not assert — must hold under
    ``python -O``):

    - **bit-identical**: every tenant's suggestion stream matches its
      single-gateway reference exactly — failover + store restore +
      replay never fork a trajectory;
    - **zero lost observations**: each tenant's gateway-side count equals
      ``rounds * q`` on whichever surviving member hosts it;
    - **fleet-wide amortization**: total device dispatches / total
      suggests < 1 across ALL members — the per-process coalescing win
      survives the scale-out (ring co-residents still stack);
    - **the kill bit**: at least one client failover actually happened,
      and every tenant experiment passes ``orion-tpu audit``.

    Returns the ``serve_fleet`` payload block."""
    import os
    import socket
    import tempfile
    import threading

    from orion_tpu import telemetry as tel
    from orion_tpu.client.experiment import ExperimentClient
    from orion_tpu.core.experiment import build_experiment
    from orion_tpu.serve.fleet import FleetState, ring_key
    from orion_tpu.serve.gateway import GatewayServer
    from orion_tpu.storage.audit import audit_experiment
    from orion_tpu.storage.base import create_storage

    if priors is None:
        priors = {f"x{j}": "uniform(0, 1)" for j in range(6)}
    if algorithms is None:
        algorithms = {
            "tpu_bo": {
                "n_init": q,
                "n_candidates": n_candidates,
                "fit_steps": fit_steps,
            }
        }
    x_names = sorted(
        (k for k in priors if k.startswith("x")), key=lambda k: int(k[1:])
    )

    def objective_values(X):
        # Hartmann6 over the x* columns; narrower spaces ride zero-padded.
        if X.shape[1] < 6:
            X = np.concatenate(
                [X, np.zeros((len(X), 6 - X.shape[1]), dtype=X.dtype)], axis=1
            )
        return [float(v) for v in _hartmann6_np(X)]

    def run_pass(serve_config, storage, barrier, controller=None):
        """Drive every tenant for ``rounds`` barrier-synchronized rounds.
        ``controller`` (fleet pass) participates in the same barrier from
        the calling thread — that is what lets it kill a member while the
        round's suggests are genuinely in flight."""
        streams, reports, errors = {}, {}, []

        def run_tenant(index):
            try:
                experiment = build_experiment(
                    storage,
                    f"{name_prefix}-{index}",
                    priors=priors,
                    algorithms=algorithms,
                    pool_size=q,
                    metadata={"user": "bench"},
                )
                experiment.serve_config = dict(serve_config)
                experiment.instantiate(seed=SEED + index)
                client = ExperimentClient(experiment)
                stream = []
                for _ in range(rounds):
                    barrier.wait(timeout=300)
                    trials = client.suggest(q)
                    X = np.asarray(
                        [
                            [t.params[name] for name in x_names]
                            for t in trials
                        ],
                        dtype=np.float32,
                    )
                    stream.append(X.tolist())
                    client.observe_all(trials, objective_values(X))
                # The Producer pushes completed trials to the gateway on
                # the NEXT suggest; flush so the last round's batch is
                # gateway-side before the zero-loss gate counts it.
                client.producer.update()
                streams[index] = stream
                reports[index] = audit_experiment(storage, experiment)
            except Exception as exc:  # surfaced after join
                errors.append(exc)
                barrier.abort()

        threads = [
            threading.Thread(target=run_tenant, args=(i,), daemon=True)
            for i in range(n_tenants)
        ]
        for thread in threads:
            thread.start()
        if controller is not None:
            controller()
        for thread in threads:
            thread.join(timeout=500)
        if errors:
            raise SystemExit(f"fleet bench tenant failed: {errors[0]!r}")
        return streams, reports

    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    failovers_before = int(tel.TELEMETRY.counter_value("serve.client.failovers"))
    try:
        with tempfile.TemporaryDirectory(prefix="orion-bench-fleet-") as tmp:
            # --- reference pass: the same tenants through ONE gateway ----
            ref_server = GatewayServer(
                window=window, max_width=max(2, n_tenants)
            )
            ref_host, ref_port = ref_server.serve_background()
            try:
                ref_streams, _ = run_pass(
                    {"address": f"{ref_host}:{ref_port}"},
                    create_storage(
                        {"type": "sqlite",
                         "path": os.path.join(tmp, "ref.sqlite")}
                    ),
                    threading.Barrier(n_tenants),
                )
            finally:
                ref_server.shutdown()
                ref_server.server_close()

            # --- fleet pass: M members, shared store, mid-stream kill ----
            def free_port():
                sock = socket.socket()
                sock.bind(("127.0.0.1", 0))
                port = sock.getsockname()[1]
                sock.close()
                return port

            members = [f"127.0.0.1:{free_port()}" for _ in range(m_gateways)]
            store = os.path.join(tmp, "tenant-store")
            gateways = [
                GatewayServer(
                    host="127.0.0.1",
                    port=int(member.rsplit(":", 1)[1]),
                    window=window,
                    max_width=max(2, n_tenants),
                    fleet=members,
                    advertise=member,
                    persist=store,
                )
                for member in members
            ]
            for gateway in gateways:
                gateway.serve_background()

            # Ring placement is known before any traffic (same HashRing on
            # every client); kill the member owning the MOST tenants so
            # the handoff path carries real load.
            fleet_state = FleetState(members)
            worker = f"{socket.gethostname()}:{os.getpid()}"
            tenant_names = [
                f"{name_prefix}-{index}-v1@{worker}"
                for index in range(n_tenants)
            ]
            placement = {member: 0 for member in members}
            for tenant in tenant_names:
                placement[fleet_state.owner(ring_key(tenant))] += 1
            victim_addr = max(placement, key=placement.get)
            victim = gateways[members.index(victim_addr)]
            survivors = [g for g in gateways if g is not victim]
            kill_round = rounds // 2
            barrier = threading.Barrier(n_tenants + 1)

            def controller():
                for round_index in range(rounds):
                    try:
                        barrier.wait(timeout=300)
                    except threading.BrokenBarrierError:
                        return
                    if round_index == kill_round:
                        # Simulated crash while the round's suggests are
                        # in flight: no farewell snapshot — durability
                        # must come from the sync persist-before-reply
                        # path alone.
                        victim.kill()

            try:
                fleet_streams, reports = run_pass(
                    {"addresses": list(members)},
                    create_storage(
                        {"type": "sqlite",
                         "path": os.path.join(tmp, "fleet.sqlite")}
                    ),
                    barrier,
                    controller=controller,
                )
            finally:
                for gateway in survivors:
                    gateway.shutdown()
                    gateway.server_close()
            # Stats survive shutdown (counters on the server object);
            # the victim's froze at the kill.
            stats = [gateway.stats_snapshot() for gateway in gateways]
    finally:
        if not was_enabled:
            tel.TELEMETRY.disable()
    failovers = (
        int(tel.TELEMETRY.counter_value("serve.client.failovers"))
        - failovers_before
    )

    # --- the gates (SystemExit: must hold under `python -O`) -------------
    for index in range(n_tenants):
        if fleet_streams.get(index) != ref_streams.get(index):
            raise SystemExit(
                f"fleet stream FORKED for tenant {index}: the killed-"
                "member run diverged from its single-gateway reference"
            )
    survivor_stats = [
        s for s, g in zip(stats, gateways) if g is not victim
    ]
    lost = {}
    for tenant in tenant_names:
        observed = max(
            (s["per_tenant"].get(tenant, {}).get("n_observed", 0)
             for s in survivor_stats),
            default=0,
        )
        if observed != rounds * q:
            lost[tenant] = observed
    if lost:
        raise SystemExit(
            f"fleet run LOST observations (want {rounds * q} each): {lost}"
        )
    total_suggests = sum(s["suggests"] for s in stats)
    total_dispatches = sum(s["dispatches"] for s in stats)
    ratio = (
        total_dispatches / total_suggests if total_suggests else None
    )
    if ratio is None or ratio >= 1.0:
        raise SystemExit(
            f"fleet-wide dispatches per suggest = {ratio} (must be < 1 "
            f"across {m_gateways} gateways): {stats}"
        )
    if failovers < 1:
        raise SystemExit(
            "the mid-stream kill never bit: no client failover happened "
            f"(victim {victim_addr} owned {placement[victim_addr]} tenants)"
        )
    audit_violations = sum(len(r.violations) for r in reports.values())
    if any(not r.ok for r in reports.values()):
        raise SystemExit(
            "fleet run audits dirty: "
            f"{ {i: r.summary() for i, r in reports.items() if not r.ok} }"
        )
    return {
        "gateways": m_gateways,
        "tenants": n_tenants,
        "rounds": rounds,
        "q": q,
        "killed": victim_addr,
        "kill_round": kill_round,
        "placement": placement,
        "suggests": total_suggests,
        "device_dispatches": total_dispatches,
        "dispatches_per_suggest": round(ratio, 4),
        "coalesce_max_width": max(s["max_width"] for s in stats),
        "failovers": failovers,
        "bit_identical": True,
        "lost_observations": 0,
        "audit_violations": audit_violations,
    }


def main_serve(m_tenants=4, rounds=6, q=16, smoke=False):
    """``bench.py --serve``: the gateway serving M concurrent experiments —
    prints ONE json line with the coalesce/latency/dispatch-amortization
    numbers (hard asserts inside bench_serve).

    ``--serve --smoke`` runs the tenants over a LOOPBACK NETDB store so
    every hop crosses a real wire, exports the merged distributed trace
    (``bench_serve_trace.json``), and hard-asserts the ISSUE-11 acceptance:
    a RemoteAlgorithm suggest, the gateway's coalesced dispatch (link),
    and the storage commit's server-side apply joined by trace_id, with
    cross-process flow events in the Perfetto file."""
    if not smoke:
        payload = {
            "metric": "serve gateway smoke",
            "serve": bench_serve(
                m_tenants=m_tenants, rounds=rounds, q=q, n_candidates=1024,
                fit_steps=8,
            ),
            # The fleet headline (ISSUE 19): M=3 gateways x K tenants with
            # a mid-stream member kill — bit-identical streams, zero lost
            # observations, fleet-wide dispatches/suggest < 1, all
            # SystemExit-gated inside.
            "serve_fleet": bench_serve_fleet(
                m_gateways=3, n_tenants=6, rounds=rounds, q=q,
                n_candidates=1024, fit_steps=8,
            ),
        }
        print(json.dumps(payload))
        return

    from orion_tpu import telemetry as tel
    from orion_tpu.storage.base import DocumentStorage
    from orion_tpu.storage.netdb import DBServer, NetworkDB
    from orion_tpu.tracing import SERVER_EXPERIMENT

    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    db_server = DBServer(port=0)
    host, port = db_server.serve_background()
    net_db = NetworkDB(host=host, port=port)
    try:
        serve_block = bench_serve(
            m_tenants=2, rounds=3, q=8, window=0.4, n_candidates=256,
            fit_steps=4, storage=DocumentStorage(net_db),
        )
        # asha_bo leg (host-tail endgame): two multi-fidelity tenants whose
        # fused-step signatures must still line up — promotions are consumed
        # host-side, only the FRESH points ride the device plan, and the
        # bucket-normalized shapes (q bucket, quantized local_sigma ladder)
        # keep both tenants coalescible.  bench_serve hard-asserts
        # max_width >= 2 inside.
        serve_asha_block = bench_serve(
            m_tenants=2, rounds=4, q=8, window=0.4, n_candidates=128,
            fit_steps=4, storage=DocumentStorage(net_db),
            algorithms={
                "asha_bo": {"n_init": 8, "n_candidates": 128, "fit_steps": 4}
            },
            priors={
                **{f"x{j}": "uniform(0, 1)" for j in range(6)},
                "epochs": "fidelity(1, 9, 3)",
            },
            name_prefix="bench-serve-asha",
        )
        db_server.flush_server_spans(force=True)
        server_spans = DocumentStorage(net_db).fetch_spans(SERVER_EXPERIMENT)
    finally:
        net_db.close()
        db_server.shutdown()
        db_server.server_close()
        if not was_enabled:
            tel.TELEMETRY.disable()
    # The 2-gateway fleet twin of the full run's M=3 leg: kill one member
    # mid-stream — zero lost, bit-identical streams, clean audits, all
    # SystemExit-gated inside bench_serve_fleet.
    serve_fleet_block = bench_serve_fleet(
        m_gateways=2, n_tenants=3, rounds=4, q=8, window=0.4,
        n_candidates=128, fit_steps=4,
    )
    spans = [s for s in tel.TELEMETRY.iter_spans() if s] + list(server_spans)
    trace_path = "bench_serve_trace.json"
    tel.write_chrome_trace(trace_path, spans)
    joined = assert_joined_serve_trace(spans)
    payload = {
        "metric": "serve gateway smoke (distributed trace)",
        "serve": serve_block,
        "serve_asha": serve_asha_block,
        "serve_fleet": serve_fleet_block,
        "serve_trace_file": trace_path,
        "trace": joined,
    }
    print(json.dumps(payload))


def assert_joined_serve_trace(spans):
    """The ISSUE-11 end-to-end join, hard-gated (SystemExit, not assert —
    must hold under ``python -O``): at least one trace_id carries BOTH the
    client's ``serve.client.suggest`` span and the netdb server's
    ``netdb.apply`` span AND is linked by a gateway ``serve.dispatch``
    span; the exported events contain >= 1 bound ``s``/``f`` flow pair."""
    from orion_tpu.telemetry import chrome_trace_events

    by_trace = {}
    for span in spans:
        trace_id = span.get("trace_id")
        if trace_id:
            by_trace.setdefault(trace_id, set()).add(span.get("name"))
    linked = set()
    for span in spans:
        if span.get("name") != "serve.dispatch":
            continue
        for link in span.get("links") or ():
            linked.add((link or {}).get("trace_id"))
    joined = [
        trace_id
        for trace_id, names in by_trace.items()
        if "serve.client.suggest" in names
        and "netdb.apply" in names
        and trace_id in linked
    ]
    if not joined:
        raise SystemExit(
            "distributed serve trace is NOT joined: no trace_id carries "
            "serve.client.suggest + netdb.apply + a serve.dispatch link "
            f"(traces seen: {len(by_trace)}, linked: {len(linked)})"
        )
    events = chrome_trace_events(spans)
    starts = {e["id"] for e in events if e.get("ph") == "s"}
    finishes = {e["id"] for e in events if e.get("ph") == "f"}
    flow_pairs = len(starts & finishes)
    if not flow_pairs:
        raise SystemExit("no cross-process flow events in the serve trace")
    return {"joined_traces": len(joined), "flow_pairs": flow_pairs}


def bench_trace(out_path, rounds=3, q=16):
    """Run a few REAL producer rounds (sqlite storage, speculation-safe
    random search) and one GP suggest pair with the unified telemetry
    registry enabled, then export the process's span ring as a Chrome
    trace-event JSON — the artifact every bench run leaves behind so the
    PR-2 pipelined commit is *visible*: in Perfetto the round's
    ``storage.commit`` span runs concurrently under the open
    ``device.dispatch`` window (speculative suggest in flight while the
    batched register writes).  The GP pair adds the
    ``jax.suggest_step.compile`` (first call, retrace) and
    ``jax.suggest_step.dispatch`` (second call, cache hit) spans.

    A DISTRIBUTED leg then runs the same producer rounds over a loopback
    netdb server, so every round's trace crosses a real wire: the server's
    adopted ``netdb.apply`` spans are fetched back through the
    ``__server__`` channel, merged by trace_id, and the exported file
    carries cross-process flow arrows.  The merged spans feed the
    critical-path attribution (``orion_tpu.tracing``): each round's wall
    time bucketed into client-host / wire / server-host / device — the
    ROADMAP item-2 burn-down number.  Returns ``(path, host_attribution)``.

    Telemetry is enabled ONLY inside this phase, so the timed benches above
    keep measuring the disabled-path cost (the production default)."""
    import os
    import tempfile

    from orion_tpu import telemetry as tel
    from orion_tpu.core.experiment import build_experiment
    from orion_tpu.core.producer import Producer
    from orion_tpu.storage.base import DocumentStorage, create_storage
    from orion_tpu.storage.netdb import DBServer, NetworkDB
    from orion_tpu.tracing import SERVER_EXPERIMENT, summarize_attribution

    def run_rounds(storage, name):
        experiment = build_experiment(
            storage,
            name,
            priors={f"x{i}": "uniform(0, 1)" for i in range(4)},
            algorithms={"random": {"seed": SEED}},
            metadata={"user": "bench"},
        )
        experiment.instantiate(seed=SEED)
        producer = Producer(experiment)
        for _ in range(rounds):
            producer.update()
            producer.produce(q)
        producer._flush_timings(force_metrics=True)

    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    phase_t0 = time.time()
    try:
        with tempfile.TemporaryDirectory(prefix="orion-bench-trace-") as tmpdir:
            storage = create_storage(
                {"type": "sqlite", "path": os.path.join(tmpdir, "trace.sqlite")}
            )
            run_rounds(storage, "bench-trace")
        # Distributed leg: loopback netdb — the round's storage commits
        # carry the trace context over the wire and come back joined.
        server = DBServer(port=0)
        host, port = server.serve_background()
        net_db = NetworkDB(host=host, port=port)
        try:
            run_rounds(DocumentStorage(net_db), "bench-trace-dist")
            server.flush_server_spans(force=True)
            server_spans = DocumentStorage(net_db).fetch_spans(SERVER_EXPERIMENT)
        finally:
            net_db.close()
            server.shutdown()
            server.server_close()
        algo = _make_algo(seed=SEED + 4, n_candidates=256, fit_steps=4)
        rng = np.random.default_rng(SEED + 4)
        X = rng.uniform(size=(16, 6)).astype(np.float32)
        _observe(algo, X, _hartmann6_np(X))
        algo.suggest(8)  # compile -> jax.suggest_step.compile span
        algo.suggest(8)  # cache hit -> jax.suggest_step.dispatch span
        spans = [s for s in tel.TELEMETRY.iter_spans() if s] + list(server_spans)
        # The exported FILE keeps everything the ring holds (earlier phases
        # like the serve leg included — their cross-track flows are part of
        # the artifact); the ATTRIBUTION covers only THIS phase's rounds, so
        # an earlier leg's deliberately-slow coalescing windows cannot skew
        # the round split.
        phase_spans = [s for s in spans if float(s.get("ts") or 0.0) >= phase_t0]
        attribution = summarize_attribution(phase_spans, root_name="producer.round")
        tel.write_chrome_trace(out_path, spans)
    finally:
        if not was_enabled:
            tel.TELEMETRY.disable()
    return out_path, attribution


def bench_device_decomposition():
    """Device-vs-tunnel split of one fused suggest round at the headline
    shape (two-chain-length subtraction; suggest_bench.py is the full
    instrument and docs/performance.md the published table)."""
    from orion_tpu.benchmarks.suggest_bench import device_seconds

    # Shorter chain/reps than the full instrument: bench.py runs every
    # round and only needs the order of magnitude next to the wall number.
    return device_seconds("hartmann6-q1024", reps=5, k_hi=9) * 1e3


def _json_payload(
    metric,
    value,
    vs_baseline,
    regret,
    anchor_regret,
    wall_ms_per_round,
    device_ms_per_round,
    breakdown_ms,
    storage_ms,
    storage_ops_per_round,
    prewarm=None,
    health=None,
    regret_gate=None,
    compiler=None,
    smoke=False,
):
    """THE output schema — built here for both the full run and --smoke, so
    the smoke test's key assertions actually cover what the full bench
    emits (two hand-built dicts would let drift ship silently)."""
    # Steady-state host tax of one round: every breakdown stage that runs
    # on host (wait_transfer is device execution + transfer; storage_ms is
    # tracked separately — the pipelined commit overlaps it with dispatch;
    # telemetry_us_saved is a SAVINGS report, not a stage).
    host_ms_per_round = round(
        sum(
            v for k, v in breakdown_ms.items()
            if k not in ("wait_transfer", "storage_ms", "telemetry_us_saved",
                         "prep_us_saved", "dispatch_us_saved")
            and v is not None
        ),
        3,
    )
    payload = {
        "schema_version": BENCH_SCHEMA_VERSION,
        "metric": metric,
        "value": value,
        "unit": "suggestions/sec",
        # Chrome trace-event JSON of a traced producer-round + GP-suggest
        # phase (bench_trace) — load in Perfetto; None only if tracing
        # itself failed (reported but never fatal to the bench).
        "trace_file": None,
        "vs_baseline": vs_baseline,
        "regret": regret,
        "anchor_regret": anchor_regret,
        # Decomposition of one q=1024 round (docs/performance.md):
        # wall = device compute + this image's host<->device tunnel
        # round trip + host-side transform/decode.
        "wall_ms_per_round": wall_ms_per_round,
        "device_ms_per_round": device_ms_per_round,
        "host_ms_per_round": host_ms_per_round,
        # Per-stage host/device split of one steady-state round
        # (bench_breakdown docstring): everything except wait_transfer is
        # host boundary tax; storage_ms is the stage the pipelined
        # producer commit overlaps with device dispatch.
        "breakdown_ms": breakdown_ms,
        # The storage edge per backend (bench_storage): wall ms of one
        # q-batch registration, and how many backend-level operations
        # (transactions / wire round trips) it cost.  The batched write
        # path keeps ops O(1) regardless of q.
        "storage_ms": storage_ms,
        "storage_ops_per_round": storage_ops_per_round,
        # The pow-2 boundary-crossing contract (bench_prewarm):
        # retraces_after_warm must be 0 — a prewarmed bucket crossing is a
        # jit-cache hit, not a dispatch stall.  None = introspection
        # unavailable (private jax accessor).
        "prewarm": prewarm,
        # Optimization health (orion_tpu.health): per-round regret curve +
        # GP/TR health series of the seed-0 regret scenario.
        "health": health,
        # Multi-seed regret-trajectory gate verdict
        # (orion_tpu.benchmarks.regret_gate vs BENCH_REGRET_BASELINE.json).
        "regret_gate": regret_gate,
        # Compiler-plane digest (orion_tpu.compiler_plane, compiler_block):
        # every XLA compile this run paid with per-plan compile_ms / flops /
        # hbm_bytes / predicted HBM-bound q, and the retrace-attribution
        # totals the smoke gate pins (retraces_attributed == retraces).
        "compiler": compiler,
        # Distributed-trace critical-path split of the traced producer
        # rounds (orion_tpu.tracing, mean ms per round): client-host /
        # wire / server-host / device — stamped by _safe_trace.
        "host_attribution": None,
        # Self-diagnosis verdict over the bench's own run (doctor_gate):
        # the summary block plus the hard-gated critical count (--smoke
        # SystemExits on any critical finding).
        "doctor": None,
        "doctor_critical": None,
    }
    if smoke:
        payload["smoke"] = True
    return payload


def bench_history_record(payload, now=None):
    """One payload -> the compact cross-run record appended to
    ``BENCH_history.jsonl``: the headline/trajectory numbers future doctor
    trend rules (and humans) join across runs, without the multi-KB curve
    and trace blocks."""
    gate = payload.get("regret_gate") or {}
    compiler = payload.get("compiler") or {}
    sharded = payload.get("sharded") or {}
    return {
        "schema_version": payload.get("schema_version"),
        "time": time.time() if now is None else now,
        "smoke": bool(payload.get("smoke")),
        "value": payload.get("value"),
        "vs_baseline": payload.get("vs_baseline"),
        "regret": payload.get("regret"),
        "wall_ms_per_round": payload.get("wall_ms_per_round"),
        "device_ms_per_round": payload.get("device_ms_per_round"),
        "host_ms_per_round": payload.get("host_ms_per_round"),
        "storage_ms": payload.get("storage_ms"),
        "regret_gate_pass": gate.get("pass"),
        "doctor_critical": payload.get("doctor_critical"),
        # Compiler-plane columns (orion_tpu.compiler_plane): total compile
        # wall ms, attribution coverage, and the worst plan's HBM footprint
        # — the trend the DX050/DX053 doctor rules will join across runs.
        # Present even when None (a backend without memory_analysis): the
        # smoke hook checks PRESENCE, the attribution gate checks equality.
        "compile_ms_total": compiler.get("compile_ms_total"),
        "retraces_attributed": compiler.get("retraces_attributed"),
        "plan_hbm_bytes_max": compiler.get("plan_hbm_bytes_max"),
        # Sharded q-walk columns (ISSUE 19 satellite): the predicted
        # HBM-bound q and the measured-vs-predicted headroom from the
        # --sharded leg — None on runs without it (or on backends whose
        # memory analysis is unknowable), present always.
        "sharded_hbm_bound_q": sharded.get("hbm_bound_q"),
        "sharded_hbm_headroom": sharded.get("hbm_headroom"),
        # Day-2 storage columns (ISSUE 20): the drained fraction of the
        # keyspace the drain leg moved, and the quorum leg's lost count
        # (ZERO by construction — trending a nonzero here is the alarm).
        # None on runs without the soak legs; smoke pins them non-null.
        "soak_drained_frac": (
            ((payload.get("drain_soak") or {}).get("drain") or {})
            .get("planned") or {}
        ).get("move_fraction"),
        "soak_quorum_lost": (payload.get("quorum_soak") or {}).get(
            "lost_observations"
        ),
    }


def append_bench_history(payload, path=None):
    """Append this run's compact record to the cross-run series.

    ``path`` resolution: explicit argument > ``ORION_TPU_BENCH_HISTORY``
    env > the checked-in ``BENCH_history.jsonl`` next to this file — for
    FULL runs only.  ``--smoke`` appends nowhere by default (tier-1 runs
    it constantly and must not dirty the committed series); point the env
    var somewhere to capture smoke records too.  Returns the path written,
    or None.  Never raises — a read-only checkout must not fail a bench."""
    import os

    if path is None:
        path = os.environ.get("ORION_TPU_BENCH_HISTORY", "").strip()
        if not path:
            if payload.get("smoke"):
                return None
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "BENCH_history.jsonl"
            )
    try:
        with open(path, "a") as handle:
            handle.write(json.dumps(bench_history_record(payload)) + "\n")
    except OSError:
        return None
    return path


def compiler_block(families=("fused_plan", "stacked"), limit=8):
    """The compiler-plane digest of THIS bench run (orion_tpu
    .compiler_plane): run the pending cost/memory analyses — each an AOT
    ``lower().compile()``, which is exactly why this only happens here, on
    the bench's declared cold path, bounded by ``limit`` with the skipped
    count reported — then return the registry summary with per-plan
    compile_ms / flops / hbm_bytes and the predicted HBM-bound q.  The
    ``retraces``/``retraces_attributed`` totals come from the PROCESS
    telemetry counters, not the registry's own bookkeeping (which is equal
    by construction): the gate's point is catching a jit call site that
    counts ``jax.retraces`` without going through the registry."""
    from orion_tpu import telemetry as tel
    from orion_tpu.compiler_plane import COMPILE_REGISTRY

    analysis = COMPILE_REGISTRY.analyze_all(families=families, limit=limit)
    summary = COMPILE_REGISTRY.summary()
    summary["analysis"] = analysis
    summary["retraces"] = int(tel.TELEMETRY.counter_value("jax.retraces"))
    summary["retraces_attributed"] = int(
        tel.TELEMETRY.counter_value("jax.retraces.attributed")
    )
    summary["retraces_prewarm_covered"] = int(
        tel.TELEMETRY.counter_value("jax.retraces.prewarm_covered")
    )
    return summary


def _check_retrace_attribution(compiler):
    """Every post-warm retrace must be attributed: a ``jax.retraces``
    sample without a ``CompileRegistry.record_retrace`` twin means some
    jit call site books stalls the flight `jax.retrace` event cannot
    explain — the self-diagnosing contract of the compiler plane."""
    retraces = compiler.get("retraces") or 0
    attributed = compiler.get("retraces_attributed") or 0
    if retraces != attributed:
        # Not an assert: the gate must hold under `python -O` too.
        raise SystemExit(
            f"retrace attribution gate failed: {retraces} jax.retraces vs "
            f"{attributed} attributed — a jit call site counts retraces "
            "outside the CompileRegistry (doctor rule DX051)"
        )


def doctor_gate(health_records, hard=False):
    """Self-diagnosis over the bench's own run (orion_tpu.diagnosis): the
    process registry's counters/gauges/histograms + the measured health
    series, run through the full doctor rule catalog.  ZERO critical
    findings is the bar — a bench that paid a retrace storm or exhausted
    a retry policy is not producing numbers worth recording.  ``--smoke``
    hard-fails (SystemExit, holds under ``python -O``); full runs warn.

    Runs BEFORE the seeded-chaos legs in --smoke: those legs inject
    faults on purpose, and a doctor reading them SHOULD complain."""
    import sys

    from orion_tpu.diagnosis import local_snapshot, run_rules

    report = run_rules(local_snapshot(health=health_records))
    if report.count("critical"):
        message = (
            "doctor found critical findings over the bench run:\n"
            + report.format_human()
        )
        if hard:
            # Not an assert: the gate must hold under `python -O` too.
            raise SystemExit("doctor gate failed: " + message)
        print("WARNING: " + message, file=sys.stderr)
    return report


def _assert_health_overhead(breakdown):
    """Health recording must stay under 1% of the steady-state round (the
    ISSUE-7 acceptance bar): one ready-data device read + a small dict."""
    health_ms = breakdown.get("health")
    round_ms = sum(
        v for k, v in breakdown.items()
        if k not in ("storage_ms", "telemetry_us_saved", "prep_us_saved",
                     "dispatch_us_saved")
        and v is not None
    )
    assert health_ms is not None and round_ms > 0
    assert health_ms <= 0.01 * round_ms, (
        f"health recording costs {health_ms}ms of a {round_ms:.3f}ms round "
        "(>1%) — the packed-GPState read contract is broken"
    )


def main(smoke=False, trace_out="bench_trace.json"):
    if smoke:
        return main_smoke(trace_out=trace_out)
    ours_sps = bench_throughput()
    breakdown = bench_breakdown()
    device_ms = bench_device_decomposition()
    storage_ms, storage_ops = bench_storage()
    breakdown["storage_ms"] = storage_ms["sqlite"]
    breakdown["telemetry_us_saved"] = bench_telemetry_batching()
    _assert_health_overhead(breakdown)
    prewarm = bench_prewarm()
    assert prewarm["retraces_after_warm"] in (None, 0), (
        f"pow-2 boundary crossing paid {prewarm['retraces_after_warm']} "
        "synchronous retrace(s) despite prewarm"
    )

    # Multi-seed regret trajectories: seed 0 replays the historical
    # anchor-parity run; the full set feeds the statistical gate.
    curves = []
    health_records = None
    for seed in GATE_SEEDS:
        curve, records = run_regret_curve(seed)
        curves.append(curve)
        if seed == SEED:
            health_records = records
    ours_regret = curves[GATE_SEEDS.index(SEED)][-1]
    gate = bench_regret_gate(curves)
    assert gate["pass"], (
        "regret gate failed: statistically significant regression vs "
        f"BENCH_REGRET_BASELINE.json — {gate}"
    )

    rng = np.random.default_rng(SEED)
    X0 = rng.uniform(size=(N_INIT, 6)).astype(np.float32)
    y0 = _hartmann6_np(X0)
    anchor_regret, anchor_times = run_anchor_regret(X0, y0)
    anchor_sps = 1.0 / float(np.median(anchor_times))

    assert ours_regret <= anchor_regret * (1.0 + REGRET_TOL) + 1e-9, (
        f"regret parity failed: ours={ours_regret:.6f} "
        f"anchor={anchor_regret:.6f} tol={REGRET_TOL}"
    )
    trace_file, host_attribution = _safe_trace(trace_out)
    compiler = compiler_block()
    _check_retrace_attribution(compiler)
    payload = _json_payload(
        metric=(
            "suggestions/sec @ q=1024, Hartmann6 "
            "(public suggest/observe, refit per round)"
        ),
        value=round(ours_sps, 2),
        vs_baseline=round(ours_sps / anchor_sps, 2),
        regret=round(ours_regret, 6),
        anchor_regret=round(anchor_regret, 6),
        wall_ms_per_round=round(1e3 * Q / ours_sps, 2),
        device_ms_per_round=round(device_ms, 2),
        breakdown_ms=breakdown,
        storage_ms=storage_ms,
        storage_ops_per_round=storage_ops,
        prewarm=prewarm,
        health=_health_payload(curves[GATE_SEEDS.index(SEED)], health_records),
        regret_gate=gate,
        compiler=compiler,
    )
    payload["trace_file"] = trace_file
    payload["host_attribution"] = host_attribution
    payload["id_hash"] = bench_id_hash(q=1024)
    doctor_report = doctor_gate(health_records, hard=False)
    payload["doctor"] = doctor_report.summary()
    payload["doctor_critical"] = doctor_report.count("critical")
    _check_host_budget(payload)
    print(json.dumps(payload))
    append_bench_history(payload)


def _safe_trace(trace_out):
    """Run the trace phase; a tracing failure must cost the bench its
    artifact (and attribution block), never its numbers.  Returns
    ``(path, host_attribution)``."""
    import traceback

    try:
        return bench_trace(trace_out)
    except Exception:
        traceback.print_exc()
        return None, None


def _host_budget_factor():
    """The wall≈device bar: host tax may be at most FACTOR x device time
    (ROADMAP item 5 tightened the ISSUE-13 2x to 1.25x).  Delegates to
    ``orion_tpu.hostbudget`` — the SAME knob the doctor's DX004 rule and
    ``orion-tpu top``'s ratio column read, so the gates cannot drift;
    ORION_TPU_HOST_BUDGET_FACTOR overrides everywhere at once."""
    from orion_tpu.hostbudget import host_budget_factor

    return host_budget_factor()


def _check_host_budget(payload, hard=False):
    """ROADMAP item-2 gate: steady-state ``host_ms_per_round`` must stay
    within FACTOR x device time.

    Full runs WARN (never fail — the headline numbers still get recorded,
    and the attribution block says where the excess lives).  ``--smoke``
    hard-fails (SystemExit, so the gate holds under ``python -O``): the
    2x target was met by ISSUE 13's vectorized codec + columnar commit,
    and the host-tail endgame (prep token, byte-hash ids) tightened the
    bar to 1.25x; tier-1 must catch a host-tax regression before the
    next full bench run does.  In smoke (no device decomposition phase)
    the device reference is the breakdown's ``wait_transfer`` stage —
    device execution + result transfer of the same measured round."""
    import sys

    factor = _host_budget_factor()
    host = payload.get("host_ms_per_round")
    device = payload.get("device_ms_per_round")
    if not device:
        device = (payload.get("breakdown_ms") or {}).get("wait_transfer")
    if host is None or not device:
        return
    if host > factor * device:
        message = (
            f"host_ms_per_round={host} exceeds the ROADMAP item-5 target of "
            f"{factor}x device time ({device} ms; ORION_TPU_HOST_BUDGET_FACTOR "
            "overrides) — see breakdown_ms and the host_attribution block "
            "for the client-host/wire/server-host/device split"
        )
        if hard:
            # Not an assert: the gate must hold under `python -O` too.
            raise SystemExit("host budget gate failed: " + message)
        print("WARNING: " + message, file=sys.stderr)


def main_chaos(rounds=6, q=8, seed=11):
    """Chaos smoke: producer rounds against fault-injected storage.

    Runs ``rounds`` produce+complete rounds twice — once over a
    FaultyDB-wrapped SQLite store, once over a loopback network server
    behind the TCP fault proxy (with a mid-run connection drop) — under a
    seeded schedule covering every fault class, then prints ONE json line
    with per-round ``storage.retries``/``reconnects``/injected-fault
    counts and the invariant auditor's verdict.  Converging through the
    schedule with zero audit violations IS the check (hard asserts);
    the numbers trend the retry tax across BENCH_* files."""
    import os
    import tempfile

    from orion_tpu import telemetry as tel
    from orion_tpu.storage.base import DocumentStorage
    from orion_tpu.storage.faults import FaultProxy, FaultSchedule, FaultyDB
    from orion_tpu.storage.sqlitedb import SQLiteDB
    from orion_tpu.testing import drive_chaos_experiment

    retry = {"max_attempts": 6, "base_delay": 0.005, "max_delay": 0.05}

    def run_rounds(storage, name, proxy=None):
        # ONE chaos driver shared with tests/functional/test_chaos.py
        # (reserve -> complete with transient backoff, bounded by a
        # convergence deadline, sweep + audit epilogue) so the bench's
        # smoke and the suite's assertions cannot drift apart.
        _exp, report = drive_chaos_experiment(
            storage,
            name=f"bench-chaos-{name}",
            priors={f"x{i}": "uniform(0, 1)" for i in range(4)},
            max_trials=rounds * q,
            pool_size=q,
            seed=seed,
            proxy=proxy,
            drop_every=3 if proxy is not None else 0,
            deadline=180.0,
        )
        return report

    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    payload = {"metric": "chaos smoke", "rounds": rounds, "q": q, "backends": {}}
    try:
        with tempfile.TemporaryDirectory(prefix="orion-bench-chaos-") as tmpdir:
            # --- sqlite through FaultyDB -----------------------------------
            schedule = FaultSchedule(
                seed=seed,
                plan={3: "error", 7: "latency", 11: "reply_lost", 15: "kill"},
                rates={"error": 0.02, "latency": 0.02},
                latency=0.002,
                max_faults=12,
            )
            inner = SQLiteDB(os.path.join(tmpdir, "chaos.sqlite"))
            storage = DocumentStorage(FaultyDB(inner, schedule), retry=retry)
            before = tel.TELEMETRY.counter_value("storage.retries")
            report = run_rounds(storage, "sqlite")
            retries = tel.TELEMETRY.counter_value("storage.retries") - before
            assert report.ok, report.summary()
            assert retries > 0, "faults fired but nothing retried"
            payload["backends"]["sqlite"] = {
                "storage_retries_per_round": round(retries / rounds, 2),
                "faults_injected": dict(schedule.injected),
                "audit_violations": len(report.violations),
            }
            inner.close()

            # --- network through the fault proxy ---------------------------
            from orion_tpu.storage.netdb import DBServer, NetworkDB

            server = DBServer(port=0)
            server.db = FaultyDB(
                server.db,
                FaultSchedule(seed=seed + 1, rates={"error": 0.02}, max_faults=8),
            )
            host, port = server.serve_background()
            proxy = FaultProxy(host, port)
            phost, pport = proxy.serve_background()
            client = NetworkDB(host=phost, port=pport, timeout=10.0, idle_probe=0.05)
            net_storage = DocumentStorage(client, retry=retry)
            before = tel.TELEMETRY.counter_value("storage.retries")
            try:
                report = run_rounds(net_storage, "network", proxy=proxy)
                retries = tel.TELEMETRY.counter_value("storage.retries") - before
                assert report.ok, report.summary()
                payload["backends"]["network"] = {
                    "storage_retries_per_round": round(retries / rounds, 2),
                    "reconnects_per_round": round(client.reconnects / rounds, 2),
                    "faults_injected": dict(server.db.faults_injected),
                    "proxy_drops": proxy.connections_dropped,
                    "audit_violations": len(report.violations),
                }
                assert client.reconnects >= 1
            finally:
                client.close()
                proxy.stop()
                server.shutdown()
                server.server_close()
    finally:
        if not was_enabled:
            tel.TELEMETRY.disable()
    print(json.dumps(payload))


def run_soak(n_workers=1000, n_experiments=24, trials_per_worker=3,
             n_routers=32, replicas=2, periodic_chaos=True, deadline=600.0,
             kill_primary=True):
    """The sharded control-plane load harness (ROADMAP item 3): drive
    ``n_workers`` simulated workers through consistent-hash routers
    against an in-process 3-shard x ``replicas``-replica topology of REAL
    netdb servers, under fault-proxy reconnect storms/partitions, a
    scripted mid-run shard restart, a replica kill — and, with
    ``kill_primary`` (the ISSUE-14 promotion leg), a PERMANENT primary
    loss on shard 0 that the router fleet must heal by electing the
    caught-up replica itself.  Hard-asserts the pass bar (zero lost
    observations, clean audits through the router AND on every shard,
    chaos signals counted, >= 1 automatic promotion with no manual
    restart) and returns the summary block for the payload.  SystemExit,
    not assert: the gate must hold under ``python -O`` too."""
    import tempfile

    from orion_tpu import telemetry as tel
    from orion_tpu.storage.soak import SoakTopology, drive_soak

    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    try:
        with tempfile.TemporaryDirectory(prefix="orion-bench-soak-") as tmpdir:
            topo = SoakTopology(
                n_shards=3, replicas=replicas, persist_dir=tmpdir
            )

            def chaos_once(storages):
                from orion_tpu.storage.soak import busiest_shard

                # Kill the BUSIEST shard's primary (the one the ring gave
                # the most experiments): the promotion must heal a shard
                # under live write load, not an idle corner.
                victim = (
                    busiest_shard(topo, storages[0].db, n_experiments)
                    if kill_primary
                    else None
                )
                topo.drop_all()
                restart_index = next(
                    i for i in range(len(topo.shards)) if i != victim
                )
                topo.shards[restart_index].restart_primary()
                # Replica 0 of (nearly) every shard dies so the read
                # path's failover leg fires regardless of where the ring
                # placed the experiments; the victim keeps its replicas —
                # it is about to lose its PRIMARY instead.
                for shard in topo.shards:
                    if shard.index == victim:
                        continue
                    shard.kill_replica(0)
                if kill_primary:
                    # The promotion leg: wait until a replica holds the
                    # full position (replication is async), then kill the
                    # primary for good.  No restart — the routers must
                    # elect the survivor on their own.
                    topo.shards[victim].kill_primary()

            try:
                result = drive_soak(
                    topo,
                    n_workers=n_workers,
                    n_experiments=n_experiments,
                    trials_per_worker=trials_per_worker,
                    n_routers=n_routers,
                    chaos=periodic_chaos,
                    chaos_period=1.0,
                    mid_hook=chaos_once,
                    deadline=deadline,
                )
            finally:
                topo.stop()
    finally:
        if not was_enabled:
            tel.TELEMETRY.disable()
    summary = result.summary()
    if result.lost_observations != 0:
        raise SystemExit(f"soak LOST observations: {summary}")
    if not result.audits_clean:
        raise SystemExit(f"soak audits dirty: {summary}")
    if sum(result.completed_per_shard.values()) != result.completed:
        raise SystemExit(f"router view != sum of shards: {summary}")
    if result.restarts < 1 or result.failovers < 1 or result.reconnects < 1:
        raise SystemExit(f"soak chaos signals never fired: {summary}")
    if kill_primary and result.promotions < 1:
        raise SystemExit(
            f"primary killed but NO automatic promotion happened: {summary}"
        )
    summary["trials_per_second"] = (
        round(result.completed / result.duration_s, 1)
        if result.duration_s else None
    )
    return summary


def run_rebalance_soak(n_workers=200, n_experiments=16, trials_per_worker=3,
                       n_routers=8, deadline=300.0):
    """The rebalance-mid-soak leg (ISSUE 14): a live topology GROWS by one
    shard at the worker barrier, every router retargets in place, and
    ``db rebalance``'s migrator moves ~1/N of the experiments — byte-
    identical copies verified doc by doc, clean destination audits, an
    atomic placement flip, source deletion — before the workers resume
    and finish on the new ring.  Hard gates: >= 1 experiment moved, the
    moved fraction stays near 1/N, zero lost observations, clean audits
    on EVERY shard (source and destination included)."""
    import tempfile

    from orion_tpu import telemetry as tel
    from orion_tpu.storage.soak import (
        SoakTopology,
        drive_soak,
        grow_and_rebalance,
    )

    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    outcome = {}
    try:
        with tempfile.TemporaryDirectory(prefix="orion-bench-rebal-") as tmpdir:
            topo = SoakTopology(n_shards=3, replicas=1, persist_dir=tmpdir)

            def rebalance_hook(storages):
                outcome.update(grow_and_rebalance(topo, storages))

            try:
                result = drive_soak(
                    topo,
                    n_workers=n_workers,
                    n_experiments=n_experiments,
                    trials_per_worker=trials_per_worker,
                    n_routers=n_routers,
                    chaos=False,
                    mid_hook=rebalance_hook,
                    deadline=deadline,
                )
            finally:
                topo.stop()
    finally:
        if not was_enabled:
            tel.TELEMETRY.disable()
    summary = result.summary()
    summary["rebalance"] = outcome
    if not outcome.get("executed"):
        raise SystemExit(f"rebalance never executed: {summary}")
    planned = outcome["planned"]
    if planned["moves"] < 1:
        raise SystemExit(f"rebalance moved NOTHING: {summary}")
    n_shards = outcome["n_shards"]
    if planned["move_fraction"] > 2.5 / n_shards:
        raise SystemExit(
            f"rebalance moved far more than ~1/N of the keyspace: {summary}"
        )
    if result.lost_observations != 0:
        raise SystemExit(f"rebalance soak LOST observations: {summary}")
    if not result.audits_clean:
        raise SystemExit(f"rebalance soak audits dirty: {summary}")
    if sum(result.completed_per_shard.values()) != result.completed:
        raise SystemExit(f"router view != sum of shards: {summary}")
    return summary


def run_drain_soak(n_workers=200, n_experiments=16, trials_per_worker=3,
                   n_routers=8, deadline=300.0):
    """The drain-mid-soak leg (ISSUE 20): at the worker barrier the
    busiest shard is DRAINED — every resident experiment migrated to its
    post-removal ring home by the crash-resumable migrator
    (storage/drain.py), zero residents verified — then removed from every
    live router's topology and stopped; the workers resume and finish on
    the shrunk ring.  Hard gates: >= 1 experiment moved, the moved
    fraction within 2x of the drained shard's ring share, ZERO residents
    left, zero lost observations, clean audits on every surviving
    shard."""
    import tempfile

    from orion_tpu import telemetry as tel
    from orion_tpu.storage.soak import (
        SoakTopology,
        drain_and_remove,
        drive_soak,
    )

    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    outcome = {}
    try:
        with tempfile.TemporaryDirectory(prefix="orion-bench-drain-") as tmpdir:
            topo = SoakTopology(n_shards=3, replicas=1, persist_dir=tmpdir)

            def drain_hook(storages):
                outcome.update(drain_and_remove(topo, storages))

            try:
                result = drive_soak(
                    topo,
                    n_workers=n_workers,
                    n_experiments=n_experiments,
                    trials_per_worker=trials_per_worker,
                    n_routers=n_routers,
                    chaos=False,
                    mid_hook=drain_hook,
                    deadline=deadline,
                )
            finally:
                topo.stop()
    finally:
        if not was_enabled:
            tel.TELEMETRY.disable()
    summary = result.summary()
    summary["drain"] = outcome
    if not outcome.get("executed"):
        raise SystemExit(f"drain never executed: {summary}")
    planned = outcome["planned"]
    if planned["moves"] < 1:
        raise SystemExit(f"drain moved NOTHING: {summary}")
    share = outcome["ring_share"]
    if planned["move_fraction"] > 2.0 * share:
        raise SystemExit(
            f"drain moved {planned['move_fraction']:.1%} of the experiments "
            f"vs a {share:.1%} ring share (over the 2x bound): {summary}"
        )
    if outcome.get("residual"):
        raise SystemExit(
            f"drained shard still holds {outcome['residual']} "
            f"experiment(s): {summary}"
        )
    if result.lost_observations != 0:
        raise SystemExit(f"drain soak LOST observations: {summary}")
    if not result.audits_clean:
        raise SystemExit(f"drain soak audits dirty: {summary}")
    if sum(result.completed_per_shard.values()) != result.completed:
        raise SystemExit(f"router view != sum of shards: {summary}")
    return summary


def run_quorum_soak(n_workers=200, n_experiments=16, trials_per_worker=3,
                    n_routers=8, deadline=300.0):
    """The quorum kill -9 leg (ISSUE 20): a 3-shard x 2-replica topology
    serving with a quorum floor of 1 — synchronous collections
    (experiments/trials/placement) acknowledge only after a replica holds
    the write — takes a PERMANENT primary kill on the busiest shard with
    **no replica catch-up wait** (``wait_catchup=False`` — the exact wait
    the async contract needed to be lossless before this PR).  Routers
    elect the max-seq replica; because every acknowledged sync write was
    replica-acked first, the winner holds all of them: zero lost BY
    CONSTRUCTION, which is the hard gate."""
    import tempfile

    from orion_tpu import telemetry as tel
    from orion_tpu.storage.soak import (
        SoakTopology,
        busiest_shard,
        drive_soak,
    )

    was_enabled = tel.TELEMETRY.enabled
    tel.TELEMETRY.enable()
    try:
        with tempfile.TemporaryDirectory(prefix="orion-bench-quorum-") as tmpdir:
            # replicas=2 is load-bearing with quorum=1: promotion removes
            # one member from the replica set (the winner) and the old
            # primary is dead — the ONE remaining replica is what keeps
            # the promoted primary's sync writes able to meet the floor.
            topo = SoakTopology(
                n_shards=3, replicas=2, persist_dir=tmpdir, quorum=1
            )

            def kill_once(storages):
                victim = busiest_shard(topo, storages[0].db, n_experiments)
                topo.shards[victim].kill_primary(wait_catchup=False)

            try:
                result = drive_soak(
                    topo,
                    n_workers=n_workers,
                    n_experiments=n_experiments,
                    trials_per_worker=trials_per_worker,
                    n_routers=n_routers,
                    chaos=False,
                    mid_hook=kill_once,
                    deadline=deadline,
                )
            finally:
                topo.stop()
    finally:
        if not was_enabled:
            tel.TELEMETRY.disable()
    summary = result.summary()
    summary["quorum"] = 1
    summary["wait_catchup"] = False
    if result.primary_kills != 1:
        raise SystemExit(f"quorum soak never killed a primary: {summary}")
    if result.promotions < 1:
        raise SystemExit(
            f"primary killed but NO automatic promotion happened: {summary}"
        )
    if result.lost_observations != 0:
        raise SystemExit(
            f"quorum soak LOST observations despite the ack floor: {summary}"
        )
    if not result.audits_clean:
        raise SystemExit(f"quorum soak audits dirty: {summary}")
    if sum(result.completed_per_shard.values()) != result.completed:
        raise SystemExit(f"router view != sum of shards: {summary}")
    return summary


def main_soak(n_workers=1000):
    """``bench.py --soak [--workers N]``: the 1000-worker headline run +
    the rebalance-, drain- and quorum-mid-soak legs."""
    summary = run_soak(n_workers=n_workers)
    rebalance = run_rebalance_soak(n_workers=min(200, n_workers))
    drain = run_drain_soak(n_workers=min(200, n_workers))
    quorum = run_quorum_soak(n_workers=min(200, n_workers))
    payload = {
        "metric": (
            f"sharded soak: {n_workers} workers, 3 shards x 2 replicas, "
            "storms+partition+restart+kill-primary(promotion)+rebalance"
            "+drain+quorum-kill"
        ),
        "n_workers": n_workers,
        "soak": summary,
        "rebalance_soak": rebalance,
        "drain_soak": drain,
        "quorum_soak": quorum,
    }
    print(json.dumps(payload))


def bench_sharded(smoke=False):
    """``--sharded``: the multichip suggest data path, measured.

    Must run in a process whose backend already exposes the mesh devices
    (real chips, or the virtual CPU mesh ``main_sharded`` re-execs into).
    Three blocks, one JSON payload:

    - ``bit_match``: one fused round on the full mesh vs the SAME plan
      forced single-device — suggestion rows, GP state and health compared
      bit for bit (the sharded gate's bit-match-or-fail contract).
    - ``placement``: per-device byte fractions of a sharded candidate pool
      (``sharding.placement_fractions``) — every mesh device must hold a
      nonzero shard, or sharding has silently regressed to one chip.
    - ``q_curve``: suggestions/sec sharded vs single-device across growing
      q (the candidate pool scales with q).  On hosts without at least one
      core/chip per mesh device (the CPU virtual mesh: N devices on one
      core) the sharded/single ratio is reported but carries no speedup
      meaning — ``parallel_capacity`` says which reading applies.
    """
    import os

    import jax

    from orion_tpu.algo.sharding import placement_fractions, shard_candidates
    from orion_tpu.algo.tpu_bo import FusedPlan, run_fused_plan
    from orion_tpu.space.dsl import build_space

    n_dev = jax.device_count()
    if n_dev < 2:
        raise SystemExit(
            "bench.py --sharded needs a multi-device backend "
            "(run via main_sharded for the virtual-mesh re-exec)"
        )
    d = 6
    if smoke:
        qs, n_candidates, fit_steps, n_hist = (8, 32), 512, 8, 24
    else:
        qs, n_candidates, fit_steps, n_hist = (1024, 4096, 16384), 16384, 40, 130
    space = build_space({f"x{i}": "uniform(0, 1)" for i in range(d)})
    rng = np.random.default_rng(SEED + 3)
    X = rng.uniform(size=(n_hist, d)).astype(np.float32)
    y = _hartmann6_np(X)

    def fresh_algo(use_mesh):
        from orion_tpu.algo.base import create_algo

        algo = create_algo(
            space,
            {"tpu_bo": {"n_init": N_INIT, "n_candidates": n_candidates,
                        "fit_steps": fit_steps, "prewarm": False,
                        "use_mesh": use_mesh}},
            seed=SEED + 3,
        )
        _observe(algo, X, y)
        return algo

    # --- bit-match leg ----------------------------------------------------
    q0 = qs[0]
    plan = fresh_algo(True).fused_step_plan(q0)
    rows_sharded, state_sharded = run_fused_plan(plan)
    single = FusedPlan(
        plan.signature, plan.arrays, dict(plan.statics, mesh=None), plan.num
    )
    rows_single, state_single = run_fused_plan(single)
    bit_match = (
        np.array_equal(np.asarray(rows_sharded), np.asarray(rows_single))
        and np.array_equal(
            np.asarray(state_sharded.alpha), np.asarray(state_single.alpha)
        )
        and np.array_equal(
            np.asarray(state_sharded.health), np.asarray(state_single.health)
        )
    )

    # --- placement leg ----------------------------------------------------
    mesh = plan.statics["mesh"]
    pool = shard_candidates(
        np.zeros((n_candidates, d), dtype=np.float32), mesh
    )
    fractions = placement_fractions(pool)
    placement = {str(dev): round(frac, 4) for dev, frac in sorted(fractions.items())}
    devices_holding = sum(1 for frac in fractions.values() if frac > 0)

    # --- q-scaling curve --------------------------------------------------
    def rounds_per_sec(algo, q, reps):
        algo._suggest_cube(q)  # compile + warm
        t0 = time.perf_counter()
        for _ in range(reps):
            np.asarray(algo._suggest_cube(q))
        return reps * q / (time.perf_counter() - t0)

    reps = 2 if smoke else 3
    sharded_algo, single_algo = fresh_algo(True), fresh_algo(False)
    q_curve = []
    for q in qs:
        sps_sharded = rounds_per_sec(sharded_algo, q, reps)
        sps_single = rounds_per_sec(single_algo, q, reps)
        q_curve.append({
            "q": q,
            "sharded_sps": round(sps_sharded, 1),
            "single_sps": round(sps_single, 1),
            "ratio": round(sps_sharded / sps_single, 3),
        })

    # --- q-walk toward the predicted HBM bound (ROADMAP item 1 tail) -----
    # Double q from the curve's floor until the NEXT doubling would cross
    # the compiler plane's predict_hbm_bound_q extrapolation (or an OOM
    # guard trips, or the step cap on unknown-capacity backends).  Each
    # step's footprint comes from the sanctioned lowered_analysis_fn path
    # — a bench IS a declared cold path, the AOT second compile is fine.
    from orion_tpu.algo.tpu_bo import _suggest_step
    from orion_tpu.compiler_plane import (
        device_hbm_capacity,
        lowered_analysis_fn,
        predict_hbm_bound_q,
    )

    capacity = device_hbm_capacity()
    walk_algo = fresh_algo(True)
    q_walk, bound_q = [], None
    walk_q = qs[0]
    for _ in range(3 if smoke else 6):
        plan = walk_algo.fused_step_plan(walk_q)
        analysis = (
            lowered_analysis_fn(_suggest_step, plan.arrays, plan.statics)()
            or {}
        )
        hbm_bytes = analysis.get("hbm_bytes")
        predicted = predict_hbm_bound_q({"q": walk_q}, hbm_bytes, capacity)
        try:
            t0 = time.perf_counter()
            np.asarray(run_fused_plan(plan)[0])
            wall_ms, oom = round((time.perf_counter() - t0) * 1e3, 2), False
        except Exception:  # the OOM guard: record the wall and stop
            wall_ms, oom = None, True
        q_walk.append({
            "q": walk_q,
            "plan_hbm_bytes": hbm_bytes,
            "predicted_hbm_bound_q": predicted,
            "wall_ms": wall_ms,
            "oom": oom,
        })
        if oom:
            break
        if predicted is not None:
            bound_q = predicted
            if 2 * walk_q >= predicted:
                break  # the next doubling would cross the predicted bound
        walk_q *= 2
    measured = [row["q"] for row in q_walk if not row["oom"]]
    walk_max_q = max(measured) if measured else None
    # Measured-vs-predicted headroom: how many x of q the device still has
    # before the plan footprint fills HBM (None when capacity or the
    # memory analysis is unknowable — CPU interop backends).
    hbm_headroom = (
        round(bound_q / walk_max_q, 2) if bound_q and walk_max_q else None
    )

    try:
        host_parallelism = len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover - non-Linux host
        host_parallelism = os.cpu_count() or 1
    return {
        "metric": f"sharded suggest over {n_dev} devices"
                  + (" (SMOKE)" if smoke else ""),
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
        # True only when every mesh device maps to its own core/chip —
        # the precondition for the ratio to mean anything as a speedup.
        "parallel_capacity": (
            jax.devices()[0].platform != "cpu" or host_parallelism >= n_dev
        ),
        "bit_match": bit_match,
        "placement": placement,
        "devices_holding_shards": devices_holding,
        "q_curve": q_curve,
        "q_walk": q_walk,
        "q_walk_max_q": walk_max_q,
        "hbm_capacity_bytes": capacity,
        "hbm_bound_q": bound_q,
        "hbm_headroom": hbm_headroom,
        "smoke": smoke,
    }


def main_sharded(smoke=False):
    """``bench.py --sharded``: run :func:`bench_sharded` on this process's
    backend when it is already multi-device; otherwise re-exec into a
    child with the 8-way virtual CPU mesh (``XLA_FLAGS`` must be set
    before the backend initializes, which in THIS process it already
    has)."""
    import jax

    if jax.device_count() > 1:
        payload = bench_sharded(smoke=smoke)
        if smoke:
            _assert_sharded_smoke(payload)
        print(json.dumps(payload))
        return
    payload = _sharded_subprocess(smoke=smoke)
    print(json.dumps(payload))


def _sharded_subprocess(smoke, n_devices=8, timeout=900.0):
    """Run ``bench.py --sharded`` in a child process under the virtual
    CPU mesh and return its parsed payload.  Used by ``main_sharded`` on
    single-device hosts and by the ``--smoke`` sharded leg (hard-asserts
    are applied in the CHILD, where the arrays live)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    cmd = [sys.executable, os.path.abspath(__file__), "--sharded"]
    if smoke:
        cmd.append("--smoke")
    proc = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=timeout
    )
    if proc.returncode != 0:
        raise SystemExit(
            "sharded leg failed in the virtual-mesh child:\n"
            + proc.stdout[-2000:] + proc.stderr[-4000:]
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _assert_sharded_smoke(payload):
    """The --smoke sharded leg's hard gate: bit-match or fail, and every
    virtual device holding a nonzero candidate shard.  SystemExit, not
    assert: the gate must hold under ``python -O`` too."""
    if not payload.get("bit_match"):
        raise SystemExit(
            "sharded smoke: sharded round does NOT bit-match single-device "
            f"— {payload}"
        )
    if payload.get("devices_holding_shards") != payload.get("devices"):
        raise SystemExit(
            "sharded smoke: candidate pool not spread over every device "
            f"— {payload.get('placement')}"
        )


def lint_preflight():
    """Self-lint the tree before timing anything: bench numbers taken on a
    contract-violating tree (a host sync inside the fused step, a storage
    op off the retry policy) are not numbers worth recording.  Hard-fails
    with the full findings list; returns the violation count (0 when the
    gate passes) for the emitted payload."""
    import os

    import orion_tpu
    from orion_tpu.analysis import format_human, run_lint

    paths = [
        os.path.dirname(os.path.abspath(orion_tpu.__file__)),
        os.path.abspath(__file__),
    ]
    diagnostics = run_lint(paths)
    if diagnostics:
        # Not an assert: the gate must hold under `python -O` too.
        raise SystemExit(
            "lint preflight failed — fix the tree before benching:\n"
            + format_human(diagnostics)
        )
    return len(diagnostics)


def main_smoke(trace_out="bench_trace.json"):
    """Tiny-n schema smoke: the same JSON line shape in seconds instead of
    minutes — no regret parity, no sklearn anchor, no device
    decomposition.  The tier-1 bench smoke test runs ``bench.py --smoke``
    and asserts the breakdown/storage keys AND the emitted trace file's
    span names, so bench schema drift (a renamed stage, a dropped counter,
    a broken trace export) is caught by the unit suite instead of the next
    full bench run."""
    lint_violations = lint_preflight()
    q = 32
    algo = _make_algo(seed=SEED + 2, n_candidates=512, fit_steps=8)
    # rounds=3, not 1: the hard host-budget gate below keys off these
    # stage MEDIANS, and a single measured round lets one scheduling
    # hiccup on a loaded machine fail the gate (observed ~1/6 runs with
    # rounds=1); three rounds vote the outlier out for ~2 rounds of
    # extra tiny-q work.
    breakdown = bench_breakdown(rounds=3, q=q, algo=algo, n_hist=20)
    storage_ms, storage_ops = bench_storage(q=64, rounds=1)
    breakdown["storage_ms"] = storage_ms["sqlite"]
    breakdown["telemetry_us_saved"] = bench_telemetry_batching(rounds=50)
    _assert_health_overhead(breakdown)
    # Trial-identity gate (host-tail endgame): the cube_hash scheme must
    # beat the per-trial repr+md5 path by >= 4x at the bench batch size,
    # and stay collision-free over the batch.
    id_hash = bench_id_hash(q=1024)
    if not id_hash["distinct_ok"] or id_hash["speedup"] < 4:
        # Not an assert: the gate must hold under `python -O` too.
        raise SystemExit(
            "id-hash gate failed: cube_hash must be >= 4x faster than md5 "
            f"at q={id_hash['q']} and collision-free — {id_hash}"
        )
    prewarm = bench_prewarm(q=8)
    assert prewarm["retraces_after_warm"] in (None, 0), (
        f"pow-2 boundary crossing paid {prewarm['retraces_after_warm']} "
        "synchronous retrace(s) despite prewarm"
    )
    # Tiny-n health payload: a real (if short) GP regret trajectory with
    # per-round health records — the schema the full bench emits.
    curve, health_records = run_regret_curve(
        SEED + 2,
        budget=48,
        q=16,
        algo_kwargs={"n_candidates": 512, "fit_steps": 8},
    )
    assert health_records and health_records[-1].get("gp_mll") is not None, (
        "smoke health records lost their GP fields"
    )
    # Gate machinery check at tiny n: the committed baseline must pass
    # against itself (the full bench compares real re-measured curves).
    gate = bench_regret_gate([list(c) for c in _baseline_curves()])
    gate["mode"] = "baseline-self"
    assert gate["pass"], f"committed regret baseline fails its own gate: {gate}"
    # Tiny serve leg (orion_tpu.serve): 2 tenants, full producer stack over
    # one in-process gateway — coalesce width >= 2, device dispatches per
    # suggest < 1, and clean audits are hard-asserted inside.  The leg runs
    # UNDER the runtime concurrency sanitizer (orion-tpu tsan): instrumented
    # lock/event shims + vector-clock race detection over the gateway's
    # annotated shared cells, with a seeded interleaving explorer perturbing
    # the dispatcher's schedules — the payload's `tsan_violations: 0` is a
    # hard assert, the dynamic twin of lint_preflight's static gate.
    from orion_tpu.analysis.sanitizer import TSAN

    # The whole bench may itself be running under `orion-tpu tsan` (env
    # instrumentation from process start): then the outer owner keeps the
    # patches and we assert on a snapshot instead of fighting over enable.
    tsan_owned = not TSAN.enabled
    if tsan_owned:
        TSAN.enable(seed=0)
    try:
        serve_block = bench_serve(
            m_tenants=2, rounds=4, q=8, window=0.4, n_candidates=128, fit_steps=4
        )
    finally:
        tsan_report = TSAN.disable() if tsan_owned else TSAN.snapshot_report()
    if tsan_report.violation_count():
        # Not an assert: the gate must hold under `python -O` too.
        raise SystemExit(
            "serve leg failed the concurrency sanitizer:\n"
            + tsan_report.format_human()
        )
    # Self-diagnosis gate, BEFORE the seeded-chaos legs below (they inject
    # faults by design — a doctor reading them should complain): zero
    # critical findings over the healthy phases' registry + health series.
    doctor_report = doctor_gate(health_records, hard=True)
    # Tiny sharded-soak leg (storage/shard.py + soak.py): 8 workers over a
    # real 3-shard x 1-replica topology with the scripted storm + shard
    # restart + replica kill + PERMANENT shard-0 primary kill — run_soak
    # hard-asserts zero lost observations, clean audits on every shard,
    # that the chaos signals (restart, failover, reconnects) actually
    # fired, and that >= 1 AUTOMATIC replica promotion healed the killed
    # shard with no human in the loop.
    soak_block = run_soak(
        n_workers=8, n_experiments=4, trials_per_worker=4, n_routers=2,
        replicas=1, periodic_chaos=False, deadline=120.0,
    )
    # Tiny rebalance-mid-soak leg: the topology grows by one shard at the
    # worker barrier, the migrator moves ~1/N of the experiments (byte-
    # identical, audited), workers finish on the new ring — zero lost.
    rebalance_block = run_rebalance_soak(
        n_workers=8, n_experiments=8, trials_per_worker=4, n_routers=2,
        deadline=120.0,
    )
    # Tiny drain-mid-soak leg (ISSUE 20): the busiest shard is emptied by
    # the crash-resumable migrator and removed mid-run — zero residents,
    # zero lost, moved fraction within 2x of its ring share.
    drain_block = run_drain_soak(
        n_workers=8, n_experiments=8, trials_per_worker=4, n_routers=2,
        deadline=120.0,
    )
    # Tiny quorum kill -9 leg (ISSUE 20): 2 replicas under a quorum floor
    # of 1, permanent busiest-primary kill with NO replica catch-up wait —
    # zero lost by construction.
    quorum_block = run_quorum_soak(
        n_workers=8, n_experiments=4, trials_per_worker=4, n_routers=2,
        deadline=120.0,
    )
    trace_file, host_attribution = _safe_trace(trace_out)
    # Smoke's round decomposition: the breakdown's wait_transfer stage IS
    # the measured device window (execution + result transfer), and the
    # wall is the full stage sum — so the appended history record carries
    # real host/device/storage columns even for smoke runs, keeping the
    # host/device ratio trendable across the whole series.
    # Compiler-plane digest + hard attribution gate: every jax.retraces
    # sample this run counted must have a CompileRegistry attribution twin
    # (the analyze pass is the bench's declared cold path for the AOT
    # second compiles).
    compiler = compiler_block()
    _check_retrace_attribution(compiler)
    smoke_device_ms = round(breakdown["wait_transfer"], 3)
    smoke_wall_ms = round(
        sum(
            v for k, v in breakdown.items()
            if k not in ("storage_ms", "telemetry_us_saved",
                         "prep_us_saved", "dispatch_us_saved")
            and v is not None
        ),
        3,
    )
    payload = _json_payload(
        metric=(
            f"SMOKE (q={q}): schema check only — run without "
            "--smoke for the headline numbers"
        ),
        value=None,
        vs_baseline=None,
        regret=None,
        anchor_regret=None,
        wall_ms_per_round=smoke_wall_ms,
        device_ms_per_round=smoke_device_ms,
        breakdown_ms=breakdown,
        storage_ms=storage_ms,
        storage_ops_per_round=storage_ops,
        prewarm=prewarm,
        health=_health_payload(curve, health_records),
        regret_gate=gate,
        compiler=compiler,
        smoke=True,
    )
    payload["trace_file"] = trace_file
    payload["host_attribution"] = host_attribution
    payload["lint_violations"] = lint_violations
    payload["tsan_violations"] = tsan_report.violation_count()
    payload["serve"] = serve_block
    payload["soak"] = soak_block
    payload["rebalance_soak"] = rebalance_block
    payload["drain_soak"] = drain_block
    payload["quorum_soak"] = quorum_block
    payload["doctor"] = doctor_report.summary()
    payload["doctor_critical"] = doctor_report.count("critical")
    # Sharded leg (ISSUE 16): the multichip suggest path under the 8-way
    # virtual CPU mesh, in a CHILD process (XLA_FLAGS must precede backend
    # init).  The child hard-asserts bit-match vs single-device and a
    # nonzero candidate shard on EVERY virtual device before printing its
    # payload; re-check both here so a child drift fails THIS gate too.
    payload["sharded"] = _sharded_subprocess(smoke=True)
    _assert_sharded_smoke(payload["sharded"])
    payload["id_hash"] = id_hash
    # Hard wall-=-device gate (ISSUE 13, tightened to 1.25x by the
    # host-tail endgame): smoke fails loudly on host-tax regressions
    # instead of warning into a log nobody reads.
    _check_host_budget(payload, hard=True)
    # The cross-run record must carry the round decomposition: a smoke
    # run that silently dropped host/device/storage columns would leave
    # the BENCH_history series untrendable for the doctor's rules.
    record = bench_history_record(payload)
    missing = [
        k for k in ("host_ms_per_round", "device_ms_per_round", "storage_ms")
        if not record.get(k)
    ]
    # Compiler-plane columns: PRESENCE check (`in`), not truthiness — a
    # backend without memory_analysis legitimately reports None for the
    # HBM column, but the key itself going missing is schema drift.
    missing += [
        k
        for k in (
            "compile_ms_total", "retraces_attributed", "plan_hbm_bytes_max"
        )
        if k not in record
    ]
    # Day-2 soak columns: hard non-null (`is None`, not truthiness — the
    # quorum leg's lost count is LEGITIMATELY 0): a smoke run just ran
    # both legs, so a None here means the record builder lost the wiring.
    missing += [
        k
        for k in ("soak_drained_frac", "soak_quorum_lost")
        if record.get(k) is None
    ]
    if missing:
        # Not an assert: the gate must hold under `python -O` too.
        raise SystemExit(
            f"bench history record dropped round-decomposition fields: {missing}"
        )
    print(json.dumps(payload))
    append_bench_history(payload)


if __name__ == "__main__":
    import sys

    argv = sys.argv[1:]
    out = "bench_trace.json"
    if "--trace-out" in argv:
        at = argv.index("--trace-out")
        if at + 1 >= len(argv):
            sys.exit("bench.py: --trace-out requires a path argument")
        out = argv[at + 1]
    if "--chaos" in argv:
        main_chaos()
    elif "--soak" in argv:
        workers = 1000
        if "--workers" in argv:
            at = argv.index("--workers")
            if at + 1 >= len(argv):
                sys.exit("bench.py: --workers requires a count argument")
            workers = int(argv[at + 1])
        main_soak(n_workers=workers)
    elif "--serve" in argv:
        main_serve(smoke="--smoke" in argv)
    elif "--sharded" in argv:
        main_sharded(smoke="--smoke" in argv)
    else:
        main(smoke="--smoke" in argv, trace_out=out)
