#!/usr/bin/env python
"""Benchmark: the BASELINE.json north-star configuration.

Measures **suggestions/sec at q=1024 on Hartmann6** for the TPU-native
batched GP-BO engine (`tpu_bo`), against the skopt-style anchor: a
sequential CPU GP-EI loop (sklearn GaussianProcessRegressor with a Matern-5/2
kernel and MLL refit per suggestion + EI argmax — which is what skopt's
`gp_minimize` does internally; skopt itself is not installed in this image).

Also sanity-checks simple-regret parity: the engine must reach at least the
anchor's regret on an equal 192-evaluation budget (asserted, not printed).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time
import warnings

import numpy as np


Q = 1024
N_HISTORY = 128
SEED = 0


def _hartmann6_np(u):
    import orion_tpu.benchmarks.functions as f
    import jax.numpy as jnp

    return np.asarray(f.hartmann6(jnp.asarray(u)))


def bench_tpu_bo():
    import jax
    import jax.numpy as jnp

    from orion_tpu.algo.base import create_algo
    from orion_tpu.space.dsl import build_space

    space = build_space({f"x{i}": "uniform(0, 1)" for i in range(6)})
    algo = create_algo(
        space,
        {"tpu_bo": {"n_init": 16, "n_candidates": 16384, "fit_steps": 40}},
        seed=SEED,
    )
    rng = np.random.default_rng(SEED)
    X = rng.uniform(size=(N_HISTORY, 6)).astype(np.float32)
    y = _hartmann6_np(X)
    params = [{f"x{i}": float(row[i]) for i in range(6)} for row in X]
    algo.observe(params, [{"objective": float(v)} for v in y])

    def one_suggest():
        state = algo._fit()
        key = algo.next_key()
        k1, k2 = jax.random.split(key)
        from orion_tpu.algo.tpu_bo import _acquire, _make_candidates

        best_x = algo._x[int(np.argmin(algo._y))]
        cands = _make_candidates(
            k1, algo.n_candidates, 6, jnp.asarray(best_x), algo.local_frac, algo.local_sigma
        )
        idx = _acquire(k2, state, cands, Q, algo.kernel, "thompson", 2.0)
        return jax.block_until_ready(jnp.take(cands, idx, axis=0))

    one_suggest()  # compile
    algo._gp_dirty = True
    one_suggest()  # compile the refit path too
    times = []
    for _ in range(5):
        algo._gp_dirty = True  # each round refits the GP: full honest cycle
        t0 = time.perf_counter()
        out = one_suggest()
        times.append(time.perf_counter() - t0)
    dt = float(np.median(times))
    assert out.shape == (Q, 6)
    return Q / dt


def bench_anchor(n_suggest=6):
    """Sequential skopt-style GP-EI on CPU: MLL refit + EI argmax per point."""
    from scipy.stats import norm
    from sklearn.gaussian_process import GaussianProcessRegressor
    from sklearn.gaussian_process.kernels import ConstantKernel, Matern, WhiteKernel

    rng = np.random.default_rng(SEED)
    X = rng.uniform(size=(N_HISTORY, 6))
    y = _hartmann6_np(X.astype(np.float32)).astype(np.float64)

    times = []
    for _ in range(n_suggest):
        t0 = time.perf_counter()
        kernel = ConstantKernel(1.0) * Matern(length_scale=np.ones(6), nu=2.5) + WhiteKernel(1e-4)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            gpr = GaussianProcessRegressor(kernel=kernel, normalize_y=True, n_restarts_optimizer=1)
            gpr.fit(X, y)
            cands = rng.uniform(size=(1000, 6))
            mu, std = gpr.predict(cands, return_std=True)
        best = y.min()
        z = (best - mu) / np.maximum(std, 1e-12)
        ei = std * (z * norm.cdf(z) + norm.pdf(z))
        xn = cands[np.argmax(ei)]
        times.append(time.perf_counter() - t0)
        yn = _hartmann6_np(xn[None].astype(np.float32))
        X = np.vstack([X, xn[None]])
        y = np.append(y, yn)
    return 1.0 / float(np.median(times))


def main():
    ours_sps = bench_tpu_bo()
    anchor_sps = bench_anchor()
    print(
        json.dumps(
            {
                "metric": "suggestions/sec @ q=1024, Hartmann6 (GP-BO refit+acquire per round)",
                "value": round(ours_sps, 2),
                "unit": "suggestions/sec",
                "vs_baseline": round(ours_sps / anchor_sps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
