"""EVC branching through the real CLI (parity model: reference
tests/functional/branching/test_branching.py)."""

import os

from orion_tpu.cli import main as cli_main
from orion_tpu.storage import create_storage

HERE = os.path.dirname(os.path.abspath(__file__))
BLACK_BOX = os.path.join(HERE, "black_box.py")


def test_hunt_with_changed_prior_branches(tmp_path):
    db = ["--storage-path", str(tmp_path / "db.pkl")]
    cli_main(["hunt", "-n", "br", *db, "--max-trials", "3", "--worker-trials", "3",
              BLACK_BOX, "-x~uniform(-50, 50)"])
    rc = cli_main(["hunt", "-n", "br", *db, "--max-trials", "3", "--worker-trials", "3",
                   BLACK_BOX, "-x~uniform(-10, 10)"])
    assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exps = {e["version"]: e for e in storage.fetch_experiments({"name": "br"})}
    assert set(exps) == {1, 2}
    child = exps[2]
    assert child["refers"]["parent_id"] == exps[1]["_id"]
    assert child["priors"] == {"/x": "uniform(-10, 10)"}
    v2_trials = [t for t in storage.fetch_trials(uid=child["_id"])]
    assert len(v2_trials) == 3
    for t in v2_trials:
        assert -10 <= t.params["/x"] <= 10


def test_resume_same_config_does_not_branch(tmp_path):
    db = ["--storage-path", str(tmp_path / "db.pkl")]
    cli_main(["hunt", "-n", "same", *db, "--max-trials", "4", "--worker-trials", "2",
              BLACK_BOX, "-x~uniform(-50, 50)"])
    cli_main(["hunt", "-n", "same", *db, "--max-trials", "4", "--worker-trials", "2",
              BLACK_BOX, "-x~uniform(-50, 50)"])
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    assert len(storage.fetch_experiments({"name": "same"})) == 1
