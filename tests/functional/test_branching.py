"""EVC branching through the real CLI (parity model: reference
tests/functional/branching/test_branching.py)."""

import os

from orion_tpu.cli import main as cli_main
from orion_tpu.storage import create_storage

HERE = os.path.dirname(os.path.abspath(__file__))
BLACK_BOX = os.path.join(HERE, "black_box.py")


def test_hunt_with_changed_prior_branches(tmp_path):
    db = ["--storage-path", str(tmp_path / "db.pkl")]
    cli_main(["hunt", "-n", "br", *db, "--max-trials", "3", "--worker-trials", "3",
              BLACK_BOX, "-x~uniform(-50, 50)"])
    rc = cli_main(["hunt", "-n", "br", *db, "--max-trials", "3", "--worker-trials", "3",
                   BLACK_BOX, "-x~uniform(-10, 10)"])
    assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exps = {e["version"]: e for e in storage.fetch_experiments({"name": "br"})}
    assert set(exps) == {1, 2}
    child = exps[2]
    assert child["refers"]["parent_id"] == exps[1]["_id"]
    assert child["priors"] == {"/x": "uniform(-10, 10)"}
    v2_trials = [t for t in storage.fetch_trials(uid=child["_id"])]
    assert len(v2_trials) == 3
    for t in v2_trials:
        assert -10 <= t.params["/x"] <= 10


def test_branch_to_names_the_child(tmp_path):
    """--branch-to gives the child a fresh name (v1) instead of a version
    bump, with the same refers/adapter wiring."""
    db = ["--storage-path", str(tmp_path / "db.pkl")]
    cli_main(["hunt", "-n", "orig", *db, "--max-trials", "3", "--worker-trials", "3",
              BLACK_BOX, "-x~uniform(-50, 50)"])
    rc = cli_main(["hunt", "-n", "orig", *db, "--branch-to", "forked",
                   "--max-trials", "3", "--worker-trials", "3",
                   BLACK_BOX, "-x~uniform(-10, 10)"])
    assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    [parent] = storage.fetch_experiments({"name": "orig"})
    [child] = storage.fetch_experiments({"name": "forked"})
    assert child["version"] == 1
    assert child["refers"]["parent_id"] == parent["_id"]
    assert child["priors"] == {"/x": "uniform(-10, 10)"}


def test_resume_same_config_does_not_branch(tmp_path):
    db = ["--storage-path", str(tmp_path / "db.pkl")]
    cli_main(["hunt", "-n", "same", *db, "--max-trials", "4", "--worker-trials", "2",
              BLACK_BOX, "-x~uniform(-50, 50)"])
    cli_main(["hunt", "-n", "same", *db, "--max-trials", "4", "--worker-trials", "2",
              BLACK_BOX, "-x~uniform(-50, 50)"])
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    assert len(storage.fetch_experiments({"name": "same"})) == 1


_CONFIG_BOX = """#!/usr/bin/env python
import argparse
from orion_tpu.client import report_results

p = argparse.ArgumentParser()
p.add_argument("-x", type=float, required=True)
p.add_argument("--config")
a = p.parse_args()
report_results([{"name": "objective", "type": "objective", "value": a.x ** 2}])
"""


def _git(repo, *argv):
    import subprocess

    subprocess.run(
        ["git", "-C", str(repo), "-c", "user.name=t", "-c", "user.email=t@t", *argv],
        check=True,
        capture_output=True,
    )


def test_hunt_branches_on_code_change(tmp_path):
    """Editing + committing the user script between hunts -> CodeConflict ->
    version bump (reference `conflicts.py:1083`, `resolve_config.py:249-289`)."""
    repo = tmp_path / "proj"
    repo.mkdir()
    script = repo / "box.py"
    script.write_text(_CONFIG_BOX)
    script.chmod(0o755)
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "v1")

    db = ["--storage-path", str(tmp_path / "db.pkl")]
    args = ["--max-trials", "2", "--worker-trials", "2", str(script),
            "-x~uniform(-5, 5)"]
    cli_main(["hunt", "-n", "code", *db, *args])
    script.write_text(_CONFIG_BOX + "\n# changed\n")
    _git(repo, "commit", "-aqm", "v2")
    rc = cli_main(["hunt", "-n", "code", *db, *args])
    assert rc == 0

    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exps = {e["version"]: e for e in storage.fetch_experiments({"name": "code"})}
    assert set(exps) == {1, 2}
    assert exps[1]["metadata"]["vcs"]["HEAD_sha"] != exps[2]["metadata"]["vcs"]["HEAD_sha"]
    adapter = exps[2]["refers"]["adapter"]
    assert adapter["of_type"] == "compositeadapter"
    assert any(a["of_type"] == "codechange" for a in adapter["adapters"])


def test_hunt_branches_on_script_config_change(tmp_path):
    """Editing the user script's templated config file between hunts ->
    ScriptConfigConflict -> version bump (reference `conflicts.py:1334`)."""
    script = tmp_path / "box.py"
    script.write_text(_CONFIG_BOX)
    script.chmod(0o755)
    conf = tmp_path / "settings.yaml"
    conf.write_text("fixed: 1\n")

    db = ["--storage-path", str(tmp_path / "db.pkl")]
    args = ["--max-trials", "2", "--worker-trials", "2", str(script),
            "-x~uniform(-5, 5)", "--config", str(conf)]
    cli_main(["hunt", "-n", "sconf", *db, *args])
    conf.write_text("fixed: 2\n")
    rc = cli_main(["hunt", "-n", "sconf", *db, *args])
    assert rc == 0

    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exps = {e["version"]: e for e in storage.fetch_experiments({"name": "sconf"})}
    assert set(exps) == {1, 2}
    h1 = exps[1]["metadata"]["script_config_hash"]
    h2 = exps[2]["metadata"]["script_config_hash"]
    assert h1 and h2 and h1 != h2


def test_argless_resume_detects_code_change(tmp_path):
    """`hunt -n name` with no command line must still branch when the stored
    script's git state changed, and the child must inherit a runnable command
    (user_args/parser_state) from the parent."""
    repo = tmp_path / "proj"
    repo.mkdir()
    script = repo / "box.py"
    script.write_text(_CONFIG_BOX)
    script.chmod(0o755)
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "v1")

    db = ["--storage-path", str(tmp_path / "db.pkl")]
    cli_main(["hunt", "-n", "argless", *db, "--max-trials", "2",
              "--worker-trials", "2", str(script), "-x~uniform(-5, 5)"])
    script.write_text(_CONFIG_BOX + "\n# changed\n")
    _git(repo, "commit", "-aqm", "v2")
    rc = cli_main(["hunt", "-n", "argless", *db, "--max-trials", "2",
                   "--worker-trials", "2"])
    assert rc == 0

    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exps = {e["version"]: e for e in storage.fetch_experiments({"name": "argless"})}
    assert set(exps) == {1, 2}
    child_meta = exps[2]["metadata"]
    assert child_meta["user_args"], "child must inherit the parent's command"
    assert [t for t in storage.fetch_trials(uid=exps[2]["_id"])
            if t.status == "completed"]


def test_untracked_file_addition_branches(tmp_path):
    """`git diff HEAD` is blind to untracked files; the signature must not be."""
    repo = tmp_path / "proj"
    repo.mkdir()
    script = repo / "box.py"
    script.write_text(_CONFIG_BOX)
    script.chmod(0o755)
    _git(repo, "init", "-q")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "v1")

    db = ["--storage-path", str(tmp_path / "db.pkl")]
    args = ["--max-trials", "2", "--worker-trials", "2", str(script),
            "-x~uniform(-5, 5)"]
    cli_main(["hunt", "-n", "untracked", *db, *args])
    (repo / "helper.py").write_text("VALUE = 3\n")  # untracked, never committed
    rc = cli_main(["hunt", "-n", "untracked", *db, *args])
    assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    versions = {e["version"] for e in storage.fetch_experiments({"name": "untracked"})}
    assert versions == {1, 2}


def test_three_generation_chain_composes_adapters(tmp_path, capsys):
    """A grandchild must see ancestors' trials through TWO composed
    adapter hops (v3<-v2 AND v2<-v1 prior narrowings applied in sequence),
    and the monitoring commands must render the whole chain — the
    single-hop branching tests cannot catch a composition bug."""
    from orion_tpu.core.experiment import build_experiment

    db = ["--storage-path", str(tmp_path / "db.pkl")]
    for prior in ("uniform(-50, 50)", "uniform(-30, 30)", "uniform(-10, 10)"):
        rc = cli_main(
            ["hunt", "-n", "chain", *db, "--max-trials", "4",
             "--worker-trials", "4", BLACK_BOX, f"-x~{prior}"]
        )
        assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exps = {e["version"]: e for e in storage.fetch_experiments({"name": "chain"})}
    assert set(exps) == {1, 2, 3}
    assert exps[3]["refers"]["parent_id"] == exps[2]["_id"]
    assert exps[2]["refers"]["parent_id"] == exps[1]["_id"]
    assert exps[3]["refers"]["root_id"] == exps[1]["_id"]

    v3 = build_experiment(storage, "chain", version=3)
    own = v3.fetch_trials()
    tree = v3.fetch_trials(with_evc_tree=True)
    # Ancestors' trials inside v3's narrowed prior flow through BOTH hops;
    # anything outside (-10, 10) must have been filtered by the composition.
    ancestors_in_range = [
        t
        for v in (1, 2)
        for t in storage.fetch_trials(uid=exps[v]["_id"])
        if -10 <= t.params["/x"] <= 10
    ]
    assert len(tree) == len(own) + len(ancestors_in_range)
    assert all(-10 <= t.params["/x"] <= 10 for t in tree)

    # The chain renders: status --expand-versions shows all three versions,
    # list shows the nested tree.  Drain output accumulated by the hunts
    # first, so the marker assertions scope to the status command alone.
    capsys.readouterr()
    assert cli_main(["status", "-n", "chain", *db, "--expand-versions"]) == 0
    out = capsys.readouterr().out
    for marker in ("chain-v1", "chain-v2", "chain-v3"):
        assert marker in out, f"{marker} missing from status output:\n{out}"
    assert cli_main(["list", *db]) == 0
    out = capsys.readouterr().out
    assert out.count("chain") >= 3
