"""Algorithm end-to-end runs through the real CLI (parity model: reference
tests/functional/algos/test_algos.py)."""

import math
import os

import pytest
import yaml

from orion_tpu.cli import main as cli_main
from orion_tpu.storage import create_storage

HERE = os.path.dirname(os.path.abspath(__file__))
FIDELITY_BOX = os.path.join(HERE, "fidelity_box.py")
BLACK_BOX = os.path.join(HERE, "black_box.py")


def test_asha_end_to_end(tmp_path):
    config = tmp_path / "conf.yaml"
    config.write_text("algorithms: asha\nstrategy: NoParallelStrategy\n")
    rc = cli_main(
        ["hunt", "-n", "asha-exp", "-c", str(config),
         "--storage-path", str(tmp_path / "db.pkl"),
         "--max-trials", "12", "--worker-trials", "12",
         FIDELITY_BOX, "-x~uniform(0, 1)", "--epochs~fidelity(1, 9, 3)"]
    )
    assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exp = storage.fetch_experiments({"name": "asha-exp"})[0]
    completed = [
        t for t in storage.fetch_trials(uid=exp["_id"]) if t.status == "completed"
    ]
    assert 6 <= len(completed) <= 12  # ASHA may declare is_done before max_trials
    fidelities = sorted({t.params["/epochs"] for t in completed})
    assert set(fidelities).issubset({1, 3, 9})
    assert len(fidelities) >= 2  # promotions actually happened
    # Promoted points re-evaluate the same x at higher fidelity.
    by_x = {}
    for t in completed:
        by_x.setdefault(t.params["/x"], []).append(t.params["/epochs"])
    assert any(len(v) > 1 for v in by_x.values())


# Every registered algorithm runs end-to-end through the REAL CLI entry
# point (parity model: reference tests/functional/algos/test_algos.py runs
# its whole roster).  Small budgets: this is a wiring smoke test — an algo
# whose config/codec/suggest path breaks the CLI must fail HERE, not in a
# user's hunt.  Model-quality claims live in the benchmark presets.
_FLAT_ROSTER = {
    "random": {},
    # 6 < max-trials: the hunt must end cleanly (AlgorithmExhausted fast
    # path) the moment the 6-point grid is consumed, not idle-wait out the
    # sampler timeout.
    "grid_search": {"n_values": 6},
    "tpe": {"n_init": 4, "n_candidates": 128},
    "cmaes": {"popsize": 6},
    "de": {"popsize": 6},
    "tpu_bo": {"n_init": 4, "n_candidates": 128, "fit_steps": 3},
    "turbo": {"n_init": 4, "n_candidates": 128, "fit_steps": 3},
}
_FIDELITY_ROSTER = {
    "asha": {},
    "hyperband": {},
    "asha_bo": {"n_init": 4, "n_candidates": 128, "fit_steps": 3},
    "bohb": {"min_points": 4, "n_candidates": 128},
}


def test_cli_smoke_covers_the_whole_registry():
    """A future BUILT-IN algorithm without CLI smoke coverage must fail
    loudly (third-party entry-point plugins are their authors' concern and
    must not flip this test when one happens to be installed)."""
    from orion_tpu.algo.base import _import_builtins, algo_registry

    _import_builtins()
    registered = {
        name for name in algo_registry.names()
        if algo_registry.get(name).__module__.startswith("orion_tpu.")
    }
    covered = set(_FLAT_ROSTER) | set(_FIDELITY_ROSTER) | {"dumbalgo"}
    assert registered - covered == set(), (
        f"algorithms missing CLI smoke coverage: {registered - covered}"
    )


def _run_hunt(tmp_path, name, algo_config, fidelity):
    config = tmp_path / "conf.yaml"
    config.write_text(
        yaml.safe_dump(
            {"algorithms": {name: algo_config}, "strategy": "NoParallelStrategy"}
        )
    )
    argv = [
        "hunt", "-n", f"{name}-smoke", "-c", str(config),
        "--storage-path", str(tmp_path / "db.pkl"),
        "--max-trials", "10", "--worker-trials", "10",
    ]
    if fidelity:
        argv += [FIDELITY_BOX, "-x~uniform(0, 1)", "--epochs~fidelity(1, 9, 3)"]
    else:
        argv += [BLACK_BOX, "-x~uniform(-50, 50)"]
    rc = cli_main(argv)
    assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exp = storage.fetch_experiments({"name": f"{name}-smoke"})[0]
    completed = [
        t for t in storage.fetch_trials(uid=exp["_id"]) if t.status == "completed"
    ]
    # Multi-fidelity schedulers may declare is_done early (first top-rung
    # completion, reference parity) — but something must have completed and
    # every objective must be a real number.
    assert len(completed) >= 4
    values = [t.objective.value for t in completed]
    assert all(math.isfinite(v) for v in values)
    return min(values)


@pytest.mark.parametrize("name", sorted(_FLAT_ROSTER))
def test_flat_roster_end_to_end(tmp_path, name):
    best = _run_hunt(tmp_path, name, _FLAT_ROSTER[name], fidelity=False)
    # Convergence sanity on the known quadratic (optimum 23.4 at x=34.56):
    # any working sampler's best-of-10 lands well inside the basin's scale.
    assert 23.4 - 1e-6 <= best < 5000.0


@pytest.mark.parametrize("name", sorted(_FIDELITY_ROSTER))
def test_fidelity_roster_end_to_end(tmp_path, name):
    best = _run_hunt(tmp_path, name, _FIDELITY_ROSTER[name], fidelity=True)
    # (x-0.6)^2 + 0.5/epochs on x in [0,1]: anything sane is far below 2.
    assert 0.0 <= best < 2.0


def _run_de_worker(db_path, conf_path):
    from orion_tpu.cli import main as _main

    # cli main reports failure via return code, not an exception — a child
    # that discards it would exit 0 on a failed hunt.
    raise SystemExit(_main(
        ["hunt", "-n", "de-pair", "-c", conf_path, "--storage-path", db_path,
         "--max-trials", "16", "--worker-trials", "16",
         BLACK_BOX, "-x~uniform(-50, 50)"]
    ))


def test_de_two_workers_one_db(tmp_path):
    """Two real DE worker processes on one DB: the budget completes with no
    duplicate trials, nothing wedges on the shared store, and every trial
    is attributed to the host:pid that reserved it.  (Cross-worker
    observation INTEGRATION — crowding accepting another worker's point —
    is pinned deterministically at unit level in test_algos.py's crowding
    tests; a multi-process run cannot guarantee both workers overlap, so
    it is not asserted here.)"""
    import multiprocessing

    db_path = str(tmp_path / "db.pkl")
    conf = tmp_path / "conf.yaml"
    conf.write_text(
        "algorithms: {de: {popsize: 6}}\nstrategy: NoParallelStrategy\n"
    )
    ctx = multiprocessing.get_context("spawn")
    workers = [
        ctx.Process(target=_run_de_worker, args=(db_path, str(conf)))
        for _ in range(2)
    ]
    try:
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=300)
        assert all(w.exitcode == 0 for w in workers), [w.exitcode for w in workers]
    finally:
        for w in workers:  # never leak a hung child holding the db lock
            if w.is_alive():  # pragma: no cover - only on failure
                w.terminate()
                w.join(timeout=30)
    storage = create_storage({"type": "pickled", "path": db_path})
    (exp,) = storage.fetch_experiments({"name": "de-pair"})
    completed = [
        t for t in storage.fetch_trials(uid=exp["_id"]) if t.status == "completed"
    ]
    assert len(completed) >= 16
    assert len({t.id for t in completed}) == len(completed)
    # Every completed trial is attributed to the host:pid that reserved it.
    workers_seen = {t.worker for t in completed}
    assert all(w for w in workers_seen), "unstamped completed trial"
