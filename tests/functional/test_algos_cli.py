"""Algorithm end-to-end runs through the real CLI (parity model: reference
tests/functional/algos/test_algos.py)."""

import os

from orion_tpu.cli import main as cli_main
from orion_tpu.storage import create_storage

HERE = os.path.dirname(os.path.abspath(__file__))
FIDELITY_BOX = os.path.join(HERE, "fidelity_box.py")
BLACK_BOX = os.path.join(HERE, "black_box.py")


def test_asha_end_to_end(tmp_path):
    config = tmp_path / "conf.yaml"
    config.write_text("algorithms: asha\nstrategy: NoParallelStrategy\n")
    rc = cli_main(
        ["hunt", "-n", "asha-exp", "-c", str(config),
         "--storage-path", str(tmp_path / "db.pkl"),
         "--max-trials", "12", "--worker-trials", "12",
         FIDELITY_BOX, "-x~uniform(0, 1)", "--epochs~fidelity(1, 9, 3)"]
    )
    assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exp = storage.fetch_experiments({"name": "asha-exp"})[0]
    completed = [
        t for t in storage.fetch_trials(uid=exp["_id"]) if t.status == "completed"
    ]
    assert 6 <= len(completed) <= 12  # ASHA may declare is_done before max_trials
    fidelities = sorted({t.params["/epochs"] for t in completed})
    assert set(fidelities).issubset({1, 3, 9})
    assert len(fidelities) >= 2  # promotions actually happened
    # Promoted points re-evaluate the same x at higher fidelity.
    by_x = {}
    for t in completed:
        by_x.setdefault(t.params["/x"], []).append(t.params["/epochs"])
    assert any(len(v) > 1 for v in by_x.values())


def test_tpe_end_to_end(tmp_path):
    config = tmp_path / "conf.yaml"
    config.write_text("algorithms:\n  tpe:\n    n_init: 6\n    n_candidates: 256\n")
    rc = cli_main(
        ["hunt", "-n", "tpe-exp", "-c", str(config),
         "--storage-path", str(tmp_path / "db.pkl"),
         "--max-trials", "10", "--worker-trials", "10",
         BLACK_BOX, "-x~uniform(-50, 50)"]
    )
    assert rc == 0
