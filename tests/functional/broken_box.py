#!/usr/bin/env python
"""Black box that always crashes (parity: reference broken_box.py)."""

import sys

sys.exit(1)
