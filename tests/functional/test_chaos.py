"""Chaos suite: experiments must complete under seeded fault schedules.

Runs a small experiment to completion on every backend while a
deterministic :class:`~orion_tpu.storage.faults.FaultSchedule` injects one
of each fault class (raise-before-apply, apply-then-reply-lost, latency
spike, mid-batch kill) into the document-DB layer — plus, on the network
backend, real connection drops through the TCP
:class:`~orion_tpu.storage.faults.FaultProxy` so the driver's reconnect
paths run, not mocks.  The run must converge through the unified retry
policy, and the storage invariant auditor must come back clean: zero
duplicated trials, zero lost observations, no orphaned reservations.
``storage.retries > 0`` proves the faults actually fired through the
retry path rather than being scheduled past the end of the run.

Tier-1 keeps the tiny schedules; the long high-rate soak is marked slow.
"""

import pytest

from orion_tpu.storage.base import DocumentStorage
from orion_tpu.storage.documents import MemoryDB
from orion_tpu.storage.faults import (
    FAULT_KINDS,
    FaultProxy,
    FaultSchedule,
    FaultyDB,
)
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.testing import drive_chaos_experiment

BACKENDS = ["memory", "pickled", "sqlite", "network"]

#: Retry knobs for chaos runs: tight backoff so the suite stays fast, but
#: enough attempts to ride out back-to-back scheduled faults.
RETRY = {"max_attempts": 6, "base_delay": 0.005, "max_delay": 0.05, "deadline": 30.0}

#: One pinned fault per round class early in the run (op indices), with
#: seeded random extras on top — deterministic AND guaranteed coverage.
TINY_PLAN = {3: "error", 8: "latency", 13: "reply_lost", 17: "kill"}
TINY_RATES = {"error": 0.03, "reply_lost": 0.02, "latency": 0.03, "kill": 0.02}


@pytest.fixture
def telemetry_enabled():
    was = TELEMETRY.enabled
    TELEMETRY.enable()
    yield TELEMETRY
    if not was:
        TELEMETRY.disable()


def _make_faulty_storage(backend, tmp_path, schedule):
    """(storage, cleanup, proxy_or_None) with the schedule installed at the
    document-DB layer (in-process backends) or server-side behind a fault
    proxy (network)."""
    if backend == "memory":
        return DocumentStorage(FaultyDB(MemoryDB(), schedule), retry=RETRY), None, None
    if backend == "pickled":
        from orion_tpu.storage.backends import PickledDB

        db = FaultyDB(PickledDB(str(tmp_path / "chaos.pkl")), schedule)
        return DocumentStorage(db, retry=RETRY), None, None
    if backend == "sqlite":
        from orion_tpu.storage.sqlitedb import SQLiteDB

        inner = SQLiteDB(str(tmp_path / "chaos.sqlite"))
        storage = DocumentStorage(FaultyDB(inner, schedule), retry=RETRY)
        return storage, inner.close, None
    # network: faults injected server-side (so the error crosses the real
    # wire protocol) AND the client connects through the fault proxy so
    # scheduled connection drops exercise genuine reconnects.
    from orion_tpu.storage.netdb import DBServer, NetworkDB

    server = DBServer(port=0)
    server.db = FaultyDB(server.db, schedule)
    host, port = server.serve_background()
    proxy = FaultProxy(host, port)
    phost, pport = proxy.serve_background()
    client = NetworkDB(host=phost, port=pport, timeout=10.0, idle_probe=0.05)
    storage = DocumentStorage(client, retry=RETRY)

    def cleanup():
        client.close()
        proxy.stop()
        server.shutdown()
        server.server_close()

    return storage, cleanup, proxy


def _assert_chaos_outcome(exp, report, schedule, max_trials, registry,
                          retries_before):
    assert report.ok, report.summary()
    completed = exp.fetch_trials_by_status("completed")
    assert len(completed) >= max_trials
    # Zero duplicated trials / zero lost observations, asserted directly on
    # top of the auditor's word.
    points = {t.hash_params for t in exp.fetch_trials()}
    assert len(points) == len(exp.fetch_trials())
    assert all(t.objective is not None for t in completed)
    # The schedule actually fired, and the retry path absorbed it.
    assert schedule.total_injected > 0, "fault schedule never fired"
    assert (
        registry.counter_value("storage.retries") > retries_before
    ), "faults fired but nothing retried — the policy is not wired in"


@pytest.mark.chaos
@pytest.mark.parametrize("backend", BACKENDS)
def test_chaos_tiny_seeded_schedule(tmp_path, backend, telemetry_enabled):
    """Tier-1 chaos: a tiny pinned-plan schedule (one fault per round
    class) on every backend; the experiment completes and audits clean."""
    registry = telemetry_enabled
    retries_before = registry.counter_value("storage.retries")
    schedule = FaultSchedule(
        seed=7, plan=dict(TINY_PLAN), rates=TINY_RATES, latency=0.005,
        max_faults=10,
    )
    storage, cleanup, proxy = _make_faulty_storage(backend, tmp_path, schedule)
    try:
        exp, report = drive_chaos_experiment(
            storage, max_trials=9, seed=1, proxy=proxy,
            drop_every=4 if proxy is not None else 0,
        )
        _assert_chaos_outcome(exp, report, schedule, 9, registry, retries_before)
        # Every round class fired at least once (kill may defer to the
        # next batch op, but a produce round always offers one).
        for kind in FAULT_KINDS:
            assert schedule.injected[kind] >= 1, (
                f"fault class {kind!r} never fired: {schedule.injected}"
            )
        if proxy is not None:
            # The connection drops exercised the driver's real reconnects.
            assert storage.db.reconnects >= 1
    finally:
        if cleanup is not None:
            cleanup()


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("backend", BACKENDS)
def test_chaos_long_schedule_soak(tmp_path, backend, telemetry_enabled):
    """The soak: higher fault rates, more trials, no pinned plan — pure
    seeded pressure.  Excluded from tier-1 (-m 'not slow')."""
    registry = telemetry_enabled
    retries_before = registry.counter_value("storage.retries")
    schedule = FaultSchedule(
        seed=23,
        rates={"error": 0.08, "reply_lost": 0.05, "latency": 0.08, "kill": 0.04},
        latency=0.01,
        max_faults=60,
    )
    storage, cleanup, proxy = _make_faulty_storage(backend, tmp_path, schedule)
    try:
        exp, report = drive_chaos_experiment(
            storage, max_trials=30, seed=2, proxy=proxy,
            drop_every=5 if proxy is not None else 0,
        )
        _assert_chaos_outcome(exp, report, schedule, 30, registry, retries_before)
    finally:
        if cleanup is not None:
            cleanup()
