"""Chaos suite: experiments must complete under seeded fault schedules.

Runs a small experiment to completion on every backend while a
deterministic :class:`~orion_tpu.storage.faults.FaultSchedule` injects one
of each fault class (raise-before-apply, apply-then-reply-lost, latency
spike, mid-batch kill) into the document-DB layer — plus, on the network
backend, real connection drops through the TCP
:class:`~orion_tpu.storage.faults.FaultProxy` so the driver's reconnect
paths run, not mocks.  The run must converge through the unified retry
policy, and the storage invariant auditor must come back clean: zero
duplicated trials, zero lost observations, no orphaned reservations.
``storage.retries > 0`` proves the faults actually fired through the
retry path rather than being scheduled past the end of the run.

Tier-1 keeps the tiny schedules; the long high-rate soak is marked slow.
"""

import pytest

from orion_tpu.storage.base import DocumentStorage
from orion_tpu.storage.documents import MemoryDB
from orion_tpu.storage.faults import (
    FAULT_KINDS,
    FaultProxy,
    FaultSchedule,
    FaultyDB,
)
from orion_tpu.telemetry import TELEMETRY
from orion_tpu.testing import drive_chaos_experiment

BACKENDS = ["memory", "pickled", "sqlite", "network"]

#: Retry knobs for chaos runs: tight backoff so the suite stays fast, but
#: enough attempts to ride out back-to-back scheduled faults.
RETRY = {"max_attempts": 6, "base_delay": 0.005, "max_delay": 0.05, "deadline": 30.0}

#: One pinned fault per round class early in the run (op indices), with
#: seeded random extras on top — deterministic AND guaranteed coverage.
TINY_PLAN = {3: "error", 8: "latency", 13: "reply_lost", 17: "kill"}
TINY_RATES = {"error": 0.03, "reply_lost": 0.02, "latency": 0.03, "kill": 0.02}


@pytest.fixture
def telemetry_enabled():
    was = TELEMETRY.enabled
    TELEMETRY.enable()
    yield TELEMETRY
    if not was:
        TELEMETRY.disable()


def _make_faulty_storage(backend, tmp_path, schedule):
    """(storage, cleanup, proxy_or_None) with the schedule installed at the
    document-DB layer (in-process backends) or server-side behind a fault
    proxy (network)."""
    if backend == "memory":
        return DocumentStorage(FaultyDB(MemoryDB(), schedule), retry=RETRY), None, None
    if backend == "pickled":
        from orion_tpu.storage.backends import PickledDB

        db = FaultyDB(PickledDB(str(tmp_path / "chaos.pkl")), schedule)
        return DocumentStorage(db, retry=RETRY), None, None
    if backend == "sqlite":
        from orion_tpu.storage.sqlitedb import SQLiteDB

        inner = SQLiteDB(str(tmp_path / "chaos.sqlite"))
        storage = DocumentStorage(FaultyDB(inner, schedule), retry=RETRY)
        return storage, inner.close, None
    # network: faults injected server-side (so the error crosses the real
    # wire protocol) AND the client connects through the fault proxy so
    # scheduled connection drops exercise genuine reconnects.
    from orion_tpu.storage.netdb import DBServer, NetworkDB

    server = DBServer(port=0)
    server.db = FaultyDB(server.db, schedule)
    host, port = server.serve_background()
    proxy = FaultProxy(host, port)
    phost, pport = proxy.serve_background()
    client = NetworkDB(host=phost, port=pport, timeout=10.0, idle_probe=0.05)
    storage = DocumentStorage(client, retry=RETRY)

    def cleanup():
        client.close()
        proxy.stop()
        server.shutdown()
        server.server_close()

    return storage, cleanup, proxy


def _assert_chaos_outcome(exp, report, schedule, max_trials, registry,
                          retries_before):
    assert report.ok, report.summary()
    completed = exp.fetch_trials_by_status("completed")
    assert len(completed) >= max_trials
    # Zero duplicated trials / zero lost observations, asserted directly on
    # top of the auditor's word.
    points = {t.hash_params for t in exp.fetch_trials()}
    assert len(points) == len(exp.fetch_trials())
    assert all(t.objective is not None for t in completed)
    # The schedule actually fired, and the retry path absorbed it.
    assert schedule.total_injected > 0, "fault schedule never fired"
    assert (
        registry.counter_value("storage.retries") > retries_before
    ), "faults fired but nothing retried — the policy is not wired in"


@pytest.mark.chaos
@pytest.mark.parametrize("backend", BACKENDS)
def test_chaos_tiny_seeded_schedule(tmp_path, backend, telemetry_enabled):
    """Tier-1 chaos: a tiny pinned-plan schedule (one fault per round
    class) on every backend; the experiment completes and audits clean."""
    registry = telemetry_enabled
    retries_before = registry.counter_value("storage.retries")
    schedule = FaultSchedule(
        seed=7, plan=dict(TINY_PLAN), rates=TINY_RATES, latency=0.005,
        max_faults=10,
    )
    storage, cleanup, proxy = _make_faulty_storage(backend, tmp_path, schedule)
    try:
        exp, report = drive_chaos_experiment(
            storage, max_trials=9, seed=1, proxy=proxy,
            drop_every=4 if proxy is not None else 0,
        )
        _assert_chaos_outcome(exp, report, schedule, 9, registry, retries_before)
        # Every round class fired at least once (kill may defer to the
        # next batch op, but a produce round always offers one).
        for kind in FAULT_KINDS:
            assert schedule.injected[kind] >= 1, (
                f"fault class {kind!r} never fired: {schedule.injected}"
            )
        if proxy is not None:
            # The connection drops exercised the driver's real reconnects.
            assert storage.db.reconnects >= 1
    finally:
        if cleanup is not None:
            cleanup()


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("backend", BACKENDS)
def test_chaos_long_schedule_soak(tmp_path, backend, telemetry_enabled):
    """The soak: higher fault rates, more trials, no pinned plan — pure
    seeded pressure.  Excluded from tier-1 (-m 'not slow')."""
    registry = telemetry_enabled
    retries_before = registry.counter_value("storage.retries")
    schedule = FaultSchedule(
        seed=23,
        rates={"error": 0.08, "reply_lost": 0.05, "latency": 0.08, "kill": 0.04},
        latency=0.01,
        max_faults=60,
    )
    storage, cleanup, proxy = _make_faulty_storage(backend, tmp_path, schedule)
    try:
        exp, report = drive_chaos_experiment(
            storage, max_trials=30, seed=2, proxy=proxy,
            drop_every=5 if proxy is not None else 0,
        )
        _assert_chaos_outcome(exp, report, schedule, 30, registry, retries_before)
    finally:
        if cleanup is not None:
            cleanup()


# --- day-2 chaos: replica kill mid-drain + quorum-ack drop (ISSUE 20) --------


class _Crash(RuntimeError):
    pass


def _day2_db(backend, tmp_path, tag, schedule):
    """(db_or_None, cleanup_or_None) — the backend-under-test store a
    shard primary runs, wrapped in the seeded FaultyDB.  ``network``
    keeps the server's native store (the wire layer IS that backend's
    subject; its extra leg is the client-side fault proxy)."""
    if backend == "memory":
        return FaultyDB(MemoryDB(), schedule), None
    if backend == "pickled":
        from orion_tpu.storage.backends import PickledDB

        return FaultyDB(PickledDB(str(tmp_path / f"{tag}.pkl")), schedule), None
    if backend == "sqlite":
        from orion_tpu.storage.sqlitedb import SQLiteDB

        inner = SQLiteDB(str(tmp_path / f"{tag}.sqlite"))
        return FaultyDB(inner, schedule), inner.close
    return None, None


@pytest.mark.chaos
@pytest.mark.parametrize("backend", BACKENDS)
def test_chaos_drain_survives_replica_kill_and_quorum_ack_drop(
    tmp_path, backend, telemetry_enabled
):
    """The day-2 leg: a seeded FaultyDB shard drain loses a replica AND
    every quorum ack mid-flight.  The contract under fire: sync writes
    during the ack blackout either apply everywhere (once acks return)
    or raise ``maybe_applied`` — never silently vanish; the drain RESUMES
    after the crash; the survivor audits clean on every backend."""
    import time as _time

    from orion_tpu.core.experiment import experiment_id
    from orion_tpu.storage.audit import audit_storage
    from orion_tpu.storage.documents import dumps_canonical
    from orion_tpu.storage.drain import Drainer
    from orion_tpu.storage.netdb import DBServer, NetworkDB
    from orion_tpu.storage.retry import MODE_ALWAYS, RetryPolicy
    from orion_tpu.storage.shard import ShardedNetworkDB
    from orion_tpu.utils.exceptions import DatabaseError, DuplicateKeyError

    schedule = FaultSchedule(
        seed=29, plan={4: "error", 9: "latency", 15: "reply_lost"},
        rates={"error": 0.02, "latency": 0.02}, latency=0.005, max_faults=8,
    )
    policy = RetryPolicy(
        max_attempts=10, base_delay=0.005, max_delay=0.05, deadline=30.0
    )

    def _r(fn):
        """Populate/verify ops ride the same always-retry discipline the
        soak workers do; a DuplicateKeyError on a resend means an earlier
        reply-lost attempt already applied — converged."""
        try:
            return policy.run(fn, op="day2", mode=MODE_ALWAYS)
        except DuplicateKeyError:
            return None

    cleanups = []
    crashed = {"done": False}
    # Victim shard: quorum=1 over two replicas, each reached THROUGH a
    # fault proxy so the test can freeze the ack stream without killing
    # the processes.
    replicas, repl_proxies = [], []
    for _ in range(2):
        replica = DBServer(port=0, replica=True)
        replica.serve_background()
        proxy = FaultProxy(*replica.address)
        proxy.serve_background()
        replicas.append(replica)
        repl_proxies.append(proxy)
    victim = DBServer(
        port=0, replicate_to=[p.address for p in repl_proxies],
        quorum=1, quorum_timeout=0.3,
    )
    db, closer = _day2_db(backend, tmp_path, "victim", schedule)
    victim.db = db if db is not None else FaultyDB(victim.db, schedule)
    if closer is not None:
        cleanups.append(closer)
    victim.serve_background()
    # The survivor runs the SAME backend: the post-drain audit must come
    # back clean on the backend under test, not a stand-in.
    survivor = DBServer(port=0)
    db, closer = _day2_db(backend, tmp_path, "survivor", schedule)
    if db is not None:
        survivor.db = db
    if closer is not None:
        cleanups.append(closer)
    survivor.serve_background()
    victim_spec = {"host": victim.address[0], "port": victim.address[1]}
    client_proxy = None
    if backend == "network":
        # The network backend's extra leg: the router dials the victim
        # through a fault proxy whose drops force real reconnects.
        client_proxy = FaultProxy(*victim.address)
        client_proxy.serve_background()
        victim_spec = {
            "host": client_proxy.address[0], "port": client_proxy.address[1],
        }
    router = ShardedNetworkDB(
        [victim_spec,
         {"host": survivor.address[0], "port": survivor.address[1]}],
        reconnect_jitter=0, timeout=5.0, placement_ttl=0.2,
    )
    direct = NetworkDB(
        host=victim.address[0], port=victim.address[1], timeout=5.0,
        reconnect_jitter=0,
    )
    try:
        names = [f"day2-{e}" for e in range(8)]
        eids = []
        for name in names:
            eid = experiment_id(name, 1, "u")
            eids.append(eid)
            _r(lambda doc={"_id": eid, "name": name, "version": 1,
                           "metadata": {"user": "u"}}:
               router.write("experiments", doc))
            for i in range(2):
                _r(lambda doc={
                    "_id": f"{eid}-t{i}", "experiment": eid,
                    "status": "completed", "objective": float(i),
                    "params": {"/x": float(i)},
                    "results": [{"name": "obj", "type": "objective",
                                 "value": float(i)}],
                    "submit_time": 1.0, "start_time": 1.0, "end_time": 2.0,
                    "heartbeat": 2.0,
                }: router.write("trials", doc))
        if not any(router.shard_for(eid) == 0 for eid in eids):
            pytest.skip("ring placed nothing on the victim (rare draw)")

        def snapshot():
            by_id = {}
            for eid in eids:
                docs = _r(
                    lambda eid=eid: router.read("trials", {"experiment": eid})
                )
                for doc in docs:
                    by_id[doc["_id"]] = dumps_canonical(doc)
            return by_id

        before = snapshot()

        def crash_once(stage, exp_id):
            if stage == "after_copy" and not crashed["done"]:
                crashed["done"] = True
                # Mid-drain: one replica dies outright, the other's ack
                # stream blackholes — every quorum ack is now dropped.
                replicas[0].shutdown()
                replicas[0].server_close()
                repl_proxies[0].stop()
                repl_proxies[1].set_blackhole(True)
                if client_proxy is not None:
                    client_proxy.drop_all()
                raise _Crash(f"mid-drain kill at {exp_id}")

        wounded = Drainer(router, 0, fence_grace=0.1, crash_at=crash_once)
        plan = wounded.plan()
        assert plan.moves and not plan.strays
        with pytest.raises(_Crash):
            wounded.run(plan)
        assert crashed["done"]
        # The ack blackout: a sync write applies locally but the reply is
        # maybe_applied — the zero-silent-loss half of the contract.
        saw_maybe_applied = False
        for _ in range(20):
            try:
                direct.write(
                    "lying_trials",
                    {"_id": "quorum-probe", "experiment": "x"},
                )
            except DuplicateKeyError:
                break  # an earlier maybe_applied attempt already applied
            except DatabaseError as exc:
                if getattr(exc, "maybe_applied", False):
                    saw_maybe_applied = True
                    break
                _time.sleep(0.02)  # an injected fault; probe again
            else:  # pragma: no cover - acks are blackholed
                break
        assert saw_maybe_applied, "ack blackout never surfaced maybe_applied"
        assert _r(
            lambda: direct.read("lying_trials", {"_id": "quorum-probe"})
        ), "maybe_applied write is not on the primary"
        # Acks return; the drain RESUMES from the standing placement docs.
        repl_proxies[1].set_blackhole(False)
        repl_proxies[1].drop_all()
        resumed = Drainer(router, 0, fence_grace=0.1)
        resumed.run()
        assert resumed.residual_experiments() == []
        # ... and the blackout write reached the surviving replica: the
        # apply-everywhere half of the contract.
        reader = NetworkDB(
            host=replicas[1].address[0], port=replicas[1].address[1],
            reconnect_jitter=0,
        )
        try:
            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline and not reader.read(
                "lying_trials", {"_id": "quorum-probe"}
            ):
                _time.sleep(0.05)
            assert reader.read("lying_trials", {"_id": "quorum-probe"})
        finally:
            reader.close()
        # Drop the shard; everything must live on the survivor, clean.
        router.set_topology(
            [{"host": survivor.address[0], "port": survivor.address[1]}]
        )
        assert snapshot() == before, "documents changed across the drain"
        reports = audit_storage(
            DocumentStorage(router, retry=RETRY), lost_timeout=3600.0
        )
        assert all(r.ok for r in reports), [r.violations for r in reports]
        assert schedule.total_injected > 0, "fault schedule never fired"
    finally:
        direct.close()
        router.close()
        for cleanup in cleanups:
            cleanup()
        if client_proxy is not None:
            client_proxy.stop()
        for proxy in repl_proxies[1:]:
            proxy.stop()
        if not crashed["done"]:
            repl_proxies[0].stop()
        for server in [victim, survivor, replicas[1]] + (
            [] if crashed["done"] else [replicas[0]]
        ):
            try:
                server.shutdown()
                server.server_close()
            except Exception:
                pass
