"""The showcase scripts in examples/ run through REAL hunts (VERDICT r4 #8)
so they cannot silently rot — the reference's runnable-demo discipline
(`/root/reference/tests/functional/demo/test_demo.py:51-102`).
"""

import os

from orion_tpu.cli import main as cli_main
from orion_tpu.storage import create_storage

HERE = os.path.dirname(os.path.abspath(__file__))
EXAMPLES = os.path.abspath(os.path.join(HERE, "..", "..", "examples"))


def _completed(path, name):
    storage = create_storage({"type": "pickled", "path": path})
    [exp] = storage.fetch_experiments({"name": name})
    return [
        t for t in storage.fetch_trials(uid=exp["_id"]) if t.status == "completed"
    ]


def test_mnist_lenet_example_end_to_end(tmp_path):
    """Mixed Real/Integer/Categorical space, real (synthetic-data) training
    in the trial subprocess — BASELINE config #4's docs example."""
    db = str(tmp_path / "db.pkl")
    rc = cli_main(
        ["hunt", "-n", "lenet-example", "--storage-path", db,
         "--max-trials", "3", "--worker-trials", "3",
         os.path.join(EXAMPLES, "mnist_lenet.py"),
         "--lr~loguniform(1e-3, 1e-1)",
         "--batch-size~uniform(64, 256, discrete=True)",
         "--width~uniform(1, 2, discrete=True)",
         "--act~choices(['relu', 'tanh'])"]
    )
    assert rc == 0
    completed = _completed(db, "lenet-example")
    assert len(completed) == 3
    for trial in completed:
        assert 0.0 <= trial.objective.value <= 1.0  # a validation error rate
        assert trial.params["/act"] in ("relu", "tanh")
        assert trial.params["/batch-size"] == int(trial.params["/batch-size"])


def test_fidelity_sweep_example_end_to_end(tmp_path):
    """Multi-fidelity ladder through ASHA: low-epoch evaluations dominate
    and at least one configuration is promoted to a higher budget."""
    db = str(tmp_path / "db.pkl")
    config = tmp_path / "conf.yaml"
    config.write_text("algorithms: {asha: {num_brackets: 2}}\n")
    rc = cli_main(
        ["hunt", "-n", "fid-example", "-c", str(config), "--storage-path", db,
         "--max-trials", "16", "--worker-trials", "16",
         os.path.join(EXAMPLES, "fidelity_sweep.py"),
         "--lr~loguniform(1e-4, 1e-1)",
         "--width~uniform(16, 256, discrete=True)",
         "--epochs~fidelity(1, 9, 3)"]
    )
    assert rc == 0
    completed = _completed(db, "fid-example")
    assert len(completed) >= 4
    epochs = sorted({t.params["/epochs"] for t in completed})
    assert set(epochs).issubset({1, 3, 9}) and len(epochs) >= 2
    by_point = {}
    for t in completed:
        by_point.setdefault((t.params["/lr"], t.params["/width"]), []).append(
            t.params["/epochs"]
        )
    assert any(len(v) > 1 for v in by_point.values())  # a real promotion
