"""info / status / list / insert / db command behavior through the real CLI.

Parity model: reference tests/functional/commands/.
"""

import os

import pytest

from orion_tpu.cli import main as cli_main
from orion_tpu.storage import create_storage

HERE = os.path.dirname(os.path.abspath(__file__))
BLACK_BOX = os.path.join(HERE, "black_box.py")


@pytest.fixture(scope="module")
def _populated_template(tmp_path_factory):
    """Run the 4-trial hunt ONCE per module; ~10s of subprocess trials that
    eight tests each paid before this was a template."""
    root = tmp_path_factory.mktemp("populated-template")
    cli_main(["hunt", "-n", "cmd-exp", "--storage-path", str(root / "db.pkl"),
              "--max-trials", "4", "--worker-trials", "4",
              BLACK_BOX, "-x~uniform(-50, 50)"])
    return root / "db.pkl"


@pytest.fixture
def populated(_populated_template, tmp_path):
    """Per-test COPY of the template DB: mutating tests (insert, resume,
    branching hunts) keep full isolation at file-copy cost."""
    import shutil

    shutil.copy(_populated_template, tmp_path / "db.pkl")
    return tmp_path, ["--storage-path", str(tmp_path / "db.pkl")]


def test_info(populated, capsys):
    tmp_path, db = populated
    rc = cli_main(["info", "-n", "cmd-exp", *db])
    assert rc == 0
    out = capsys.readouterr().out
    assert "cmd-exp" in out
    assert "/x: uniform(-50, 50)" in out
    assert "trials completed: 4" in out
    assert "best evaluation:" in out


def test_status(populated, capsys):
    tmp_path, db = populated
    rc = cli_main(["status", *db])
    assert rc == 0
    out = capsys.readouterr().out
    # Default aggregates a name's versions under the bare name (reference
    # shows per-version sections only with --expand-versions).
    assert "cmd-exp" in out and "cmd-exp-v1" not in out
    assert "completed" in out and "4" in out


def test_status_expand_versions(populated, capsys):
    tmp_path, db = populated
    rc = cli_main(["status", "--expand-versions", *db])
    assert rc == 0
    out = capsys.readouterr().out
    assert "cmd-exp-v1" in out


def test_status_all_lists_trials(populated, capsys):
    tmp_path, db = populated
    rc = cli_main(["status", "-n", "cmd-exp", "--all", *db])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("completed") == 4


def test_list_shows_evc_tree(populated, capsys):
    tmp_path, db = populated
    # Branch it to get a tree.
    cli_main(["hunt", "-n", "cmd-exp", *db, "--max-trials", "2", "--worker-trials", "0",
              BLACK_BOX, "-x~uniform(-10, 10)"])
    rc = cli_main(["list", *db])
    assert rc == 0
    out = capsys.readouterr().out
    assert "cmd-exp-v1" in out
    assert "└── cmd-exp-v2" in out


def test_insert_and_defaults(populated, capsys):
    tmp_path, db = populated
    rc = cli_main(["insert", "-n", "cmd-exp", *db, "x=3.5"])
    assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exp = storage.fetch_experiments({"name": "cmd-exp"})[0]
    new = [t for t in storage.fetch_trials(uid=exp["_id"]) if t.status == "new"]
    assert len(new) == 1
    assert new[0].params == {"/x": 3.5}


def test_insert_rejects_out_of_bounds(populated):
    tmp_path, db = populated
    with pytest.raises(ValueError):
        cli_main(["insert", "-n", "cmd-exp", *db, "x=999"])


def test_db_test_checks(populated, capsys):
    tmp_path, db = populated
    rc = cli_main(["db", "test", *db])
    assert rc == 0
    out = capsys.readouterr().out
    assert "check presence... ok" in out
    assert "check creation... ok" in out
    assert "check operations... ok" in out


def test_db_upgrade_backfills(populated, capsys):
    tmp_path, db = populated
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    # Simulate an old-schema experiment document.
    storage.db.write("experiments", {"_id": "old1", "name": "legacy"})
    rc = cli_main(["db", "upgrade", *db])
    assert rc == 0
    doc = storage.fetch_experiments({"name": "legacy"})[0]
    assert doc["version"] == 1
    assert doc["priors"] == {}
    assert doc["refers"] == {}


def test_db_setup_writes_user_config(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("XDG_CONFIG_HOME", str(tmp_path / "cfg"))
    rc = cli_main(["db", "setup", "--path", str(tmp_path / "mydb.pkl")])
    assert rc == 0
    import yaml

    path = tmp_path / "cfg" / "orion_tpu" / "config.yaml"
    data = yaml.safe_load(path.read_text())
    assert data["storage"]["type"] == "pickled"
    assert data["storage"]["path"] == str(tmp_path / "mydb.pkl")


def test_sectioned_config_files_are_not_silently_ignored(tmp_path, capsys):
    """`experiment:`-wrapped keys and the reference's `producer:`/`database:`
    sections must configure the run — a config whose algorithms sat under
    `experiment:` previously ran RANDOM search without a word."""
    conf = tmp_path / "exp.yaml"
    conf.write_text(
        "experiment:\n"
        "  algorithms:\n"
        "    grid_search:\n"
        "      n_values: 3\n"
        "producer:\n"
        "  strategy: StubParallelStrategy\n"
        f"database:\n  type: pickleddb\n  path: {tmp_path / 'ref.pkl'}\n"
    )
    rc = cli_main(["hunt", "-n", "sect", "-c", str(conf), "--max-trials", "3",
                   "--working-dir", str(tmp_path / "w"),
                   BLACK_BOX, "-x~uniform(-5, 5)"])
    assert rc == 0
    capsys.readouterr()
    # The database: section routed storage to the reference-style pickleddb
    # path, and the experiment: section selected grid_search.
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "ref.pkl")})
    [exp] = storage.fetch_experiments({"name": "sect"})
    assert "grid_search" in exp["algorithms"]
    rc = cli_main(["info", "-n", "sect", "-c", str(conf)])
    assert rc == 0
    assert "grid_search" in capsys.readouterr().out


def test_sectioned_user_level_config(tmp_path, monkeypatch):
    """The ~/.config user file layer normalizes sections too — that is
    exactly where reference users keep their `database:` section."""
    cfg_dir = tmp_path / "xdg" / "orion_tpu"
    cfg_dir.mkdir(parents=True)
    (cfg_dir / "config.yaml").write_text(
        f"database:\n  type: pickleddb\n  path: {tmp_path / 'user.pkl'}\n"
    )
    monkeypatch.setenv("XDG_CONFIG_HOME", str(tmp_path / "xdg"))
    from orion_tpu.config import resolve_config

    config = resolve_config()
    assert config["storage"]["type"] == "pickleddb"
    assert config["storage"]["path"] == str(tmp_path / "user.pkl")


def test_sectioned_config_top_level_wins():
    """experiment:-hoisted keys lose WHOLE to explicit top-level ones
    (shallow replace): never a merged two-algorithm dict create_algo
    would reject."""
    from orion_tpu.config import normalize_sections

    cfg = normalize_sections(
        {"experiment": {"algorithms": {"tpe": {}}}, "algorithms": {"random": {}}}
    )
    assert cfg["algorithms"] == {"random": {}}


def test_hunt_n_workers_shares_the_budget(tmp_path, capsys):
    """--n-workers N spawns N-1 identical child hunts against the shared
    storage; the cohort completes the global budget exactly once."""
    db = ["--storage-path", str(tmp_path / "db.pkl")]
    rc = cli_main(["hunt", "-n", "nw", *db, "--max-trials", "8",
                   "--n-workers", "2", "--working-dir", str(tmp_path / "w"),
                   BLACK_BOX, "-x~uniform(-5, 5)"])
    assert rc == 0
    assert "trials completed:" in capsys.readouterr().out
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    [exp] = storage.fetch_experiments({"name": "nw"})
    trials = storage.fetch_trials(uid=exp["_id"])
    completed = sum(1 for t in trials if t.status == "completed")
    # Async workers check is_done before consuming, so a final in-flight
    # trial per extra worker may land past the budget — same soft-budget
    # semantics as N manually-launched hunts (and the reference).
    assert 8 <= completed <= 9


def test_hunt_n_workers_refuses_memory_storage(capsys):
    rc = cli_main(["hunt", "-n", "nwm", "--debug", "--max-trials", "2",
                   "--n-workers", "2", BLACK_BOX, "-x~uniform(-5, 5)"])
    assert rc == 1
    assert "in-memory storage is per-process" in capsys.readouterr().err


def test_setup_and_test_db_top_level_aliases(tmp_path, monkeypatch, capsys):
    """`setup` and `test-db` mirror `db setup` / `db test` (reference
    `cli/setup.py`, `cli/test_db.py` historical spellings)."""
    monkeypatch.setenv("XDG_CONFIG_HOME", str(tmp_path / "cfg"))
    assert cli_main(["setup", "--storage-type", "sqlite",
                     "--path", str(tmp_path / "a.sqlite")]) == 0
    import yaml

    data = yaml.safe_load(
        (tmp_path / "cfg" / "orion_tpu" / "config.yaml").read_text()
    )
    assert data["storage"]["type"] == "sqlite"
    assert cli_main(["test-db"]) == 0
    out = capsys.readouterr().out
    assert "check presence... ok" in out
    assert "check operations... ok" in out


def test_branching_diff_lines_colorize_on_tty(monkeypatch):
    import io

    from orion_tpu.utils.diff import colorize_diff_line

    class Tty(io.StringIO):
        def isatty(self):
            return True

    monkeypatch.delenv("NO_COLOR", raising=False)  # ambient CI shells set it
    assert colorize_diff_line("+ x~uniform(0,1)", stream=Tty()).startswith("\x1b[0;32m")
    assert colorize_diff_line("- y~uniform(0,1)", stream=Tty()).startswith("\x1b[0;31m")
    assert colorize_diff_line("~ z: a -> b", stream=Tty()).startswith("\x1b[0;33m")
    # Non-TTY (scripted sessions, tests) and NO_COLOR stay plain.
    assert colorize_diff_line("+ x", stream=io.StringIO()) == "+ x"
    monkeypatch.setenv("NO_COLOR", "1")
    assert colorize_diff_line("+ x", stream=Tty()) == "+ x"


def test_resume_preserves_stored_budgets(populated, capsys):
    """Regression: resolver defaults must not override stored per-experiment
    settings on resume (max_trials inf clobbered a stored value)."""
    tmp_path, db = populated
    rc = cli_main(["info", "-n", "cmd-exp", *db])
    assert rc == 0
    out = capsys.readouterr().out
    assert "max_trials: 4" in out


def test_info_unknown_experiment_no_ghost(tmp_path, capsys):
    """Regression: read-only commands must not persist ghost experiments;
    the unknown name surfaces as a one-line error, not a traceback."""
    db = ["--storage-path", str(tmp_path / "db.pkl")]
    assert cli_main(["info", "-n", "typo", *db]) == 1
    assert "no experiment matching" in capsys.readouterr().err
    assert cli_main(["insert", "-n", "typo", *db, "x=1"]) == 1
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    assert storage.fetch_experiments({}) == []


def test_info_wrong_version_no_ghost(populated, capsys):
    tmp_path, db = populated
    assert cli_main(["info", "-n", "cmd-exp", "--exp-version", "99", *db]) == 1
    assert "no experiment matching" in capsys.readouterr().err
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    assert len(storage.fetch_experiments({"name": "cmd-exp"})) == 1


def test_status_collapse_aggregates_tree(populated, capsys):
    tmp_path, db = populated
    cli_main(["hunt", "-n", "cmd-exp", *db, "--max-trials", "6", "--worker-trials", "2",
              BLACK_BOX, "-x~uniform(-10, 10)"])
    capsys.readouterr()  # drop the hunt's own stats output
    rc = cli_main(["status", "--collapse", *db])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("cmd-exp") == 1  # one collapsed tree, not per-version
    assert "6" in out  # 4 (v1) + 2 (v2) completed


def test_env_var_coercion(tmp_path, monkeypatch):
    monkeypatch.setenv("ORION_MAX_TRIALS", "7")
    from orion_tpu.config import resolve_config

    config = resolve_config()
    assert config["max_trials"] == 7.0
    assert isinstance(config["max_trials"], float)


def test_config_file_heartbeat_governs_lost_trial_sweep(tmp_path):
    """A config-file `heartbeat:` must change the sweep threshold — the knob
    was previously defined in DEFAULTS but never plumbed (round-1 verdict)."""
    import argparse
    import time as _time

    from orion_tpu.cli.base import build_from_args
    from orion_tpu.core.trial import Trial

    conf = tmp_path / "orion.yaml"
    conf.write_text("heartbeat: 7.5\nmax_idle_time: 3.0\n")
    args = argparse.Namespace(
        name="hb-exp",
        exp_version=None,
        config=str(conf),
        debug=False,
        storage_path=str(tmp_path / "db.pkl"),
        manual_resolution=False,
        user_args=[BLACK_BOX, "-x~uniform(-5, 5)"],
    )
    experiment, _parser = build_from_args(args)
    assert experiment.heartbeat == 7.5

    # A reserved trial whose heartbeat is older than 7.5s is swept...
    trial = Trial(experiment=experiment.id, params={"/x": 1.0}, status="new")
    experiment.storage.register_trial(trial)
    reserved = experiment.storage.reserve_trial(experiment.id)
    experiment.storage._db.write(
        "trials", {"heartbeat": _time.time() - 8.0}, query={"_id": reserved.id}
    )
    experiment.fix_lost_trials()
    statuses = {t.id: t.status for t in experiment.fetch_trials()}
    assert statuses[reserved.id] == "interrupted"

    # ...but with the default 120s threshold it would have survived.
    conf2 = tmp_path / "orion2.yaml"
    conf2.write_text("heartbeat: 120.0\n")
    args.config = str(conf2)
    args.name = "hb-exp"
    experiment2, _ = build_from_args(args)
    assert experiment2.heartbeat == 120.0
    trial2 = Trial(experiment=experiment2.id, params={"/x": 2.0}, status="new")
    experiment2.storage.register_trial(trial2)
    reserved2 = experiment2.storage.reserve_trial(experiment2.id)
    experiment2.storage._db.write(
        "trials", {"heartbeat": _time.time() - 8.0}, query={"_id": reserved2.id}
    )
    experiment2.fix_lost_trials()
    statuses = {t.id: t.status for t in experiment2.fetch_trials()}
    assert statuses[reserved2.id] == "reserved"


def test_heartbeat_cli_flag_overrides_config_file(tmp_path):
    import argparse

    from orion_tpu.cli.base import build_from_args

    conf = tmp_path / "orion.yaml"
    conf.write_text("heartbeat: 99.0\nmax_idle_time: 44.0\n")
    args = argparse.Namespace(
        name="hb-cli",
        exp_version=None,
        config=str(conf),
        debug=False,
        storage_path=str(tmp_path / "db.pkl"),
        manual_resolution=False,
        user_args=[BLACK_BOX, "-x~uniform(-5, 5)"],
        heartbeat=33.0,
    )
    experiment, _ = build_from_args(args)
    assert experiment.heartbeat == 33.0  # flag beats config file
    assert experiment.max_idle_time == 44.0  # config file beats default


def test_user_namespacing(tmp_path, capsys):
    """-u/--user scopes lookups: the same name under another user is
    invisible (reference `cli/base.py:94`)."""
    db = ["--storage-path", str(tmp_path / "db.pkl")]
    cli_main(["hunt", "-n", "ns", "-u", "alice", *db, "--max-trials", "2",
              "--worker-trials", "2", BLACK_BOX, "-x~uniform(-5, 5)"])
    capsys.readouterr()

    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    [exp] = storage.fetch_experiments({"name": "ns"})
    assert exp["metadata"]["user"] == "alice"

    # bob filters it out of status...
    rc = cli_main(["status", "-u", "bob", *db])
    assert rc == 0
    assert "No experiment found" in capsys.readouterr().out
    # ...and cannot info it (clean one-line error, exit 1).
    assert cli_main(["info", "-n", "ns", "-u", "bob", *db]) == 1
    assert "no experiment matching" in capsys.readouterr().err
    # alice sees it.
    rc = cli_main(["info", "-n", "ns", "-u", "alice", *db])
    assert rc == 0
    assert "ns" in capsys.readouterr().out


def test_experiment_view_blocks_writes(populated):
    import argparse

    import pytest as _pytest

    from orion_tpu.cli.base import build_from_args

    tmp_path, db = populated
    args = argparse.Namespace(
        name="cmd-exp", exp_version=None, config=None, debug=False,
        storage_path=db[1], manual_resolution=False, user=None, user_args=[],
    )
    view, _ = build_from_args(
        args, need_user_args=False, allow_create=False, view=True
    )
    assert view.name == "cmd-exp"
    assert len(view.fetch_trials()) == 4
    assert view.stats()["trials_completed"] == 4
    with _pytest.raises(AttributeError):
        view.register_trial(None)
    with _pytest.raises(AttributeError):
        view.max_trials = 3


def test_info_shows_latency_percentiles(populated, capsys):
    """Producer telemetry surfaces as suggest/observe percentiles in info
    (SURVEY §5 timing hooks; round-1 verdict #9)."""
    tmp_path, db = populated
    rc = cli_main(["info", "-n", "cmd-exp", *db])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Performance" in out
    assert "suggest:" in out and "p50" in out and "p99" in out
    assert "observe:" in out


def test_two_users_can_own_same_experiment_name(tmp_path):
    """Per-user namespacing must actually hold two users' same-named
    experiments (identity + unique index include metadata.user)."""
    db = ["--storage-path", str(tmp_path / "db.pkl")]
    for user, lo, hi in (("alice", -5, 5), ("bob", -9, 9)):
        rc = cli_main(["hunt", "-n", "shared", "-u", user, *db,
                       "--max-trials", "2", "--worker-trials", "2",
                       BLACK_BOX, f"-x~uniform({lo}, {hi})"])
        assert rc == 0
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exps = storage.fetch_experiments({"name": "shared"})
    assert len(exps) == 2
    assert {e["metadata"]["user"] for e in exps} == {"alice", "bob"}
    assert len({e["_id"] for e in exps}) == 2


def test_db_copy_between_backends(tmp_path):
    """`db copy` migrates an experiment between backends and is idempotent."""
    from orion_tpu.cli import main

    src = str(tmp_path / "src.pkl")
    dst = str(tmp_path / "dst.sqlite")
    assert main([
        "hunt", "-n", "copy-exp", "--storage-path", src, "--max-trials", "3",
        "--working-dir", str(tmp_path / "w"), BLACK_BOX, "-x~uniform(0,1)",
    ]) == 0
    assert main(["db", "copy", "--src", src, "--dst", dst]) == 0
    # The copied experiment is fully usable from the new backend.
    assert main(["status", "--storage-path", dst]) == 0
    from orion_tpu.storage import create_storage

    out = create_storage({"type": "sqlite", "path": dst})
    exps = out.fetch_experiments({"name": "copy-exp"})
    assert len(exps) == 1
    assert len(out.fetch_trials(uid=exps[0]["_id"])) == 3
    # Idempotent re-copy: nothing duplicated.
    assert main(["db", "copy", "--src", src, "--dst", dst]) == 0
    assert len(out.fetch_trials(uid=exps[0]["_id"])) == 3


def test_db_copy_refuses_conflicting_ids(tmp_path):
    """Same _id, different content -> loud failure, nothing cross-wired."""
    from orion_tpu.cli import main
    from orion_tpu.storage import create_storage

    src = str(tmp_path / "a.pkl")
    dst = str(tmp_path / "b.pkl")
    s = create_storage({"type": "pickled", "path": src})
    s.db.write("experiments", {"_id": 1, "name": "left", "version": 1})
    # Src trials that would cross-wire onto dst's unrelated experiment 1.
    s.db.write("trials", {"_id": "t1", "experiment": 1, "status": "new"})
    create_storage({"type": "pickled", "path": dst}).db.write(
        "experiments", {"_id": 1, "name": "right", "version": 1}
    )
    assert main(["db", "copy", "--src", src, "--dst", dst]) == 1
    out = create_storage({"type": "pickled", "path": dst})
    assert [e["name"] for e in out.db.read("experiments")] == ["right"]
    assert out.db.read("trials") == []  # conflict aborts the WHOLE copy


def test_db_copy_refuses_unique_index_collision(tmp_path):
    """Distinct _ids but same (name, version, user) — the 'same experiment
    created independently on both sides' case — must abort during PLANNING,
    not traceback mid-write with earlier docs already committed."""
    from orion_tpu.cli import main
    from orion_tpu.storage import create_storage

    src = str(tmp_path / "a.pkl")
    dst = str(tmp_path / "b.pkl")
    config = {"name": "exp", "version": 1, "metadata": {"user": "alice"}}
    s = create_storage({"type": "pickled", "path": src})
    s.db.write("experiments", {"_id": "src-id", **config})
    s.db.write("trials", {"_id": "t1", "experiment": "src-id", "status": "new"})
    create_storage({"type": "pickled", "path": dst}).db.write(
        "experiments", {"_id": "dst-id", **config}
    )
    assert main(["db", "copy", "--src", src, "--dst", dst]) == 1
    out = create_storage({"type": "pickled", "path": dst})
    assert [e["_id"] for e in out.db.read("experiments")] == ["dst-id"]
    assert out.db.read("trials") == []  # nothing was copied


def test_db_copy_idempotent_across_representations(tmp_path):
    """Re-copying must merge even when backend representations differ:
    numpy values in the pickled source (dict.__eq__ would raise) and
    tuples that come back as lists through the sqlite destination."""
    import numpy as np

    from orion_tpu.cli import main
    from orion_tpu.storage import create_storage

    src = str(tmp_path / "a.pkl")
    dst = str(tmp_path / "b.sqlite")
    s = create_storage({"type": "pickled", "path": src})
    s.db.write(
        "experiments",
        {"_id": "e1", "name": "exp", "version": 1,
         "metadata": {"user": "u", "arr": np.arange(3), "tup": (1, 2),
                      "nan": float("nan")}},  # NaN != NaN must not re-conflict
    )
    assert main(["db", "copy", "--src", src, "--dst", dst]) == 0
    # Second run: dst already holds the JSON-normalized form.
    assert main(["db", "copy", "--src", src, "--dst", dst]) == 0
    out = create_storage({"type": "sqlite", "path": dst})
    assert len(out.db.read("experiments")) == 1


def test_db_copy_refuses_duplicates_within_source(tmp_path):
    """Two src experiments sharing (name, version, user) under different _ids
    (legacy databases tolerate this; index backfill is last-wins) must abort
    during planning, not DuplicateKeyError mid-write."""
    from orion_tpu.cli import main
    from orion_tpu.storage import create_storage

    from orion_tpu.storage.backends import PickledDB

    src = str(tmp_path / "a.pkl")
    dst = str(tmp_path / "b.pkl")
    config = {"name": "exp", "version": 1, "metadata": {"user": "alice"}}
    # Bypass index enforcement the way a legacy DB would: raw backend writes
    # before any storage protocol has ensured the unique index.
    raw = PickledDB(src)
    raw.write("experiments", {"_id": 1, **config})
    raw.write("experiments", {"_id": 2, **config})
    assert main(["db", "copy", "--src", src, "--dst", dst]) == 1
    out = create_storage({"type": "pickled", "path": dst})
    assert out.db.read("experiments") == []  # nothing was copied


def test_sqlite_routing_treats_empty_file_as_new(tmp_path):
    """A zero-byte *.sqlite file (crash between connect and first schema
    commit, or a pre-touched path) must stay on the sqlite backend."""
    from orion_tpu.storage.sqlitedb import sqlite_path_selected

    path = tmp_path / "db.sqlite"
    path.touch()
    assert sqlite_path_selected(str(path))
    other = tmp_path / "db.pkl"
    other.touch()
    assert not sqlite_path_selected(str(other))


# --- db dump / db load ------------------------------------------------------


def _seed_storage(path):
    st = create_storage({"type": "sqlite", "path": str(path)})
    from orion_tpu.core.trial import Result, Trial

    st.create_experiment({"name": "dmp", "version": 1, "metadata": {"user": "u"}})
    exp = st.fetch_experiments({"name": "dmp"})[0]
    for i in range(3):
        st.register_trial(
            Trial(experiment=exp["_id"], params={"/x": float(i)})
        )
    t = st.reserve_trial(exp["_id"])
    st.update_completed_trial(t, [Result("o", "objective", 0.25)])
    return st, exp


def test_db_dump_load_roundtrip(tmp_path, capsys):
    """dump -> load into a fresh backend reproduces every document; a second
    load is an idempotent no-op."""
    src_path = tmp_path / "src.sqlite"
    _seed_storage(src_path)
    dump = tmp_path / "dump.jsonl"
    assert cli_main(["db", "dump", "--src", str(src_path), "--out", str(dump)]) == 0
    assert cli_main(
        ["db", "load", "--src", str(dump), "--dst", str(tmp_path / "dst.sqlite")]
    ) == 0
    dst = create_storage({"type": "sqlite", "path": str(tmp_path / "dst.sqlite")})
    exp = dst.fetch_experiments({"name": "dmp"})[0]
    trials = dst.fetch_trials(uid=exp["_id"])
    assert len(trials) == 3
    assert sum(1 for t in trials if t.status == "completed") == 1
    # Idempotent merge.
    assert cli_main(
        ["db", "load", "--src", str(dump), "--dst", str(tmp_path / "dst.sqlite")]
    ) == 0
    assert len(dst.fetch_trials(uid=exp["_id"])) == 3
    out = capsys.readouterr().out
    assert "already present" in out


def test_db_load_mongoexport_array(tmp_path):
    """A mongoexport --jsonArray file (Mongo extended JSON: $oid/$date
    wrappers) loads with --collection, normalized to this framework's plain
    documents — the reference-Oríon migration path docs/design.md names."""
    import json

    exps = [
        {
            "_id": {"$oid": "64b1f0c2e4b0a1a2b3c4d5e6"},
            "name": "legacy",
            "version": 1,
            "metadata": {
                "user": "u",
                "datetime": {"$date": "2023-07-14T12:00:00Z"},
            },
        }
    ]
    path = tmp_path / "experiments.json"
    path.write_text(json.dumps(exps))
    dst = tmp_path / "dst.sqlite"
    assert cli_main(
        ["db", "load", "--src", str(path), "--dst", str(dst),
         "--collection", "experiments"]
    ) == 0
    st = create_storage({"type": "sqlite", "path": str(dst)})
    exp = st.fetch_experiments({"name": "legacy"})[0]
    assert exp["_id"] == "64b1f0c2e4b0a1a2b3c4d5e6"
    assert isinstance(exp["metadata"]["datetime"], float)  # epoch seconds


def test_db_load_conflict_aborts_before_writing(tmp_path, capsys):
    """Same _id with different content aborts the WHOLE load."""
    import json

    src_path = tmp_path / "src.sqlite"
    _, exp = _seed_storage(src_path)
    dump = tmp_path / "dump.jsonl"
    assert cli_main(["db", "dump", "--src", str(src_path), "--out", str(dump)]) == 0
    dst_path = tmp_path / "dst.sqlite"
    dst = create_storage({"type": "sqlite", "path": str(dst_path)})
    dst.create_experiment(
        {"_id": exp["_id"], "name": "OTHER", "version": 9, "metadata": {"user": "x"}}
    )
    rc = cli_main(["db", "load", "--src", str(dump), "--dst", str(dst_path)])
    assert rc == 1
    assert "NOTHING was loaded" in capsys.readouterr().err
    # The conflicting load wrote no trials.
    assert dst.fetch_trials(uid=exp["_id"]) == []


def test_db_load_raw_lines_require_collection(tmp_path, capsys):
    path = tmp_path / "raw.jsonl"
    path.write_text('{"name": "n", "version": 1}\n')
    rc = cli_main(["db", "load", "--src", str(path),
                   "--dst", str(tmp_path / "d.sqlite")])
    assert rc == 1
    assert "collection" in capsys.readouterr().err


def test_db_dump_refuses_missing_source(tmp_path, capsys):
    """A typo'd --src must not create an empty DB and truncate the backup."""
    out = tmp_path / "backup.jsonl"
    out.write_text("precious\n")
    rc = cli_main(["db", "dump", "--src", str(tmp_path / "typo.sqlite"),
                   "--out", str(out)])
    assert rc == 1
    assert "does not exist" in capsys.readouterr().err
    assert out.read_text() == "precious\n"  # prior backup untouched
    assert not (tmp_path / "typo.sqlite").exists()


def test_db_load_unique_index_collision_detected_in_plan(tmp_path, capsys):
    """Distinct _ids sharing an experiment's name/version/user must abort in
    the PLAN phase with the actionable message, not die mid-write."""
    src_path = tmp_path / "src.sqlite"
    _seed_storage(src_path)
    dump = tmp_path / "dump.jsonl"
    assert cli_main(["db", "dump", "--src", str(src_path), "--out", str(dump)]) == 0
    dst_path = tmp_path / "dst.sqlite"
    dst = create_storage({"type": "sqlite", "path": str(dst_path)})
    dst.create_experiment(
        {"_id": "OTHER-ID", "name": "dmp", "version": 1, "metadata": {"user": "u"}}
    )
    rc = cli_main(["db", "load", "--src", str(dump), "--dst", str(dst_path)])
    assert rc == 1
    err = capsys.readouterr().err
    assert "NOTHING was loaded" in err and "version" in err
    assert dst.fetch_trials(uid="OTHER-ID") == []


def test_db_load_concatenated_dumps_merge(tmp_path):
    """cat day1.jsonl day2.jsonl: repeated identical documents merge as
    'already present', they are not conflicts."""
    src_path = tmp_path / "src.sqlite"
    _seed_storage(src_path)
    dump = tmp_path / "dump.jsonl"
    assert cli_main(["db", "dump", "--src", str(src_path), "--out", str(dump)]) == 0
    doubled = tmp_path / "doubled.jsonl"
    doubled.write_text(dump.read_text() + dump.read_text())
    dst_path = tmp_path / "dst.sqlite"
    assert cli_main(["db", "load", "--src", str(doubled), "--dst", str(dst_path)]) == 0
    dst = create_storage({"type": "sqlite", "path": str(dst_path)})
    exp = dst.fetch_experiments({"name": "dmp"})[0]
    assert len(dst.fetch_trials(uid=exp["_id"])) == 3


def test_db_load_idless_raw_docs_dedup_by_content(tmp_path):
    """Raw JSONL documents without _id must not duplicate on re-load."""
    raw = tmp_path / "raw.jsonl"
    raw.write_text('{"experiment": "e1", "params": {"/x": 1.0}, "status": "new"}\n')
    dst_path = tmp_path / "dst.sqlite"
    for _ in range(2):
        assert cli_main(["db", "load", "--src", str(raw), "--dst", str(dst_path),
                         "--collection", "trials"]) == 0
    dst = create_storage({"type": "sqlite", "path": str(dst_path)})
    assert len(dst.fetch_trials(uid="e1")) == 1


def test_db_dump_load_preserves_wrapper_shaped_values(tmp_path):
    """Our own dump format is lossless: a legitimate document value shaped
    like a Mongo wrapper must NOT be rewritten on load."""
    src_path = tmp_path / "src.sqlite"
    st = create_storage({"type": "sqlite", "path": str(src_path)})
    st.create_experiment(
        {"name": "wrap", "version": 1,
         "metadata": {"user": "u", "odd": {"$date": 123}}}
    )
    dump = tmp_path / "dump.jsonl"
    assert cli_main(["db", "dump", "--src", str(src_path), "--out", str(dump)]) == 0
    dst_path = tmp_path / "dst.sqlite"
    assert cli_main(["db", "load", "--src", str(dump), "--dst", str(dst_path)]) == 0
    dst = create_storage({"type": "sqlite", "path": str(dst_path)})
    exp = dst.fetch_experiments({"name": "wrap"})[0]
    assert exp["metadata"]["odd"] == {"$date": 123}


def test_db_copy_refuses_missing_source(tmp_path, capsys):
    rc = cli_main(["db", "copy", "--src", str(tmp_path / "typo.pkl"),
                   "--dst", str(tmp_path / "d.sqlite")])
    assert rc == 1
    assert "does not exist" in capsys.readouterr().err
    assert not (tmp_path / "typo.pkl").exists()


def test_audit_clean_experiment(populated, capsys):
    tmp_path, db = populated
    rc = cli_main(["audit", "-n", "cmd-exp", *db])
    out = capsys.readouterr().out
    assert rc == 0
    assert "audit: OK" in out
    assert "4 completed" in out


def test_audit_reports_violations_and_exits_nonzero(populated, capsys):
    tmp_path, db = populated
    # Corrupt the store the way a dead worker would leave it: a reserved
    # trial whose heartbeat went stale far past the sweep threshold.
    storage = create_storage({"type": "pickled", "path": str(tmp_path / "db.pkl")})
    exp = storage.fetch_experiments({"name": "cmd-exp"})[0]
    from orion_tpu.core.trial import Trial

    storage.register_trial(
        Trial(
            experiment=exp["_id"], status="reserved", params={"/x": 3.25},
            start_time=1.0, heartbeat=1.0,
        )
    )
    rc = cli_main(["audit", "-n", "cmd-exp", *db])
    out = capsys.readouterr().out
    assert rc == 1
    assert "orphaned-reservation" in out


def test_audit_all_experiments(populated, capsys):
    tmp_path, db = populated
    rc = cli_main(["audit", "--all", *db])
    out = capsys.readouterr().out
    assert rc == 0
    assert "audit: OK" in out
