#!/usr/bin/env python
"""Quadratic black box used by functional tests.

Parity model: reference tests/functional/demo/black_box.py — known optimum
f(34.56) = 23.4, reports objective + gradient, asserts the worker env
contract is present.
"""

import argparse
import os

from orion_tpu.client import report_results


def main():
    assert os.environ.get("ORION_TRIAL_ID"), "env contract missing: ORION_TRIAL_ID"
    assert os.environ.get("ORION_EXPERIMENT_NAME"), "env contract missing"
    parser = argparse.ArgumentParser()
    parser.add_argument("-x", type=float, required=True)
    args = parser.parse_args()
    y = (args.x - 34.56) ** 2 + 23.4
    report_results(
        [
            {"name": "objective", "type": "objective", "value": y},
            {"name": "gradient", "type": "gradient", "value": [2 * (args.x - 34.56)]},
        ]
    )


if __name__ == "__main__":
    main()
