"""Import shim for the reference Oríon package (read-only, /root/reference).

The migration fixture (``reference_orion_db.pkl``) is PRODUCED BY the
reference's own storage write path (its PickledDB + Trial.to_dict schema —
see gen_reference_db.py), and unpickling it back requires the reference
package importable — exactly like a real user migrating from Oríon, who has
``orion`` installed next to this framework.

The reference image copy has no installed distribution, so three
packaging-level dependencies are stubbed before import — ONLY plumbing, no
reference behavior is replaced:

- ``appdirs``: config-directory lookup (reference vendors it when packaged).
- ``pkg_resources``: entry-point discovery; its factories
  (`core/utils/__init__.py:80-160`) otherwise find implementations through
  the installed distribution's entry points, so `register_factories`
  registers the same classes the reference's setup.py advertises.
- ``pymongo``: imported unconditionally by its mongodb driver module; the
  fixture never touches MongoDB.
"""

import sys
import types

REF_SRC = "/root/reference/src"


def install_reference(ref_src=REF_SRC, appdir_base="/tmp/orion-ref-appdirs"):
    """Make ``import orion`` resolve to the reference checkout."""
    if ref_src not in sys.path:
        sys.path.insert(0, ref_src)
    if "appdirs" not in sys.modules:
        appdirs = types.ModuleType("appdirs")

        class AppDirs:
            def __init__(self, *args, **kwargs):
                pass

            user_data_dir = appdir_base + "/data"
            site_data_dir = appdir_base + "/site_data"
            user_config_dir = appdir_base + "/config"
            site_config_dir = appdir_base + "/site_config"

        appdirs.AppDirs = AppDirs
        sys.modules["appdirs"] = appdirs
    if "pkg_resources" not in sys.modules:
        pkg = types.ModuleType("pkg_resources")
        pkg.iter_entry_points = lambda *a, **k: []

        class DistributionNotFound(Exception):
            pass

        def _raise(*args, **kwargs):
            raise DistributionNotFound()

        pkg.DistributionNotFound = DistributionNotFound
        pkg.get_distribution = _raise
        sys.modules["pkg_resources"] = pkg
    if "pymongo" not in sys.modules:
        pymongo = types.ModuleType("pymongo")
        errors = types.ModuleType("pymongo.errors")
        for name in (
            "DuplicateKeyError",
            "BulkWriteError",
            "ConnectionFailure",
            "OperationFailure",
        ):
            setattr(errors, name, type(name, (Exception,), {}))

        class MongoClient:
            PORT = 27017

        pymongo.MongoClient = MongoClient
        pymongo.errors = errors
        sys.modules["pymongo"] = pymongo
        sys.modules["pymongo.errors"] = errors


def register_factories():
    """Register the implementations the reference's setup.py entry points
    advertise (``Storage`` -> Legacy, ``OptimizationAlgorithm`` -> Random)."""
    import orion.algo.random as random_mod
    import orion.storage.legacy as legacy_mod
    from orion.algo.base import OptimizationAlgorithm
    from orion.storage.base import Storage

    Storage.types = [legacy_mod.Legacy]
    Storage.typenames = ["legacy"]
    OptimizationAlgorithm.types = [random_mod.Random]
    OptimizationAlgorithm.typenames = ["random"]
