"""Generate ``reference_orion_db.pkl`` by driving the REFERENCE's own
storage write path (VERDICT r4 next-6).

Everything that touches the database here is reference code:
``Experiment.configure`` writes the experiment document
(`/root/reference/src/orion/core/worker/experiment.py:469-560`),
``Experiment.register_trial`` + ``Legacy.push_trial_results`` /
``set_trial_status`` write the trial documents in the reference's
``Trial.to_dict`` schema (`core/worker/trial.py`), and ``PickledDB``
serializes its EphemeralDB to disk (`core/io/database/pickleddb.py`).  The
committed fixture is therefore a REAL reference artifact, not an imitation
— the migration tests (test_reference_migration.py) prove ``db load`` +
``db upgrade`` + an argless resumed hunt against the real thing.

Regenerate with:  python tests/functional/fixtures/gen_reference_db.py
"""

import datetime
import os
import random
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "reference_orion_db.pkl")


def main(out=OUT):
    sys.path.insert(0, HERE)
    from reference_shim import install_reference, register_factories

    install_reference()
    for stale in (out, out + ".lock"):
        if os.path.exists(stale):
            os.remove(stale)
    register_factories()

    from orion.storage.base import Storage

    Storage(
        of_type="legacy",
        config={"database": {"type": "PickledDB", "host": out}},
    )

    from orion.core.worker.experiment import Experiment
    from orion.core.worker.trial import Trial

    exp = Experiment("legacy-hunt", user="legacy_user")
    exp.configure(
        dict(
            name="legacy-hunt",
            metadata={
                "user": "legacy_user",
                "priors": {"/x": "uniform(-50, 50)"},
                "user_args": ["./black_box.py", "-x~uniform(-50, 50)"],
                "user_script": "./black_box.py",
            },
            pool_size=2,
            max_trials=30,
            algorithms={"random": {}},
        )
    )

    rng = random.Random(7)
    storage = exp._storage
    for i in range(8):
        trial = Trial(
            params=[
                {"name": "/x", "type": "real", "value": rng.uniform(-50, 50)}
            ]
        )
        exp.register_trial(trial)
        if i < 5:  # five completed, three still 'new' for the resume to pick up
            storage.set_trial_status(trial, "reserved")
            x = trial.params[0].value
            trial.results = [
                Trial.Result(
                    name="objective",
                    type="objective",
                    value=(x - 34.56) ** 2 + 23.4,
                )
            ]
            trial.status = "completed"
            trial.end_time = datetime.datetime.utcnow()
            storage.push_trial_results(trial)
            storage.set_trial_status(trial, "completed")

    if os.path.exists(out + ".lock"):
        os.remove(out + ".lock")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
