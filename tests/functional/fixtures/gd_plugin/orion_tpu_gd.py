"""Third-party algorithm plugin fixture.

Mirror of the reference's pip-installable plugin test package
(`tests/functional/gradient_descent_algo/` — its ``Gradient_Descent``
registers through an entry point and the functional suite proves the
plugin system by converging it on the quadratic demo).  This package is
NOT part of orion_tpu: it is installed by the plugin functional test into
an isolated ``--target`` dir and discovered purely through its
``orion_tpu.algo`` entry point.
"""

from orion_tpu.algo.base import BaseAlgorithm


class GradientDescent(BaseAlgorithm):
    """Toy steepest descent driven by the trial's reported ``gradient``
    result (the quadratic demo box reports one next to its objective)."""

    def __init__(self, space, seed=None, learning_rate=0.1):
        super().__init__(space, seed=seed, learning_rate=learning_rate)
        self.learning_rate = float(learning_rate)
        self._point = None  # last observed params (user space)
        self._grad = None  # its gradient, aligned with sorted param names

    def suggest(self, num=1):
        if self._point is None or self._grad is None:
            return self.space.sample(self.next_key(), n=num)
        names = [d.name for d in self.space.opt_dims]
        lows_highs = dict(zip(names, self.space.interval()))
        step = {}
        for name, grad in zip(names, self._grad):
            low, high = lows_highs[name]
            value = self._point[name] - self.learning_rate * grad
            step[name] = min(max(value, low), high)
        extra = self.space.sample(self.next_key(), n=num - 1) if num > 1 else []
        return [step] + extra

    def observe(self, params_list, results):
        for params, result in zip(params_list, results):
            grad = result.get("gradient")
            if grad is None:
                continue  # lies / gradient-less results steer nothing
            self._point = dict(params)
            self._grad = [float(g) for g in grad]
        self._n_observed += len(params_list)

    def state_dict(self):
        out = super().state_dict()
        out["point"] = self._point
        out["grad"] = self._grad
        return out

    def set_state(self, state):
        super().set_state(state)
        self._point = state["point"]
        self._grad = state["grad"]
