"""Sharded control-plane soak suite (storage/soak.py).

Tier-1 keeps the tiny deterministic run: 3 shards x 1 replica, a seeded
PR-5 ``FaultSchedule`` installed server-side on every primary, and ONE
scripted mid-run chaos action (reconnect storm + shard restart + replica
kill) executed at a worker barrier — timing-proof, every signal
guaranteed to fire.  The 1000-worker wall-clock soak (3 shards x 2
replicas, periodic storms/partitions/restarts) is marked ``slow``.

Pass bar everywhere: the run completes, ZERO lost observations, the
invariant audit comes back clean through the router AND on every shard
individually, the per-shard completed counts sum to the router's view,
and the chaos actually registered in the counters (faults fired,
reconnects/failovers moved).
"""

import pytest

from orion_tpu.storage.faults import FaultSchedule, FaultyDB
from orion_tpu.storage.soak import (
    ReplicaProvisioner,
    SoakTopology,
    busiest_shard,
    drain_and_remove,
    drive_soak,
    grow_and_rebalance,
)
from orion_tpu.telemetry import TELEMETRY

#: One pinned fault per round class early on, seeded extras on top — the
#: same discipline as the single-server chaos suite (test_chaos.py).
TINY_PLAN = {3: "error", 8: "latency", 13: "reply_lost", 17: "kill"}
TINY_RATES = {"error": 0.02, "reply_lost": 0.01}


@pytest.fixture
def telemetry_enabled():
    was = TELEMETRY.enabled
    TELEMETRY.enable()
    yield TELEMETRY
    if not was:
        TELEMETRY.disable()


def _assert_soak_outcome(result, expect_faults=None, expect_restarts=0):
    assert result.lost_observations == 0, result.summary()
    assert result.completed == result.registered
    assert result.audits_clean, result.summary()
    # The router's completed count is exactly the sum of its shards —
    # the two views of the same data cannot disagree.
    assert sum(result.completed_per_shard.values()) == result.completed
    if expect_faults is not None:
        for schedule in expect_faults:
            assert schedule.total_injected > 0, (
                f"fault schedule never fired: {schedule.injected}"
            )
    assert result.restarts == expect_restarts


@pytest.mark.chaos
def test_sharded_chaos_tiny_seeded_schedule_with_restart(tmp_path,
                                                         telemetry_enabled):
    """Tier-1: 3 shards, seeded server-side faults on every primary, one
    scripted shard restart + reconnect storm + replica kill at the worker
    barrier; zero lost observations and clean audits everywhere."""
    registry = telemetry_enabled
    retries_before = registry.counter_value("storage.retries")
    topo = SoakTopology(n_shards=3, replicas=1, persist_dir=str(tmp_path))
    schedules = []
    for shard in topo.shards:
        schedule = FaultSchedule(
            seed=7 + shard.index, plan=dict(TINY_PLAN), rates=TINY_RATES,
            latency=0.005, max_faults=12,
        )
        schedules.append(schedule)
        shard.install_faults(lambda db, s=schedule: FaultyDB(db, s))

    def chaos_once():
        topo.drop_all()  # reconnect storm
        topo.shards[1].restart_primary()  # shard kill/restart (persisted)
        for shard in topo.shards:
            # Replica loss on EVERY shard: the read failover fires no
            # matter where the ring placed the experiments.
            shard.kill_replica(0)

    try:
        result = drive_soak(
            topo, n_workers=12, n_experiments=6, trials_per_worker=4,
            n_routers=4, chaos=False, mid_hook=chaos_once, deadline=120.0,
        )
    finally:
        topo.stop()
    _assert_soak_outcome(result, expect_faults=schedules, expect_restarts=1)
    # The chaos signals all registered where operators would look.
    assert result.reconnects >= 1, "the storm never forced a reconnect"
    assert result.failovers >= 1, "the killed replica never forced a failover"
    assert (
        registry.counter_value("storage.retries") > retries_before
    ), "faults fired but nothing retried — the policy is not wired in"


@pytest.mark.chaos
@pytest.mark.tsan
def test_sharded_router_concurrent_workers_tsan_clean(tmp_path):
    """The router's ring/owner/seq tables under the runtime sanitizer:
    concurrent workers fanning out, routing, and replica-reading through
    shared routers must produce zero data races or lock-order cycles
    (the annotated cells are ShardedNetworkDB._owners/_shard_state/_stats)."""
    topo = SoakTopology(n_shards=3, replicas=1, persist_dir=None)
    try:
        result = drive_soak(
            topo, n_workers=8, n_experiments=4, trials_per_worker=2,
            n_routers=2, chaos=False, deadline=60.0,
        )
    finally:
        topo.stop()
    _assert_soak_outcome(result)


@pytest.mark.chaos
def test_promotion_soak_tiny(tmp_path, telemetry_enabled):
    """Tier-1 promotion soak (ISSUE 14): the BUSIEST shard's primary dies
    for good at the worker barrier — no restart, no human — and the
    router fleet must elect its caught-up replica and finish with zero
    lost observations and clean audits everywhere."""
    topo = SoakTopology(n_shards=3, replicas=1, persist_dir=str(tmp_path))

    def chaos_once(storages):
        victim = busiest_shard(topo, storages[0].db, 6)
        topo.shards[victim].kill_primary()

    try:
        result = drive_soak(
            topo, n_workers=12, n_experiments=6, trials_per_worker=4,
            n_routers=4, chaos=False, mid_hook=chaos_once, deadline=120.0,
        )
    finally:
        topo.stop()
    _assert_soak_outcome(result)
    assert result.primary_kills == 1
    assert result.promotions >= 1, (
        "primary killed but nothing promoted: " + str(result.summary())
    )


@pytest.mark.chaos
def test_rebalance_soak_tiny(tmp_path, telemetry_enabled):
    """Tier-1 rebalance-mid-soak (ISSUE 14): the topology grows by one
    shard at the worker barrier, every live router retargets in place,
    the migrator moves ~1/N of the experiments (byte-identical copies,
    audited, atomic placement flip) and the workers finish on the new
    ring with zero lost observations."""
    topo = SoakTopology(n_shards=3, replicas=1, persist_dir=str(tmp_path))
    outcome = {}

    def rebalance_hook(storages):
        # THE shared hook body (bench.py --soak runs the same scenario).
        outcome.update(grow_and_rebalance(topo, storages))

    try:
        result = drive_soak(
            topo, n_workers=12, n_experiments=8, trials_per_worker=4,
            n_routers=4, chaos=False, mid_hook=rebalance_hook, deadline=120.0,
        )
    finally:
        topo.stop()
    _assert_soak_outcome(result)
    assert outcome.get("executed") is True
    assert outcome["planned"]["moves"] >= 1
    # ~1/N invariant, loosely bounded (hash variance on 8 experiments).
    assert outcome["planned"]["move_fraction"] <= 2.5 / len(topo.shards)
    # The new shard actually serves: at least one experiment completed on
    # a shard index >= 3 OR nothing hashed there (moves landed elsewhere) —
    # the audits above already covered every shard either way.
    assert set(result.completed_per_shard) == {s.index for s in topo.shards}


@pytest.mark.chaos
def test_drain_soak_tiny(tmp_path, telemetry_enabled):
    """Tier-1 drain-mid-soak (ISSUE 20): the busiest shard is DRAINED and
    REMOVED at the worker barrier — survivor-ring migration, zero
    residual, every live router retargeted — and the workers finish on
    the shrunk topology with zero lost observations and clean audits.
    The twin of the ``bench.py --soak`` drain gate (one shared scenario:
    ``drain_and_remove``)."""
    topo = SoakTopology(n_shards=3, replicas=1, persist_dir=str(tmp_path))
    outcome = {}

    def drain_hook(storages):
        outcome.update(drain_and_remove(topo, storages))

    try:
        result = drive_soak(
            topo, n_workers=12, n_experiments=8, trials_per_worker=4,
            n_routers=4, chaos=False, mid_hook=drain_hook, deadline=120.0,
        )
    finally:
        topo.stop()
    _assert_soak_outcome(result)
    assert outcome.get("executed") is True
    assert outcome["residual"] == 0
    assert outcome["planned"]["moves"] >= 1
    assert outcome["n_shards"] == 2
    # The drained fraction tracks the shard's true ring share (2x bound:
    # hash variance on 8 experiments is wide, systematic drift is not).
    assert outcome["planned"]["move_fraction"] <= 2.0 * outcome["ring_share"]
    # Everything now lives on (and audits clean on) the two survivors.
    assert set(result.completed_per_shard) == {0, 1}


@pytest.mark.chaos
def test_quorum_soak_kill_without_catchup_tiny(tmp_path, telemetry_enabled):
    """Tier-1 quorum soak (ISSUE 20): ``quorum=1`` over 2 replicas, the
    busiest primary killed with NO replication catch-up wait — the ack
    floor itself is the zero-loss mechanism (an acked sync write is on a
    replica by construction; the max-seq election winner carries it)."""
    topo = SoakTopology(
        n_shards=3, replicas=2, persist_dir=str(tmp_path), quorum=1,
    )

    def chaos_once(storages):
        victim = busiest_shard(topo, storages[0].db, 6)
        topo.shards[victim].kill_primary(wait_catchup=False)

    try:
        result = drive_soak(
            topo, n_workers=12, n_experiments=6, trials_per_worker=4,
            n_routers=4, chaos=False, mid_hook=chaos_once, deadline=120.0,
        )
    finally:
        topo.stop()
    _assert_soak_outcome(result)
    assert result.primary_kills == 1
    assert result.promotions >= 1, (
        "primary killed but nothing promoted: " + str(result.summary())
    )


@pytest.mark.chaos
def test_replica_auto_reprovision_heals_promoted_shard(tmp_path,
                                                      telemetry_enabled):
    """Day-2 self-repair (ISSUE 20): after a promotion leaves a shard one
    replica short forever, a router configured with a
    ``replica_provisioner`` detects the dead replica, provisions a fresh
    empty server, has the promoted primary adopt it (bounded snapshot
    resync) and swaps it into the replica set — no human in the loop."""
    import time as _time

    from orion_tpu.core.experiment import experiment_id

    registry = telemetry_enabled
    topo = SoakTopology(n_shards=2, replicas=2, persist_dir=str(tmp_path))
    provisioner = ReplicaProvisioner()
    router = topo.make_router(
        replica_reads=False,
        replica_provisioner=provisioner,
        reprovision_after=0.5,
        promote_after=0.3,
    )
    try:
        eid = experiment_id("repro-0", 1, "soak")
        victim = router.shard_for(eid)
        router.write(
            "experiments",
            {"_id": eid, "name": "repro-0", "version": 1,
             "metadata": {"user": "soak"}},
        )
        topo.shards[victim].wait_replicated()
        # The one-short-forever state: a replica dies AND the primary dies
        # for good; the election heals the primary, reprovisioning must
        # heal the replica set.
        topo.shards[victim].kill_replica(0)
        topo.shards[victim].kill_primary(wait_catchup=False)
        deadline = _time.monotonic() + 30.0
        n = 0
        while _time.monotonic() < deadline and router.promotions < 1:
            n += 1
            try:
                router.write(
                    "trials",
                    {"_id": f"{eid}-t{n}", "experiment": eid,
                     "status": "new", "params": {"/x": float(n)}},
                )
            except Exception:
                _time.sleep(0.05)
        assert router.promotions >= 1, "election never healed the primary"
        while _time.monotonic() < deadline and router.reprovisions < 1:
            _time.sleep(0.1)
        assert router.reprovisions >= 1, "dead replica never reprovisioned"
        assert registry.counter_value("storage.shard.reprovisions") >= 1
        assert provisioner.servers, "the provisioner was never asked"
        # The adopted replica converges and the shard reports full health.
        def healed():
            for entry in router.replication_health():
                if entry["index"] != victim or entry.get("error"):
                    continue
                rows = entry.get("replicas", [])
                if rows and all(not r.get("error") for r in rows):
                    return True
            return False

        while _time.monotonic() < deadline and not healed():
            _time.sleep(0.1)
        assert healed(), router.replication_health()
        assert (
            registry.gauge_value("storage.reprovision.in_progress", 0.0)
            == 0.0
        )
    finally:
        router.close()
        topo.stop()
        provisioner.stop()


@pytest.mark.chaos
@pytest.mark.slow
def test_thousand_worker_promotion_soak(tmp_path, telemetry_enabled):
    """The 1000-worker promotion soak (kept out of tier-1): periodic
    storms + a permanent busiest-primary kill at the barrier; the fleet
    heals itself, zero lost."""
    topo = SoakTopology(n_shards=3, replicas=2, persist_dir=str(tmp_path))

    def chaos_once(storages):
        victim = busiest_shard(topo, storages[0].db, 24)
        for shard in topo.shards:
            if shard.index != victim:
                shard.kill_replica(0)
        topo.shards[victim].kill_primary()

    try:
        result = drive_soak(
            topo, n_workers=1000, n_experiments=24, trials_per_worker=3,
            n_routers=32, chaos=True, chaos_period=1.0, mid_hook=chaos_once,
            deadline=600.0,
        )
    finally:
        topo.stop()
    assert result.registered == 3000
    _assert_soak_outcome(
        result,
        expect_restarts=result.restarts,  # periodic chaos restarts freely
    )
    assert result.primary_kills == 1
    assert result.promotions >= 1


@pytest.mark.chaos
@pytest.mark.slow
def test_thousand_worker_soak(tmp_path, telemetry_enabled):
    """THE headline soak: 1000 workers over 3 shards x 2 replicas under
    periodic reconnect storms, partitions and shard restarts, plus the
    deterministic mid-run restart/replica-kill.  Zero lost observations,
    clean audits on every shard, failover and degraded-mode loss counted."""
    topo = SoakTopology(n_shards=3, replicas=2, persist_dir=str(tmp_path))

    def chaos_once():
        topo.drop_all()
        topo.shards[2].restart_primary()
        for shard in topo.shards:
            shard.kill_replica(0)

    try:
        result = drive_soak(
            topo, n_workers=1000, n_experiments=24, trials_per_worker=3,
            n_routers=32, chaos=True, chaos_period=1.0, mid_hook=chaos_once,
            deadline=600.0,
        )
    finally:
        topo.stop()
    assert result.registered == 3000
    _assert_soak_outcome(
        result,
        expect_restarts=result.restarts,  # periodic chaos may add more
    )
    assert result.restarts >= 1
    assert result.reconnects >= 1
    assert result.failovers >= 1
